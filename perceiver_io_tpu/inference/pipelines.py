"""Task pipelines — the torch-free equivalent of the reference's Hugging
Face pipeline registrations (SURVEY.md §2.2): ``text-generation``,
``fill-mask``, ``sentiment-analysis``, ``image-classification``, the custom
``optical-flow`` pipeline (``optical_flow/huggingface.py:71-124``) and the
custom ``symbolic-audio-generation`` pipeline
(``symbolic/huggingface.py:161-298``).

Each pipeline wraps (model, params, preprocessing) behind one callable; model
forwards are jitted once per pipeline and batches are padded to static
shapes, so repeated calls never recompile. :func:`pipeline` dispatches on
task name like ``transformers.pipeline``; :func:`pipeline_from_pretrained`
builds one straight from a ``save_pretrained`` dir via the embedded config.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.inference.generate import GenerationConfig, generate
from perceiver_io_tpu.inference.mask_filler import MaskFiller
from perceiver_io_tpu.inference.samplers import SamplingConfig


class _Pipeline:
    """Shared (model, params) plumbing; jitted apply cached per pipeline."""

    def __init__(self, model, params):
        self.model = model
        self.params = params
        self._apply = jax.jit(self._forward)

    def _forward(self, params, *args, **kwargs):
        return self.model.apply({"params": params}, *args, **kwargs)


def _warn_sampling_ignored_under_beam(num_beams, temperature, top_k, top_p):
    """Beam dispatch is deterministic; sampling knobs would be silently
    dropped (HF warns the same way for ``temperature`` + ``do_sample=False``)."""
    if num_beams > 1 and (temperature != 1.0 or top_k is not None or top_p is not None):
        import warnings

        warnings.warn(
            "temperature/top_k/top_p are ignored when num_beams > 1 — beam "
            "search decodes deterministically",
            UserWarning,
            stacklevel=3,
        )


def _pad_batch(rows: List[np.ndarray], pad_id: int, side: str) -> Tuple[np.ndarray, np.ndarray]:
    width = max(len(r) for r in rows)
    out = np.full((len(rows), width), pad_id, np.int32)
    for i, row in enumerate(rows):
        if side == "left":
            out[i, width - len(row):] = row
        else:
            out[i, : len(row)] = row
    return out, out == pad_id


class TextGenerationPipeline(_Pipeline):
    """``pipeline("text-generation")`` parity (reference
    ``clm/huggingface.py:100-143``): prompts → continuation text via the
    on-device ``lax.scan`` decode loop.

    With ``bucketing=True`` calls route through the shape-bucketed serving
    engine (``perceiver_io_tpu.serving``): prompts are padded to a static
    bucket grid and micro-batched, so ragged call patterns hit a small
    pre-compilable executor set instead of one trace per exact batch shape.
    Greedy output is token-identical either way (generation is left-pad
    invariant); ``serving_stats()`` exposes the engine counters.
    """

    def __init__(self, model, params, tokenizer, *, bucketing: bool = False,
                 bucket_table=None, decode_strategy: Optional[str] = None):
        super().__init__(model, params)
        self.tokenizer = tokenizer
        self.bucketing = bucketing
        self._bucket_table = bucket_table
        #: per-phase cache strategy (inference/decode_strategy.py) applied
        #: to every generate dispatch and the lazily built serving engine;
        #: None defers to PERCEIVER_DECODE_STRATEGY / the measured registry
        self.decode_strategy = decode_strategy
        self._engine = None

    def _make_config(
        self, *, max_new_tokens: int = 64, min_new_tokens: int = 0,
        num_latents: int = 1, temperature: float = 1.0,
        top_k: Optional[int] = None, top_p: Optional[float] = None,
        repetition_penalty: float = 1.0, num_beams: int = 1,
        length_penalty: float = 1.0,
    ) -> GenerationConfig:
        return GenerationConfig(
            max_new_tokens=max_new_tokens,
            min_new_tokens=min_new_tokens,
            num_latents=num_latents,
            pad_token_id=self.tokenizer.pad_token_id or 0,
            eos_token_id=self.tokenizer.eos_token_id,
            num_beams=num_beams,
            length_penalty=length_penalty,
            sampling=SamplingConfig(temperature=temperature, top_k=top_k, top_p=top_p,
                                    repetition_penalty=repetition_penalty),
        )

    def _ensure_engine(self, config: GenerationConfig):
        if self._engine is None:
            from perceiver_io_tpu.serving import ServingEngine

            self._engine = ServingEngine(
                self.model, self.params, config, table=self._bucket_table,
                decode_strategy=self.decode_strategy,
            )
        return self._engine

    def warmup(self, **gen_kwargs) -> int:
        """Ahead-of-time compile of every serving bucket (``bucketing=True``
        only); returns the number of fresh executor compiles."""
        if not self.bucketing:
            raise ValueError("warmup() requires bucketing=True")
        config = self._make_config(**gen_kwargs)
        return self._ensure_engine(config).warmup(config)

    def serving_stats(self) -> Optional[dict]:
        """Engine counters (compiles, queue waits, cache hits) or ``None``
        when bucketing is off / nothing was served yet."""
        return self._engine.stats() if self._engine is not None else None

    def __call__(
        self,
        prompts: Union[str, Sequence[str]],
        *,
        max_new_tokens: int = 64,
        min_new_tokens: int = 0,
        num_latents: int = 1,
        temperature: float = 1.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        repetition_penalty: float = 1.0,
        num_beams: int = 1,
        length_penalty: float = 1.0,
        seed: int = 0,
        return_full_text: bool = True,
    ) -> List[str]:
        _warn_sampling_ignored_under_beam(num_beams, temperature, top_k, top_p)
        single = isinstance(prompts, str)
        batch = [prompts] if single else list(prompts)
        encoded = [np.asarray(self.tokenizer.encode(p), np.int32) for p in batch]
        pad_id = self.tokenizer.pad_token_id or 0

        config = self._make_config(
            max_new_tokens=max_new_tokens, min_new_tokens=min_new_tokens,
            num_latents=num_latents, temperature=temperature, top_k=top_k,
            top_p=top_p, repetition_penalty=repetition_penalty,
            num_beams=num_beams, length_penalty=length_penalty,
        )
        if self.bucketing and num_beams == 1:
            rows = self._ensure_engine(config).serve(
                encoded, config, rng=jax.random.PRNGKey(seed)
            )
        else:
            ids, pad = _pad_batch(encoded, pad_id, "left")
            pad_count = pad.sum(axis=1).astype(np.int32)
            rows = np.asarray(generate(
                self.model,
                self.params,
                jnp.asarray(ids),
                config,
                rng=jax.random.PRNGKey(seed),
                prompt_pad_count=jnp.asarray(pad_count),
                decode_strategy=self.decode_strategy,
            ))
        texts = []
        for prompt, row in zip(batch, rows):
            new = self.tokenizer.decode([t for t in row.tolist() if t != pad_id])
            texts.append(prompt + new if return_full_text else new)
        return texts[0:1] if single else texts


class FillMaskPipeline(_Pipeline):
    """``pipeline("fill-mask")`` parity: top-k fillings per masked text."""

    def __init__(self, model, params, preprocessor):
        super().__init__(model, params)
        self._filler = MaskFiller(preprocessor)

    def __call__(
        self, texts: Union[str, Sequence[str]], *, top_k: int = 5
    ) -> List[List[str]]:
        batch = [texts] if isinstance(texts, str) else list(texts)
        _, filled = self._filler.fill(self.model, self.params, batch, top_k)
        return filled


class TextClassificationPipeline(_Pipeline):
    """``pipeline("sentiment-analysis")`` parity (reference
    ``classifier/huggingface.py``)."""

    def __init__(self, model, params, preprocessor, labels: Sequence[str] = ("NEGATIVE", "POSITIVE")):
        super().__init__(model, params)
        self.preprocessor = preprocessor
        self.labels = list(labels)

    def __call__(self, texts: Union[str, Sequence[str]]) -> List[Dict[str, Any]]:
        batch = [texts] if isinstance(texts, str) else list(texts)
        ids, pad = self.preprocessor.preprocess_batch(batch)
        logits = self._apply(self.params, jnp.asarray(ids), pad_mask=jnp.asarray(pad))
        probs = np.asarray(jax.nn.softmax(logits.astype(jnp.float32), axis=-1))
        out = []
        for row in probs:
            idx = int(row.argmax())
            out.append({"label": self.labels[idx], "score": float(row[idx])})
        return out


class ImageClassificationPipeline(_Pipeline):
    """``pipeline("image-classification")`` parity (reference
    ``image_classifier/huggingface.py:37-235``): channels-last uint8 images →
    top-k labels."""

    def __init__(self, model, params, preprocessor=None, labels: Optional[Sequence[str]] = None):
        from perceiver_io_tpu.data.vision import ImagePreprocessor

        super().__init__(model, params)
        self.preprocessor = preprocessor or ImagePreprocessor()
        self.labels = labels

    def __call__(
        self, images: np.ndarray, *, top_k: int = 1
    ) -> List[List[Dict[str, Any]]]:
        x = self.preprocessor(np.asarray(images))
        logits = self._apply(self.params, jnp.asarray(x))
        probs = np.asarray(jax.nn.softmax(logits.astype(jnp.float32), axis=-1))
        results = []
        for row in probs:
            order = np.argsort(-row)[:top_k]
            results.append(
                [
                    {
                        "label": self.labels[i] if self.labels else int(i),
                        "score": float(row[i]),
                    }
                    for i in order
                ]
            )
        return results


class OpticalFlowPipeline(_Pipeline):
    """The reference's custom ``optical-flow`` pipeline
    (``optical_flow/huggingface.py:71-124``): frame pairs → per-pixel flow,
    micro-batched over patches with static compiled shapes, optionally
    rendered to RGB."""

    def __init__(self, model, params, *, patch_size: Tuple[int, int] = (368, 496),
                 patch_min_overlap: int = 20, batch_size: int = 1, render: bool = False):
        from perceiver_io_tpu.data.vision import OpticalFlowProcessor

        super().__init__(model, params)
        self.processor = OpticalFlowProcessor(
            patch_size=patch_size, patch_min_overlap=patch_min_overlap
        )
        self.batch_size = batch_size
        self.render = render

    def __call__(
        self,
        image_pairs: Union[Tuple[np.ndarray, np.ndarray], Sequence[Tuple[np.ndarray, np.ndarray]]],
    ):
        single = (
            len(image_pairs) == 2
            and isinstance(image_pairs[0], np.ndarray)
            and image_pairs[0].ndim >= 2
        )
        pairs = [image_pairs] if single else list(image_pairs)

        def model_fn(x):
            return np.asarray(self._apply(self.params, jnp.asarray(x)))

        flow = self.processor.process(model_fn, pairs, batch_size=self.batch_size)
        if self.render:
            from perceiver_io_tpu.data.vision import render_optical_flow

            rendered = np.stack([render_optical_flow(f) for f in flow])
            return rendered[0] if single else rendered
        return flow[0] if single else flow


class SymbolicAudioPipeline(_Pipeline):
    """The reference's custom ``symbolic-audio-generation`` pipeline
    (``symbolic/huggingface.py:161-298``): MIDI (or event ids) in → token
    generation → MIDI out; optional WAV rendering via a fluidsynth
    subprocess when both pretty_midi and fluidsynth are present."""

    def __init__(self, model, params):
        super().__init__(model, params)

    def __call__(
        self,
        prompts: Union[Sequence[int], Sequence[Sequence[int]], "np.ndarray"],
        *,
        max_new_tokens: int = 256,
        min_new_tokens: int = 0,
        num_latents: int = 1,
        temperature: float = 1.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        repetition_penalty: float = 1.0,
        num_beams: int = 1,
        length_penalty: float = 1.0,
        seed: int = 0,
    ) -> List[np.ndarray]:
        from perceiver_io_tpu.data.audio import PAD_TOKEN

        _warn_sampling_ignored_under_beam(num_beams, temperature, top_k, top_p)
        if isinstance(prompts, np.ndarray) and prompts.ndim == 1:
            batch = [np.asarray(prompts, np.int32)]
        elif isinstance(prompts, (list, tuple)) and prompts and np.isscalar(prompts[0]):
            batch = [np.asarray(prompts, np.int32)]  # single flat prompt
        else:
            batch = [np.asarray(r, np.int32) for r in prompts]  # ragged batch
        ids, pad = _pad_batch(batch, PAD_TOKEN, "left")
        pad_count = pad.sum(axis=1).astype(np.int32)

        config = GenerationConfig(
            max_new_tokens=max_new_tokens,
            min_new_tokens=min_new_tokens,
            num_latents=num_latents,
            pad_token_id=PAD_TOKEN,
            num_beams=num_beams,
            length_penalty=length_penalty,
            sampling=SamplingConfig(temperature=temperature, top_k=top_k, top_p=top_p,
                                    repetition_penalty=repetition_penalty),
        )
        out = generate(
            self.model,
            self.params,
            jnp.asarray(ids),
            config,
            rng=jax.random.PRNGKey(seed),
            prompt_pad_count=jnp.asarray(pad_count),
        )
        return [np.concatenate([p, row]) for p, row in zip(batch, np.asarray(out))]

    def generate_midi(self, prompt_events: Sequence[int], path=None, **kwargs):
        """Generate and decode to a MIDI object/file (requires pretty_midi)."""
        from perceiver_io_tpu.data.audio import decode_to_midi_file

        events = self([np.asarray(prompt_events, np.int32)], **kwargs)[0]
        return decode_to_midi_file(events, path)

    @staticmethod
    def render_wav(midi_path: str, wav_path: str, sound_font: str) -> None:
        """WAV render through the fluidsynth CLI (the reference shells out
        the same way, ``symbolic/huggingface.py:270-279``)."""
        import subprocess

        subprocess.run(
            ["fluidsynth", "-ni", sound_font, midi_path, "-F", wav_path],
            check=True,
        )


_TASKS = {
    "text-generation": TextGenerationPipeline,
    "fill-mask": FillMaskPipeline,
    "sentiment-analysis": TextClassificationPipeline,
    "text-classification": TextClassificationPipeline,
    "image-classification": ImageClassificationPipeline,
    "optical-flow": OpticalFlowPipeline,
    "symbolic-audio-generation": SymbolicAudioPipeline,
}


def pipeline(task: str, model, params, *args, **kwargs):
    """``transformers.pipeline``-shaped dispatch by task name."""
    if task not in _TASKS:
        raise ValueError(f"unknown task {task!r}; available: {sorted(_TASKS)}")
    return _TASKS[task](model, params, *args, **kwargs)


def cast_float_params(params, dtype):
    """Cast floating-point param leaves to ``dtype`` (ints/bools untouched).

    For inference, bf16 weight storage halves the HBM weight traffic of every
    matmul in the decode loop — which is bandwidth-bound at small batch — vs
    keeping fp32 weights and casting inside the step. Training keeps fp32
    master params; this is an inference-side transform only."""
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        params,
    )


def pipeline_from_pretrained(task: str, path: str, *args, dtype=None,
                             params_dtype=None,
                             attention_impl: str = "auto", **kwargs):
    """Build a pipeline straight from a ``save_pretrained`` dir: the embedded
    config picks the model class (reference ``from_pretrained`` parity).

    :param dtype: computation dtype (bf16 keeps the MXU at full rate).
    :param params_dtype: storage dtype for the loaded weights — pass
        ``jnp.bfloat16`` to halve decode-loop weight traffic
        (:func:`cast_float_params`); ``None`` keeps the checkpoint's dtype.
        The cast happens after a full-precision restore, so load-time peak
        host memory is ~1.5× the fp32 tree (~2 GB for the largest reference
        model); restore-into-dtype via ``load_pretrained(target=...)`` is the
        escape hatch if that ever matters.
    """
    from perceiver_io_tpu.models import model_for_config
    from perceiver_io_tpu.training.checkpoint import load_pretrained

    params, config = load_pretrained(path)
    if config is None:
        raise ValueError(f"{path} has no embedded model config")
    if params_dtype is not None:
        params = cast_float_params(params, params_dtype)
    model = model_for_config(config, dtype=dtype, attention_impl=attention_impl)
    return pipeline(task, model, params, *args, **kwargs)
