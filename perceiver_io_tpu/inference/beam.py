"""Beam search decoding for Perceiver AR sequence models.

Semantics follow HF ``GenerationMixin`` beam search (the decoding surface the
reference exposes and tests — reference
``tests/causal_language_model_pipeline_test.py:37-38``,
``tests/symbolic_audio_model_pipeline_test.py:95-96``), re-formulated as one
jittable ``lax.scan`` over the same right-aligned static window as
:mod:`perceiver_io_tpu.inference.generate`:

- beam scores start ``[0, -1e9, ...]`` so step 1 fans out of beam 0;
- per step: ``log_softmax`` over next-token logits, cumulative scores,
  top-``2k`` candidates over the flattened ``(k·V)`` score matrix;
- candidates ending in EOS are moved into a per-batch hypothesis buffer
  (score length-normalized at insertion, ``score / gen_len**length_penalty``
  with ``gen_len`` counting *generated* tokens only, matching the vectorized
  ``_beam_search`` in transformers >= 4.50 — older HF ``BeamHypotheses.add``
  normalized by prompt + generated); the first ``k`` non-EOS candidates
  continue as live beams;
- termination is by ``max_new_tokens`` (``early_stopping=False`` semantics:
  the search runs to max length, then live beams are finalized against the
  hypothesis buffer).

``min_new_tokens`` masks EOS to ``-inf`` until that many tokens exist
(HF ``MinNewTokensLengthLogitsProcessor``); driving it equal to
``max_new_tokens`` gives the deterministic full-length search the reference
parity tests use.

All shapes are static: beams ride the batch axis (``b·k`` windows), beam
reindexing is a gather, and per-beam token histories live in a carried
``(b, k, max_new)`` buffer that is reindexed alongside the beams.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from perceiver_io_tpu.inference.generate import (
    GenerationConfig,
    _decode_forward,
    _pad_positions,
)
from perceiver_io_tpu.inference.samplers import (
    apply_min_new_tokens,
    apply_repetition_penalty,
)

NEG_INF = -1e9


def beam_search(
    model,
    params,
    input_ids: jnp.ndarray,
    config: GenerationConfig,
    *,
    num_beams: int = 3,
    length_penalty: float = 1.0,
    prompt_pad_count: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Beam-search ``config.max_new_tokens`` tokens after ``input_ids``.

    :param input_ids: ``(b, prompt_len)`` prompt, left-padded if ragged.
    :return: ``(b, max_new_tokens)`` ids of the best beam (pad after EOS).
    """
    b, prompt_len = input_ids.shape
    n = model.max_seq_len
    max_latents = model.max_latents
    if not 0 < prompt_len <= n:
        raise ValueError(f"prompt length out of valid range [1..{n}]")
    if not 0 < config.num_latents <= max_latents:
        raise ValueError(
            f"num_latents={config.num_latents} out of valid range [1..{max_latents}]"
        )
    num_latents = min(prompt_len, config.num_latents)
    prefix_len = prompt_len - num_latents
    if prefix_len > model.max_prefix_len:
        raise ValueError(
            f"for sequence length {prompt_len}, num_latents must be >= "
            f"{num_latents + prefix_len - model.max_prefix_len}"
        )
    if prompt_pad_count is None:
        prompt_pad_count = jnp.zeros((b,), jnp.int32)
    executor = _beam_executor(
        model, config, b, prompt_len, num_latents, num_beams,
        float(length_penalty), str(input_ids.dtype),
    )
    return executor(params, input_ids, prompt_pad_count)


_EXECUTOR_CACHE: dict = {}


def _beam_executor(
    model, config, b: int, prompt_len: int, num_latents: int,
    num_beams: int, length_penalty: float, ids_dtype: str,
):
    """Compile-once beam program per static plan (same rationale and keying
    as ``generate._generation_executor`` — the eager body re-traced the
    whole scan on every call)."""
    from perceiver_io_tpu.inference.generate import (
        cached_executor,
        ledger_model_id,
        model_fingerprint,
    )
    from perceiver_io_tpu.models.core.modules import trace_env_fingerprint

    key = (
        type(model).__qualname__, model_fingerprint(model), config,
        b, prompt_len, num_latents, num_beams, length_penalty, ids_dtype,
        trace_env_fingerprint(),
    )
    return cached_executor(
        _EXECUTOR_CACHE, key,
        lambda: _build_beam_executor(
            model, config, b, prompt_len, num_latents, num_beams,
            length_penalty, ids_dtype,
        ),
        max_entries=32,
        ledger_site="beam",
        ledger_components=lambda: {
            "model": ledger_model_id(model),
            # max_new_tokens is routine per-request variation — it belongs
            # to beam_plan (the compiled scan length), not the `config`
            # retrace reason (sampling/eos/latents; docs/observability.md)
            "config": dataclasses.replace(config, max_new_tokens=0),
            "bucket_shape": f"{b}x{prompt_len}",
            "num_latents": num_latents,
            "beam_plan": (
                f"k={num_beams},lp={length_penalty},"
                f"steps={config.max_new_tokens}"
            ),
            "ids_dtype": ids_dtype,
            "trace_env": trace_env_fingerprint(),
        },
    )


def _build_beam_executor(
    model, config, b: int, prompt_len: int, num_latents: int,
    num_beams: int, length_penalty: float, ids_dtype: str,
):
    n = model.max_seq_len
    max_latents = model.max_latents
    k = num_beams
    t_max = config.max_new_tokens
    vocab = model.config.vocab_size
    eos = config.eos_token_id
    min_new = min(config.min_new_tokens, t_max) if eos is not None else t_max
    rep_penalty = config.sampling.repetition_penalty

    def run(params, input_ids, prompt_pad_count):
        # Beams ride the batch axis: (b, k, ...) flattened to (b*k, ...).
        window = jnp.full((b, n), config.pad_token_id, input_ids.dtype)
        window = window.at[:, n - prompt_len :].set(input_ids)
        window = jnp.repeat(window, k, axis=0)
        pad_count = jnp.repeat(
            prompt_pad_count.astype(jnp.int32) + (n - prompt_len), k, axis=0
        )
        beam_scores = jnp.full((b, k), NEG_INF, jnp.float32).at[:, 0].set(0.0)

        rows = jnp.arange(b)[:, None]  # (b, 1) batch index for beam gathers

        def step(carry, t):
            window, pad_count, m, beam_scores, tok_buf, hyp_scores, hyp_tokens = carry

            logits = model.apply(
                {"params": params}, window, pad_count, m, method=_decode_forward
            )  # (b*k, V)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            if rep_penalty != 1.0:
                # HF beam order: processors run on the log-probs
                # (modeling _beam_search: log_softmax then logits_processor)
                logp = apply_repetition_penalty(
                    logp, window, rep_penalty, _pad_positions(pad_count, n)
                )
            if eos is not None:
                logp = apply_min_new_tokens(logp, t, min_new, eos)
            scores = (beam_scores.reshape(b * k, 1) + logp).reshape(b, k * vocab)

            # Top-2k candidates (sorted descending, as HF), then the first k
            # non-EOS candidates continue as live beams.
            cand_scores, cand_idx = jax.lax.top_k(scores, 2 * k)
            cand_beam = cand_idx // vocab  # (b, 2k)
            cand_tok = (cand_idx % vocab).astype(jnp.int32)

            if eos is not None:
                is_eos = cand_tok == eos
                # EOS candidates ranked among the first k enter the hypothesis
                # buffer, length-normalized at insertion (HF BeamHypotheses.add:
                # keep the k best, displacing the worst). Up to k candidates can
                # finish in one step — statically unrolled best-first inserts.
                in_first_k = jnp.arange(2 * k)[None, :] < k
                hyp_cand_score = jnp.where(
                    is_eos & in_first_k,
                    cand_scores / ((t + 1.0) ** length_penalty),
                    -jnp.inf,
                )
                for _ in range(k):
                    best_e = jnp.argmax(hyp_cand_score, axis=1)  # (b,)
                    best_score = jnp.take_along_axis(
                        hyp_cand_score, best_e[:, None], 1
                    )[:, 0]
                    src_beam = jnp.take_along_axis(cand_beam, best_e[:, None], 1)[:, 0]
                    hist = tok_buf[rows[:, 0], src_beam]  # (b, t_max)
                    hist = jnp.where(jnp.arange(t_max)[None, :] == t, eos, hist)
                    worst = jnp.argmin(hyp_scores, axis=1)  # (b,)
                    worst_score = jnp.take_along_axis(hyp_scores, worst[:, None], 1)[:, 0]
                    replace = best_score > worst_score
                    hyp_scores = hyp_scores.at[rows[:, 0], worst].set(
                        jnp.where(replace, best_score, worst_score)
                    )
                    old_rows = hyp_tokens[rows[:, 0], worst]
                    hyp_tokens = hyp_tokens.at[rows[:, 0], worst].set(
                        jnp.where(replace[:, None], hist, old_rows)
                    )
                    # consume this candidate
                    hyp_cand_score = hyp_cand_score.at[rows[:, 0], best_e].set(-jnp.inf)
                # Live beams: first k non-EOS candidates, in candidate order
                # (stable sort on the EOS flag preserves score order).
                order = jnp.argsort(is_eos.astype(jnp.int32), axis=1, stable=True)
                live = order[:, :k]
            else:
                live = jnp.broadcast_to(jnp.arange(k)[None, :], (b, k))

            new_scores = jnp.take_along_axis(cand_scores, live, 1)  # (b, k)
            new_beam = jnp.take_along_axis(cand_beam, live, 1)
            new_tok = jnp.take_along_axis(cand_tok, live, 1)

            # Reindex beam state, then advance the windows with the new tokens.
            window = window.reshape(b, k, n)[rows, new_beam].reshape(b * k, n)
            pad_count = pad_count.reshape(b, k)[rows, new_beam].reshape(b * k)
            tok_buf = tok_buf[rows, new_beam]
            tok_buf = jnp.where(
                (jnp.arange(t_max) == t)[None, None, :], new_tok[..., None], tok_buf
            )
            window = jnp.concatenate(
                [window[:, 1:], new_tok.reshape(b * k, 1).astype(window.dtype)], axis=1
            )
            pad_count = jnp.maximum(pad_count - 1, 0)
            m = jnp.minimum(m + 1, max_latents)

            carry = (window, pad_count, m, new_scores, tok_buf, hyp_scores, hyp_tokens)
            return carry, None

        # pad-filled, not zeros: a finished hypothesis's history is copied
        # into the pool wholesale, so post-EOS slots must already hold pad.
        tok_buf = jnp.full((b, k, t_max), config.pad_token_id, jnp.int32)
        hyp_scores = jnp.full((b, k), -jnp.inf, jnp.float32)
        hyp_tokens = jnp.full((b, k, t_max), config.pad_token_id, jnp.int32)
        carry = (
            window,
            pad_count,
            jnp.asarray(num_latents, jnp.int32),
            beam_scores,
            tok_buf,
            hyp_scores,
            hyp_tokens,
        )
        carry, _ = jax.lax.scan(step, carry, jnp.arange(t_max))
        _, _, _, beam_scores, tok_buf, hyp_scores, hyp_tokens = carry

        # Finalize (HF with early_stopping=False at max length): live beams join
        # the hypothesis pool, length-normalized at generated length.
        live_final = beam_scores / (float(t_max) ** length_penalty)
        all_scores = jnp.concatenate([hyp_scores, live_final], axis=1)  # (b, 2k)
        all_tokens = jnp.concatenate([hyp_tokens, tok_buf], axis=1)  # (b, 2k, t_max)
        best = jnp.argmax(all_scores, axis=1)
        return all_tokens[jnp.arange(b), best].astype(input_ids.dtype)

    return jax.jit(run)
