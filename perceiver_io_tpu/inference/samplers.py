"""Logit samplers: greedy, temperature, top-k, nucleus (top-p) — the sampling
modes the reference exercises through HF ``GenerationMixin`` (reference
``tests/causal_language_model_pipeline_test.py:17-48``), as pure jittable
functions.

Filters compose in HF's order: temperature → top-k → top-p.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = float(jnp.finfo(jnp.float32).min)


@dataclass(frozen=True)
class SamplingConfig:
    do_sample: bool = False
    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    #: HF ``RepetitionPenaltyLogitsProcessor``: tokens already in the context
    #: get ``score/p`` (if positive) or ``score*p`` (if negative). 1.0 = off.
    repetition_penalty: float = 1.0


def apply_min_new_tokens(
    logits: jnp.ndarray, t: jnp.ndarray, min_new: int, eos_token_id: int
) -> jnp.ndarray:
    """HF ``MinNewTokensLengthLogitsProcessor``: EOS is unreachable until
    ``min_new`` tokens have been generated. ``t`` is the 0-based global
    generation step. No-op when ``min_new <= 0`` (static)."""
    if min_new <= 0:
        return logits
    vocab = logits.shape[-1]
    blocked = (t < min_new) & (jnp.arange(vocab) == eos_token_id)[None, :]
    return jnp.where(blocked, -jnp.inf, logits)


def apply_repetition_penalty(
    logits: jnp.ndarray,
    context_ids: jnp.ndarray,
    penalty: float,
    context_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """HF ``RepetitionPenaltyLogitsProcessor`` semantics: for every token id
    present in ``context_ids``, divide its (positive) logit by ``penalty`` or
    multiply a negative logit by it.

    :param logits: ``(b, vocab)``.
    :param context_ids: ``(b, n)`` token history (e.g. the decode window).
    :param context_mask: optional ``(b, n)`` True = IGNORE this position
        (padding slots must not penalize the pad token id).
    """
    b, vocab = logits.shape
    ids = context_ids
    if context_mask is not None:
        ids = jnp.where(context_mask, vocab, ids)  # out-of-range → dropped
    seen = jnp.zeros((b, vocab + 1), bool).at[jnp.arange(b)[:, None], ids].set(True)
    seen = seen[:, :vocab]
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Keep the k highest logits per row, mask the rest to -inf. ``k`` is
    clamped to the vocabulary size (HF GenerationMixin behavior)."""
    k = min(k, logits.shape[-1])
    kth = jnp.sort(logits, axis=-1)[..., -k : -k + 1] if k > 1 else jnp.max(
        logits, axis=-1, keepdims=True
    )
    return jnp.where(logits < kth, NEG_INF, logits)


def apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest set of tokens with cumulative
    probability ≥ p (the most-probable token always survives)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # mask tokens whose *preceding* cumulative mass already reached p; the
    # argmax always survives (even for p=0, matching HF's min-one-token rule)
    sorted_keep = ((cum - probs) < p).at[..., 0].set(True)
    # threshold logit = smallest kept logit
    kth = jnp.min(jnp.where(sorted_keep, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < kth, NEG_INF, logits)


def sample_logits(
    rng: jax.Array, logits: jnp.ndarray, config: SamplingConfig,
    context_ids: Optional[jnp.ndarray] = None,
    context_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """:param logits: ``(b, vocab)`` next-token logits.
    :param context_ids: ``(b, n)`` token history for the repetition penalty
        (ignored when ``config.repetition_penalty == 1.0``).
    :param context_mask: ``(b, n)`` True = ignore this history position.
    :return: ``(b,)`` int32 sampled token ids."""
    logits = logits.astype(jnp.float32)
    if config.repetition_penalty != 1.0 and context_ids is not None:
        # processors run before the greedy argmax too (HF order)
        logits = apply_repetition_penalty(
            logits, context_ids, config.repetition_penalty, context_mask
        )
    if not config.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if config.temperature != 1.0:
        logits = logits / config.temperature
    if config.top_k is not None and config.top_k > 0:
        logits = apply_top_k(logits, config.top_k)
    if config.top_p is not None and config.top_p < 1.0:
        logits = apply_top_p(logits, config.top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
