"""Logit samplers: greedy, temperature, top-k, nucleus (top-p) — the sampling
modes the reference exercises through HF ``GenerationMixin`` (reference
``tests/causal_language_model_pipeline_test.py:17-48``), as pure jittable
functions.

Filters compose in HF's order: temperature → top-k → top-p.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = float(jnp.finfo(jnp.float32).min)


@dataclass(frozen=True)
class SamplingConfig:
    do_sample: bool = False
    temperature: float = 1.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None


def apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Keep the k highest logits per row, mask the rest to -inf. ``k`` is
    clamped to the vocabulary size (HF GenerationMixin behavior)."""
    k = min(k, logits.shape[-1])
    kth = jnp.sort(logits, axis=-1)[..., -k : -k + 1] if k > 1 else jnp.max(
        logits, axis=-1, keepdims=True
    )
    return jnp.where(logits < kth, NEG_INF, logits)


def apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest set of tokens with cumulative
    probability ≥ p (the most-probable token always survives)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # mask tokens whose *preceding* cumulative mass already reached p; the
    # argmax always survives (even for p=0, matching HF's min-one-token rule)
    sorted_keep = ((cum - probs) < p).at[..., 0].set(True)
    # threshold logit = smallest kept logit
    kth = jnp.min(jnp.where(sorted_keep, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < kth, NEG_INF, logits)


def sample_logits(
    rng: jax.Array, logits: jnp.ndarray, config: SamplingConfig
) -> jnp.ndarray:
    """:param logits: ``(b, vocab)`` next-token logits.
    :return: ``(b,)`` int32 sampled token ids."""
    logits = logits.astype(jnp.float32)
    if not config.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if config.temperature != 1.0:
        logits = logits / config.temperature
    if config.top_k is not None and config.top_k > 0:
        logits = apply_top_k(logits, config.top_k)
    if config.top_p is not None and config.top_p < 1.0:
        logits = apply_top_p(logits, config.top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
