"""Inference layer — autoregressive generation as a single compile-once
``lax.scan`` that keeps the whole decode loop on-device (the reference
re-dispatches a Python-driven full forward per token, reference
``perceiver/model/text/clm/huggingface.py:53-74``), plus logit samplers and
MLM mask filling. A cached-decode fast path for the latent-growth phase is
the planned perf-pass follow-up (see ``generate.py`` docstring for why exact
caching interacts with the prefix/latent boundary).
"""
from perceiver_io_tpu.inference.samplers import SamplingConfig, sample_logits
from perceiver_io_tpu.inference.generate import generate
from perceiver_io_tpu.inference.mask_filler import MaskFiller
from perceiver_io_tpu.inference.pipelines import (
    FillMaskPipeline,
    ImageClassificationPipeline,
    OpticalFlowPipeline,
    SymbolicAudioPipeline,
    TextClassificationPipeline,
    TextGenerationPipeline,
    pipeline,
    pipeline_from_pretrained,
)

__all__ = [
    "SamplingConfig",
    "sample_logits",
    "generate",
    "MaskFiller",
    "pipeline",
    "pipeline_from_pretrained",
    "TextGenerationPipeline",
    "FillMaskPipeline",
    "TextClassificationPipeline",
    "ImageClassificationPipeline",
    "OpticalFlowPipeline",
    "SymbolicAudioPipeline",
]
