"""Inference layer — autoregressive generation as a single compile-once
``lax.scan`` that keeps the whole decode loop on-device (the reference
re-dispatches a Python-driven full forward per token, reference
``perceiver/model/text/clm/huggingface.py:53-74``), plus beam search, logit
samplers and MLM mask filling. Cached decode covers the latent-growth phase
incrementally and the prefix-growth phase via a cross-k/v cache with per-step
boundary migration (see ``generate.py`` docstring for the phase analysis).
"""
from perceiver_io_tpu.inference.samplers import SamplingConfig, sample_logits
from perceiver_io_tpu.inference.decode_strategy import (
    DecodeStrategy,
    autotune_boundary,
    resolve_decode_strategy,
)
from perceiver_io_tpu.inference.generate import (
    GenerationConfig,
    executor_cache_stats,
    generate,
    reset_executor_caches,
)
from perceiver_io_tpu.inference.beam import beam_search
from perceiver_io_tpu.inference.mask_filler import MaskFiller
from perceiver_io_tpu.inference.pipelines import (
    FillMaskPipeline,
    ImageClassificationPipeline,
    OpticalFlowPipeline,
    SymbolicAudioPipeline,
    TextClassificationPipeline,
    TextGenerationPipeline,
    pipeline,
    cast_float_params,
    pipeline_from_pretrained,
)

__all__ = [
    "SamplingConfig",
    "sample_logits",
    "generate",
    "GenerationConfig",
    "DecodeStrategy",
    "autotune_boundary",
    "resolve_decode_strategy",
    "executor_cache_stats",
    "reset_executor_caches",
    "beam_search",
    "MaskFiller",
    "pipeline",
    "cast_float_params",
    "pipeline_from_pretrained",
    "TextGenerationPipeline",
    "FillMaskPipeline",
    "TextClassificationPipeline",
    "ImageClassificationPipeline",
    "OpticalFlowPipeline",
    "SymbolicAudioPipeline",
]
