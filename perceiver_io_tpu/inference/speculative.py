"""Self-draft speculative decoding — k tokens per verified forward.

The window-phase decode step (``generate.py`` phase 3, and the slot
engine's recompute executors) pays one full-model forward per emitted
token. This module trades that for a **draft/verify** round
(PAPERS.md: speculative sampling; docs/serving.md "Speculative
decoding"):

- **Draft** (:func:`propose_tokens`): ``k`` candidate tokens from a
  *truncated* latent stack — only the first ``draft_layers`` of the
  model's self-attention layers run, on the full model's own parameters.
  No second checkpoint, no distilled head: the Perceiver AR stack is the
  draft model's prefix, so drafting costs roughly
  ``draft_layers / num_layers`` of a step plus the (shared) cross-attend.
- **Verify** (:func:`verify_lanes`): ONE batched full-model forward
  scores all ``k + 1`` positions. Each of the ``k + 1`` *lanes* is
  exactly the right-aligned window the non-speculative engine would have
  seen after emitting the first ``j + 1`` candidates — same shift, same
  pad clamp, same per-row latent count — stacked along the batch axis
  into a single ``_decode_forward`` call. Exactness is by construction,
  not by approximation: lane ``j``'s logits are bitwise the logits the
  plain step would have produced, in *every* window regime (latent
  growth, the ``m == max_latents`` boundary, mid-burst boundary
  crossings, sliding window).
- **Accept** (:func:`accept_prefix`): the longest prefix of drafted
  tokens matching the verified greedy argmax is emitted —
  ``n_e ∈ [1, k+1]`` tokens per round (the verified position after the
  last match always emits, so a round never stalls). Greedy output is
  therefore **token-identical** to the non-speculative step; speculation
  only changes how many forwards buy those tokens.

Greedy-only: acceptance compares argmaxes, so sampling
(``do_sample=True``) or a non-unit repetition penalty (applied before
argmax in ``sample_logits``) would break the identity — both are
rejected loudly at validation time, never silently ignored.

Whether a round PAYS is ``acceptance × k`` against ``k`` extra drafts +
lane-widened verify — a platform/shape property measured by
``decode_strategy.autotune_speculation`` and persisted beside the
cached-vs-recompute, KV-layout, and prefix-cache axes.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.inference.generate import GenerationConfig, _decode_forward
from perceiver_io_tpu.inference.samplers import apply_min_new_tokens
from perceiver_io_tpu.ops.position import RotaryEmbedding, positions

_MODE_RE = re.compile(r"k(\d+)d(\d+)")


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Static speculation geometry: ``k`` drafted tokens per round from a
    ``draft_layers``-deep truncated stack. Both are compile-time constants
    (the round's shapes depend on them), so they ride in executor cache
    keys, never in traced state."""

    k: int
    draft_layers: int

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"speculation k must be >= 1, got {self.k}")
        if self.draft_layers < 1:
            raise ValueError(
                f"draft_layers must be >= 1, got {self.draft_layers}"
            )

    @property
    def mode(self) -> str:
        return f"k{self.k}d{self.draft_layers}"


def parse_speculation(mode: Optional[str]) -> Optional[SpecConfig]:
    """``"off"``/None -> None; ``"k4d1"`` -> :class:`SpecConfig`(4, 1)."""
    if mode is None or mode == "off":
        return None
    match = _MODE_RE.fullmatch(mode)
    if match is None:
        raise ValueError(
            f"speculation mode must be 'off' or 'k<K>d<D>', got {mode!r}"
        )
    return SpecConfig(int(match.group(1)), int(match.group(2)))


def validate_spec(spec: SpecConfig, model, config: GenerationConfig) -> None:
    """Reject geometries/configs where the token-identity guarantee cannot
    hold — loudly, at build time (a silent fallback would let an operator
    believe they are measuring speculation when they are not)."""
    num_layers = int(model.config.num_self_attention_layers)
    if spec.draft_layers > num_layers:
        raise ValueError(
            f"draft_layers={spec.draft_layers} exceeds the model's "
            f"{num_layers}-layer stack; the draft must be a truncation"
        )
    if config.num_beams > 1:
        raise ValueError("speculation is greedy-only; num_beams must be 1")
    if config.sampling.do_sample:
        raise ValueError(
            "speculation is greedy-only: acceptance compares argmaxes, so "
            "do_sample=True cannot be token-identical — disable one of them"
        )
    if float(config.sampling.repetition_penalty) != 1.0:
        raise ValueError(
            "speculation requires repetition_penalty == 1.0: the greedy "
            "sampler applies the penalty before argmax, which the verify "
            "lanes do not model"
        )


def draft_forward(mdl, window, pad_count, m, draft_layers: int):
    """Truncated-stack forward: the :func:`~perceiver_io_tpu.inference.
    generate._decode_forward` prologue (embedding, boundary-normalized
    cross-attention) followed by only the first ``draft_layers``
    self-attention layers (first-layer-rotary semantics preserved, same
    manual loop as ``_latent_stack_capture``), then the output head.

    With ``draft_layers == num_self_attention_layers`` this IS the full
    forward (the probe benches rely on that: acceptance is exactly 1.0);
    shallower drafts trade acceptance for per-draft cost.
    """
    ar = mdl.perceiver_ar
    b, n = window.shape
    num_latents = mdl.max_latents

    pad_mask = jnp.arange(n)[None, :] < pad_count[:, None]
    abs_pos = positions(b, n, shift=pad_count[:, None])
    emb, frq = ar.input_adapter(window, abs_pos=abs_pos)

    layer = ar.cross_attention
    ca = layer.cross_attn
    mha = ca.attention
    m = jnp.asarray(m)
    m_col = m[:, None] if m.ndim else m
    is_latent = (jnp.arange(n) >= n - num_latents)[None, :] & (
        jnp.arange(n)[None, :] >= n - m_col
    )
    x_q_all = ca.q_norm(emb)
    x_kv = jnp.where(is_latent[..., None], x_q_all, ca.kv_norm(emb))
    x_q = x_q_all[:, -num_latents:]
    q = mha.project_q(x_q, RotaryEmbedding(frq, right_align=True))
    k, v = mha.project_kv(x_kv, RotaryEmbedding(frq, right_align=True))
    attn = mha.attend(q, k, v, pad_mask=pad_mask, deterministic=True)
    x = attn + emb[:, -num_latents:]
    x = layer.mlp(x) + x

    stack_pad = jnp.broadcast_to(
        jnp.arange(num_latents)[None, :] < num_latents - m_col, (b, num_latents)
    )
    rot_latent = RotaryEmbedding(frq[:, -num_latents:], right_align=True)
    for i, sa_layer in enumerate(ar.self_attention.layers[:draft_layers]):
        sa = sa_layer.self_attn
        r = rot_latent if (i == 0 or ar.self_attention.rotary_all_layers) else None
        normed = sa.norm(x)
        q_s = sa.attention.project_q(normed, r)
        k_s, v_s = sa.attention.project_kv(normed, r)
        attn = sa.attention.attend(
            q_s, k_s, v_s, pad_mask=stack_pad, deterministic=True
        )
        x = attn + x
        x = sa_layer.mlp(x) + x

    x_last = x[:, -1]
    if mdl.config.output_norm:
        x_last = mdl.out_norm(x_last)
    return mdl.output_adapter(x_last[:, None], ar.input_adapter.embeddings)[:, 0]


def propose_tokens(
    mdl, window, pad_count, m, steps, logits,
    k: int, draft_layers: int, min_new: int, eos_token_id: int,
):
    """Draft phase: ``(b, k+1)`` candidates. ``cand[:, 0]`` is *exact* — the
    greedy token of the already-verified ``logits`` (the same min-new-EOS
    suppression and float32 argmax the plain step applies). ``cand[:, 1:]``
    come from ``k`` truncated-stack steps, each advancing the window one
    shift as the real step would, so drafted positions see the geometry
    (pad clamp, latent growth) verification will re-check."""
    num_latents = mdl.max_latents
    tok = jnp.argmax(
        apply_min_new_tokens(
            logits.astype(jnp.float32), steps[:, None], min_new, eos_token_id
        ),
        axis=-1,
    ).astype(window.dtype)
    cand = [tok]
    w, p, mm, st = window, pad_count, m, steps
    for _ in range(k):
        w = jnp.concatenate([w[:, 1:], tok[:, None]], axis=1)
        p = jnp.maximum(p - 1, 0)
        mm = jnp.minimum(mm + 1, num_latents)
        st = st + 1
        dlogits = draft_forward(mdl, w, p, mm, draft_layers).astype(jnp.float32)
        tok = jnp.argmax(
            apply_min_new_tokens(dlogits, st[:, None], min_new, eos_token_id),
            axis=-1,
        ).astype(window.dtype)
        cand.append(tok)
    return jnp.stack(cand, axis=1)


def verify_lanes(mdl, window, pad_count, m, cand):
    """Verify phase: ONE full-model forward over the ``k+1`` lanes.

    Lane ``j`` (``j ∈ [0, k]``) reconstructs the exact state the plain
    engine would hold after emitting ``cand[:, :j+1]``: window
    ``ext[:, j+1 : j+1+n]`` (``ext`` = window ‖ candidates), pad
    ``max(pad - (j+1), 0)``, latent count ``min(m + j + 1, max_latents)``.
    Lanes stack along batch into a ``(b·(k+1), n)`` call — a single
    fixed-shape dispatch whose row ``b·j`` logits are bitwise what the
    ``j``-th sequential step would have produced, in every phase regime.

    :return: ``(b, k+1, vocab)`` lane logits (raw model dtype).
    """
    b, n = window.shape
    k1 = cand.shape[1]
    num_latents = mdl.max_latents

    ext = jnp.concatenate([window, cand.astype(window.dtype)], axis=1)
    lanes = jnp.stack([ext[:, j + 1 : j + 1 + n] for j in range(k1)], axis=1)
    offs = jnp.arange(1, k1 + 1, dtype=jnp.int32)
    lane_pad = jnp.maximum(pad_count[:, None] - offs[None, :], 0)
    m_b = jnp.broadcast_to(jnp.asarray(m), (b,))
    lane_m = jnp.minimum(m_b[:, None] + offs[None, :], num_latents)
    lane_logits = _decode_forward(
        mdl,
        lanes.reshape(b * k1, n),
        lane_pad.reshape(b * k1).astype(pad_count.dtype),
        lane_m.reshape(b * k1),
    )
    return lane_logits.reshape(b, k1, -1)


def accept_prefix(lane_logits, cand, steps, min_new: int, eos_token_id: int):
    """Accept phase (pure jnp, shared by the engine executor and the
    standalone loop): longest matching drafted prefix + the logits that
    seed the next round.

    ``cand[:, j+1]`` is accepted iff it equals the verified greedy token of
    lane ``j`` (float32, min-new suppression at the step count the plain
    engine would have used) *and* every earlier draft matched — the
    cumulative product. ``n_e = 1 + accepted ∈ [1, k+1]``; the next-round
    logits are lane ``n_e - 1``'s, raw (suppression is re-applied at
    sampling time, exactly like the plain step's stored logits).

    :return: ``(n_e (b,) int32, next_logits (b, vocab))``
    """
    b, k1, vocab = lane_logits.shape
    k = k1 - 1
    if k > 0:
        st = steps[:, None] + jnp.arange(1, k + 1, dtype=jnp.int32)[None, :]
        masked = apply_min_new_tokens(
            lane_logits[:, :k].astype(jnp.float32).reshape(b * k, vocab),
            st.reshape(b * k, 1),
            min_new,
            eos_token_id,
        )
        pred = jnp.argmax(masked, axis=-1).reshape(b, k).astype(cand.dtype)
        match = (cand[:, 1:] == pred).astype(jnp.int32)
        n_e = 1 + jnp.sum(jnp.cumprod(match, axis=1), axis=1)
    else:
        n_e = jnp.ones((b,), jnp.int32)
    next_logits = jnp.take_along_axis(
        lane_logits, (n_e - 1)[:, None, None], axis=1
    )[:, 0]
    return n_e.astype(jnp.int32), next_logits


def advance_window(window, pad_count, m, cand, n_e, num_latents: int):
    """Advance the right-aligned window state past ``n_e`` accepted tokens —
    the burst form of the plain step's shift-by-one: new window
    ``ext[:, n_e : n_e+n]`` per row, pad/latent clamps applied exactly as
    ``n_e`` sequential steps would have.

    :return: ``(window, pad_count, m)`` advanced.
    """
    n = window.shape[1]
    ext = jnp.concatenate([window, cand.astype(window.dtype)], axis=1)
    idx = n_e[:, None] + jnp.arange(n)[None, :]
    new_window = jnp.take_along_axis(ext, idx, axis=1)
    new_pad = jnp.maximum(pad_count - n_e, 0)
    new_m = jnp.minimum(jnp.asarray(m) + n_e, num_latents)
    return new_window, new_pad, new_m


def speculative_generate(
    model,
    params,
    input_ids: jnp.ndarray,
    config: GenerationConfig,
    spec: SpecConfig,
    *,
    prompt_pad_count: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Greedy generation through draft/verify rounds — the standalone
    (engine-free) loop, token-identical to :func:`~perceiver_io_tpu.
    inference.generate.generate` by the lane construction.

    Host-looped (one jitted round function, reused across rounds) rather
    than scanned: ``n_e`` is data-dependent, and the host owns EOS/
    ``max_new_tokens`` truncation mid-burst just as the slot engine does.

    :return: ``(b, max_new_tokens)`` generated ids (pad after EOS) — the
        same contract as ``generate()``.
    """
    validate_spec(spec, model, config)
    b, prompt_len = input_ids.shape
    n = model.max_seq_len
    max_latents = model.max_latents
    if not 0 < prompt_len <= n:
        raise ValueError(f"prompt length out of valid range [1..{n}]")
    num_latents = min(prompt_len, config.num_latents)
    if prompt_pad_count is None:
        prompt_pad_count = jnp.zeros((b,), jnp.int32)

    min_new = config.min_new_tokens if config.eos_token_id is not None else 0
    eos = config.eos_token_id if config.eos_token_id is not None else 0
    window = jnp.concatenate(
        [
            jnp.full((b, n - prompt_len), config.pad_token_id, input_ids.dtype),
            input_ids,
        ],
        axis=1,
    ) if prompt_len < n else input_ids
    pad = jnp.asarray(n - prompt_len + prompt_pad_count, jnp.int32)
    m = jnp.full((b,), num_latents, jnp.int32)
    steps = jnp.zeros((b,), jnp.int32)

    def prefill(p, w, pc, mm):
        return model.apply({"params": p}, w, pc, mm, method=_decode_forward)

    def round_fn(p, w, pc, mm, st, lo):
        cand = model.apply(
            {"params": p}, w, pc, mm, st, lo,
            spec.k, spec.draft_layers, min_new, eos,
            method=propose_tokens,
        )
        lane_logits = model.apply(
            {"params": p}, w, pc, mm, cand, method=verify_lanes
        )
        n_e, next_logits = accept_prefix(lane_logits, cand, st, min_new, eos)
        new_w, new_pc, new_mm = advance_window(w, pc, mm, cand, n_e, max_latents)
        return cand, n_e, new_w, new_pc, new_mm, st + n_e, next_logits

    prefill_jit = jax.jit(prefill)
    round_jit = jax.jit(round_fn)

    logits = prefill_jit(params, window, pad, m)
    emitted = [[] for _ in range(b)]
    done = [False] * b
    while not all(done):
        cand, n_e, window, pad, m, steps, logits = round_jit(
            params, window, pad, m, steps, logits
        )
        cand_np = np.asarray(jax.device_get(cand))
        n_e_np = np.asarray(jax.device_get(n_e))
        for row in range(b):
            if done[row]:
                continue
            for j in range(int(n_e_np[row])):
                token = int(cand_np[row, j])
                emitted[row].append(token)
                if (
                    config.eos_token_id is not None
                    and token == config.eos_token_id
                ) or len(emitted[row]) >= config.max_new_tokens:
                    done[row] = True
                    break

    out = np.full((b, config.max_new_tokens), config.pad_token_id, np.int32)
    for row in range(b):
        out[row, : len(emitted[row])] = emitted[row]
    return jnp.asarray(out)
