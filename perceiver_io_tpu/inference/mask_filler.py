"""Top-k mask filling for masked language models (reference
``perceiver/model/text/mlm/utils.py:4-27``): replace every ``<mask>`` token
with its k-th most likely prediction and decode, yielding k filled variants
per input text.
"""
from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class MaskFiller:
    """:param preprocessor: a text preprocessor exposing ``tokenizer`` and
    ``preprocess_batch(texts) -> (input_ids, pad_mask)`` (NumPy/JAX arrays),
    e.g. :class:`perceiver_io_tpu.data.text.TextPreprocessor`."""

    def __init__(self, preprocessor):
        self.preprocessor = preprocessor
        self._jit_apply = None  # cached per model instance
        self._jit_model = None

    def fill(
        self,
        model,
        params,
        masked_text_batch: Sequence[str],
        num_predictions: int,
    ) -> Tuple[List[str], List[List[str]]]:
        tokenizer = self.preprocessor.tokenizer
        masked_text_batch = [
            ms.replace("<mask>", tokenizer.mask_token) for ms in masked_text_batch
        ]
        xs, pad_mask = self.preprocessor.preprocess_batch(masked_text_batch)
        xs = np.asarray(xs)

        if self._jit_apply is None or self._jit_model is not model:
            self._jit_apply = jax.jit(
                lambda p, x, m: model.apply({"params": p}, x, pad_mask=m)
            )
            self._jit_model = model
        logits = self._jit_apply(params, jnp.asarray(xs), jnp.asarray(pad_mask))

        pred_mask = xs == tokenizer.mask_token_id
        masked_logits = np.asarray(logits)[pred_mask, :]
        # top-k prediction ids per masked position, most likely first
        pred_ids = np.argsort(-masked_logits, axis=-1)[:, :num_predictions]

        results = []
        filled = xs.copy()
        for i in range(num_predictions):
            filled[pred_mask] = pred_ids[:, i]
            results.append(tokenizer.batch_decode(filled, skip_special_tokens=True))

        return masked_text_batch, list(map(list, zip(*results)))
