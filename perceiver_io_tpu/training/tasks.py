"""Task step functions — the training semantics of the reference's Lightning
wrappers, as pure ``(params, batch, rng) -> (loss, metrics)`` functions for
:func:`perceiver_io_tpu.parallel.make_train_step`.

Batches are dicts with the reference's collator fields (``labels``,
``input_ids``, ``pad_mask``; reference ``perceiver/data/text/collator.py:16-22``
uses a tuple — a dict is the pytree-friendly equivalent).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100  # torch cross_entropy ignore_index, used throughout the reference


def masked_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Token-mean CE ignoring ``IGNORE_INDEX`` labels — semantics of torch
    ``F.cross_entropy(logits, labels)`` with default mean reduction."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    valid = labels != IGNORE_INDEX
    safe = jnp.where(valid, labels, 0)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    return nll.sum() / jnp.maximum(1, valid.sum())


def _rngs(rng) -> Optional[dict]:
    if rng is None:
        return None
    d, p = jax.random.split(rng)
    return {"dropout": d, "prefix": p}


def clm_loss_fn(model, max_latents: int) -> Callable:
    """Perceiver AR causal-LM step: ``prefix_len = seq_len - max_latents``,
    pad labels ignored, loss on the last ``max_latents`` positions only
    (reference ``perceiver/model/text/clm/lightning.py:86-102``)."""

    def loss_fn(params, batch, rng):
        input_ids = batch["input_ids"]
        labels = batch["labels"]
        pad_mask = batch.get("pad_mask")
        prefix_len = input_ids.shape[1] - max_latents
        if pad_mask is not None:
            labels = jnp.where(pad_mask, IGNORE_INDEX, labels)
        logits = model.apply(
            {"params": params},
            input_ids,
            prefix_len,
            pad_mask=pad_mask,
            deterministic=rng is None,
            rngs=_rngs(rng),
        )
        loss = masked_cross_entropy(logits, labels[:, prefix_len:])
        return loss, {}

    return loss_fn


def mlm_loss_fn(model) -> Callable:
    """Masked-LM step: CE over all positions, unmasked labels = -100
    (reference ``perceiver/model/text/mlm/lightning.py:57-62``)."""

    def loss_fn(params, batch, rng):
        logits = model.apply(
            {"params": params},
            batch["input_ids"],
            pad_mask=batch.get("pad_mask"),
            deterministic=rng is None,
            rngs=_rngs(rng),
        )
        loss = masked_cross_entropy(logits, batch["labels"])
        return loss, {}

    return loss_fn


def image_classifier_loss_fn(model) -> Callable:
    """Image classifier step over ``{"image", "label"}`` batches (the vision
    datamodule contract; reference ``image_classifier/lightning.py:12-41``)."""

    def loss_fn(params, batch, rng):
        logits = model.apply(
            {"params": params}, batch["image"], deterministic=rng is None
        )
        labels = batch["label"]
        loss = masked_cross_entropy(logits, labels)
        acc = jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
        return loss, {"accuracy": acc}

    return loss_fn


def classifier_loss_fn(model) -> Callable:
    """Classifier step: CE + accuracy (reference
    ``perceiver/model/core/lightning.py:50-76``; accuracy reduction across
    devices comes from sharding, the ``sync_dist=True`` equivalent)."""

    def loss_fn(params, batch, rng):
        logits = model.apply(
            {"params": params},
            batch["input_ids"],
            pad_mask=batch.get("pad_mask"),
            deterministic=rng is None,
            rngs=_rngs(rng),
        )
        labels = batch["labels"]
        loss = masked_cross_entropy(logits, labels)
        acc = jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
        return loss, {"accuracy": acc}

    return loss_fn
