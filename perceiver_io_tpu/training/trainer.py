"""The trainer loop — Lightning-free equivalent of the reference's
``Trainer.fit(model, datamodule)`` flow (reference
``perceiver/scripts/cli.py``, ``perceiver/model/core/lightning.py``):

step-based training with periodic validation, best-``val_loss`` orbax
checkpointing, learning-rate + loss logging (TensorBoard when torch is
importable, JSONL always), and rank-0 end-of-validation callbacks (the
qualitative text-sampling hooks, reference ``clm/lightning.py:113-151``).

The loop body is host-side Python; every numeric step is one jitted SPMD
call. Metrics are device scalars fetched once per log interval so logging
never stalls the device queue (Lightning's ``sync_dist=True`` reduction is
implicit: metric arrays are replicated outputs of the sharded step).
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import sys
import time
import traceback
from collections import deque
from typing import Any, Callable, Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from perceiver_io_tpu.observability import MetricsRegistry

from perceiver_io_tpu.parallel import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_train_step,
    shard_or_assemble,
)
from perceiver_io_tpu.training.checkpoint import (
    BestCheckpointManager,
    ResumeCheckpointManager,
)


@dataclasses.dataclass
class TrainerConfig:
    """Trainer hyperparameters (the ``--trainer.*`` surface of the reference
    CLI, reference ``perceiver/scripts/trainer.yaml``)."""

    max_steps: int
    val_check_interval: int = 1000
    log_every_n_steps: int = 50
    limit_val_batches: Optional[int] = None
    limit_test_batches: Optional[int] = None
    default_root_dir: str = "logs"
    max_checkpoints: int = 1
    grad_clip_norm: Optional[float] = None
    #: split each batch into N microbatches and average their gradients
    #: inside the jitted step. NOTE: unlike Lightning's
    #: ``accumulate_grad_batches`` (which multiplies the loader batch), this
    #: DIVIDES the given batch — pass the full effective batch size and use
    #: this knob to bound activation memory per microbatch
    grad_accum_steps: int = 1
    #: run N optimizer steps per device program (``lax.scan`` over stacked
    #: batches) — amortizes host dispatch latency; steps that need host-side
    #: work (validation, snapshots, profiling) automatically run singly.
    #: Trades preemption-response latency (≤ N steps) for throughput.
    steps_per_execution: int = 1
    seed: int = 0
    enable_checkpointing: bool = True
    enable_tensorboard: bool = True
    #: shard the sequence dim of batches over the ``seq`` mesh axis
    #: (context parallelism; XLA partitions attention over kv accordingly)
    shard_seq: bool = False
    #: capture a jax.profiler trace of _PROFILE_WINDOW steps starting here
    #: into <default_root_dir>/profile (None disables)
    profile_start: Optional[int] = None
    #: snapshot the full TrainState (step, params, optimizer state) every N
    #: steps into <default_root_dir>/resume for mid-training resume
    save_state_every_n_steps: Optional[int] = None
    #: resume from the latest TrainState snapshot in this directory (a
    #: <root>/resume dir, or a root containing one) — Lightning
    #: ``fit(ckpt_path=...)`` parity; the loss trajectory of a resumed run
    #: matches the uninterrupted run exactly (per-step rng is fold_in-derived
    #: and the data stream is fast-forwarded)
    resume: Optional[str] = None
    #: halt when the train loss goes non-finite — checked at each log flush
    #: and before every TrainState snapshot (a diverged state is never
    #: snapshotted, so existing snapshots stay a finite resume point); the
    #: device queue is never stalled per-step (Lightning ``detect_anomaly``
    #: role). ``False`` disables all non-finite handling (policy ``off``)
    #: unless ``non_finite_policy`` is explicitly skip/rollback.
    terminate_on_non_finite: bool = True
    #: what a non-finite train loss does (docs/reliability.md):
    #: ``halt`` (raise at the log flush — the historical behavior),
    #: ``skip`` (discard that step's update, keep the last-good state, count
    #: it in ``Trainer.fault_stats``), or ``rollback`` (skip, and after
    #: ``non_finite_rollback_after`` consecutive bad steps restore the latest
    #: finite TrainState snapshot and fast-forward the data stream — requires
    #: ``save_state_every_n_steps``). skip/rollback check the loss every step
    #: (one device fetch per step) and force ``steps_per_execution=1``
    #: scheduling, trading dispatch throughput for recoverability. NOTE:
    #: rollback pins roughly ``save_state_every_n_steps +
    #: non_finite_rollback_after`` recent batches in host memory (the
    #: exact-replay buffer) — budget the snapshot cadence accordingly
    #: (e.g. a 5000-step cadence with 2 MB batches pins ~10 GB host RAM).
    non_finite_policy: str = "halt"
    #: K consecutive non-finite steps trigger the policy's escalation:
    #: ``rollback`` restores the latest snapshot, ``skip`` halts (a streak
    #: that long is persistent divergence, not a transient fault)
    non_finite_rollback_after: int = 3
    #: give up (raise) after this many rollbacks in one fit — a persistent
    #: divergence is a hyperparameter problem, not a transient fault
    non_finite_max_rollbacks: int = 3


#: steps traced per jax.profiler capture: [profile_start, profile_start + _PROFILE_WINDOW)
_PROFILE_WINDOW = 3


def _check_uniform_block(block, k_exec: int) -> None:
    """Fused multi-step blocks np.stack ``k_exec`` batches — a user-supplied
    iterable yielding ragged batches would otherwise die in an opaque
    broadcast error deep inside tree_map. Built-in loaders use
    ``drop_last=True``; arbitrary ``fit()`` iterables must match it."""
    ref = block[0]
    ref_structure = jax.tree_util.tree_structure(ref)
    ref_shapes = [np.shape(leaf) for leaf in jax.tree_util.tree_leaves(ref)]
    for i, b in enumerate(block[1:], 1):
        structure = jax.tree_util.tree_structure(b)
        shapes = [np.shape(leaf) for leaf in jax.tree_util.tree_leaves(b)]
        if structure != ref_structure or shapes != ref_shapes:
            raise ValueError(
                f"steps_per_execution={k_exec} requires fixed-shape batches, "
                f"but batch {i} of the block has leaves {shapes} vs the "
                f"block's first batch {ref_shapes} — use a loader that drops "
                "or pads the last partial batch (built-in loaders use "
                "drop_last=True)"
            )


@jax.jit
def _params_finite(params) -> jnp.ndarray:
    """Device-side all-finite reduction over a param tree (one fused pass;
    used to guard TrainState snapshots against persisting diverged state)."""
    leaves = [
        jnp.isfinite(x).all()
        for x in jax.tree_util.tree_leaves(params)
        if jnp.issubdtype(x.dtype, jnp.floating)
    ]
    return jnp.all(jnp.stack(leaves)) if leaves else jnp.asarray(True)


def _effective_non_finite_policy(cfg: TrainerConfig) -> str:
    """halt | skip | rollback | off. ``terminate_on_non_finite=False`` keeps
    its historical meaning (no checks at all) unless the new policy field is
    explicitly set to a recovering mode."""
    if cfg.non_finite_policy not in ("halt", "skip", "rollback"):
        raise ValueError(
            f"non_finite_policy must be halt|skip|rollback, got "
            f"{cfg.non_finite_policy!r}"
        )
    if cfg.non_finite_policy != "halt":
        return cfg.non_finite_policy
    return "halt" if cfg.terminate_on_non_finite else "off"


class _BatchStream:
    """The trainer's seekable view of ``train_data``: cycles on exhaustion
    (rejecting one-shot generators), counts batches handed out
    (``position``, 0-based), fast-forwards to a resume point, and — when a
    replay buffer is enabled — rewinds to a recent position so the rollback
    policy replays the exact batches the rolled-back steps consumed.

    The rewind never touches the underlying iterable: handed-out batches are
    retained in a bounded deque and replayed from memory, after which the
    live iterator resumes exactly where it left off. That keeps rollback
    correct for *any* iterable (lists, loaders, streaming pipelines) at the
    cost of ``replay_buffer`` batches of host memory.
    """

    def __init__(self, data: Iterable, *, replay_buffer: int = 0):
        self._data = data
        self._iter = iter(data)
        self.position = 0  # index of the next batch next() hands out
        self._pulled = 0  # batches pulled off the underlying iterator
        self._buffer: Optional[deque] = (
            deque(maxlen=replay_buffer) if replay_buffer > 0 else None
        )
        self._replay: deque = deque()

    def _pull(self):
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = iter(self._data)
            try:
                return next(self._iter)
            except StopIteration:
                raise ValueError(
                    "train_data is exhausted and not re-iterable "
                    "(one-shot generator?); pass a list or a loader"
                ) from None

    def next(self):
        if self._replay:
            pos, batch = self._replay.popleft()
            self.position = pos + 1
            return batch
        batch = self._pull()
        if self._buffer is not None:
            self._buffer.append((self.position, batch))
        self.position += 1
        self._pulled = self.position
        return batch

    def fast_forward(self, n: int) -> None:
        """Position a FRESH stream so the next batch is batch ``n`` — the
        resume replay. Loaders with a ``skip_batches`` hook jump in O(1);
        anything else is consumed batch by batch."""
        if n <= 0:
            return
        if hasattr(self._data, "skip_batches") and hasattr(self._data, "__len__"):
            self._data.skip_batches(n)
            self._iter = iter(self._data)
            self.position = self._pulled = n
        else:
            for _ in range(n):
                self.next()

    def rewind_to(self, n: int) -> None:
        """Re-position so the next batch handed out is batch ``n`` again,
        replaying retained batches (rollback fast-forward). Everything
        already pulled off the underlying iterator — including batches ahead
        of ``position`` left over from an earlier rewind — must replay from
        the buffer, because the live iterator cannot be stepped back."""
        if n > self.position:
            raise ValueError(f"rewind_to({n}) is ahead of position {self.position}")
        entries = dict(self._buffer or ())
        entries.update(self._replay)
        wanted = sorted((p, b) for p, b in entries.items() if p >= n)
        if [p for p, _ in wanted] != list(range(n, self._pulled)):
            raise RuntimeError(
                f"rollback to batch {n} exceeds the replay buffer (retained "
                f"{[p for p, _ in wanted]}, pulled {self._pulled}); raise the "
                "snapshot cadence coverage or lower non_finite_rollback_after"
            )
        self._replay = deque(wanted)
        self.position = n


class Trainer:
    """Step-based fit/validate driver.

    :param loss_fn: ``(params, batch, rng) -> (loss, metrics)`` (one of
        :mod:`perceiver_io_tpu.training.tasks`).
    :param callbacks: callables ``(trainer, state, step, val_metrics)`` run on
        process 0 after each validation pass. A raising callback is logged
        and counted (``fault_stats["callback_errors"]``), never fatal.
    :param chaos: optional fault-injection registry
        (:class:`~perceiver_io_tpu.reliability.ChaosRegistry`); consulted
        once per optimizer step at the ``trainer.step`` site. None (the
        default) skips the hook entirely.
    :param registry: metrics registry the trainer's counters/histograms live
        on (``trainer_steps_total``, ``trainer_step_ms``, fault counters...);
        defaults to a private one (docs/observability.md).
    :param tracer: optional :class:`~perceiver_io_tpu.observability.Tracer`
        — one trace per ``fit`` with per-step ``trainer.data_wait`` /
        ``trainer.step`` / ``trainer.log_flush`` / ``trainer.checkpoint``
        spans. None skips every span site.
    :param profiler_trigger: optional
        :class:`~perceiver_io_tpu.observability.ProfilerTrigger` — fed each
        single step's host time; when the p95 regresses, the next step runs
        under a ``jax.profiler`` capture.
    :param snapshot_writer: optional
        :class:`~perceiver_io_tpu.observability.SnapshotWriter` — cadence
        checked at every log flush, forced once at ``fit`` exit.
    """

    def __init__(
        self,
        config: TrainerConfig,
        mesh,
        loss_fn: Callable,
        tx: optax.GradientTransformation,
        *,
        model_config: Any = None,
        lr_schedule: Optional[optax.Schedule] = None,
        callbacks: Sequence[Callable] = (),
        chaos=None,
        registry: Optional[MetricsRegistry] = None,
        tracer=None,
        profiler_trigger=None,
        snapshot_writer=None,
    ):
        self.config = config
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.tx = tx
        self.model_config = model_config
        self.lr_schedule = lr_schedule
        self.callbacks = list(callbacks)
        self.state: Optional[TrainState] = None
        self._shardings = None
        self._ckpt: Optional[BestCheckpointManager] = None
        self._eval_step = None
        self._tb = None
        self._metrics_file = None
        self._chaos = chaos
        self._policy = _effective_non_finite_policy(config)
        self.registry = registry if registry is not None else MetricsRegistry()
        self.registry.declare_counters(
            "trainer_steps_total",
            "trainer_skipped_steps_total",
            "trainer_rollbacks_total",
            "trainer_callback_errors_total",
        )
        self._tracer = tracer
        self._fit_trace: Optional[str] = None
        self._profiler_trigger = profiler_trigger
        self._snapshot_writer = snapshot_writer
        #: fault-recovery counters for this trainer's lifetime (kept as a
        #: plain dict for compatibility; each increment is mirrored onto the
        #: registry under ``trainer_*_total``)
        self.fault_stats = {"skipped_steps": 0, "rollbacks": 0, "callback_errors": 0}

        if config.enable_checkpointing:
            # Created on EVERY process: orbax save of multi-host sharded
            # arrays is a collective (each host writes its own shards).
            self._ckpt = BestCheckpointManager(
                os.path.join(config.default_root_dir, "checkpoints"),
                max_to_keep=config.max_checkpoints,
            )
        self._open_writers()

    @property
    def is_main_process(self) -> bool:
        """``rank_zero_only`` parity (reference ``clm/lightning.py:113``)."""
        return jax.process_index() == 0

    def _span(self, name: str, **attrs):
        """A span under this fit's trace, or a no-op when tracing is off —
        the zero-cost-when-unset contract the chaos hooks follow."""
        if self._tracer is None:
            return contextlib.nullcontext()
        return self._tracer.span(name, trace_id=self._fit_trace, **attrs)

    def _count_fault(self, key: str) -> None:
        """Increment one ``fault_stats`` counter and its registry mirror."""
        self.fault_stats[key] += 1
        self.registry.inc(f"trainer_{key}_total")

    def _record_step_time(self, step_ms: float, trigger) -> None:
        """One home for the fenced/dispatch metric-name split and the
        trigger feed — the fused and single-step paths must never diverge
        on it. Without a trigger nothing syncs per step, so the honest
        export name is dispatch time; the fenced name only exists when the
        trigger forced the per-step sync."""
        self.registry.observe(
            "trainer_step_ms" if trigger is not None
            else "trainer_step_dispatch_ms",
            step_ms,
        )
        if trigger is not None:
            trigger.observe(step_ms)

    def _open_writers(self) -> None:
        """(Re)open the rank-0 metrics JSONL + TensorBoard writers — called
        at construction and again by ``fit`` after a previous fit closed
        them (metrics.jsonl is append-mode, so re-fitting appends)."""
        if not self.is_main_process:
            return
        cfg = self.config
        os.makedirs(cfg.default_root_dir, exist_ok=True)
        if self._metrics_file is None:
            self._metrics_file = open(
                os.path.join(cfg.default_root_dir, "metrics.jsonl"), "a"
            )
        if cfg.enable_tensorboard and self._tb is None:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(os.path.join(cfg.default_root_dir, "tb"))
            except Exception:
                self._tb = None

    def _close_writers(self) -> None:
        """Deterministically flush + close metrics.jsonl and the TensorBoard
        writer (idempotent) — ``fit`` calls this on every exit path so a
        crashed run still leaves complete, closed log files."""
        if self._metrics_file is not None:
            self._metrics_file.close()
            self._metrics_file = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    def log_metrics(self, step: int, metrics: dict, prefix: str = "") -> None:
        if not self.is_main_process or self._metrics_file is None:
            return
        scalars = {f"{prefix}{k}": float(v) for k, v in metrics.items()}
        self._metrics_file.write(json.dumps({"step": step, **scalars}) + "\n")
        self._metrics_file.flush()
        if self._tb is not None:
            for k, v in scalars.items():
                self._tb.add_scalar(k, v, step)

    def log_text(self, step: int, tag: str, text: str) -> None:
        """Qualitative text logging (generated samples, filled masks) — the
        reference renders these into TensorBoard text panels.

        Schema: text events are namespaced under one ``"text"`` key
        (``{"step": N, "text": {tag: text}}``) so metrics.jsonl scalar rows
        stay all-float and parsers never type-sniff per value. Old mixed
        files read back through ``observability.read_metrics_jsonl``."""
        if not self.is_main_process or self._metrics_file is None:
            return
        self._metrics_file.write(
            json.dumps({"step": step, "text": {tag: text}}) + "\n"
        )
        self._metrics_file.flush()
        if self._tb is not None:
            self._tb.add_text(tag, text, step)

    def fit(
        self,
        init_params_fn: Callable[[], Any],
        train_data: Iterable,
        val_data: Optional[Callable[[], Iterable]] = None,
        *,
        initial_params: Any = None,
    ) -> TrainState:
        """Run the training loop.

        :param train_data: re-iterable of host batch dicts (e.g. a list or a
            DataModule loader) — cycled when exhausted. One-shot generators
            are rejected on the first wrap-around.
        :param val_data: zero-arg callable returning a fresh validation
            iterable (an epoch) — called at every validation pass.
        :param initial_params: optional pre-built params (warm start) used
            instead of ``init_params_fn``'s fresh init values.
        """
        cfg = self.config

        # Preemption grace: TPU pods get a SIGTERM shortly before the machine
        # is reclaimed. Install the handler BEFORE state setup — the initial
        # compile can take minutes and a preemption during it must not kill
        # the process uncleanly. The loop finishes the in-flight step,
        # snapshots the TrainState, and exits so --resume continues exactly
        # where the preempted run stopped.
        prev_handler = None
        self._preempted = False
        self._open_writers()  # re-fit after a closed fit reopens (append)
        if cfg.save_state_every_n_steps is not None:

            def _on_sigterm(signum, frame):
                self._preempted = True

            try:
                import signal

                prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
            except ValueError:  # not the main thread — no signal hooks
                prev_handler = None
        try:
            return self._fit_inner(
                cfg, init_params_fn, train_data, val_data, initial_params
            )
        finally:
            # deterministic log teardown: metrics.jsonl and the TB writer are
            # complete and closed on every exit path, crash included
            if self._snapshot_writer is not None:
                # never raises: a full disk must not mask the fit's outcome
                self._snapshot_writer.maybe_write(force=True)
            self._close_writers()
            if prev_handler is not None:
                import signal

                signal.signal(signal.SIGTERM, prev_handler)

    def _fit_inner(self, cfg, init_params_fn, train_data, val_data, initial_params):
        if self._policy == "rollback" and cfg.save_state_every_n_steps is None:
            # validate before any compile so the misconfiguration fails in
            # milliseconds, not after state setup
            raise ValueError(
                "non_finite_policy='rollback' requires "
                "save_state_every_n_steps (it restores the latest "
                "TrainState snapshot)"
            )
        self.setup_state(init_params_fn, initial_params=initial_params)
        train_step = make_train_step(
            self.loss_fn,
            self.mesh,
            self._shardings,
            grad_clip_norm=cfg.grad_clip_norm,
            grad_accum_steps=cfg.grad_accum_steps,
            # skip/rollback may hand the PRE-step state back to the loop, so
            # its buffers must survive the step: no donation (the same 2×
            # state memory the discarded update would have freed)
            donate=self._policy not in ("skip", "rollback"),
        )
        rng = jax.random.PRNGKey(cfg.seed)

        # The restore source may be a different run's dir and must not be
        # rotated/pruned by this run's saves — restore first, then open the
        # save manager on <default_root_dir>/resume.
        start_step = 1
        if cfg.resume is not None:
            restore_mgr = ResumeCheckpointManager(
                self._resume_dir(cfg.resume), create=False
            )
            try:
                self.state = restore_mgr.restore_latest(self.state)
            finally:
                restore_mgr.close()
            start_step = int(self.state.step) + 1
            self.log_metrics(start_step - 1, {"resumed_at": start_step - 1})

        resume_mgr: Optional[ResumeCheckpointManager] = None
        if cfg.save_state_every_n_steps is not None:
            resume_mgr = ResumeCheckpointManager(
                os.path.join(cfg.default_root_dir, "resume")
            )
        if self._policy == "rollback":
            stale = resume_mgr.latest_step
            if stale is not None and stale > start_step - 1:
                # snapshots AHEAD of this run's start can only come from a
                # previous run into the same root; restoring one mid-rollback
                # would graft a foreign trajectory onto this run
                raise ValueError(
                    f"{os.path.join(cfg.default_root_dir, 'resume')} holds a "
                    f"step-{stale} snapshot from a previous run (this run "
                    f"starts at step {start_step}); pass resume= to continue "
                    "that run, or point default_root_dir at a fresh directory"
                )
            if stale is None:
                # guarantee a restore point exists even before the first
                # periodic save — a divergence inside the first save window
                # rolls back to the (finite) initial state
                resume_mgr.save(start_step - 1, self.state)

        # rollback replays at most one save window plus the bad streak; keep
        # that many handed-out batches replayable (plus slack for the fused
        # block the streak may start inside)
        replay = 0
        if self._policy == "rollback":
            replay = (
                cfg.save_state_every_n_steps
                + cfg.non_finite_rollback_after
                + cfg.steps_per_execution
                + 1
            )
        stream = _BatchStream(train_data, replay_buffer=replay)

        # Replay the data stream to the resume point so a resumed run sees
        # the same batches the uninterrupted run would (batch n drives step
        # n + 1).
        stream.fast_forward(start_step - 1)

        try:
            self._fit_loop(
                cfg, train_step, rng, stream, val_data, resume_mgr, start_step
            )
        finally:
            # even a crashed step must not leak the snapshot manager (the
            # SIGTERM handler is restored by fit()'s own finally)
            if resume_mgr is not None:
                resume_mgr.close()
        return self.state

    def _block_ok(self, cfg, start: int, k: int, val_data, resume_mgr) -> bool:
        """Whether steps ``[start, start+k-1]`` may run as one device program:
        no step *interior* to the block (the last one is handled after the
        block returns) needs host-side work — validation, state snapshot, or
        the profiler capture window."""
        if start + k - 1 > cfg.max_steps or self._preempted:
            return False
        if self._policy in ("skip", "rollback"):
            # recovering policies check (and may discard) every step singly
            return False
        if self._profiler_trigger is not None and self._profiler_trigger.armed:
            # an armed p95-regression capture traces ONE representative step
            return False
        for idx in range(start, start + k - 1):
            if resume_mgr is not None and idx % cfg.save_state_every_n_steps == 0:
                return False
            if val_data is not None and idx % cfg.val_check_interval == 0:
                return False
        if cfg.profile_start is not None and start + k > cfg.profile_start:
            # singles from just before the capture window until past it
            if start <= cfg.profile_start + _PROFILE_WINDOW - 1:
                return False
        return True

    def _chaos_step_metrics(self, metrics: dict) -> dict:
        """Consult the chaos registry once per optimizer step; a ``nan``
        fault corrupts the reported loss (driving the non-finite policies),
        an ``error`` fault raises at the step boundary."""
        fault = self._chaos.hit("trainer.step")
        if fault is None:
            return metrics
        if fault.kind == "error":
            raise fault.make_error()
        if fault.kind == "nan":
            metrics = dict(metrics)
            metrics["loss"] = float("nan")
        return metrics

    def _rollback(self, cfg, stream, resume_mgr, step_idx: int) -> int:
        """Restore the latest finite TrainState snapshot and rewind the data
        stream to it; returns the step index to resume from. Raises after
        ``non_finite_max_rollbacks`` — persistent divergence is a
        hyperparameter problem, not a transient fault."""
        self._rollbacks_this_fit += 1
        if self._rollbacks_this_fit > cfg.non_finite_max_rollbacks:
            raise FloatingPointError(
                f"train loss stayed non-finite through "
                f"{cfg.non_finite_max_rollbacks} rollbacks (last at step "
                f"{step_idx}); halting — lower the lr / tighten grad clip"
            )
        snap_step = resume_mgr.latest_step
        if snap_step is None or snap_step > stream.position:
            raise RuntimeError(
                f"rollback found no usable snapshot (latest={snap_step}, "
                f"stream position={stream.position}) — the resume dir was "
                "modified mid-run?"
            )
        self.state = resume_mgr.restore_latest(self.state)
        stream.rewind_to(snap_step)
        self._count_fault("rollbacks")
        self.log_metrics(
            step_idx,
            {"rollback_to_step": snap_step, "rollbacks": self.fault_stats["rollbacks"]},
        )
        return snap_step + 1

    def _fit_loop(
        self, cfg, train_step, rng, stream, val_data, resume_mgr, start_step
    ) -> None:
        window: list = []
        profiling = False
        t0 = time.time()
        if self._tracer is not None:
            self._fit_trace = self._tracer.new_trace_id()
        trigger = self._profiler_trigger
        self._bad_streak = 0
        self._rollbacks_this_fit = 0
        snap_after_recovery = False
        k_exec = cfg.steps_per_execution
        multi_step = None
        if k_exec > 1:
            multi_step = make_train_step(
                self.loss_fn,
                self.mesh,
                self._shardings,
                grad_clip_norm=cfg.grad_clip_norm,
                grad_accum_steps=cfg.grad_accum_steps,
                multi_steps=k_exec,
            )
        with self.mesh:
            step_idx = start_step
            while step_idx <= cfg.max_steps:
                if multi_step is not None and self._block_ok(
                    cfg, step_idx, k_exec, val_data, resume_mgr
                ):
                    # one device program for k_exec steps (amortized dispatch)
                    with self._span("trainer.data_wait", step=step_idx, batches=k_exec):
                        block = [stream.next() for _ in range(k_exec)]
                    _check_uniform_block(block, k_exec)
                    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *block)
                    stacked = shard_or_assemble(
                        stacked, self.mesh, shard_seq=cfg.shard_seq, stacked_steps=True
                    )
                    rngs = jnp.stack(
                        [jax.random.fold_in(rng, step_idx + i) for i in range(k_exec)]
                    )
                    block_t0 = time.perf_counter()
                    with self._span(
                        "trainer.step", step=step_idx, fused=k_exec,
                        measures="fenced" if trigger is not None else "dispatch",
                    ):
                        self.state, stacked_metrics = multi_step(self.state, stacked, rngs)
                        if trigger is not None:
                            # the trigger needs real step time, not async
                            # dispatch time — fence the block (its cost is
                            # amortized over k_exec steps)
                            jax.block_until_ready(stacked_metrics["loss"])
                    self.registry.inc("trainer_steps_total", k_exec)
                    self._record_step_time(
                        (time.perf_counter() - block_t0) * 1e3 / k_exec, trigger
                    )
                    per_step = [
                        {k: v[i] for k, v in stacked_metrics.items()}
                        for i in range(k_exec)
                    ]
                    n_ran = k_exec
                else:
                    with self._span("trainer.data_wait", step=step_idx):
                        batch = stream.next()
                    # fold_in (not sequential split): step k's rng is a pure
                    # function of (seed, k), so a resumed run replays the
                    # identical dropout/augmentation stream
                    step_rng = jax.random.fold_in(rng, step_idx)
                    batch = shard_or_assemble(
                        batch, self.mesh, shard_seq=cfg.shard_seq
                    )
                    if cfg.profile_start is not None and step_idx == cfg.profile_start:
                        jax.profiler.start_trace(
                            os.path.join(cfg.default_root_dir, "profile")
                        )
                        profiling = True
                    prev_state = (
                        self.state if self._policy in ("skip", "rollback") else None
                    )
                    # p95-regression capture: the trigger armed on a previous
                    # step's time, so THIS (representative) step is traced
                    # an armed capture must wait out an active profile_start
                    # trace: jax.profiler allows one session at a time, and
                    # nesting would kill the run the telemetry observes
                    capture = (
                        trigger.capture(step=step_idx)
                        if trigger is not None and trigger.armed and not profiling
                        else contextlib.nullcontext()
                    )
                    step_t0 = time.perf_counter()
                    # the `measures` attr is the span-side analog of the
                    # trainer_step_ms / trainer_step_dispatch_ms split: an
                    # unfenced step span times async dispatch, and the device
                    # work it launched surfaces later under log_flush's value
                    # fetch — readers must not attribute it there
                    with capture, self._span(
                        "trainer.step", step=step_idx,
                        measures="fenced" if trigger is not None else "dispatch",
                    ):
                        self.state, metrics = train_step(self.state, batch, step_rng)
                        if trigger is not None:
                            # a per-step fence: without it step_ms would be
                            # async-dispatch microseconds and the trigger
                            # could never see a real device regression (and
                            # an armed capture would trace only dispatch).
                            # The sync cost is the same one skip/rollback
                            # already pay — the price of opting in.
                            jax.block_until_ready(metrics["loss"])
                    self.registry.inc("trainer_steps_total")
                    self._record_step_time(
                        (time.perf_counter() - step_t0) * 1e3, trigger
                    )
                    per_step = [metrics]
                    n_ran = 1
                    if profiling and step_idx >= cfg.profile_start + _PROFILE_WINDOW - 1:
                        jax.block_until_ready(metrics["loss"])
                        jax.profiler.stop_trace()
                        profiling = False

                if self._chaos is not None:
                    per_step = [self._chaos_step_metrics(m) for m in per_step]

                if self._policy in ("skip", "rollback"):
                    # per-step divergence check (one device fetch per step —
                    # the price of recoverability; halt keeps the lazy path)
                    if not np.isfinite(float(per_step[0].get("loss", 0.0))):
                        self._bad_streak += 1
                        if self._bad_streak >= cfg.non_finite_rollback_after:
                            if self._policy == "rollback":
                                step_idx = self._rollback(
                                    cfg, stream, resume_mgr, step_idx
                                )
                                self._bad_streak = 0
                                window, t0 = [], time.time()
                                if resume_mgr is not None and self._preempted:
                                    # post-rollback state IS the snapshot —
                                    # nothing new to persist before exiting
                                    self.log_metrics(
                                        step_idx, {"preempted_at": step_idx}
                                    )
                                    break
                                continue
                            # K consecutive bad steps under skip is persistent
                            # divergence, not a transient — and the last-good
                            # state skip reverts to may itself hide an earlier
                            # finite-loss overflow; stop burning the budget
                            raise FloatingPointError(
                                f"train loss non-finite for {self._bad_streak} "
                                f"consecutive steps (last at step {step_idx}) "
                                "under non_finite_policy='skip'; halting — "
                                "lower the lr / tighten grad clip, or use "
                                "'rollback' with snapshots"
                            )
                        # skip: discard the bad update, keep last-good state
                        self.state = prev_state
                        self._count_fault("skipped_steps")
                        snap_after_recovery = True
                        self.log_metrics(
                            step_idx,
                            {"non_finite_skipped": self.fault_stats["skipped_steps"]},
                        )
                        if resume_mgr is not None and self._preempted:
                            # preemption during a bad streak: persist the
                            # last-good state (if it is in fact finite) and
                            # exit before the platform's hard kill
                            if _params_finite(self.state.params):
                                resume_mgr.save(step_idx, self.state)
                            self.log_metrics(step_idx, {"preempted_at": step_idx})
                            break
                        step_idx += 1
                        continue
                    self._bad_streak = 0

                for m in per_step:
                    window.append(m)
                step_idx += n_ran - 1  # bookkeeping below runs at the block's last step

                def flush_window(step_idx=step_idx):
                    nonlocal window, t0
                    with self._span("trainer.log_flush", step=step_idx):
                        mean = {
                            k: float(np.mean([float(m[k]) for m in window]))
                            for k in window[0]
                        }
                        if self.lr_schedule is not None:
                            mean["lr"] = float(self.lr_schedule(step_idx))
                        mean["steps_per_sec"] = len(window) / (time.time() - t0)
                        self.registry.set_gauge(
                            "trainer_steps_per_sec", mean["steps_per_sec"]
                        )
                        if "loss" in mean and np.isfinite(mean["loss"]):
                            self.registry.set_gauge("trainer_loss", mean["loss"])
                        self.log_metrics(step_idx, mean, prefix="train/")
                    if self._snapshot_writer is not None:
                        self._snapshot_writer.maybe_write()
                    window, t0 = [], time.time()
                    if self._policy == "halt" and not np.isfinite(
                        mean.get("loss", 0.0)
                    ):
                        raise FloatingPointError(
                            f"train loss went non-finite at step {step_idx} "
                            f"({mean['loss']}); halting — resume from the last "
                            "snapshot with a lower lr / grad clip, or set "
                            "non_finite_policy=skip|rollback to recover in place"
                        )

                if (
                    window
                    and step_idx % cfg.log_every_n_steps < n_ran
                    and step_idx >= cfg.log_every_n_steps
                ):
                    flush_window()

                if resume_mgr is not None and (
                    step_idx % cfg.save_state_every_n_steps == 0
                    or self._preempted
                    or snap_after_recovery
                ):
                    # the loss is computed on PRE-update params, so it can
                    # be finite while the update just overflowed — check the
                    # post-update state itself before persisting it
                    if self._policy == "off" or _params_finite(self.state.params):
                        with self._span(
                            "trainer.checkpoint", step=step_idx, kind="resume"
                        ):
                            resume_mgr.save(step_idx, self.state)
                        snap_after_recovery = False
                    elif self._policy == "rollback":
                        # don't kill a run whose own policy can recover: skip
                        # the save (existing snapshots stay finite) and let
                        # the next step's non-finite loss trigger rollback
                        self.log_metrics(
                            step_idx, {"snapshot_refused_non_finite": step_idx}
                        )
                    else:
                        raise FloatingPointError(
                            f"params went non-finite by step {step_idx}; "
                            "snapshot refused — resume from the previous "
                            "snapshot with a lower lr / grad clip"
                        )
                if resume_mgr is not None and self._preempted:
                    self.log_metrics(step_idx, {"preempted_at": step_idx})
                    break

                if val_data is not None and step_idx % cfg.val_check_interval == 0:
                    if window:  # flush partial window so steps_per_sec stays honest
                        flush_window()
                    val_metrics = self.validate(val_data())
                    self.log_metrics(step_idx, val_metrics, prefix="val/")
                    if self._ckpt is not None and "loss" in val_metrics:
                        with self._span(
                            "trainer.checkpoint", step=step_idx, kind="best"
                        ):
                            self._ckpt.save(
                                step_idx,
                                self.state.params,
                                self.model_config,
                                val_metrics["loss"],
                            )
                    for cb in self.callbacks:
                        if self.is_main_process:
                            # a broken qualitative-sampling callback must not
                            # kill a multi-hour run: log the traceback, count
                            # it, keep training
                            try:
                                cb(self, self.state, step_idx, val_metrics)
                            except Exception:
                                self._count_fault("callback_errors")
                                name = getattr(cb, "__name__", repr(cb))
                                print(
                                    f"[trainer] validation callback {name} "
                                    f"failed at step {step_idx}:\n"
                                    f"{traceback.format_exc()}",
                                    file=sys.stderr,
                                    flush=True,
                                )
                                self.log_metrics(
                                    step_idx,
                                    {"callback_errors": self.fault_stats["callback_errors"]},
                                )
                    t0 = time.time()
                step_idx += 1
            if profiling:  # max_steps ended inside the capture window
                jax.profiler.stop_trace()

    @staticmethod
    def _resume_dir(path: str) -> str:
        """Accept a ``<root>/resume`` dir or a root containing one."""
        sub = os.path.join(path, "resume")
        return sub if os.path.isdir(sub) else path

    def setup_state(
        self,
        init_params_fn: Callable[[], Any],
        *,
        initial_params: Any = None,
    ) -> TrainState:
        """Create (or warm-start) the sharded train state without fitting —
        the ``validate``-only entry (reference CLI subcommand parity)."""
        self.state, self._shardings = create_train_state(
            init_params_fn,
            self.tx,
            self.mesh,
            initial_params=initial_params,
        )
        return self.state

    def validate(self, val_data: Iterable) -> dict:
        """Deterministic full pass over ``val_data``; returns mean metrics."""
        return self._evaluate(val_data, self.config.limit_val_batches)

    def test(self, test_data: Iterable) -> dict:
        """Deterministic full pass over the test split; metrics keyed
        ``test_*`` (reference ``LitClassifier.test_step`` sync-logs
        ``test_loss``/``test_acc``, ``core/lightning.py:70-76``)."""
        metrics = self._evaluate(test_data, self.config.limit_test_batches)
        return {f"test_{k}": v for k, v in metrics.items()}

    def _evaluate(self, data: Iterable, limit_batches: Optional[int]) -> dict:
        if self._eval_step is None:  # jit once; re-jitting per call would recompile
            self._eval_step = make_eval_step(self.loss_fn, self.mesh, self._shardings)
        eval_step = self._eval_step
        totals: dict = {}
        count = 0
        with self.mesh:
            for i, batch in enumerate(data):
                if limit_batches is not None and i >= limit_batches:
                    break
                metrics = eval_step(
                    self.state,
                    shard_or_assemble(batch, self.mesh, shard_seq=self.config.shard_seq),
                )
                for k, v in metrics.items():
                    totals[k] = totals.get(k, 0.0) + float(v)
                count += 1
        return {k: v / max(1, count) for k, v in totals.items()}

    def close(self):
        """Release checkpoint managers and log writers (idempotent; ``fit``
        already closed the writers on its way out)."""
        if self._ckpt is not None:
            self._ckpt.close()
            self._ckpt = None
        self._close_writers()
