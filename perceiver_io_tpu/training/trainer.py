"""The trainer loop — Lightning-free equivalent of the reference's
``Trainer.fit(model, datamodule)`` flow (reference
``perceiver/scripts/cli.py``, ``perceiver/model/core/lightning.py``):

step-based training with periodic validation, best-``val_loss`` orbax
checkpointing, learning-rate + loss logging (TensorBoard when torch is
importable, JSONL always), and rank-0 end-of-validation callbacks (the
qualitative text-sampling hooks, reference ``clm/lightning.py:113-151``).

The loop body is host-side Python; every numeric step is one jitted SPMD
call. Metrics are device scalars fetched once per log interval so logging
never stalls the device queue (Lightning's ``sync_dist=True`` reduction is
implicit: metric arrays are replicated outputs of the sharded step).
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from perceiver_io_tpu.parallel import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_train_step,
    shard_or_assemble,
)
from perceiver_io_tpu.training.checkpoint import (
    BestCheckpointManager,
    ResumeCheckpointManager,
)


@dataclasses.dataclass
class TrainerConfig:
    """Trainer hyperparameters (the ``--trainer.*`` surface of the reference
    CLI, reference ``perceiver/scripts/trainer.yaml``)."""

    max_steps: int
    val_check_interval: int = 1000
    log_every_n_steps: int = 50
    limit_val_batches: Optional[int] = None
    limit_test_batches: Optional[int] = None
    default_root_dir: str = "logs"
    max_checkpoints: int = 1
    grad_clip_norm: Optional[float] = None
    #: split each batch into N microbatches and average their gradients
    #: inside the jitted step. NOTE: unlike Lightning's
    #: ``accumulate_grad_batches`` (which multiplies the loader batch), this
    #: DIVIDES the given batch — pass the full effective batch size and use
    #: this knob to bound activation memory per microbatch
    grad_accum_steps: int = 1
    #: run N optimizer steps per device program (``lax.scan`` over stacked
    #: batches) — amortizes host dispatch latency; steps that need host-side
    #: work (validation, snapshots, profiling) automatically run singly.
    #: Trades preemption-response latency (≤ N steps) for throughput.
    steps_per_execution: int = 1
    seed: int = 0
    enable_checkpointing: bool = True
    enable_tensorboard: bool = True
    #: shard the sequence dim of batches over the ``seq`` mesh axis
    #: (context parallelism; XLA partitions attention over kv accordingly)
    shard_seq: bool = False
    #: capture a jax.profiler trace of _PROFILE_WINDOW steps starting here
    #: into <default_root_dir>/profile (None disables)
    profile_start: Optional[int] = None
    #: snapshot the full TrainState (step, params, optimizer state) every N
    #: steps into <default_root_dir>/resume for mid-training resume
    save_state_every_n_steps: Optional[int] = None
    #: resume from the latest TrainState snapshot in this directory (a
    #: <root>/resume dir, or a root containing one) — Lightning
    #: ``fit(ckpt_path=...)`` parity; the loss trajectory of a resumed run
    #: matches the uninterrupted run exactly (per-step rng is fold_in-derived
    #: and the data stream is fast-forwarded)
    resume: Optional[str] = None
    #: halt when the train loss goes non-finite — checked at each log flush
    #: and before every TrainState snapshot (a diverged state is never
    #: snapshotted, so existing snapshots stay a finite resume point); the
    #: device queue is never stalled per-step (Lightning ``detect_anomaly``
    #: role)
    terminate_on_non_finite: bool = True


#: steps traced per jax.profiler capture: [profile_start, profile_start + _PROFILE_WINDOW)
_PROFILE_WINDOW = 3


def _check_uniform_block(block, k_exec: int) -> None:
    """Fused multi-step blocks np.stack ``k_exec`` batches — a user-supplied
    iterable yielding ragged batches would otherwise die in an opaque
    broadcast error deep inside tree_map. Built-in loaders use
    ``drop_last=True``; arbitrary ``fit()`` iterables must match it."""
    ref = block[0]
    ref_structure = jax.tree_util.tree_structure(ref)
    ref_shapes = [np.shape(leaf) for leaf in jax.tree_util.tree_leaves(ref)]
    for i, b in enumerate(block[1:], 1):
        structure = jax.tree_util.tree_structure(b)
        shapes = [np.shape(leaf) for leaf in jax.tree_util.tree_leaves(b)]
        if structure != ref_structure or shapes != ref_shapes:
            raise ValueError(
                f"steps_per_execution={k_exec} requires fixed-shape batches, "
                f"but batch {i} of the block has leaves {shapes} vs the "
                f"block's first batch {ref_shapes} — use a loader that drops "
                "or pads the last partial batch (built-in loaders use "
                "drop_last=True)"
            )


@jax.jit
def _params_finite(params) -> jnp.ndarray:
    """Device-side all-finite reduction over a param tree (one fused pass;
    used to guard TrainState snapshots against persisting diverged state)."""
    leaves = [
        jnp.isfinite(x).all()
        for x in jax.tree_util.tree_leaves(params)
        if jnp.issubdtype(x.dtype, jnp.floating)
    ]
    return jnp.all(jnp.stack(leaves)) if leaves else jnp.asarray(True)


class Trainer:
    """Step-based fit/validate driver.

    :param loss_fn: ``(params, batch, rng) -> (loss, metrics)`` (one of
        :mod:`perceiver_io_tpu.training.tasks`).
    :param callbacks: callables ``(trainer, state, step, val_metrics)`` run on
        process 0 after each validation pass.
    """

    def __init__(
        self,
        config: TrainerConfig,
        mesh,
        loss_fn: Callable,
        tx: optax.GradientTransformation,
        *,
        model_config: Any = None,
        lr_schedule: Optional[optax.Schedule] = None,
        callbacks: Sequence[Callable] = (),
    ):
        self.config = config
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.tx = tx
        self.model_config = model_config
        self.lr_schedule = lr_schedule
        self.callbacks = list(callbacks)
        self.state: Optional[TrainState] = None
        self._shardings = None
        self._ckpt: Optional[BestCheckpointManager] = None
        self._eval_step = None
        self._tb = None
        self._metrics_file = None

        if config.enable_checkpointing:
            # Created on EVERY process: orbax save of multi-host sharded
            # arrays is a collective (each host writes its own shards).
            self._ckpt = BestCheckpointManager(
                os.path.join(config.default_root_dir, "checkpoints"),
                max_to_keep=config.max_checkpoints,
            )
        if self.is_main_process:
            os.makedirs(config.default_root_dir, exist_ok=True)
            self._metrics_file = open(
                os.path.join(config.default_root_dir, "metrics.jsonl"), "a"
            )
            if config.enable_tensorboard:
                try:
                    from torch.utils.tensorboard import SummaryWriter

                    self._tb = SummaryWriter(os.path.join(config.default_root_dir, "tb"))
                except Exception:
                    self._tb = None

    @property
    def is_main_process(self) -> bool:
        """``rank_zero_only`` parity (reference ``clm/lightning.py:113``)."""
        return jax.process_index() == 0

    def log_metrics(self, step: int, metrics: dict, prefix: str = "") -> None:
        if not self.is_main_process:
            return
        scalars = {f"{prefix}{k}": float(v) for k, v in metrics.items()}
        self._metrics_file.write(json.dumps({"step": step, **scalars}) + "\n")
        self._metrics_file.flush()
        if self._tb is not None:
            for k, v in scalars.items():
                self._tb.add_scalar(k, v, step)

    def log_text(self, step: int, tag: str, text: str) -> None:
        """Qualitative text logging (generated samples, filled masks) — the
        reference renders these into TensorBoard text panels."""
        if not self.is_main_process:
            return
        self._metrics_file.write(json.dumps({"step": step, tag: text}) + "\n")
        self._metrics_file.flush()
        if self._tb is not None:
            self._tb.add_text(tag, text, step)

    def fit(
        self,
        init_params_fn: Callable[[], Any],
        train_data: Iterable,
        val_data: Optional[Callable[[], Iterable]] = None,
        *,
        initial_params: Any = None,
    ) -> TrainState:
        """Run the training loop.

        :param train_data: re-iterable of host batch dicts (e.g. a list or a
            DataModule loader) — cycled when exhausted. One-shot generators
            are rejected on the first wrap-around.
        :param val_data: zero-arg callable returning a fresh validation
            iterable (an epoch) — called at every validation pass.
        :param initial_params: optional pre-built params (warm start) used
            instead of ``init_params_fn``'s fresh init values.
        """
        cfg = self.config

        # Preemption grace: TPU pods get a SIGTERM shortly before the machine
        # is reclaimed. Install the handler BEFORE state setup — the initial
        # compile can take minutes and a preemption during it must not kill
        # the process uncleanly. The loop finishes the in-flight step,
        # snapshots the TrainState, and exits so --resume continues exactly
        # where the preempted run stopped.
        prev_handler = None
        self._preempted = False
        if cfg.save_state_every_n_steps is not None:

            def _on_sigterm(signum, frame):
                self._preempted = True

            try:
                import signal

                prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
            except ValueError:  # not the main thread — no signal hooks
                prev_handler = None
        try:
            return self._fit_inner(
                cfg, init_params_fn, train_data, val_data, initial_params
            )
        finally:
            if prev_handler is not None:
                import signal

                signal.signal(signal.SIGTERM, prev_handler)

    def _fit_inner(self, cfg, init_params_fn, train_data, val_data, initial_params):
        self.setup_state(init_params_fn, initial_params=initial_params)
        train_step = make_train_step(
            self.loss_fn,
            self.mesh,
            self._shardings,
            grad_clip_norm=cfg.grad_clip_norm,
            grad_accum_steps=cfg.grad_accum_steps,
        )
        rng = jax.random.PRNGKey(cfg.seed)

        # The restore source may be a different run's dir and must not be
        # rotated/pruned by this run's saves — restore first, then open the
        # save manager on <default_root_dir>/resume.
        start_step = 1
        if cfg.resume is not None:
            restore_mgr = ResumeCheckpointManager(
                self._resume_dir(cfg.resume), create=False
            )
            try:
                self.state = restore_mgr.restore_latest(self.state)
            finally:
                restore_mgr.close()
            start_step = int(self.state.step) + 1
            self.log_metrics(start_step - 1, {"resumed_at": start_step - 1})

        resume_mgr: Optional[ResumeCheckpointManager] = None
        if cfg.save_state_every_n_steps is not None:
            resume_mgr = ResumeCheckpointManager(
                os.path.join(cfg.default_root_dir, "resume")
            )

        data_iter = iter(train_data)

        def next_batch():
            nonlocal data_iter
            try:
                return next(data_iter)
            except StopIteration:
                data_iter = iter(train_data)
                try:
                    return next(data_iter)
                except StopIteration:
                    raise ValueError(
                        "train_data is exhausted and not re-iterable "
                        "(one-shot generator?); pass a list or a loader"
                    ) from None

        # Replay the data stream to the resume point so a resumed run sees
        # the same batches the uninterrupted run would. Loaders with a
        # ``skip_batches`` hook (data.loader.DataLoader) fast-forward in
        # O(1); anything else is consumed batch by batch.
        if start_step > 1:
            if hasattr(train_data, "skip_batches") and hasattr(train_data, "__len__"):
                train_data.skip_batches(start_step - 1)
                data_iter = iter(train_data)
            else:
                for _ in range(start_step - 1):
                    next_batch()

        try:
            self._fit_loop(
                cfg, train_step, rng, next_batch, val_data, resume_mgr, start_step
            )
        finally:
            # even a crashed step must not leak the snapshot manager (the
            # SIGTERM handler is restored by fit()'s own finally)
            if resume_mgr is not None:
                resume_mgr.close()
        return self.state

    def _block_ok(self, cfg, start: int, k: int, val_data, resume_mgr) -> bool:
        """Whether steps ``[start, start+k-1]`` may run as one device program:
        no step *interior* to the block (the last one is handled after the
        block returns) needs host-side work — validation, state snapshot, or
        the profiler capture window."""
        if start + k - 1 > cfg.max_steps or self._preempted:
            return False
        for idx in range(start, start + k - 1):
            if resume_mgr is not None and idx % cfg.save_state_every_n_steps == 0:
                return False
            if val_data is not None and idx % cfg.val_check_interval == 0:
                return False
        if cfg.profile_start is not None and start + k > cfg.profile_start:
            # singles from just before the capture window until past it
            if start <= cfg.profile_start + _PROFILE_WINDOW - 1:
                return False
        return True

    def _fit_loop(
        self, cfg, train_step, rng, next_batch, val_data, resume_mgr, start_step
    ) -> None:
        window: list = []
        profiling = False
        t0 = time.time()
        k_exec = cfg.steps_per_execution
        multi_step = None
        if k_exec > 1:
            multi_step = make_train_step(
                self.loss_fn,
                self.mesh,
                self._shardings,
                grad_clip_norm=cfg.grad_clip_norm,
                grad_accum_steps=cfg.grad_accum_steps,
                multi_steps=k_exec,
            )
        with self.mesh:
            step_idx = start_step
            while step_idx <= cfg.max_steps:
                if multi_step is not None and self._block_ok(
                    cfg, step_idx, k_exec, val_data, resume_mgr
                ):
                    # one device program for k_exec steps (amortized dispatch)
                    block = [next_batch() for _ in range(k_exec)]
                    _check_uniform_block(block, k_exec)
                    stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *block)
                    stacked = shard_or_assemble(
                        stacked, self.mesh, shard_seq=cfg.shard_seq, stacked_steps=True
                    )
                    rngs = jnp.stack(
                        [jax.random.fold_in(rng, step_idx + i) for i in range(k_exec)]
                    )
                    self.state, stacked_metrics = multi_step(self.state, stacked, rngs)
                    per_step = [
                        {k: v[i] for k, v in stacked_metrics.items()}
                        for i in range(k_exec)
                    ]
                    n_ran = k_exec
                else:
                    batch = next_batch()
                    # fold_in (not sequential split): step k's rng is a pure
                    # function of (seed, k), so a resumed run replays the
                    # identical dropout/augmentation stream
                    step_rng = jax.random.fold_in(rng, step_idx)
                    batch = shard_or_assemble(
                        batch, self.mesh, shard_seq=cfg.shard_seq
                    )
                    if cfg.profile_start is not None and step_idx == cfg.profile_start:
                        jax.profiler.start_trace(
                            os.path.join(cfg.default_root_dir, "profile")
                        )
                        profiling = True
                    self.state, metrics = train_step(self.state, batch, step_rng)
                    per_step = [metrics]
                    n_ran = 1
                    if profiling and step_idx >= cfg.profile_start + _PROFILE_WINDOW - 1:
                        jax.block_until_ready(metrics["loss"])
                        jax.profiler.stop_trace()
                        profiling = False

                for m in per_step:
                    window.append(m)
                step_idx += n_ran - 1  # bookkeeping below runs at the block's last step

                def flush_window(step_idx=step_idx):
                    nonlocal window, t0
                    mean = {
                        k: float(np.mean([float(m[k]) for m in window]))
                        for k in window[0]
                    }
                    if self.lr_schedule is not None:
                        mean["lr"] = float(self.lr_schedule(step_idx))
                    mean["steps_per_sec"] = len(window) / (time.time() - t0)
                    self.log_metrics(step_idx, mean, prefix="train/")
                    window, t0 = [], time.time()
                    if cfg.terminate_on_non_finite and not np.isfinite(
                        mean.get("loss", 0.0)
                    ):
                        raise FloatingPointError(
                            f"train loss went non-finite at step {step_idx} "
                            f"({mean['loss']}); halting — resume from the last "
                            "snapshot with a lower lr / grad clip"
                        )

                if (
                    step_idx % cfg.log_every_n_steps < n_ran
                    and step_idx >= cfg.log_every_n_steps
                ):
                    flush_window()

                if resume_mgr is not None and (
                    step_idx % cfg.save_state_every_n_steps == 0
                    or self._preempted
                ):
                    # the loss is computed on PRE-update params, so it can
                    # be finite while the update just overflowed — check the
                    # post-update state itself before persisting it
                    if cfg.terminate_on_non_finite and not _params_finite(
                        self.state.params
                    ):
                        raise FloatingPointError(
                            f"params went non-finite by step {step_idx}; "
                            "snapshot refused — resume from the previous "
                            "snapshot with a lower lr / grad clip"
                        )
                    resume_mgr.save(step_idx, self.state)
                if resume_mgr is not None and self._preempted:
                    self.log_metrics(step_idx, {"preempted_at": step_idx})
                    break

                if val_data is not None and step_idx % cfg.val_check_interval == 0:
                    if window:  # flush partial window so steps_per_sec stays honest
                        flush_window()
                    val_metrics = self.validate(val_data())
                    self.log_metrics(step_idx, val_metrics, prefix="val/")
                    if self._ckpt is not None and "loss" in val_metrics:
                        self._ckpt.save(
                            step_idx,
                            self.state.params,
                            self.model_config,
                            val_metrics["loss"],
                        )
                    for cb in self.callbacks:
                        if self.is_main_process:
                            cb(self, self.state, step_idx, val_metrics)
                    t0 = time.time()
                step_idx += 1
            if profiling:  # max_steps ended inside the capture window
                jax.profiler.stop_trace()

    @staticmethod
    def _resume_dir(path: str) -> str:
        """Accept a ``<root>/resume`` dir or a root containing one."""
        sub = os.path.join(path, "resume")
        return sub if os.path.isdir(sub) else path

    def setup_state(
        self,
        init_params_fn: Callable[[], Any],
        *,
        initial_params: Any = None,
    ) -> TrainState:
        """Create (or warm-start) the sharded train state without fitting —
        the ``validate``-only entry (reference CLI subcommand parity)."""
        self.state, self._shardings = create_train_state(
            init_params_fn,
            self.tx,
            self.mesh,
            initial_params=initial_params,
        )
        return self.state

    def validate(self, val_data: Iterable) -> dict:
        """Deterministic full pass over ``val_data``; returns mean metrics."""
        return self._evaluate(val_data, self.config.limit_val_batches)

    def test(self, test_data: Iterable) -> dict:
        """Deterministic full pass over the test split; metrics keyed
        ``test_*`` (reference ``LitClassifier.test_step`` sync-logs
        ``test_loss``/``test_acc``, ``core/lightning.py:70-76``)."""
        metrics = self._evaluate(test_data, self.config.limit_test_batches)
        return {f"test_{k}": v for k, v in metrics.items()}

    def _evaluate(self, data: Iterable, limit_batches: Optional[int]) -> dict:
        if self._eval_step is None:  # jit once; re-jitting per call would recompile
            self._eval_step = make_eval_step(self.loss_fn, self.mesh, self._shardings)
        eval_step = self._eval_step
        totals: dict = {}
        count = 0
        with self.mesh:
            for i, batch in enumerate(data):
                if limit_batches is not None and i >= limit_batches:
                    break
                metrics = eval_step(
                    self.state,
                    shard_or_assemble(batch, self.mesh, shard_seq=self.config.shard_seq),
                )
                for k, v in metrics.items():
                    totals[k] = totals.get(k, 0.0) + float(v)
                count += 1
        return {k: v / max(1, count) for k, v in totals.items()}

    def close(self):
        if self._ckpt is not None:
            self._ckpt.close()
        if self._tb is not None:
            self._tb.close()
        if self._metrics_file is not None:
            self._metrics_file.close()
