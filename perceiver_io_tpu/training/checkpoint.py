"""Checkpointing — orbax-backed, with the reference's three interoperable
forms (SURVEY.md §5.4):

- **trainer checkpoints**: best-``val_loss``-monitored, weights-only by
  default (reference ``perceiver/scripts/trainer.yaml:7-12``), with the model
  config embedded as metadata so a checkpoint alone rebuilds the model
  (``save_hyperparameters()`` parity);
- **pretrained dirs**: ``save_pretrained``/``load_pretrained`` — params +
  config, the HF-dir equivalent consumed by the inference pipelines;
- **warm-start graph**: ``load_subtree`` pulls a sub-pytree (e.g. just the
  encoder) out of any checkpoint into a fresh model — the two-stage
  classifier flow (reference ``classifier/lightning.py:30-37``).

Sharded ``jax.Array`` trees save and restore natively (each host writes its
shards); restore takes an abstract target so a checkpoint written on one mesh
reloads onto another — something torch FSDP checkpoints cannot do without
consolidation.
"""
from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from perceiver_io_tpu.models.core.config import config_from_dict, config_to_dict

CONFIG_FILE = "config.json"
PARAMS_DIR = "params"


def save_pretrained(path: str, params: Any, config: Any, *, extra: Optional[dict] = None) -> None:
    """Write a self-describing model dir: orbax params + JSON config."""
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    meta = {"model_config": config_to_dict(config) if config is not None else None}
    if extra:
        meta.update(extra)
    with open(os.path.join(path, CONFIG_FILE), "w") as f:
        json.dump(meta, f, indent=2, default=str)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(path, PARAMS_DIR), params, force=True)
    ckptr.wait_until_finished()


def load_config(path: str) -> Any:
    with open(os.path.join(os.path.abspath(path), CONFIG_FILE)) as f:
        meta = json.load(f)
    d = meta.get("model_config")
    return config_from_dict(None, d) if d is not None else None


def _trainer_checkpoint_root(path: str) -> Optional[str]:
    """If ``path`` is (or is ``<root>/best`` of) a trainer checkpoint dir —
    an orbax CheckpointManager root with numeric step dirs — return the
    root, else None."""
    if os.path.basename(path) == "best" and not os.path.exists(path):
        path = os.path.dirname(path)
    if not os.path.isdir(path) or os.path.exists(os.path.join(path, PARAMS_DIR)):
        return None
    has_steps = any(name.isdigit() for name in os.listdir(path))
    return path if has_steps else None


def load_pretrained(path: str, *, target: Any = None):
    """:return: (params, config). ``target`` — an abstract pytree (e.g. from
    ``jax.eval_shape``) with shardings for direct-to-mesh restore; omit for
    host restore.

    Accepts either a ``save_pretrained`` dir or a trainer checkpoint dir
    (``<root>/checkpoints`` or the ``<root>/checkpoints/best`` alias), which
    restores the best-``val_loss`` step."""
    path = os.path.abspath(path)
    ckpt_root = _trainer_checkpoint_root(path)
    if ckpt_root is not None:
        manager = BestCheckpointManager(ckpt_root)
        try:
            return manager.restore_best(target=target)
        finally:
            manager.close()
    config = load_config(path)
    ckptr = ocp.StandardCheckpointer()
    params = ckptr.restore(os.path.join(path, PARAMS_DIR), target)
    return params, config


def load_subtree(path: str, subtree: str, *, target: Any = None):
    """Load one sub-pytree (``'encoder'``, ``'perceiver_ar'`` …) from a saved
    model — partial/pretrained-subtree warm start."""
    params, _ = load_pretrained(path, target=None)
    node = params
    for key in subtree.split("/"):
        node = node[key]
    if target is not None:
        node = jax.tree_util.tree_map(lambda t, x: jax.device_put(x, t.sharding), target, node)
    return node


class ResumeCheckpointManager:
    """Periodic full-``TrainState`` snapshots (step + params + optimizer
    state, which embeds the LR-schedule position) for mid-training resume —
    the Lightning ``Trainer.fit(ckpt_path=...)`` capability the reference
    inherits. Restore takes the live sharded state as template, so snapshots
    reload directly onto the mesh (and onto a *different* mesh, which torch
    optimizer checkpoints cannot do without consolidation)."""

    def __init__(self, directory: str, *, max_to_keep: int = 2, create: bool = True):
        """:param create: make the directory (save side). Pass False for a
        pure-read restore so a mistyped path fails cleanly instead of
        leaving an empty directory tree behind."""
        self.directory = os.path.abspath(directory)
        if create:
            os.makedirs(self.directory, exist_ok=True)
        elif not os.path.isdir(self.directory):
            raise FileNotFoundError(f"no resume snapshots in {self.directory}")
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=False,
                create=create,
            ),
        )

    @staticmethod
    def _tree(state) -> dict:
        return {"step": state.step, "params": state.params, "opt_state": state.opt_state}

    def save(self, step: int, state) -> None:
        self._manager.save(step, args=ocp.args.StandardSave(self._tree(state)))
        self._manager.wait_until_finished()

    @property
    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def restore_latest(self, state):
        """:param state: the freshly initialized sharded TrainState (shape,
        dtype, and sharding template). :return: TrainState at the snapshot."""
        step = self.latest_step
        if step is None:
            raise FileNotFoundError(f"no resume snapshots in {self.directory}")
        target = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            self._tree(state),
        )
        restored = self._manager.restore(step, args=ocp.args.StandardRestore(target))
        return state.replace(
            step=restored["step"],
            params=restored["params"],
            opt_state=restored["opt_state"],
        )

    def close(self):
        """Idempotent: crash-path cleanup (trainer ``finally`` blocks) may
        race a normal close — the second call is a no-op."""
        if self._manager is not None:
            self._manager.close()
            self._manager = None


class BestCheckpointManager:
    """Keeps the k best checkpoints by ``val_loss`` — the reference's
    ``ModelCheckpoint(monitor="val_loss", save_weights_only=True)``
    (``trainer.yaml:7-12``). Checkpoint dirs are named
    ``step=<n>-val_loss=<v>`` like the reference's ``.ckpt`` files."""

    def __init__(self, directory: str, *, max_to_keep: int = 1):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                best_fn=lambda metrics: metrics["val_loss"],
                best_mode="min",
                enable_async_checkpointing=False,
            ),
        )

    def save(self, step: int, params: Any, config: Any, val_loss: float) -> None:
        with open(os.path.join(self.directory, CONFIG_FILE), "w") as f:
            json.dump(
                {"model_config": config_to_dict(config) if config is not None else None},
                f,
                indent=2,
                default=str,
            )
        self._manager.save(
            step,
            args=ocp.args.StandardSave(params),
            metrics={"val_loss": float(val_loss)},
        )
        self._manager.wait_until_finished()

    @property
    def best_step(self) -> Optional[int]:
        return self._manager.best_step()

    def restore_best(self, *, target: Any = None):
        step = self.best_step
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        params = self._manager.restore(step, args=ocp.args.StandardRestore(target))
        with open(os.path.join(self.directory, CONFIG_FILE)) as f:
            d = json.load(f).get("model_config")
        return params, (config_from_dict(None, d) if d is not None else None)

    def close(self):
        """Idempotent — see :meth:`ResumeCheckpointManager.close`."""
        if self._manager is not None:
            self._manager.close()
            self._manager = None
