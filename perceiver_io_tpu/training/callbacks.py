"""Validation-time qualitative callbacks — parity with the reference's
rank-0 end-of-validation sampling (generated text, reference
``clm/lightning.py:113-151``; filled mask predictions rendered to the logger,
``mlm/lightning.py:77-94``). Callbacks run on process 0 only (the trainer
gates them) and log through :meth:`Trainer.log_text`.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class TextSamplingCallback:
    """Sample continuations from the current weights after every validation
    pass (causal LM / symbolic audio families)."""

    def __init__(
        self,
        model,
        tokenizer,
        prompt: str = "A man",
        *,
        max_new_tokens: int = 128,
        num_latents: int = 64,
        top_k: Optional[int] = 40,
        seed: int = 0,
    ):
        self.model = model
        self.tokenizer = tokenizer
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.num_latents = num_latents
        self.top_k = top_k
        self.seed = seed

    def __call__(self, trainer, state, step: int, val_metrics: dict) -> None:
        from perceiver_io_tpu.inference.generate import GenerationConfig, generate
        from perceiver_io_tpu.inference.samplers import SamplingConfig

        ids = jnp.asarray([self.tokenizer.encode(self.prompt)], jnp.int32)
        num_latents = min(self.num_latents, ids.shape[1])
        out = generate(
            self.model,
            state.params,
            ids,
            GenerationConfig(
                max_new_tokens=self.max_new_tokens,
                num_latents=num_latents,
                pad_token_id=self.tokenizer.pad_token_id or 0,
                eos_token_id=self.tokenizer.eos_token_id,
                sampling=SamplingConfig(do_sample=True, top_k=self.top_k),
            ),
            rng=jax.random.fold_in(jax.random.PRNGKey(self.seed), step),
        )
        text = self.prompt + self.tokenizer.decode(np.asarray(out)[0].tolist())
        trainer.log_text(step, "samples/generated", text)


class MaskFillingCallback:
    """Fill masked validation samples after every validation pass (MLM
    family); logs the top-k fillings per sample."""

    def __init__(self, model, preprocessor, masked_samples: Sequence[str], *, top_k: int = 3):
        self.model = model
        self.preprocessor = preprocessor
        self.masked_samples = list(masked_samples)
        self.top_k = top_k

    def __call__(self, trainer, state, step: int, val_metrics: dict) -> None:
        from perceiver_io_tpu.inference.mask_filler import MaskFiller

        filler = MaskFiller(self.preprocessor)
        _, filled = filler.fill(self.model, state.params, self.masked_samples, self.top_k)
        for sample, fillings in zip(self.masked_samples, filled):
            trainer.log_text(step, "samples/fill_mask", f"{sample!r} -> {fillings}")
