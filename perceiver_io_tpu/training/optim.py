"""Optimizer construction: optax chains with optional parameter freezing.

Freezing replaces the reference's ``requires_grad=False`` pattern (two-stage
text-classifier training loads an MLM encoder and freezes it, reference
``perceiver/model/text/classifier/lightning.py:30-37``,
``perceiver/model/core/utils.py:37-39``): frozen subtrees get
``optax.set_to_zero`` via ``optax.multi_transform``, so their parameters and
optimizer state never change (and Adam allocates no moments for them).
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence, Union

import optax

ScheduleOrFloat = Union[float, optax.Schedule]


def make_optimizer(
    learning_rate: ScheduleOrFloat,
    *,
    optimizer: str = "adamw",
    weight_decay: float = 0.0,
    b1: float = 0.9,
    b2: float = 0.999,
    frozen_prefixes: Sequence[str] = (),
) -> optax.GradientTransformation:
    """Build the training transformation.

    :param frozen_prefixes: flax param-path prefixes (e.g. ``("encoder",)``)
        whose parameters are excluded from updates.
    """
    if optimizer == "adamw":
        tx = optax.adamw(learning_rate, b1=b1, b2=b2, weight_decay=weight_decay)
    elif optimizer == "adam":
        tx = optax.adam(learning_rate, b1=b1, b2=b2)
    elif optimizer == "sgd":
        tx = optax.sgd(learning_rate)
    elif optimizer == "lamb":
        tx = optax.lamb(learning_rate, b1=b1, b2=b2, weight_decay=weight_decay)
    else:
        raise ValueError(f"unknown optimizer {optimizer!r}")

    if not frozen_prefixes:
        return tx

    def label_fn(params):
        import jax

        def label(key_path, _):
            path = "/".join(str(getattr(k, "key", k)) for k in key_path)
            frozen = any(
                path == p or path.startswith(p + "/") for p in frozen_prefixes
            )
            return "frozen" if frozen else "trainable"

        return jax.tree_util.tree_map_with_path(label, params)

    return optax.multi_transform(
        {"trainable": tx, "frozen": optax.set_to_zero()}, label_fn
    )
