"""Training layer — a Lightning-free trainer with the reference's training
semantics (reference ``perceiver/model/core/lightning.py``,
``perceiver/scripts/cli.py``, ``perceiver/scripts/lrs.py``):

- optax optimizers + warmup schedules stepped per optimizer step;
- task step functions (CLM/MLM/classifier) producing loss + metrics;
- orbax checkpointing monitored on ``val_loss`` with config metadata;
- metric logging (TensorBoard when available, JSONL always);
- rank-0 qualitative sampling callbacks at validation epochs.
"""
from perceiver_io_tpu.training.callbacks import MaskFillingCallback, TextSamplingCallback
from perceiver_io_tpu.training.lrs import constant_with_warmup, cosine_with_warmup
from perceiver_io_tpu.training.optim import make_optimizer
from perceiver_io_tpu.training.tasks import (
    classifier_loss_fn,
    clm_loss_fn,
    image_classifier_loss_fn,
    mlm_loss_fn,
)
from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

__all__ = [
    "MaskFillingCallback",
    "TextSamplingCallback",
    "constant_with_warmup",
    "cosine_with_warmup",
    "make_optimizer",
    "classifier_loss_fn",
    "clm_loss_fn",
    "image_classifier_loss_fn",
    "mlm_loss_fn",
    "Trainer",
    "TrainerConfig",
]
