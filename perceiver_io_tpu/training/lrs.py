"""Learning-rate schedules (reference ``perceiver/scripts/lrs.py:7-38``),
as optax schedules stepped once per optimizer step (the reference configures
its schedulers with ``interval="step"``, ``perceiver/scripts/cli.py:44-47``).
"""
from __future__ import annotations

import jax.numpy as jnp
import optax


def cosine_with_warmup(
    base_lr: float,
    *,
    warmup_steps: int,
    training_steps: int,
    min_fraction: float = 1e-1,
) -> optax.Schedule:
    """Linear warmup then cosine decay to ``min_fraction * base_lr``
    (reference ``CosineWithWarmupLR``, ``lrs.py:7-27``)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warmup = step / jnp.maximum(1.0, warmup_steps)
        progress = (step - warmup_steps) / jnp.maximum(1.0, training_steps - warmup_steps)
        progress = jnp.clip(progress, 0.0, 1.0)
        cosine = min_fraction + (1.0 - min_fraction) * 0.5 * (1.0 + jnp.cos(jnp.pi * progress))
        return base_lr * jnp.where(step < warmup_steps, warmup, cosine)

    return schedule


def constant_with_warmup(base_lr: float, *, warmup_steps: int) -> optax.Schedule:
    """Linear warmup then constant (reference ``ConstantWithWarmupLR``,
    ``lrs.py:30-38``)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        return base_lr * jnp.minimum(1.0, step / jnp.maximum(1.0, warmup_steps))

    return schedule
