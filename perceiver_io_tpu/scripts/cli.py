"""Config-driven training CLI — the reference's LightningCLI surface
(``perceiver/scripts/cli.py:13-48``) without Lightning/jsonargparse:

    python -m perceiver_io_tpu.scripts.text.clm fit \
        --data=wikitext --data.max_seq_len=4096 \
        --model.num_latents=512 --optimizer.lr=2e-4 \
        --trainer.max_steps=10000 --trainer.default_root_dir=logs

Flags are generated from dataclass fields (``--model.*`` from the family's
model config, ``--data.*`` from the datamodule constructor, ``--trainer.*``
from :class:`~perceiver_io_tpu.training.trainer.TrainerConfig`, plus
``--optimizer.*`` / ``--lr_scheduler.*``). ``--config file.yaml`` loads
defaults (CLI flags win), mirroring the reference's ``trainer.yaml`` default
config file; ``link`` functions propagate data-derived values into the model
config (``link_arguments`` parity, e.g. vocab_size — reference
``scripts/text/mlm.py:12-16``). Subcommands: ``fit``, ``validate``,
``test``, ``preproc`` (the reference LightningCLI exposes
fit/validate/test, ``perceiver/scripts/cli.py:13-48``); ``validate`` and
``test`` take ``--ckpt <dir>`` to evaluate a saved model; ``serve`` takes
``--ckpt <dir>`` plus ``--serve.*`` flags and runs bucketed text
generation through the serving engine (docs/serving.md) — prompts from a
file or stdin, one JSON completion line each, engine stats at the end.

Model-family entry points are declarative :class:`ModelFamily` records; see
``perceiver_io_tpu/scripts/text/clm.py`` for the pattern.
"""
from __future__ import annotations

import argparse
import dataclasses
import enum
import sys
import typing
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import numpy as np


# -- dataclass <-> flags ---------------------------------------------------
def _unwrap_optional(tp):
    origin = typing.get_origin(tp)
    if origin is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0], True
    return tp, False


def _parse_value(text: str, tp) -> Any:
    tp, optional = _unwrap_optional(tp)
    if optional and text.lower() in ("none", "null"):
        return None
    origin = typing.get_origin(tp)
    if tp is bool:
        if text.lower() in ("true", "1", "yes"):
            return True
        if text.lower() in ("false", "0", "no"):
            return False
        raise ValueError(f"invalid bool {text!r}")
    if tp in (int, float, str):
        return tp(text)
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        return tp[text]
    if origin in (tuple, list):
        elem = (typing.get_args(tp) or (str,))[0]
        if elem is Ellipsis:
            elem = str
        items = [t for t in text.replace("(", "").replace(")", "").split(",") if t != ""]
        seq = [_parse_value(t.strip(), elem) for t in items]
        return tuple(seq) if origin is tuple else seq
    # fall back to python literal-ish string
    return text


def _coerce(value: Any, tp) -> Any:
    """Coerce a YAML-loaded value to the field type."""
    if isinstance(value, str):
        return _parse_value(value, tp)
    tp2, _ = _unwrap_optional(tp)
    if value is not None and typing.get_origin(tp2) is tuple:
        elem = (typing.get_args(tp2) or (str,))[0]
        return tuple(value)
    if value is not None and isinstance(tp2, type) and issubclass(tp2, enum.Enum) and not isinstance(value, tp2):
        return tp2[value]
    return value


def flag_specs(cls, prefix: str, nested: Optional[Dict[str, type]] = None) -> Dict[str, Any]:
    """``{dotted_flag: type}`` for a dataclass, recursing into nested
    dataclass fields (``nested`` overrides TypeVar-typed fields with
    concrete classes — PerceiverIOConfig is Generic[E, D])."""
    nested = nested or {}
    specs: Dict[str, Any] = {}
    cls = typing.get_origin(cls) or cls  # unwrap PerceiverIOConfig[E, D]
    hints = typing.get_type_hints(cls)
    for field in dataclasses.fields(cls):
        tp = nested.get(field.name, hints.get(field.name, str))
        if dataclasses.is_dataclass(tp):
            specs.update(flag_specs(tp, f"{prefix}.{field.name}"))
        else:
            specs[f"{prefix}.{field.name}"] = tp
    return specs


def build_dataclass(cls, values: Dict[str, Any], prefix: str,
                    nested: Optional[Dict[str, type]] = None):
    """Instantiate ``cls`` from dotted ``values``."""
    nested = nested or {}
    cls = typing.get_origin(cls) or cls  # unwrap PerceiverIOConfig[E, D]
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for field in dataclasses.fields(cls):
        tp = nested.get(field.name, hints.get(field.name, str))
        key = f"{prefix}.{field.name}"
        if dataclasses.is_dataclass(tp):
            sub_keys = [k for k in values if k.startswith(key + ".")]
            if sub_keys or not _has_default(field):
                kwargs[field.name] = build_dataclass(tp, values, key)
        elif key in values:
            kwargs[field.name] = _coerce(values[key], tp)
    return cls(**kwargs)


def _has_default(field) -> bool:
    return (
        field.default is not dataclasses.MISSING
        or field.default_factory is not dataclasses.MISSING
    )


# -- optimizer / scheduler args -------------------------------------------
@dataclasses.dataclass
class OptimizerArgs:
    """``--optimizer.*`` (reference exposes these via Lightning's optimizer
    wiring, ``scripts/cli.py:37-48``)."""

    lr: float = 1e-3
    optimizer: str = "adamw"
    weight_decay: float = 0.0
    b1: float = 0.9
    b2: float = 0.999


@dataclasses.dataclass
class LRSchedulerArgs:
    """``--lr_scheduler.*`` (reference ``perceiver/scripts/lrs.py:7-38``)."""

    name: str = "cosine"  # cosine | constant | none
    warmup_steps: int = 0
    min_fraction: float = 0.1
    training_steps: Optional[int] = None  # linked to trainer.max_steps


@dataclasses.dataclass
class HttpArgs:
    """``--serve.http.*``: the async HTTP/SSE streaming gateway
    (docs/serving.md "Streaming"). Setting ``--serve.http.port`` switches
    ``serve`` from the prompts-file/stdin batch loop to a network server:
    ``POST /v1/generate`` streams each token as it decodes, ``GET
    /healthz`` is the load-balancer probe, ``GET /metrics`` the Prometheus
    scrape. Client disconnects cancel the request mid-generation (slot +
    KV pool pages freed); TTFT is anchored at socket accept."""

    #: bind port; set it to enable gateway mode (0 = ephemeral, printed to
    #: stderr). None (default) keeps the batch prompts loop.
    port: Optional[int] = None
    host: str = "127.0.0.1"
    #: default wire framing: ``sse`` (Server-Sent Events) or ``jsonl``
    #: (one JSON object per line); per-request override via the body's
    #: ``"stream"`` field
    stream: str = "sse"
    #: shut the gateway down after this many streams reach a terminal
    #: state (scripted runs / tests); None = serve until interrupted
    max_streams: Optional[int] = None


@dataclasses.dataclass
class MeshServeArgs:
    """``--serve.mesh.*``: the sharded serving runtime (docs/serving.md
    "Sharded serving"). Passing any ``--serve.mesh.*`` flag compiles the
    slot engine's executors over a ``data`` × ``model`` device mesh:
    slots/batch shard along ``data`` (``--serve.slots`` must divide
    evenly), attention heads and KV caches — dense per-slot AND the paged
    pool — along ``model`` (the model's head count must divide evenly),
    params get the Megatron TP placement. With ``--serve.replicas=N``
    each replica claims the next disjoint ``data×model`` device group, so
    the fleet scales as N replicas × M-device replicas. Greedy output
    stays token-identical to the unsharded engine; a 1×1 mesh reproduces
    it exactly."""

    #: slot/batch-parallel axis size
    data: int = 1
    #: tensor-parallel axis size (attention heads, KV caches)
    model: int = 1
    #: index of the first claimed device — replica i of a fleet starts at
    #: ``device_offset + i * data * model``
    device_offset: int = 0


@dataclasses.dataclass
class AutoscaleArgs:
    """``--serve.autoscale.*``: SLO-driven fleet elasticity
    (docs/serving.md "Elasticity"). Setting ``--serve.autoscale.max``
    attaches a :class:`~perceiver_io_tpu.serving.FleetAutoscaler` to the
    fleet router (built even at ``--serve.replicas=1``): sustained SLO burn
    (``--obs.slo.*``) or queue pressure scales replicas up to ``max``
    through the degradation ladder; recovery scales back down to ``min``
    with zero dropped in-flight requests (exactly-once failover replay,
    pool pages returned tagged ``scale_down``). Off unless ``max`` set."""

    #: replica ceiling; setting it enables the autoscaler
    max: Optional[int] = None
    #: replica floor — scale-down never goes below it
    min: int = 1
    #: hysteresis: per-direction cooldowns (seconds, on the fleet clock);
    #: the down cooldown gates on the last scale action in EITHER direction
    up_cooldown_s: float = 15.0
    down_cooldown_s: float = 60.0
    #: consecutive control-loop polls of fresh evidence before acting
    up_evidence: int = 2
    down_evidence: int = 5
    #: queue-depth watermarks as multiples of total healthy slot capacity:
    #: depth above high x capacity is a scale-up trigger (even without SLO
    #: targets); depth must fall below low x capacity to count as
    #: scale-down evidence
    queue_high: float = 1.0
    queue_low: float = 0.25
    #: slot count for replicas spawned on the scale-up path (slots engine
    #: only) — applied via the warm-cache resize_slots rebuild before the
    #: replica takes traffic
    scale_up_slots: Optional[int] = None


@dataclasses.dataclass
class ServeArgs:
    """``--serve.*`` flags for the ``serve`` subcommand: bucketed text
    generation over a ``save_pretrained`` checkpoint (docs/serving.md)."""

    #: prompts file, one per line; omitted = read prompts from stdin
    prompts: Optional[str] = None
    max_new_tokens: int = 64
    num_latents: int = 1
    temperature: float = 0.0  # greedy by default — deterministic serving
    #: scheduler: ``bucket`` packs whole micro-batches per compiled
    #: generation; ``slots`` is token-granular continuous batching over a
    #: persistent multi-slot decode state (docs/serving.md — prefer it for
    #: mixed traffic; it requires prompt_len + max_new_tokens <= context)
    engine: str = "bucket"
    #: persistent decode slots for ``--serve.engine=slots``
    slots: int = 8
    #: chunked prefill for the slot engine: split long-prompt admission into
    #: fixed-size chunks interleaved with resident decode steps (None = off;
    #: docs/serving.md)
    prefill_chunk: Optional[int] = None
    #: boundary-phase decode strategy: ``auto`` measures cached-vs-recompute
    #: at warmup and memoizes the winner (inference/decode_strategy.py);
    #: ``cached``/``recompute`` pin it (and beat PERCEIVER_DECODE_STRATEGY,
    #: which ``auto`` defers to). Exact either way — greedy output is
    #: token-identical across settings.
    decode_strategy: str = "auto"
    #: optional JSON path persisting the autotuner's verdicts, so one
    #: deployment measures once (also via PERCEIVER_DECODE_STRATEGY_FILE)
    decode_strategy_file: Optional[str] = None
    #: slot-engine cross-KV layout (docs/serving.md "Block-paged KV"):
    #: ``dense`` = per-slot worst-case caches; ``paged`` = shared block
    #: pool + per-slot block tables (more residents per HBM byte under
    #: long-tail traffic; greedy output identical); ``paged_int8`` = the
    #: paged pool quantized to int8 with per-(position, head) f32 dequant
    #: scales (docs/serving.md "Quantized KV" — ~3-4x residents per HBM
    #: byte; approximate: bounded greedy logit drift, gated by the
    #: autotuner's quality probe); ``auto`` measures at warmup and
    #: memoizes the winner (beaten by an explicit layout, defers to
    #: PERCEIVER_KV_LAYOUT)
    kv_layout: str = "auto"
    #: token positions per KV pool block (paged layout; default
    #: min(16, context))
    kv_block_size: Optional[int] = None
    #: usable KV pool capacity in blocks (paged layout). Default = dense
    #: capacity (slots x pages-per-slot); set it LOWER to serve the same
    #: slot count in less HBM — requests that can't currently fit wait at
    #: the queue head, ones that never could reject at submit. Sizing the
    #: pool requires a paged --serve.kv_layout (a dense resolution would
    #: silently discard the budget, so the engine rejects the combination)
    kv_blocks: Optional[int] = None
    #: cross-request prefix sharing for the paged slot engine
    #: (docs/serving.md "Prefix sharing"): ``on`` maps hot prompt-prefix
    #: blocks by reference with copy-on-write instead of re-projecting
    #: them (greedy output identical; TTFT for a hot system prompt
    #: collapses to the suffix projection); ``auto`` defers to
    #: PERCEIVER_PREFIX_CACHE then the measured registry (off when
    #: unrecorded). ``on`` requires --serve.kv_layout=paged.
    prefix_cache: str = "auto"
    #: self-draft speculative decoding for the slot engine (docs/serving.md
    #: "Speculative decoding"): ``k<K>d<D>`` drafts K candidate tokens per
    #: step with a D-layer truncated latent stack (same checkpoint, no
    #: second model) and verifies all K+1 positions in ONE batched forward
    #: — greedy output stays token-identical to ``off``; throughput
    #: improves when acceptance is high enough that multi-token steps beat
    #: one-token steps. ``auto`` defers to PERCEIVER_SPECULATION, then
    #: measures acceptance x per-step cost at warmup and memoizes the
    #: verdict (falls back to ``off`` when drafting doesn't pay).
    #: Greedy-only: sampling/beams/repetition-penalty reject loudly.
    speculation: str = "auto"
    #: preemption mode for the paged slot engine (docs/serving.md
    #: "Preemption & priorities"): ``recompute`` switches admission to
    #: optimistic lazy paging — requests admit when their PROMPT pages
    #: (plus --serve.admit_headroom_blocks) fit rather than reserving the
    #: worst case up front, and on genuine pool exhaustion the engine
    #: preempts the lowest-priority victim (pages returned, request
    #: requeued, greedy replay token-identical). ``swap`` ships the
    #: victim's mapped KV pages (plus int8 scales) to host memory instead
    #: of discarding them, and restores them into whatever free blocks
    #: exist at readmission — the victim pays transfer instead of
    #: recompute, the win once generated >> prompt. ``auto`` decides
    #: per victim from the live recompute-vs-swap post-mortem model.
    #: ``off`` (default) keeps strict worst-case reservations. Requires a
    #: paged --serve.kv_layout.
    preemption: Optional[str] = None
    #: decode headroom blocks granted beyond the prompt at lazy admission
    #: (--serve.preemption only): higher = fewer early preemptions, lower
    #: = more residents per HBM byte. Default 0.
    admit_headroom_blocks: int = 0
    #: host-swap link prior in GB/s (--serve.preemption=swap|auto only):
    #: seeds the per-victim swap-vs-recompute cost model before the first
    #: measured transfer calibrates it. Unset = the per-platform calibrated
    #: value persisted in --serve.decode_strategy_file, else 16.0.
    swap_gbps: Optional[float] = None
    #: prompt-length bucket grid; default = powers of two up to the context
    prompt_buckets: Optional[typing.Tuple[int, ...]] = None
    #: micro-batch size grid (``bucket`` engine; ignored by ``slots``)
    batch_buckets: typing.Tuple[int, ...] = (1, 2, 4, 8)
    #: compile every bucket before accepting traffic
    warmup: bool = True
    seed: int = 0
    #: append the engine stats JSON line to stdout after the results
    stats: bool = True
    #: bounded queue depth — submissions past it backpressure (the CLI then
    #: drains a micro-batch and resubmits); None = unbounded. With
    #: ``replicas > 1`` this bounds the FLEET (queued + dispatched), not
    #: each engine — admission is lifted to the router.
    max_queue: Optional[int] = None
    #: per-request deadline in seconds; requests that wait longer complete
    #: with a ``timed_out`` record instead of occupying a bucket slot
    deadline_s: Optional[float] = None
    #: engine replicas behind a supervised FleetRouter (docs/serving.md):
    #: load-aware dispatch, per-replica circuit breakers, failover with
    #: exactly-once replay. 1 (default) drives the engine directly — no
    #: fleet layer, no semantic drift.
    replicas: int = 1
    #: with ``replicas > 1``: re-dispatch a failed replica's in-flight
    #: requests to survivors, replayed from their prompts (greedy outputs
    #: stay token-identical). false = a replica failure fails its
    #: in-flight requests terminally.
    failover: bool = True
    #: with ``replicas > 1``: wall-time deadline on one supervised replica
    #: step — a slower (but returning) step marks the replica hung and
    #: fails over its work. None (default) disables hang detection: set it
    #: comfortably above your worst expected step (a cold compile inside
    #: the first unwarmed step would otherwise trip it). A step that never
    #: RETURNS is out of scope for the in-line supervisor — see
    #: docs/serving.md.
    step_timeout_s: Optional[float] = None
    #: the ``--serve.http.*`` sub-group: the async HTTP/SSE streaming
    #: gateway (docs/serving.md "Streaming"); off unless ``http.port`` set
    http: HttpArgs = dataclasses.field(default_factory=HttpArgs)
    #: the ``--serve.autoscale.*`` sub-group: SLO-driven fleet elasticity
    #: (docs/serving.md "Elasticity"); off unless ``autoscale.max`` set
    autoscale: AutoscaleArgs = dataclasses.field(default_factory=AutoscaleArgs)
    #: the ``--serve.mesh.*`` sub-group: sharded serving over the
    #: parallelism mesh (docs/serving.md "Sharded serving"); off unless a
    #: mesh flag is passed (slots engine only)
    mesh: MeshServeArgs = dataclasses.field(default_factory=MeshServeArgs)


def _serve_decode_mode(flag_value: str) -> str:
    """Resolve ``--serve.decode_strategy`` against the process-wide env
    override (docs/serving.md). The flag's ``"auto"`` default must not mask
    ``PERCEIVER_DECODE_STRATEGY`` — ``resolve()`` only consults the env when
    handed ``None``, and the engine always receives an explicit mode so
    warmup knows whether to autotune — so ``auto`` defers to the env var
    while a pinned ``cached``/``recompute`` flag beats it."""
    import os

    from perceiver_io_tpu.inference import decode_strategy as strategy_mod

    if flag_value not in strategy_mod.MODES:
        raise SystemExit(
            "--serve.decode_strategy must be one of "
            f"{'|'.join(strategy_mod.MODES)}, got {flag_value!r}"
        )
    if flag_value != "auto":
        return flag_value
    env_mode = os.environ.get(strategy_mod.ENV_VAR)
    if not env_mode:
        return flag_value
    if env_mode not in strategy_mod.MODES:
        raise SystemExit(
            f"{strategy_mod.ENV_VAR} must be one of "
            f"{'|'.join(strategy_mod.MODES)}, got {env_mode!r}"
        )
    return env_mode


def _serve_kv_layout(flag_value: str) -> str:
    """Resolve ``--serve.kv_layout`` against ``PERCEIVER_KV_LAYOUT`` — the
    same deference rules as :func:`_serve_decode_mode`: an explicit
    ``dense``/``paged`` flag beats the env var; the ``auto`` default
    defers to it (then to the measured registry at engine construction)."""
    import os

    from perceiver_io_tpu.inference import decode_strategy as strategy_mod

    if flag_value not in strategy_mod.KV_LAYOUTS:
        raise SystemExit(
            "--serve.kv_layout must be one of "
            f"{'|'.join(strategy_mod.KV_LAYOUTS)}, got {flag_value!r}"
        )
    if flag_value != "auto":
        return flag_value
    env_mode = os.environ.get(strategy_mod.ENV_KV_LAYOUT)
    if not env_mode:
        return flag_value
    if env_mode not in strategy_mod.KV_LAYOUTS:
        raise SystemExit(
            f"{strategy_mod.ENV_KV_LAYOUT} must be one of "
            f"{'|'.join(strategy_mod.KV_LAYOUTS)}, got {env_mode!r}"
        )
    return env_mode


def _serve_prefix_cache(flag_value: str) -> str:
    """Resolve ``--serve.prefix_cache`` against ``PERCEIVER_PREFIX_CACHE``
    — the same deference rules as :func:`_serve_kv_layout`: an explicit
    ``on``/``off`` flag beats the env var; the ``auto`` default defers to
    it (then to the measured registry at engine construction)."""
    import os

    from perceiver_io_tpu.inference import decode_strategy as strategy_mod

    if flag_value not in strategy_mod.PREFIX_CACHE_MODES:
        raise SystemExit(
            "--serve.prefix_cache must be one of "
            f"{'|'.join(strategy_mod.PREFIX_CACHE_MODES)}, got {flag_value!r}"
        )
    if flag_value != "auto":
        return flag_value
    env_mode = os.environ.get(strategy_mod.ENV_PREFIX_CACHE)
    if not env_mode:
        return flag_value
    if env_mode not in strategy_mod.PREFIX_CACHE_MODES:
        raise SystemExit(
            f"{strategy_mod.ENV_PREFIX_CACHE} must be one of "
            f"{'|'.join(strategy_mod.PREFIX_CACHE_MODES)}, got {env_mode!r}"
        )
    return env_mode


def _serve_speculation(flag_value: str) -> str:
    """Resolve ``--serve.speculation`` against ``PERCEIVER_SPECULATION`` —
    the same deference rules as :func:`_serve_kv_layout`: an explicit
    ``off``/``k<K>d<D>`` flag beats the env var; the ``auto`` default
    defers to it (then to the measured registry at engine construction,
    with an acceptance-probe autotune at warmup when unrecorded)."""
    import os

    from perceiver_io_tpu.inference import decode_strategy as strategy_mod

    if flag_value not in strategy_mod.SPECULATION_MODES:
        raise SystemExit(
            "--serve.speculation must be one of "
            f"{'|'.join(strategy_mod.SPECULATION_MODES)}, got {flag_value!r}"
        )
    if flag_value != "auto":
        return flag_value
    env_mode = os.environ.get(strategy_mod.ENV_SPECULATION)
    if not env_mode:
        return flag_value
    if env_mode not in strategy_mod.SPECULATION_MODES:
        raise SystemExit(
            f"{strategy_mod.ENV_SPECULATION} must be one of "
            f"{'|'.join(strategy_mod.SPECULATION_MODES)}, got {env_mode!r}"
        )
    return env_mode


def _obs_kit(obs, root: str, *, is_main: bool = True,
             passed: Optional[set] = None) -> Dict[str, Any]:
    """Materialize the ``--obs.*`` flag group (docs/observability.md) into
    registry / tracer / snapshot-writer / profiler-trigger objects. Every
    field defaults to off; the events sink, snapshot writer, and profiler
    trigger are all rank-0 only (non-main processes would race the same
    files under a shared root dir). Returns ``{"registry", "tracer",
    "sink", "snapshot_writer", "trigger"}`` — callers must ``close()`` the
    sink when done."""
    import os

    from perceiver_io_tpu.observability import (
        JsonlSpanSink,
        MetricsRegistry,
        ProfilerTrigger,
        SamplingSpanSink,
        SnapshotWriter,
        Tracer,
    )

    def _resolve(path: str) -> str:
        if not os.path.isabs(path):
            path = os.path.join(root, path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        return path

    # inapplicable-flag convention: the sampling / rotation knobs shape the
    # events.jsonl stream, so asking for them without a stream must not
    # silently do nothing
    if obs.events_path is None:
        for flag, value in (
            ("--obs.trace_sample", obs.trace_sample),
            ("--obs.trace_keep_slow_ms", obs.trace_keep_slow_ms),
            ("--obs.events_max_bytes", obs.events_max_bytes),
        ):
            if value is not None:
                raise SystemExit(
                    f"{flag} shapes the span stream; set --obs.events_path "
                    "to enable it (docs/observability.md)"
                )
    if obs.trace_sample is not None and not 0.0 < obs.trace_sample <= 1.0:
        raise SystemExit(
            f"--obs.trace_sample must be in (0, 1], got {obs.trace_sample}"
        )
    if obs.trace_keep_slow_ms is not None and obs.trace_sample is None:
        raise SystemExit(
            "--obs.trace_keep_slow_ms is a trace-sampling tail-keep rule; "
            "set --obs.trace_sample to enable sampling"
        )
    registry = MetricsRegistry()
    sink = None
    tracer = None
    if obs.events_path is not None and is_main:
        import time

        sink = JsonlSpanSink(
            _resolve(obs.events_path), max_bytes=obs.events_max_bytes
        )
        if obs.trace_sample is not None:
            # deterministic head sampling + tail-keep between tracer and
            # disk (docs/observability.md "Trace sampling"); kit["sink"]
            # is the OUTER sink so close() flushes undecided traces first
            sink = SamplingSpanSink(
                sink, rate=obs.trace_sample,
                keep_slow_ms=obs.trace_keep_slow_ms, registry=registry,
            )
        # per-run ID prefix: the sink appends, and a restarted process would
        # otherwise re-issue t000001... — colliding with the previous run's
        # spans in the same file and breaking the trace-ID join
        tracer = Tracer(
            sink=sink, prefix=f"{os.getpid():x}.{int(time.time()) & 0xFFFFFF:x}."
        )
    snapshot_writer = None
    if (obs.snapshot_every_s is not None or obs.snapshot_path is not None) and is_main:
        from perceiver_io_tpu.observability import default_ledger, default_registry

        snapshot_writer = SnapshotWriter(
            registry,
            _resolve(obs.snapshot_path or "metrics_snapshot.json"),
            every_s=obs.snapshot_every_s,
            # every written snapshot embeds the device-cost ledger table
            # (per-executor compile/memory costs for an offline `obs
            # report`) AND the process-wide registry, where the ledger's
            # counter families, the executor-cache counters, and the
            # hbm/resident gauges live — the run-scoped registry alone
            # would silently drop them
            extra=lambda: {
                "compile_ledger": default_ledger().snapshot(),
                "process_metrics": default_registry().snapshot(),
            },
        )
    slo_monitor = None
    if obs.slo.enabled and is_main:
        from perceiver_io_tpu.observability import SLOMonitor

        # SLO targets (docs/observability.md): burn-rate gauges/counters on
        # the kit registry (single-engine serving shares it; a fleet keeps
        # its fleet_* families there too), breach events on the kit tracer
        # when events are on, and breach -> profiler-trigger arming when a
        # trigger exists. run_serve wires the latency/disposition feeds.
        slo_monitor = SLOMonitor(
            obs.slo.policy(),
            registry=registry,
            tracer=None,  # run_serve swaps in its tracer (always built there)
            fast_window_s=obs.slo.fast_window_s,
            slow_window_s=obs.slo.slow_window_s,
            breach_burn_rate=obs.slo.burn_rate,
        )
    flight_recorder = None
    if obs.incident.dir is not None:
        if is_main:
            from perceiver_io_tpu.observability import FlightRecorder

            # the incident flight recorder (docs/observability.md "Flight
            # recorder & incident bundles"): bundle dir resolved like the
            # other --obs paths; the tracer is attached here when events
            # are on and re-attached by run_serve (which always builds one)
            incident_dir = obs.incident.dir
            if not os.path.isabs(incident_dir):
                incident_dir = os.path.join(root, incident_dir)
            flight_recorder = FlightRecorder(
                incident_dir,
                tracer=tracer,
                registry=registry,
                cooldown_s=obs.incident.cooldown_s,
                max_bundles=obs.incident.max_bundles,
                keep_spans=obs.incident.keep_spans,
            )
    elif obs.incident != type(obs.incident)() or any(
        k.startswith("obs.incident.") for k in (passed or ())
    ):
        # inapplicable-flag convention: tuning a recorder that was never
        # enabled must not silently do nothing (`passed` catches a flag
        # explicitly set to its default, which the dataclass compare misses)
        raise SystemExit(
            "--obs.incident.* tunes the incident flight recorder, which is "
            "enabled by setting --obs.incident.dir (docs/observability.md)"
        )
    timeline = None
    timeline_export = None
    if obs.timeline.enabled:
        if obs.timeline.swap_gbps <= 0:
            raise SystemExit(
                f"--obs.timeline.swap_gbps must be > 0, got "
                f"{obs.timeline.swap_gbps}"
            )
        if is_main:
            from perceiver_io_tpu.observability import StepTimeline

            # the scheduler step timeline (docs/observability.md "Scheduler
            # timeline & post-mortems"): run_serve attaches this ring to
            # every engine it builds; the export lands at serve end
            timeline = StepTimeline(cap=obs.timeline.steps, registry=registry)
            if obs.timeline.export is not None:
                timeline_export = _resolve(obs.timeline.export)
    elif obs.timeline != type(obs.timeline)() or any(
        k.startswith("obs.timeline.") for k in (passed or ())
    ):
        # inapplicable-flag convention, same as --obs.incident.*
        raise SystemExit(
            "--obs.timeline.* tunes the scheduler step timeline, which is "
            "enabled by setting --obs.timeline.steps (docs/observability.md)"
        )
    trigger = None
    if obs.profile_on_regress_factor is not None and is_main:
        if jax.process_count() > 1:
            # an armed trigger flips process 0 to single-step scheduling
            # while other processes stay fused — desynchronized collectives
            # hang the SPMD run. Restricted until arming is rank-broadcast.
            print(
                "[obs] profile_on_regress_factor is single-process only; "
                "disabled for this multi-host run",
                file=sys.stderr, flush=True,
            )
        else:
            trigger = ProfilerTrigger(
                os.path.join(root, "profile_regress"),
                factor=obs.profile_on_regress_factor,
            )
    if slo_monitor is not None:
        slo_monitor.profiler_trigger = trigger
        # an SLO breach dumps an incident bundle, same stance as arming
        # the profiler trigger (docs/observability.md)
        slo_monitor.flight_recorder = flight_recorder
    return {
        "registry": registry,
        "tracer": tracer,
        "sink": sink,
        "snapshot_writer": snapshot_writer,
        "trigger": trigger,
        "slo_monitor": slo_monitor,
        "flight_recorder": flight_recorder,
        "timeline": timeline,
        "timeline_export": timeline_export,
    }


# -- the CLI ---------------------------------------------------------------
@dataclasses.dataclass
class ModelFamily:
    """Declarative description of one trainable model family.

    :param build_model: ``(model_cfg, data_module) -> flax module``
    :param make_loss: ``(model, model_cfg) -> loss_fn`` for the train step.
    :param init_args: ``(model_cfg, batch) -> (args, kwargs)`` used for
        ``model.init`` on the first host batch.
    :param link: ``(data_module, values dict) -> None`` — mutate dotted model
        values from data properties before the model config is built
        (``link_arguments`` parity).
    :param initial_params: optional ``(model, model_cfg, data_module) ->
        params`` warm-start hook (e.g. encoder from MLM checkpoint).
    """

    name: str
    config_class: type
    data_registry: Dict[str, Callable]
    build_model: Callable
    make_loss: Callable
    init_args: Callable
    nested: Optional[Dict[str, type]] = None
    link: Optional[Callable] = None
    defaults: Optional[Dict[str, Any]] = None
    initial_params: Optional[Callable] = None
    frozen_prefixes: Optional[Callable] = None  # (model_cfg) -> tuple of paths


def _wants_help(argv: Sequence[str]) -> bool:
    """True when a standalone ``-h``/``--help`` appears. Tokens consumed as
    the *value* of a space-separated flag don't count: ``--data.text --help``
    is a (strange) value, not a help request."""
    expecting_value = False
    for tok in argv:
        if expecting_value:
            expecting_value = False
            continue
        if tok in ("-h", "--help"):
            return True
        if tok.startswith("--") and "=" not in tok:
            expecting_value = True
    return False


def _parse_dotted(argv: Sequence[str], known: Dict[str, Any]) -> Dict[str, Any]:
    values: Dict[str, Any] = {}
    i = 0
    argv = list(argv)
    while i < len(argv):
        arg = argv[i]
        if not arg.startswith("--"):
            raise SystemExit(f"unexpected argument {arg!r}")
        if "=" in arg:
            key, text = arg[2:].split("=", 1)
        else:
            key = arg[2:]
            if i + 1 >= len(argv):
                raise SystemExit(f"missing value for --{key}")
            text = argv[i + 1]
            i += 1
        if key not in known:
            raise SystemExit(
                f"unknown flag --{key}; known flags include: "
                + ", ".join(sorted(known)[:12])
                + ", ..."
            )
        values[key] = _parse_value(text, known[key]) if isinstance(text, str) else text
        i += 1
    return values


class CLI:
    """fit/validate/preproc driver for one :class:`ModelFamily`."""

    def __init__(self, family: ModelFamily):
        self.family = family

    # -- flag space --------------------------------------------------------
    def _known_flags(self, data_cls) -> Dict[str, Any]:
        from perceiver_io_tpu.observability import ObservabilityArgs
        from perceiver_io_tpu.training.trainer import TrainerConfig

        known: Dict[str, Any] = {"config": str, "data": str, "params": str, "ckpt": str}
        known.update(flag_specs(self.family.config_class, "model", self.family.nested))
        known.update(_ctor_flag_specs(data_cls, "data"))
        known.update(flag_specs(TrainerConfig, "trainer"))
        known.update(flag_specs(OptimizerArgs, "optimizer"))
        known.update(flag_specs(LRSchedulerArgs, "lr_scheduler"))
        known.update(flag_specs(ObservabilityArgs, "obs"))
        from perceiver_io_tpu.parallel import MeshConfig

        known.update(flag_specs(MeshConfig, "mesh"))
        return known

    def main(self, argv: Optional[Sequence[str]] = None) -> Any:
        argv = list(sys.argv[1:] if argv is None else argv)
        if not argv or _wants_help(argv):
            # help anywhere in argv (e.g. `fit --help`), like jsonargparse
            self._print_help()
            return None
        subcommand = argv[0]
        if subcommand not in ("fit", "validate", "test", "preproc", "serve", "obs"):
            raise SystemExit(
                f"unknown subcommand {subcommand!r} "
                "(fit|validate|test|preproc|serve|obs)"
            )
        if subcommand == "obs":
            # offline analyzers — no checkpoint, no datamodule, no jax work:
            # `obs report` reads the artifacts a run left behind, `obs
            # incident` reads one flight-recorder bundle
            # (docs/observability.md)
            if len(argv) < 2 or argv[1] not in (
                "report", "incident", "timeline"
            ):
                raise SystemExit(
                    "usage: obs report --events <events.jsonl> "
                    "[--snapshot <snapshot.json>] [--top N] [--json true]\n"
                    "       obs incident --bundle <incident dir> "
                    "[--top N] [--json true]\n"
                    "       obs timeline --timeline <timeline.jsonl> "
                    "[--events <events.jsonl>] [--snapshot <snapshot.json>] "
                    "[--trace_out <trace.json>] [--top N] [--json true]"
                )
            import json as _json

            from perceiver_io_tpu.observability import report as report_mod

            if argv[1] == "incident":
                known = {"bundle": str, "top": int, "json": bool}
                vals = _parse_dotted(argv[2:], known)
                if "bundle" not in vals:
                    raise SystemExit(
                        "obs incident requires --bundle <incident dir>"
                    )
                try:
                    text = report_mod.run_incident(
                        vals["bundle"], top=int(vals.get("top", 8)),
                        as_json=bool(vals.get("json", False)),
                    )
                # JSONDecodeError IS a ValueError — catch it first, with
                # the bundle path the generic message would drop
                except _json.JSONDecodeError as e:
                    raise SystemExit(
                        f"obs incident: bundle manifest is not valid JSON "
                        f"({vals.get('bundle')}: {e})"
                    )
                except (OSError, ValueError) as e:
                    raise SystemExit(f"obs incident: {e}")
                print(text)
                return text
            if argv[1] == "timeline":
                known = {
                    "timeline": str, "events": str, "snapshot": str,
                    "trace_out": str, "top": int, "json": bool,
                }
                vals = _parse_dotted(argv[2:], known)
                if "timeline" not in vals:
                    raise SystemExit(
                        "obs timeline requires --timeline <timeline.jsonl> "
                        "(a --obs.timeline.export file)"
                    )
                try:
                    text = report_mod.run_timeline(
                        vals["timeline"], vals.get("events"),
                        vals.get("snapshot"),
                        trace_out=vals.get("trace_out"),
                        top=int(vals.get("top", 20)),
                        as_json=bool(vals.get("json", False)),
                    )
                # JSONDecodeError IS a ValueError — catch it first, with
                # the artifact path the generic message would drop
                except _json.JSONDecodeError as e:
                    raise SystemExit(
                        f"obs timeline: artifact is not valid JSON "
                        f"({vals.get('timeline')}: {e})"
                    )
                except (OSError, ValueError) as e:
                    raise SystemExit(f"obs timeline: {e}")
                print(text)
                return text
            known = {"events": str, "snapshot": str, "top": int, "json": bool}
            vals = _parse_dotted(argv[2:], known)
            if "events" not in vals:
                raise SystemExit("obs report requires --events <events.jsonl>")
            try:
                text = report_mod.run(
                    vals["events"], vals.get("snapshot"),
                    top=int(vals.get("top", 20)),
                    as_json=bool(vals.get("json", False)),
                )
            except OSError as e:
                # bad artifact paths get the same clean one-line errors as
                # every other flag mistake, not a traceback
                raise SystemExit(f"obs report: {e}")
            except _json.JSONDecodeError as e:
                raise SystemExit(
                    f"obs report: --snapshot is not valid JSON "
                    f"({vals.get('snapshot')}: {e})"
                )
            print(text)
            return text
        if subcommand == "serve":
            # serve needs no datamodule: the checkpoint's embedded config
            # picks the model, and prompts come from a file or stdin.
            from perceiver_io_tpu.observability import ObservabilityArgs

            known = {"ckpt": str, "params": str}
            known.update(flag_specs(ServeArgs, "serve"))
            known.update(flag_specs(ObservabilityArgs, "obs"))
            return self.run_serve(_parse_dotted(argv[1:], known))

        # data module choice first (its ctor defines the --data.* space)
        data_name = None
        for arg in argv[1:]:
            if arg.startswith("--data=") :
                data_name = arg.split("=", 1)[1]
            elif arg == "--data":
                idx = argv.index(arg)
                data_name = argv[idx + 1] if idx + 1 < len(argv) else None
        registry = self.family.data_registry
        if data_name is None:
            data_name = next(iter(registry))
        if data_name not in registry:
            raise SystemExit(
                f"unknown data module {data_name!r}; choose from {sorted(registry)}"
            )
        data_cls = registry[data_name]

        known = self._known_flags(data_cls)
        values = dict(self.family.defaults or {})
        cli_values = _parse_dotted(argv[1:], known)
        if "config" in cli_values:
            import yaml

            with open(cli_values.pop("config")) as fh:
                for key, val in (yaml.safe_load(fh) or {}).items():
                    values[key] = val
        values.update(cli_values)
        values.pop("data", None)
        return self.run(subcommand, data_cls, values)

    # -- execution ---------------------------------------------------------
    def run(self, subcommand: str, data_cls, values: Dict[str, Any]) -> Any:
        import optax

        from perceiver_io_tpu.parallel import MeshConfig, make_mesh
        from perceiver_io_tpu.training.lrs import constant_with_warmup, cosine_with_warmup
        from perceiver_io_tpu.training.optim import make_optimizer
        from perceiver_io_tpu.training.trainer import Trainer, TrainerConfig

        if any(k.startswith("obs.slo.") for k in values):
            # inapplicable-flag convention: SLO targets judge SERVING token
            # latency; a fit run has no TTFT to monitor. Checked before any
            # datamodule/model work so the error is instant.
            raise SystemExit(
                "--obs.slo.* applies to the serve subcommand (SLO targets "
                "monitor serving token latency; docs/observability.md)"
            )
        if any(k.startswith("obs.timeline.") for k in values):
            # same stance: the step timeline records SCHEDULER passes —
            # only the serve engines have one
            raise SystemExit(
                "--obs.timeline.* applies to the serve subcommand (the "
                "step timeline records scheduler passes; "
                "docs/observability.md)"
            )
        data_kwargs = {
            k.split(".", 1)[1]: v for k, v in values.items() if k.startswith("data.")
        }
        dm = data_cls(**data_kwargs)
        dm.prepare_data()
        if subcommand == "preproc":
            return None
        dm.setup()

        if self.family.link is not None:
            self.family.link(dm, values)
        model_cfg = build_dataclass(
            self.family.config_class, values, "model", self.family.nested
        )
        model = self.family.build_model(model_cfg, dm)

        trainer_cfg = build_dataclass(TrainerConfig, values, "trainer")
        opt = build_dataclass(OptimizerArgs, values, "optimizer")
        lrs = build_dataclass(LRSchedulerArgs, values, "lr_scheduler")

        steps = lrs.training_steps or trainer_cfg.max_steps
        if lrs.name == "cosine":
            schedule = cosine_with_warmup(
                opt.lr, warmup_steps=lrs.warmup_steps,
                training_steps=steps, min_fraction=lrs.min_fraction,
            )
        elif lrs.name == "constant":
            schedule = constant_with_warmup(opt.lr, warmup_steps=lrs.warmup_steps)
        else:
            schedule = None
        tx = make_optimizer(
            schedule if schedule is not None else opt.lr,
            optimizer=opt.optimizer,
            weight_decay=opt.weight_decay,
            b1=opt.b1,
            b2=opt.b2,
            frozen_prefixes=(
                self.family.frozen_prefixes(model_cfg)
                if self.family.frozen_prefixes is not None
                else ()
            ),
        )

        mesh = make_mesh(build_dataclass(MeshConfig, values, "mesh"))
        from perceiver_io_tpu.observability import ObservabilityArgs

        obs = build_dataclass(ObservabilityArgs, values, "obs")
        kit = _obs_kit(
            obs, trainer_cfg.default_root_dir,
            is_main=jax.process_index() == 0, passed=set(values),
        )
        trainer = Trainer(
            trainer_cfg,
            mesh,
            self.family.make_loss(model, model_cfg),
            tx,
            model_config=model_cfg,
            lr_schedule=schedule,
            registry=kit["registry"],
            tracer=kit["tracer"],
            profiler_trigger=kit["trigger"],
            snapshot_writer=kit["snapshot_writer"],
        )

        first_batch = next(iter(dm.train_dataloader()))

        def init_params():
            args, kwargs = self.family.init_args(model_cfg, first_batch)
            return model.init(jax.random.PRNGKey(trainer_cfg.seed), *args, **kwargs)[
                "params"
            ]

        initial = None
        if values.get("ckpt") or values.get("params"):
            # Full-model warm start from a save_pretrained dir or trainer
            # checkpoint dir (reference ``--model.params`` reload,
            # ``clm/lightning.py:44-52``; ``--ckpt`` is the evaluation-time
            # spelling, matching the reference's ``test --ckpt_path``).
            from perceiver_io_tpu.training.checkpoint import load_pretrained

            initial, _ = load_pretrained(values.get("ckpt") or values["params"])
        elif self.family.initial_params is not None:
            initial = self.family.initial_params(model, model_cfg, dm)

        try:
            if subcommand in ("validate", "test"):
                trainer.setup_state(init_params, initial_params=initial)
                loader = dm.test_dataloader() if subcommand == "test" else dm.val_dataloader()
                metrics = trainer.test(loader) if subcommand == "test" else trainer.validate(loader)
                trainer.close()
                import json as _json

                print(_json.dumps({k: round(float(v), 6) for k, v in metrics.items()}))
                return metrics

            state = trainer.fit(
                init_params,
                dm.train_dataloader(),
                val_data=dm.val_dataloader,
                initial_params=initial,
            )
            trainer.close()
            return state
        finally:
            # validate/test never reach fit's own forced write — the flag
            # must not be silently ignored on those subcommands (fit already
            # wrote; a second identical write is harmless)
            if kit["snapshot_writer"] is not None:
                kit["snapshot_writer"].maybe_write(force=True)
            if kit["sink"] is not None:
                kit["sink"].close()

    # -- serving -----------------------------------------------------------
    def run_serve(self, values: Dict[str, Any]) -> list:
        """``serve --ckpt <dir>``: bucketed text generation over a saved
        model — prompts (file or stdin) → one JSON line per completion,
        plus a final engine-stats line (docs/serving.md).

        Error isolation (docs/reliability.md): an infeasible prompt (empty /
        longer than the largest bucket) becomes a per-line
        ``{"prompt": ..., "error": ...}`` record instead of aborting the
        run; a bounded queue (``--serve.max_queue``) backpressures by
        draining a micro-batch before resubmitting; timed-out or failed
        requests surface their status per line.

        ``--serve.replicas=N`` (N > 1) serves through a supervised
        :class:`~perceiver_io_tpu.serving.FleetRouter` — load-aware
        dispatch over N engine replicas with circuit breakers and
        (``--serve.failover``) exactly-once failover replay
        (docs/serving.md); the router mirrors the engine surface, so the
        prompt loop below is identical either way.
        """
        import json
        import os
        import time

        from perceiver_io_tpu.data.text.tokenizers import ByteTokenizer
        from perceiver_io_tpu.inference.generate import GenerationConfig
        from perceiver_io_tpu.inference.samplers import SamplingConfig
        from perceiver_io_tpu.models import model_for_config
        from perceiver_io_tpu.observability import ObservabilityArgs, Tracer
        from perceiver_io_tpu.serving import (
            BucketTable,
            QueueFull,
            ServingEngine,
            SlotServingEngine,
        )
        from perceiver_io_tpu.training.checkpoint import load_pretrained

        ckpt = values.get("ckpt") or values.get("params")
        if not ckpt:
            raise SystemExit("serve requires --ckpt <save_pretrained dir>")
        args = build_dataclass(ServeArgs, values, "serve")
        obs = build_dataclass(ObservabilityArgs, values, "obs")
        kit = _obs_kit(obs, os.getcwd(), passed=set(values))
        # serve lines always carry a trace_id (the events.jsonl join key),
        # so the engine always gets a tracer — sink-less when --obs.events_path
        # is unset (spans stay in the bounded in-memory buffer).
        tracer = kit["tracer"] or Tracer()
        if kit["slo_monitor"] is not None:
            # slo.breach / slo.recover events land on the run's tracer
            # (into events.jsonl when configured — the obs-report timeline)
            kit["slo_monitor"].tracer = tracer
        if kit["flight_recorder"] is not None:
            # the recorder's span ring and its incident.dump events ride
            # the run's one tracer (sink-less runs still bundle from the
            # in-memory ring)
            kit["flight_recorder"].tracer = tracer
        # the device-cost ledger's builds stream into events.jsonl as
        # `ledger.compile` events, so an offline `obs report` over the
        # events alone still carries the compile/memory table
        from perceiver_io_tpu.observability import default_ledger

        ledger = default_ledger()
        detach_ledger = ledger.attach(
            lambda rec: tracer.event(
                "ledger.compile",
                site=rec["site"],
                compile_ms=rec["compile_ms"],
                flops=rec["flops"],
                bytes_accessed=rec["bytes_accessed"],
                argument_bytes=rec["argument_bytes"],
                output_bytes=rec["output_bytes"],
                temp_bytes=rec["temp_bytes"],
                retrace=rec["retrace"],
                reasons=",".join(rec["retrace_reasons"]),
                bucket_shape=rec["components"].get("bucket_shape"),
            )
        ) if kit["sink"] is not None else (lambda: None)
        # everything from here on runs under the teardown finally:
        # an error in checkpoint load / engine build / warmup must
        # still detach the ledger callback (it closes over THIS
        # run's tracer+sink — leaking it would stream later runs'
        # compiles into a dead events file) and close the artifacts
        try:
            params, model_cfg = load_pretrained(ckpt)
            if model_cfg is None:
                raise SystemExit(f"{ckpt} has no embedded model config")
            model = model_for_config(model_cfg)
            from perceiver_io_tpu.models.text.clm import CausalLanguageModel

            if not isinstance(model, CausalLanguageModel):
                # The decode side is the byte tokenizer; a non-text AR family
                # (e.g. symbolic audio) would sample ids the tokenizer cannot
                # decode — fail fast instead of mid-stream.
                raise SystemExit(
                    "serve currently supports text CLM checkpoints (byte "
                    f"tokenizer); got {type(model).__name__}"
                )

            table = BucketTable.for_model(model)
            if args.prompt_buckets or tuple(args.batch_buckets) != (1, 2, 4, 8):
                table = BucketTable(
                    prompt_lens=tuple(args.prompt_buckets or table.prompt_lens),
                    batch_sizes=tuple(args.batch_buckets),
                )
            tok = ByteTokenizer(padding_side="left")
            gen_cfg = GenerationConfig(
                max_new_tokens=args.max_new_tokens,
                num_latents=args.num_latents,
                pad_token_id=tok.pad_token_id or 0,
                eos_token_id=tok.eos_token_id,
                sampling=SamplingConfig(temperature=args.temperature),
            )
            if args.engine not in ("bucket", "slots"):
                raise SystemExit(
                    f"--serve.engine must be 'bucket' or 'slots', got {args.engine!r}"
                )
            from perceiver_io_tpu.inference import decode_strategy as strategy_mod

            decode_mode = _serve_decode_mode(args.decode_strategy)
            if args.decode_strategy_file:
                # persisted verdicts short-circuit the warmup autotune; fresh
                # verdicts measured this run are written back on warmup
                strategy_mod.load_registry(args.decode_strategy_file)
            if args.replicas < 1:
                raise SystemExit(
                    f"--serve.replicas must be >= 1, got {args.replicas}"
                )
            if args.preemption is not None:
                from perceiver_io_tpu.serving.slots import PREEMPTION_MODES

                if args.preemption not in PREEMPTION_MODES:
                    raise SystemExit(
                        "--serve.preemption must be one of "
                        f"{PREEMPTION_MODES}, got {args.preemption!r}"
                    )
            if args.admit_headroom_blocks < 0:
                raise SystemExit(
                    "--serve.admit_headroom_blocks must be >= 0, got "
                    f"{args.admit_headroom_blocks}"
                )
            if args.admit_headroom_blocks and args.preemption is None:
                # inapplicable-flag convention: headroom only shapes lazy
                # admission, which --serve.preemption enables
                raise SystemExit(
                    "--serve.admit_headroom_blocks applies with "
                    "--serve.preemption (strict reservations already "
                    "cover the worst case)"
                )
            if args.swap_gbps is not None:
                if args.preemption not in ("swap", "auto"):
                    # inapplicable-flag convention: the link prior only
                    # feeds the swap-vs-recompute cost model
                    raise SystemExit(
                        "--serve.swap_gbps applies with "
                        "--serve.preemption=swap|auto (no other mode "
                        "ships KV pages over the host link)"
                    )
                if args.swap_gbps <= 0:
                    raise SystemExit(
                        f"--serve.swap_gbps must be > 0, got "
                        f"{args.swap_gbps}"
                    )
            autoscale = args.autoscale
            if autoscale.max is None and any(
                k.startswith("serve.autoscale.") for k in values
            ):
                # inapplicable-flag convention: tuning an autoscaler that
                # was never enabled must not silently do nothing
                raise SystemExit(
                    "--serve.autoscale.* tunes the fleet autoscaler, which "
                    "is enabled by setting --serve.autoscale.max"
                )
            if autoscale.max is not None:
                if autoscale.max < max(autoscale.min, args.replicas):
                    raise SystemExit(
                        f"--serve.autoscale.max ({autoscale.max}) must be >= "
                        f"max(--serve.autoscale.min ({autoscale.min}), "
                        f"--serve.replicas ({args.replicas}))"
                    )
                if autoscale.scale_up_slots is not None and args.engine != "slots":
                    raise SystemExit(
                        "--serve.autoscale.scale_up_slots applies to "
                        "--serve.engine=slots (the bucket engine has no "
                        "persistent decode slots to resize)"
                    )
            # the autoscaler drives FleetRouter.add/remove_replica, so
            # enabling it builds the fleet layer even at one replica
            fleet_mode = args.replicas > 1 or autoscale.max is not None
            if not fleet_mode:
                # inapplicable-flag convention (same as --serve.prefill_chunk
                # with the bucket engine): asking for fleet supervision
                # without a fleet must not silently do nothing
                if args.step_timeout_s is not None:
                    raise SystemExit(
                        "--serve.step_timeout_s applies to --serve.replicas > 1 "
                        "(hang detection is fleet supervision; a single engine "
                        "is driven directly)"
                    )
                if not args.failover:
                    print(
                        "[serve] --serve.failover=false is a no-op with "
                        "--serve.replicas=1 (no fleet layer, so there is no "
                        "failover to disable)",
                        file=sys.stderr, flush=True,
                    )
            engine_kwargs = dict(
                rng=jax.random.PRNGKey(args.seed),
                # with a fleet, admission (bounded queue + deadlines) is
                # lifted to the router; the engines stay unbounded and
                # enforce only the remaining deadline handed over per
                # dispatch
                max_queue=None if fleet_mode else args.max_queue,
                default_deadline_s=None if fleet_mode else args.deadline_s,
                # fleet replicas keep PRIVATE registries so serve_stats'
                # per_replica engine stats attribute to one replica each
                # (a shared registry would show fleet-wide aggregates on
                # every row); the kit registry then carries the fleet_*
                # supervision families
                registry=None if fleet_mode else kit["registry"],
                tracer=tracer,
                # serve-side p95 regression trigger: the slot engine feeds
                # per-token decode-step times, the bucket engine per-batch
                # execute times; an armed trigger captures the next dispatch
                profiler_trigger=kit["trigger"],
                decode_strategy=decode_mode,
            )
            kv_mode = _serve_kv_layout(args.kv_layout)
            prefix_mode = _serve_prefix_cache(args.prefix_cache)
            spec_mode = _serve_speculation(args.speculation)
            if (
                args.engine == "slots"
                and args.warmup
                and spec_mode == "auto"
                and strategy_mod.lookup_speculation(model) is None
            ):
                # measure once, memoize (docs/serving.md "Speculative
                # decoding"): A/B each draft geometry against "off" on the
                # probe workload and record acceptance x per-step cost; the
                # verdict lands in the strategy registry so a persisted
                # --serve.decode_strategy_file skips this on the next boot
                t0 = time.monotonic()
                spec_mode = strategy_mod.autotune_speculation(model, params)
                print(
                    f"[serve] speculation autotune picked {spec_mode!r} in "
                    f"{time.monotonic() - t0:.1f}s", file=sys.stderr,
                    flush=True,
                )
            flight_recorder = kit["flight_recorder"]
            # sharded serving (docs/serving.md "Sharded serving"): any
            # --serve.mesh.* flag opts in — including an explicit 1x1
            # degenerate mesh (the byte-identical single-device form)
            mesh_requested = any(k.startswith("serve.mesh.") for k in values)
            mesh_alloc = None
            if mesh_requested:
                if args.engine != "slots":
                    raise SystemExit(
                        "--serve.mesh.* applies to --serve.engine=slots "
                        "(the sharded runtime compiles the slot engine's "
                        "executors over the mesh; the bucket engine is "
                        "single-device)"
                    )
                from perceiver_io_tpu.serving import (
                    MeshGroupAllocator,
                    ServingMeshSpec,
                    fleet_mesh_specs,
                )

                try:
                    base_spec = ServingMeshSpec(
                        data=args.mesh.data, model=args.mesh.model,
                        device_offset=args.mesh.device_offset,
                    )
                    # the INITIAL fleet must fit the device budget outright
                    # (autoscaler spawns past it wrap around the allocator,
                    # documented on sharding.MeshGroupAllocator)
                    fleet_mesh_specs(base_spec, max(1, args.replicas))
                except ValueError as e:
                    raise SystemExit(f"--serve.mesh.*: {e}")
                # one shared allocator hands each spawn the first FREE
                # disjoint device group — initial replicas, crash rebuilds
                # (the crashed group frees for its rebuild), scale-ups
                mesh_alloc = MeshGroupAllocator(base_spec)
            if args.engine == "slots":
                # swap modes let the engine resolve the link rate itself
                # (explicit --serve.swap_gbps > per-platform calibrated
                # registry entry > 16.0 prior); other modes keep the
                # post-mortem denominator pinned to the obs-side flag
                if args.preemption in ("swap", "auto"):
                    link_gbps = args.swap_gbps
                else:
                    link_gbps = obs.timeline.swap_gbps

                def make_engine():
                    eng = SlotServingEngine(
                        model, params, gen_cfg, table, slots=args.slots,
                        prefill_chunk=args.prefill_chunk,
                        kv_layout=kv_mode, kv_block_size=args.kv_block_size,
                        kv_blocks=args.kv_blocks, prefix_cache=prefix_mode,
                        preemption=args.preemption,
                        admit_headroom_blocks=args.admit_headroom_blocks,
                        speculation=spec_mode,
                        mesh=(
                            mesh_alloc.acquire() if mesh_alloc is not None
                            else None
                        ),
                        swap_link_gbps=link_gbps,
                        **engine_kwargs
                    )
                    # inside the factory, not after it: fleet replica
                    # restarts / autoscaler spawns rebuild engines through
                    # this factory and must keep the pool-exhaustion seam
                    eng.flight_recorder = flight_recorder
                    # shared ring: every replica's passes land in ONE
                    # step-ordered timeline (--obs.timeline.steps)
                    eng.timeline = kit["timeline"]
                    return eng
            else:
                if args.prefill_chunk is not None:
                    raise SystemExit(
                        "--serve.prefill_chunk applies to --serve.engine=slots "
                        "(the bucket engine has no resident decode to interleave)"
                    )
                # inapplicable-flag convention: an explicitly paged (or
                # sized) KV pool on the bucket engine must not silently do
                # nothing. Checked on the RAW flags, not the env-resolved
                # mode: a machine-wide PERCEIVER_KV_LAYOUT set for slot
                # deployments must not break unrelated bucket-engine jobs
                # on the same host.
                if args.kv_layout != "auto" or args.kv_block_size is not None \
                        or args.kv_blocks is not None:
                    raise SystemExit(
                        "--serve.kv_layout/--serve.kv_block_size/"
                        "--serve.kv_blocks apply to --serve.engine=slots "
                        "(the bucket engine has no persistent KV state to page)"
                    )
                if args.prefix_cache != "auto":
                    raise SystemExit(
                        "--serve.prefix_cache applies to --serve.engine=slots "
                        "with the paged KV layout (the bucket engine has no "
                        "block tables to share)"
                    )
                if args.preemption is not None \
                        or args.admit_headroom_blocks != 0 \
                        or args.swap_gbps is not None:
                    raise SystemExit(
                        "--serve.preemption/--serve.admit_headroom_blocks/"
                        "--serve.swap_gbps apply to --serve.engine=slots "
                        "with a paged KV layout (the bucket engine has no "
                        "page pool to preempt from)"
                    )
                if args.speculation != "auto":
                    raise SystemExit(
                        "--serve.speculation applies to --serve.engine=slots "
                        "(the bucket engine has no resident decode loop to "
                        "draft ahead of)"
                    )

                def make_engine():
                    eng = ServingEngine(
                        model, params, gen_cfg, table, **engine_kwargs
                    )
                    eng.flight_recorder = flight_recorder
                    eng.timeline = kit["timeline"]
                    return eng
            if fleet_mode:
                from perceiver_io_tpu.serving import FleetRouter

                # the fleet mirrors the engine request surface, so the
                # whole prompt loop below drives it unchanged; the warm
                # executor caches are process-global, so N replicas cost
                # one compile pass
                engine = FleetRouter(
                    [make_engine] * args.replicas,
                    max_pending=args.max_queue,
                    default_deadline_s=args.deadline_s,
                    failover=args.failover,
                    step_timeout_s=args.step_timeout_s,
                    registry=kit["registry"],
                    tracer=tracer,
                    # telemetry-driven admission (docs/observability.md): a
                    # sustained burn tightens max_pending/deadline shedding
                    slo_monitor=kit["slo_monitor"],
                    slo_shed_factor=obs.slo.shed_factor,
                    # replica failures / breaker opens dump incident
                    # bundles (docs/observability.md)
                    flight_recorder=flight_recorder,
                )
                if autoscale.max is not None:
                    from perceiver_io_tpu.serving import FleetAutoscaler

                    # ctor installs itself on the router; every fleet
                    # step() polls it (docs/serving.md "Elasticity")
                    FleetAutoscaler(
                        engine,
                        max_replicas=autoscale.max,
                        min_replicas=autoscale.min,
                        up_cooldown_s=autoscale.up_cooldown_s,
                        down_cooldown_s=autoscale.down_cooldown_s,
                        up_evidence=autoscale.up_evidence,
                        down_evidence=autoscale.down_evidence,
                        queue_high=autoscale.queue_high,
                        queue_low=autoscale.queue_low,
                        scale_up_slots=autoscale.scale_up_slots,
                        tracer=tracer,
                    )
            else:
                engine = make_engine()
                if kit["slo_monitor"] is not None:
                    # single-engine SLO feeds: the engine mirrors every
                    # TTFT/ITL sample into the monitor, and the error-rate
                    # dimension diffs the serving_* disposition counters
                    # (same registry) per poll
                    engine.latency_sink = kit["slo_monitor"].sink
                    kit["slo_monitor"].watch_counters(
                        kit["registry"].counters, prefix="serving"
                    )
            if flight_recorder is not None:
                # dump-time state sources (docs/observability.md): health
                # (the fleet's embeds replica_detail), SLO burn state,
                # autoscaler ladder state, and the paged pool(s)
                flight_recorder.add_source("health", engine.health)
                if kit["slo_monitor"] is not None:
                    flight_recorder.add_source("slo", kit["slo_monitor"].stats)
                autoscaler = getattr(engine, "autoscaler", None)
                if autoscaler is not None:
                    flight_recorder.add_source("autoscaler", autoscaler.stats)
                if fleet_mode:
                    def _fleet_pools():
                        return {
                            str(r.replica_id): r.engine._pool.stats()
                            for r in engine.replicas
                            if getattr(r.engine, "_pool", None) is not None
                        }

                    flight_recorder.add_source("kv_pool", _fleet_pools)
                elif getattr(engine, "_pool", None) is not None:
                    flight_recorder.add_source("kv_pool", engine._pool.stats)
                if kit["timeline"] is not None:
                    # ring summary lands in every bundle; the full records
                    # live in the --obs.timeline.export JSONL
                    flight_recorder.add_source(
                        "timeline", kit["timeline"].summary
                    )
                # per-victim recompute-vs-swap post-mortems (docs/
                # observability.md "Scheduler timeline & post-mortems")
                if fleet_mode:
                    def _fleet_postmortems():
                        return {
                            str(r.replica_id): r.engine.postmortems()
                            for r in engine.replicas
                            if hasattr(r.engine, "postmortems")
                        }

                    flight_recorder.add_source(
                        "preemption_postmortems", _fleet_postmortems
                    )
                elif hasattr(engine, "postmortems"):
                    flight_recorder.add_source(
                        "preemption_postmortems", engine.postmortems
                    )
            if args.warmup:
                t0 = time.monotonic()
                compiles = engine.warmup()
                print(
                    f"[serve] warmup compiled {compiles} executors in "
                    f"{time.monotonic() - t0:.1f}s", file=sys.stderr, flush=True,
                )
                if args.decode_strategy_file and (
                    decode_mode == "auto"
                    or (args.engine == "slots" and (
                        kv_mode == "auto" or args.speculation == "auto"
                        or args.preemption in ("swap", "auto")
                    ))
                ):
                    strategy_mod.save_registry(args.decode_strategy_file)

            if args.http.port is not None:
                # gateway mode (docs/serving.md "Streaming"): serve over
                # HTTP instead of the prompts loop — requests arrive on
                # sockets, tokens stream back as they decode
                if args.prompts:
                    raise SystemExit(
                        "--serve.prompts applies to the batch loop; with "
                        "--serve.http.port set, prompts arrive over "
                        "POST /v1/generate"
                    )
                return self._serve_http(engine, tok, args, kit)

            if args.prompts:
                with open(args.prompts) as fh:
                    prompts = [line.rstrip("\n") for line in fh if line.strip()]
            else:
                prompts = [line.rstrip("\n") for line in sys.stdin if line.strip()]
            if not prompts:
                raise SystemExit("serve: no prompts (empty file/stdin)")

            return self._serve_prompts(engine, tok, prompts, args, kit)
        finally:
            # swap transfers calibrate the per-platform link rate DURING
            # serving — persist the measured value beside spec_entries so
            # the next process prices swap-vs-recompute from evidence
            if args.decode_strategy_file \
                    and args.preemption in ("swap", "auto"):
                strategy_mod.save_registry(args.decode_strategy_file)
            # fit's teardown parity: even an exception mid-drain leaves a
            # final snapshot and a closed events file
            detach_ledger()
            ledger.update_device_gauges()
            if kit["snapshot_writer"] is not None:
                kit["snapshot_writer"].maybe_write(force=True)
            if kit["timeline_export"] is not None and kit["timeline"] is not None:
                # same stance as the snapshot: an exception mid-drain still
                # leaves the ring on disk for `obs timeline`
                n = kit["timeline"].write_jsonl(kit["timeline_export"])
                print(
                    f"[serve] timeline: wrote {n} step records to "
                    f"{kit['timeline_export']}",
                    file=sys.stderr, flush=True,
                )
            if kit["sink"] is not None:
                kit["sink"].close()

    def _serve_http(self, engine, tok, args, kit) -> list:
        """``serve --serve.http.port=N``: run the async HTTP/SSE streaming
        gateway over the built engine/fleet until ``--serve.http.max_streams``
        terminal streams (or Ctrl-C), then drain and print the final
        ``serve_stats`` line — gateway wire counters included."""
        import json
        import time

        from perceiver_io_tpu.serving.gateway import STREAM_MODES, StreamingGateway

        if args.http.stream not in STREAM_MODES:
            raise SystemExit(
                "--serve.http.stream must be one of "
                f"{'|'.join(STREAM_MODES)}, got {args.http.stream!r}"
            )
        t0 = time.monotonic()
        gateway = StreamingGateway(
            engine,
            host=args.http.host,
            port=args.http.port,
            stream=args.http.stream,
            encode=lambda text: tok.encode(text),
            decode=lambda ids: tok.decode(ids),
            registry=kit["registry"],
            tracer=engine.tracer if hasattr(engine, "tracer") else None,
            slo_monitor=kit["slo_monitor"],
            snapshot_writer=kit["snapshot_writer"],
            flight_recorder=kit["flight_recorder"],
            max_streams=args.http.max_streams,
        )
        gateway.run_in_thread()
        print(
            f"[serve] http gateway listening on {gateway.host}:{gateway.port} "
            f"(stream={args.http.stream}"
            + (f", max_streams={args.http.max_streams}"
               if args.http.max_streams is not None else "")
            + ")",
            file=sys.stderr, flush=True,
        )
        try:
            gateway.wait()
        except KeyboardInterrupt:
            print("[serve] interrupt: shutting the gateway down",
                  file=sys.stderr, flush=True)
        finally:
            gateway.close()
        engine.drain()
        if kit["slo_monitor"] is not None:
            # unconditional final poll (the _serve_prompts convention): the
            # fleet router polls at the START of each step, so the last
            # drain step's dispositions would otherwise never be diffed
            # into the monitor's error window
            kit["slo_monitor"].poll()
        wall_s = time.monotonic() - t0
        if args.stats:
            from perceiver_io_tpu.observability import default_ledger, default_registry

            stats = engine.stats()
            stats["health"] = engine.health()
            stats["wall_s"] = round(wall_s, 3)
            stats["gateway"] = gateway.stats()
            stats["metrics"] = engine.registry.snapshot()
            stats["compile_ledger"] = default_ledger().snapshot()
            stats["process_metrics"] = default_registry().snapshot()
            if kit["slo_monitor"] is not None and "slo" not in stats:
                stats["slo"] = kit["slo_monitor"].stats()
            if kit["flight_recorder"] is not None:
                stats["incident"] = kit["flight_recorder"].stats()
            if kit["timeline"] is not None and "timeline" not in stats:
                # fleet stats() has no ring of its own; the shared ring's
                # summary rides the run record (single-engine stats()
                # already embeds it)
                stats["timeline"] = kit["timeline"].summary()
            print(json.dumps({"serve_stats": stats}), flush=True)
        return []

    def _serve_prompts(self, engine, tok, prompts, args, kit) -> list:
        import json
        import time

        from perceiver_io_tpu.serving import QueueFull

        t0 = time.monotonic()
        pad_id = tok.pad_token_id or 0
        # (prompt, ServeRequest | None, error | None, trace_id | None, status)
        handles: list = []
        for p in prompts:
            ids = np.asarray(tok.encode(p), np.int32)
            try:
                # backpressure: make room BEFORE submitting so a full queue
                # drains work instead of tripping the shed counter (shed
                # should count true rejections, not this retry loop). A
                # fleet with every breaker open makes no progress until a
                # cooldown elapses — yield instead of hot-spinning (plain
                # engines never report no-progress; their step always
                # works when pending)
                while not engine.health()["ready"] and engine.pending():
                    if (
                        engine.step() == 0
                        and not getattr(engine, "last_step_made_progress", True)
                    ):
                        time.sleep(0.005)
                req = engine.submit(ids)
                handles.append((p, req, None, req.trace_id, None))
            except (ValueError, QueueFull) as e:
                # reject/shed this line, keep serving the rest; the engine
                # already emitted this submission's terminal span — carry its
                # trace ID (and the SAME terminal status the span/counters
                # use) so the error record joins against events.jsonl
                handles.append(
                    (p, None, f"{type(e).__name__}: {e}",
                     getattr(e, "trace_id", None),
                     "shed" if isinstance(e, QueueFull) else "rejected")
                )
            if kit["snapshot_writer"] is not None:
                kit["snapshot_writer"].maybe_write()
            if kit["flight_recorder"] is not None:
                kit["flight_recorder"].maybe_record()
        # CLI-driven drain (not the blocking engine.drain()): the snapshot
        # cadence must keep firing while the queue — the bulk of the run's
        # wall time — generates, or a mid-run poller sees stale telemetry.
        # pending(), not step()'s return value: a slot-engine step advances
        # one token and legitimately disposes of nothing mid-generation.
        # The SLO monitor is polled per pass for the single-engine path
        # (the fleet router polls it inside its own step()).
        slo_monitor = kit["slo_monitor"]
        fleet_polls = hasattr(engine, "slo_monitor")
        while engine.pending():
            if (
                engine.step() == 0
                and not getattr(engine, "last_step_made_progress", True)
            ):
                time.sleep(0.005)  # fleet waiting out a breaker cooldown
            if slo_monitor is not None and not fleet_polls:
                slo_monitor.poll()
            if kit["snapshot_writer"] is not None:
                kit["snapshot_writer"].maybe_write()
            if kit["flight_recorder"] is not None:
                # the incident ring's periodic "before" evidence rides the
                # same opportunistic cadence as the snapshot writer
                kit["flight_recorder"].maybe_record()
        if slo_monitor is not None:
            # unconditional final poll: the fleet router polls at the START
            # of each step, so the last step's dispositions would otherwise
            # never be diffed into the error window (a duplicate poll is an
            # idempotent counter diff — harmless for the single-engine path)
            slo_monitor.poll()
        engine.drain()  # queue already empty: just stop accepting
        wall_s = time.monotonic() - t0

        results = []
        for p, req, error, trace_id, status in handles:
            if req is not None and req.status == "ok":
                completion = tok.decode([t for t in req.result.tolist() if t != pad_id])
                results.append({
                    "prompt": p, "completion": completion,
                    "status": "ok", "trace_id": trace_id,
                })
            else:
                results.append({
                    "prompt": p,
                    "error": error if req is None else (req.error or req.status),
                    "status": status if req is None else req.status,
                    "trace_id": trace_id,
                })
        for row in results:
            print(json.dumps(row), flush=True)
        if args.stats:
            from perceiver_io_tpu.observability import default_ledger, default_registry

            stats = engine.stats()
            stats["health"] = engine.health()
            stats["wall_s"] = round(wall_s, 3)
            stats["metrics"] = engine.registry.snapshot()
            # the engine's stats() carries the ledger rollup; serve_stats is
            # the run's one durable record, so it ships the full per-executor
            # compile/memory table AND the process-wide registry (compile_*/
            # retrace_*/executor_cache_* counters, hbm/resident gauges —
            # families that live beside, not on, the engine's registry)
            stats["compile_ledger"] = default_ledger().snapshot()
            stats["process_metrics"] = default_registry().snapshot()
            if kit["slo_monitor"] is not None and "slo" not in stats:
                # fleet stats() already embeds the monitor; single-engine
                # runs attach it here so serve_stats always carries the
                # burn/breach summary when SLO targets were set
                stats["slo"] = kit["slo_monitor"].stats()
            if kit["flight_recorder"] is not None:
                # the run's one durable record names every bundle written
                stats["incident"] = kit["flight_recorder"].stats()
            if kit["timeline"] is not None and "timeline" not in stats:
                stats["timeline"] = kit["timeline"].summary()
            print(json.dumps({"serve_stats": stats}), flush=True)
        return results

    def _print_help(self) -> None:
        print(f"usage: {self.family.name} {{fit|validate|test|preproc|serve|obs}} [--flag=value ...]")
        print("flag groups: --model.* --data.* --trainer.* --optimizer.* "
              "--lr_scheduler.* --obs.* --config=<yaml> --data=<name> --ckpt=<dir>")
        print("serve: --ckpt=<dir> --serve.prompts=<file|stdin> --serve.max_new_tokens "
              "--serve.engine={bucket|slots} --serve.slots --serve.prefill_chunk "
              "--serve.decode_strategy={auto|cached|recompute} "
              "--serve.decode_strategy_file "
              "--serve.speculation={auto|off|k<K>d<D>} "
              "--serve.prompt_buckets --serve.batch_buckets --serve.warmup "
              "--serve.max_queue --serve.deadline_s "
              "--serve.replicas=<n> --serve.failover={true|false} "
              "--serve.step_timeout_s=<s>")
        print("serve autoscale: --serve.autoscale.max=<n> --serve.autoscale.min "
              "--serve.autoscale.up_cooldown_s --serve.autoscale.down_cooldown_s "
              "--serve.autoscale.up_evidence --serve.autoscale.down_evidence "
              "--serve.autoscale.queue_high --serve.autoscale.queue_low "
              "--serve.autoscale.scale_up_slots — SLO-driven fleet elasticity: "
              "burn/queue pressure scales replicas up to max, cooldown-gated "
              "zero-downtime scale-down (docs/serving.md)")
        print("serve mesh: --serve.mesh.data=<n> --serve.mesh.model=<n> "
              "--serve.mesh.device_offset=<i> — sharded serving over the "
              "parallelism mesh (slots engine): slots shard along data, "
              "attention heads + KV caches along model; with replicas each "
              "replica owns the next disjoint data x model device group "
              "(docs/serving.md \"Sharded serving\")")
        print("serve http gateway: --serve.http.port=<n|0> --serve.http.host "
              "--serve.http.stream={sse|jsonl} --serve.http.max_streams — "
              "POST /v1/generate streams tokens as they decode; GET /healthz, "
              "GET /metrics; client disconnects cancel mid-generation "
              "(docs/serving.md)")
        print("observability: --obs.events_path=<events.jsonl> --obs.snapshot_every_s "
              "--obs.snapshot_path --obs.profile_on_regress_factor "
              "--obs.trace_sample=<0..1> --obs.trace_keep_slow_ms "
              "--obs.events_max_bytes (fit and serve; docs/observability.md)")
        print("incident flight recorder: --obs.incident.dir=<dir> "
              "--obs.incident.cooldown_s --obs.incident.max_bundles "
              "--obs.incident.keep_spans — triggered bounded bundles at the "
              "serving seams (SLO breach, replica failure, pool exhaustion, "
              "autoscaler escalation, gateway mass-disconnect); analyze with "
              "obs incident --bundle=<dir>")
        print("slo (serve): --obs.slo.ttft_p95_ms --obs.slo.inter_token_p95_ms "
              "--obs.slo.error_rate --obs.slo.fast_window_s --obs.slo.slow_window_s "
              "--obs.slo.burn_rate --obs.slo.shed_factor — burn-rate monitor, "
              "breach events, fleet admission tightening")
        print("timeline (serve): --obs.timeline.steps=<n> --obs.timeline.export"
              "=<timeline.jsonl> --obs.timeline.swap_gbps — per-pass scheduler "
              "ring (admissions, slot occupancy, preemption post-mortems); "
              "analyze with obs timeline")
        print("obs report: --events=<events.jsonl> [--snapshot=<snapshot.json>] "
              "[--top N] [--json true] — offline latency/compile/padding report")
        print("obs timeline: --timeline=<timeline.jsonl> [--events=<events.jsonl>] "
              "[--snapshot=<snapshot.json>] [--trace_out=<trace.json>] — "
              "per-slot gantt + per-request decomposition + Chrome-trace export")
        print(f"data modules: {sorted(self.family.data_registry)}")


def _ctor_flag_specs(cls, prefix: str) -> Dict[str, Any]:
    """Flag specs from ``__init__`` signatures (datamodules are plain
    classes, not dataclasses). Walks the MRO while ``**kwargs`` forwards to
    the base class, so subclass flags include inherited knobs."""
    import inspect

    specs: Dict[str, Any] = {}
    for klass in cls.__mro__:
        if klass is object or "__init__" not in vars(klass):
            continue
        sig = inspect.signature(klass.__init__)
        hints = typing.get_type_hints(klass.__init__)
        has_var_kw = False
        for name, param in sig.parameters.items():
            if param.kind is inspect.Parameter.VAR_KEYWORD:
                has_var_kw = True
                continue
            if name == "self" or param.kind is inspect.Parameter.VAR_POSITIONAL:
                continue
            specs.setdefault(f"{prefix}.{name}", hints.get(name, str))
        if not has_var_kw:
            break
    return specs
