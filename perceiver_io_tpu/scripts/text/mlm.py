"""Masked language model CLI (reference ``perceiver/scripts/text/mlm.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from perceiver_io_tpu.data.text.sources import (
    BookCorpusDataModule,
    ImdbDataModule,
    ListDataModule,
    WikipediaDataModule,
    WikiTextDataModule,
    SyntheticTextDataModule,
)
from perceiver_io_tpu.models.text.common import TextEncoderConfig
from perceiver_io_tpu.models.text.mlm import (
    MaskedLanguageModel,
    MaskedLanguageModelConfig,
    TextDecoderConfig,
)
from perceiver_io_tpu.scripts.cli import CLI, ModelFamily
from perceiver_io_tpu.training.tasks import mlm_loss_fn

DATA = {
    "synthetic": SyntheticTextDataModule,
    "wikitext": WikiTextDataModule,
    "imdb": ImdbDataModule,
    "bookcorpus": BookCorpusDataModule,
    "wikipedia": WikipediaDataModule,
    "list": ListDataModule,
}


def _link(dm, values):
    """data.vocab_size/max_seq_len → encoder + decoder (reference
    ``mlm.py:12-16``)."""
    values.setdefault("model.encoder.vocab_size", dm.vocab_size)
    values.setdefault("model.encoder.max_seq_len", dm.max_seq_len)
    values.setdefault("model.decoder.vocab_size", dm.vocab_size)
    values.setdefault("model.decoder.max_seq_len", dm.max_seq_len)


FAMILY = ModelFamily(
    name="perceiver_io_tpu.scripts.text.mlm",
    config_class=MaskedLanguageModelConfig,
    nested={"encoder": TextEncoderConfig, "decoder": TextDecoderConfig},
    data_registry=DATA,
    build_model=lambda cfg, dm: MaskedLanguageModel(cfg, dtype=jnp.bfloat16),
    make_loss=lambda model, cfg: mlm_loss_fn(model),
    init_args=lambda cfg, batch: ((jnp.asarray(batch["input_ids"][:1]),), {}),
    link=_link,
    # Paper config (reference ``mlm.py:18-40``): 8 cross-attention v channels
    # etc. are already the dataclass defaults; the CLI pins the data task.
    defaults={
        "data.task": "mlm",
        "model.num_latents": 256,
        "model.num_latent_channels": 1280,
        "lr_scheduler.name": "cosine",
        "lr_scheduler.warmup_steps": 1000,
    },
)


def main(argv=None):
    return CLI(FAMILY).main(argv)


if __name__ == "__main__":
    main()
