"""CLI entry points."""
