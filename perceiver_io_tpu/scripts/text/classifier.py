"""Text classifier CLI (reference ``perceiver/scripts/text/classifier.py``).

Two-stage training parity (reference ``classifier/lightning.py:14-44``):
``--model.encoder.params=<pretrained-dir>`` warm-starts the encoder from a
saved MLM checkpoint; ``--model.encoder.freeze=true`` masks its parameters
out of the optimizer (decoder-only stage).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from perceiver_io_tpu.data.text.sources import ImdbDataModule, ListDataModule, SyntheticTextDataModule
from perceiver_io_tpu.models.text.classifier import TextClassifier, TextClassifierConfig
from perceiver_io_tpu.models.core.config import ClassificationDecoderConfig
from perceiver_io_tpu.models.text.common import TextEncoderConfig
from perceiver_io_tpu.scripts.cli import CLI, ModelFamily
from perceiver_io_tpu.training.tasks import classifier_loss_fn

DATA = {
    "synthetic": SyntheticTextDataModule,
    "imdb": ImdbDataModule,
    "list": ListDataModule,
}


def _link(dm, values):
    values.setdefault("model.encoder.vocab_size", dm.vocab_size)
    values.setdefault("model.encoder.max_seq_len", dm.max_seq_len)
    if dm.num_classes is not None:
        values.setdefault("model.decoder.num_classes", dm.num_classes)


def _initial_params(model, cfg, dm):
    """Warm start: replace the fresh encoder subtree with the pretrained one
    (reference ``classifier/lightning.py:30-37``)."""
    if cfg.encoder.params is None:
        return None
    from perceiver_io_tpu.training.checkpoint import load_subtree

    batch_ids = jnp.zeros((1, cfg.encoder.max_seq_len), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), batch_ids)["params"]
    params = dict(params)
    params["encoder"] = load_subtree(
        cfg.encoder.params, "encoder", target=params["encoder"]
    )
    return params


FAMILY = ModelFamily(
    name="perceiver_io_tpu.scripts.text.classifier",
    config_class=TextClassifierConfig,
    nested={"encoder": TextEncoderConfig, "decoder": ClassificationDecoderConfig},
    data_registry=DATA,
    build_model=lambda cfg, dm: TextClassifier(cfg),
    make_loss=lambda model, cfg: classifier_loss_fn(model),
    init_args=lambda cfg, batch: ((jnp.asarray(batch["input_ids"][:1]),), {}),
    link=_link,
    initial_params=_initial_params,
    frozen_prefixes=lambda cfg: ("encoder",) if cfg.encoder.freeze else (),
    defaults={
        "data.task": "clf",
        "model.num_latents": 256,
        "model.num_latent_channels": 1280,
        "model.decoder.num_output_query_channels": 1280,
        "lr_scheduler.name": "constant",
        "lr_scheduler.warmup_steps": 100,
    },
)


def main(argv=None):
    return CLI(FAMILY).main(argv)


if __name__ == "__main__":
    main()
