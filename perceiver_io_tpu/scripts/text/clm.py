"""Causal language model CLI (reference ``perceiver/scripts/text/clm.py``):

    python -m perceiver_io_tpu.scripts.text.clm fit --data=wikitext \
        --data.dataset_dir=.cache/wikitext --trainer.max_steps=10000
"""
from __future__ import annotations

import jax.numpy as jnp

from perceiver_io_tpu.data.text.sources import (
    BookCorpusDataModule,
    Enwik8DataModule,
    ListDataModule,
    WikipediaDataModule,
    WikiTextDataModule,
    SyntheticTextDataModule,
)
from perceiver_io_tpu.data.text.streaming import C4DataModule
from perceiver_io_tpu.models.text.clm import CausalLanguageModel, CausalLanguageModelConfig
from perceiver_io_tpu.scripts.cli import CLI, ModelFamily
from perceiver_io_tpu.training.tasks import clm_loss_fn

DATA = {
    "synthetic": SyntheticTextDataModule,
    "wikitext": WikiTextDataModule,
    "enwik8": Enwik8DataModule,
    "bookcorpus": BookCorpusDataModule,
    "wikipedia": WikipediaDataModule,
    "c4": C4DataModule,
    "list": ListDataModule,
}


def _link(dm, values):
    """data.vocab_size/max_seq_len → model.* (reference ``clm.py:12-14``)."""
    values.setdefault("model.vocab_size", dm.vocab_size)
    values.setdefault("model.max_seq_len", dm.max_seq_len)


FAMILY = ModelFamily(
    name="perceiver_io_tpu.scripts.text.clm",
    config_class=CausalLanguageModelConfig,
    data_registry=DATA,
    build_model=lambda cfg, dm: CausalLanguageModel(cfg, dtype=jnp.bfloat16),
    make_loss=lambda model, cfg: clm_loss_fn(model, cfg.max_latents),
    init_args=lambda cfg, batch: (
        (jnp.asarray(batch["input_ids"][:1]), cfg.max_seq_len - cfg.max_latents),
        {},
    ),
    link=_link,
    # Paper config of the reference CLI (``scripts/text/clm.py:16-23``).
    defaults={
        "data.task": "clm",
        "data.padding_side": "left",
        "model.max_latents": 512,
        "model.num_channels": 512,
        "lr_scheduler.name": "cosine",
        "lr_scheduler.warmup_steps": 200,
    },
)


def main(argv=None):
    return CLI(FAMILY).main(argv)


if __name__ == "__main__":
    main()
