"""Training CLI layer — the reference's ``perceiver/scripts/`` surface
(SURVEY.md §2.4) rebuilt on the dataclass CLI engine in
:mod:`perceiver_io_tpu.scripts.cli`."""
