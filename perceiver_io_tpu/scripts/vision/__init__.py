"""CLI entry points."""
