"""Image classifier CLI (reference ``perceiver/scripts/vision/image_classifier.py``):

    python -m perceiver_io_tpu.scripts.vision.image_classifier fit \
        --data=mnist --trainer.max_steps=5000
"""
from __future__ import annotations

import jax.numpy as jnp

from perceiver_io_tpu.data.vision import MNISTDataModule, SyntheticImageDataModule
from perceiver_io_tpu.models.core.config import ClassificationDecoderConfig
from perceiver_io_tpu.models.vision.image_classifier import (
    ImageClassifier,
    ImageClassifierConfig,
    ImageEncoderConfig,
)
from perceiver_io_tpu.scripts.cli import CLI, ModelFamily
from perceiver_io_tpu.training.tasks import image_classifier_loss_fn

DATA = {"mnist": MNISTDataModule, "synthetic": SyntheticImageDataModule}


def _link(dm, values):
    values.setdefault("model.encoder.image_shape", dm.image_shape)
    values.setdefault("model.decoder.num_classes", dm.num_classes)


FAMILY = ModelFamily(
    name="perceiver_io_tpu.scripts.vision.image_classifier",
    config_class=ImageClassifierConfig,
    nested={"encoder": ImageEncoderConfig, "decoder": ClassificationDecoderConfig},
    data_registry=DATA,
    build_model=lambda cfg, dm: ImageClassifier(cfg),
    make_loss=lambda model, cfg: image_classifier_loss_fn(model),
    init_args=lambda cfg, batch: ((jnp.asarray(batch["image"][:1]),), {}),
    link=_link,
    # Paper config of the reference CLI (``vision/image_classifier.py:8-30``):
    # 32 latents × 128 channels on MNIST.
    defaults={
        "model.num_latents": 32,
        "model.num_latent_channels": 128,
        "model.encoder.num_frequency_bands": 32,
        "model.decoder.num_output_query_channels": 128,
        "lr_scheduler.name": "cosine",
        "lr_scheduler.warmup_steps": 500,
    },
)


def main(argv=None):
    return CLI(FAMILY).main(argv)


if __name__ == "__main__":
    main()
