"""CLI entry points."""
