"""Symbolic audio model CLI (reference ``perceiver/scripts/audio/symbolic.py``):

    python -m perceiver_io_tpu.scripts.audio.symbolic fit --data=maestro \
        --data.dataset_dir=.cache/maestro --trainer.max_steps=10000
"""
from __future__ import annotations

import jax.numpy as jnp

from perceiver_io_tpu.data.audio import (
    GiantMidiPianoDataModule,
    MaestroV3DataModule,
    SymbolicAudioDataModule,
    SyntheticSymbolicAudioDataModule,
)
from perceiver_io_tpu.models.audio.symbolic import (
    SymbolicAudioModel,
    SymbolicAudioModelConfig,
)
from perceiver_io_tpu.scripts.cli import CLI, ModelFamily
from perceiver_io_tpu.training.tasks import clm_loss_fn

DATA = {
    "synthetic": SyntheticSymbolicAudioDataModule,
    "maestro": MaestroV3DataModule,
    "giantmidi": GiantMidiPianoDataModule,
    "symbolic": SymbolicAudioDataModule,
}


def _link(dm, values):
    values.setdefault("model.vocab_size", dm.vocab_size)
    values.setdefault("model.max_seq_len", dm.max_seq_len)


FAMILY = ModelFamily(
    name="perceiver_io_tpu.scripts.audio.symbolic",
    config_class=SymbolicAudioModelConfig,
    data_registry=DATA,
    build_model=lambda cfg, dm: SymbolicAudioModel(cfg, dtype=jnp.bfloat16),
    make_loss=lambda model, cfg: clm_loss_fn(model, cfg.max_latents),
    init_args=lambda cfg, batch: (
        (jnp.asarray(batch["input_ids"][:1]), cfg.max_seq_len - cfg.max_latents),
        {},
    ),
    link=_link,
    # Paper config (reference ``audio/symbolic.py:9-29``): GiantMIDI model,
    # 6144 ctx / 2048 latents when trained at full scale.
    defaults={
        "model.max_latents": 2048,
        "model.num_channels": 768,
        "lr_scheduler.name": "cosine",
        "lr_scheduler.warmup_steps": 500,
    },
)


def main(argv=None):
    return CLI(FAMILY).main(argv)


if __name__ == "__main__":
    main()
