"""Cross-cutting utilities: analytic FLOPs/params estimation for the scaling
study (reference ``examples/scaling/clm/scaling/flops.py``) and first-class
``jax.profiler`` tracing (the reference has no profiling story, SURVEY.md §5.1).
"""
from perceiver_io_tpu.utils.flops import (
    ComputeEstimator,
    count_params,
    num_training_steps,
    num_training_tokens,
    training_flops,
)
from perceiver_io_tpu.utils.profiling import StepTimer, trace

__all__ = [
    "ComputeEstimator",
    "count_params",
    "num_training_tokens",
    "num_training_steps",
    "training_flops",
    "StepTimer",
    "trace",
]
