"""Analytic compute/parameter estimators for the Perceiver AR scaling study.

Capability parity with the reference's estimator
(``examples/scaling/clm/scaling/flops.py:7-190``; assumptions from Kaplan et
al. §2.1 and the Chinchilla appendix): training FLOPs *per latent token* for
the decoder-equivalent self-attention stack and for the prefix
cross-attention extra, dataset-size helpers, and ``C ≈ 6N``.

Differences from the reference: parameter counts come from
``jax.eval_shape`` over the real flax model — no materialized weights, so
sweeping a config grid is free — and :func:`training_flops_total` gives the
absolute per-step FLOPs the benchmark uses for MFU accounting.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Optional


def count_params(model, *init_args, **init_kwargs) -> int:
    """Trainable parameter count via ``jax.eval_shape`` (no allocation)."""
    import jax
    import numpy as np

    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), *init_args, **init_kwargs)
    )
    return int(
        sum(np.prod(leaf.shape) for leaf in jax.tree.leaves(shapes.get("params", shapes)))
    )


@dataclass
class ComputeEstimator:
    """Training FLOPs per latent token for Perceiver AR (reference
    ``flops.py:7-88`` semantics: forward ≈ ⅓ of forward+backward)."""

    vocab_size: int
    max_seq_len: int
    num_latents: int

    @property
    def num_prefix(self) -> int:
        return self.max_seq_len - self.num_latents

    # -- per-component forward FLOPs per latent token ----------------------
    @staticmethod
    def _input_embed(num_channels: int) -> int:
        return 4 * num_channels

    @staticmethod
    def _mlp_layer(num_channels: int) -> int:
        return 16 * num_channels**2

    def _self_attn_layer(self, num_channels: int) -> int:
        qkv = 6 * num_channels**2
        attn = 2 * num_channels * self.num_latents
        out = 2 * num_channels**2
        return qkv + attn + out

    def _cross_attn_layer(self, num_channels: int) -> int:
        kv = 4 * num_channels**2
        attn = 2 * num_channels * self.num_latents
        return kv + attn

    def _final_logits(self, num_channels: int) -> int:
        return 2 * num_channels * self.vocab_size

    # -- public surface ----------------------------------------------------
    def self_attn(self, num_channels: int, num_layers: int) -> int:
        """fwd+bwd FLOPs per latent token of the decoder-equivalent stack
        (``num_layers`` includes the hybrid cross-attention layer)."""
        forward = (
            self._input_embed(num_channels)
            + self._self_attn_layer(num_channels) * num_layers
            + self._mlp_layer(num_channels) * num_layers
            + self._final_logits(num_channels)
        )
        return forward * 3

    def cross_attn(self, num_channels: int, prefix_dropout: float = 0.5) -> int:
        """fwd+bwd FLOPs per latent token of the prefix extra."""
        ratio = self.num_prefix / self.num_latents
        embed_prefix = self._input_embed(num_channels) * ratio
        attn_prefix = self._cross_attn_layer(num_channels) * ratio * (1.0 - prefix_dropout)
        return int(embed_prefix + attn_prefix) * 3

    def total(self, num_channels: int, num_layers: int, prefix_dropout: float = 0.5) -> int:
        return self.self_attn(num_channels, num_layers) + self.cross_attn(
            num_channels, prefix_dropout
        )


def flops_approx(num_params: int) -> int:
    """Kaplan ``C = 6N`` fwd+bwd FLOPs per token approximation."""
    return 6 * num_params


@dataclass
class ScalingLaw:
    """Compute-optimal allocation ``N_opt = k_n·C^a``, ``D_opt = k_d·C^b``
    (reference ``examples/scaling/clm/scaling/laws.py:7-35``)."""

    a: float
    b: float
    k_n: float
    k_d: float

    def n_opt(self, flops: float) -> float:
        return self.k_n * flops**self.a

    def d_opt(self, flops: float) -> float:
        return self.k_d * flops**self.b

    def __str__(self) -> str:
        return (
            f"N_opt = {self.k_n:.4f} * C ** {self.a:.2f}\n"
            f"D_opt = {self.k_d:.4f} * C ** {self.b:.2f}"
        )


def fit_power_law(xs, ys, m: float, k0: float = 0.5) -> float:
    """Least-squares fit of ``y = k·x^m`` for fixed exponent ``m``: closed
    form ``k = Σ(y·x^m) / Σ(x^2m)`` — no scipy dependency needed."""
    import numpy as np

    xs = np.asarray(xs, dtype=np.float64) ** m
    ys = np.asarray(ys, dtype=np.float64)
    return float((xs * ys).sum() / (xs * xs).sum())


def fit_scaling_law(flops_arr, params_arr, tokens_arr, a: float, b: float) -> ScalingLaw:
    """Fit compute-optimal coefficients from (C, N, D) triples of the runs on
    the loss-vs-compute frontier (reference ``laws.py:25-28``)."""
    return ScalingLaw(
        a=a,
        b=b,
        k_n=fit_power_law(flops_arr, params_arr, m=a),
        k_d=fit_power_law(flops_arr, tokens_arr, m=b),
    )


def num_training_tokens(num_steps: int, num_latents: int, batch_size: int) -> int:
    return batch_size * num_latents * num_steps


def num_training_steps(num_tokens: int, num_latents: int, batch_size: int) -> int:
    return math.ceil(num_tokens / num_latents / batch_size)


def training_flops(
    estimator: ComputeEstimator,
    num_channels: int,
    num_layers: int,
    num_steps: int,
    batch_size: int,
    prefix_dropout: float = 0.5,
) -> tuple:
    """(total training FLOPs, total latent tokens) for a run — the quantity
    the compute-optimal scaling curves are plotted over."""
    tokens = num_training_tokens(num_steps, estimator.num_latents, batch_size)
    per_token = estimator.total(num_channels, num_layers, prefix_dropout)
    return per_token * tokens, tokens


def training_flops_per_step(
    estimator: ComputeEstimator,
    num_channels: int,
    num_layers: int,
    batch_size: int,
    prefix_dropout: float = 0.0,
) -> int:
    """Absolute fwd+bwd FLOPs of ONE training step — MFU accounting for the
    benchmark (eval-mode prefix_dropout = 0 counts the full prefix)."""
    per_token = estimator.total(num_channels, num_layers, prefix_dropout)
    return per_token * batch_size * estimator.num_latents
