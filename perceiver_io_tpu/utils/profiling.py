"""Profiling — first-class ``jax.profiler`` capture and step timing.

The reference has no tracing/profiling subsystem at all (SURVEY.md §5.1);
this is the TPU-native upgrade: :func:`trace` wraps a region in a
``jax.profiler`` capture viewable in TensorBoard/Perfetto (device timelines,
HLO cost attribution, HBM usage), and :class:`StepTimer` measures steady-state
step time with correct ``block_until_ready`` fencing — the number
``bench.py`` reports.
"""
from __future__ import annotations

import contextlib
import time
from typing import Callable, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str, *, host_tracer_level: int = 2):
    """Capture a ``jax.profiler`` trace of the enclosed region:

        with trace("logs/profile"):
            state, metrics = train_step(state, batch, rng)
            jax.block_until_ready(metrics)
    """
    # ProfileOptions landed after jax 0.4.x; on older jax start_trace takes
    # no options object and host_tracer_level stays at its default (same
    # getattr version-shim discipline as pltpu.CompilerParams).
    options_cls = getattr(jax.profiler, "ProfileOptions", None)
    if options_cls is not None:
        options = options_cls()
        options.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(log_dir, profiler_options=options)
    else:
        jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Steady-state step timing: warmup (compile) steps excluded, device
    queue drained per sample so host dispatch can't hide device time."""

    def __init__(self, warmup: int = 2):
        self.warmup = warmup

    def measure(
        self,
        step_fn: Callable[[], object],
        *,
        iters: int = 20,
        flops_per_step: Optional[int] = None,
        peak_flops: Optional[float] = None,
        registry=None,
        name: str = "step_timer",
    ) -> dict:
        """:param step_fn: zero-arg callable returning device output(s).
        :param flops_per_step: if given, report achieved FLOP/s.
        :param peak_flops: if also given, report MFU against it.
        :param registry: optional
            :class:`~perceiver_io_tpu.observability.MetricsRegistry` — the
            measured numbers are published as ``<name>_*`` gauges so bench
            timing exports through the same path as live telemetry.
        """
        for _ in range(self.warmup):
            jax.block_until_ready(step_fn())
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = step_fn()
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters

        result = {"step_time_s": dt, "steps_per_sec": 1.0 / dt}
        if flops_per_step:
            result["flops_per_sec"] = flops_per_step / dt
            if peak_flops:
                result["mfu"] = flops_per_step / dt / peak_flops
        if registry is not None:
            registry.set_gauge(f"{name}_step_time_ms", dt * 1e3)
            registry.set_gauge(f"{name}_steps_per_sec", result["steps_per_sec"])
            if "flops_per_sec" in result:
                registry.set_gauge(f"{name}_flops_per_sec", result["flops_per_sec"])
            if "mfu" in result:
                registry.set_gauge(f"{name}_mfu", result["mfu"])
        return result
