"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

The reference has **no** sequence parallelism: it reaches long context purely
architecturally (Perceiver AR latent bottleneck, SURVEY.md §5.7). Going
beyond parity, this module shards the *sequence* dimension of attention over
the mesh: each device holds one contiguous chunk of q and of k/v, and k/v
chunks rotate around the ring via ``jax.lax.ppermute`` (one ICI hop per
step) while each device folds every visiting chunk into an online-softmax
accumulator (running max / running sum — the same math as the Pallas flash
kernel, at ring-block granularity). Peak memory per device is
O(local_q × local_kv) instead of O(n²), and the ppermute of the next chunk
overlaps with compute on the current one under XLA's async collectives.

Masking matches :func:`perceiver_io_tpu.ops.attention.dot_product_attention`:
right-aligned causal of unequal global q/kv lengths (offset ``j - i``,
reference ``modules.py:120-125``) and boolean key pad masks (True = pad).
Chunks are contiguous: global q row ``s·i_loc + r``, global kv col
``src·j_loc + c`` for the chunk originating on device ``src``.

Two entry points:

- :func:`ring_attention` — per-device body, for call sites already inside
  ``shard_map`` (e.g. a fully sequence-parallel train step);
- :func:`ring_attention_sharded` — standalone: takes mesh-sharded global
  arrays, applies ``shard_map`` over the given axis itself.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_MASK = -0.7 * float(jnp.finfo(jnp.float32).max)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    axis_size: int,
    pad_mask: Optional[jnp.ndarray] = None,
    causal: bool = False,
) -> jnp.ndarray:
    """Per-device ring attention body (call inside ``shard_map``).

    :param q: local ``(b, h, i_loc, d)`` pre-scaled queries — the chunk of
        the global query this device owns.
    :param k: local ``(b, h, j_loc, d)`` keys.
    :param v: local ``(b, h, j_loc, dv)`` values.
    :param pad_mask: local boolean ``(b, j_loc)``, True marks padding.
    :param axis_name: mesh axis the sequence is sharded over.
    :param axis_size: static size of that axis (= number of ring steps).
    :param causal: right-aligned causal over the *global* lengths.
    :return: local ``(b, h, i_loc, dv)`` output chunk.
    """
    s = jax.lax.axis_index(axis_name)
    b, h, i_loc, _ = q.shape
    j_loc, dv = k.shape[2], v.shape[3]
    # Offset of the shifted causal diagonal, from the static global lengths.
    offset = (j_loc - i_loc) * axis_size if causal else None

    qf = q
    m = jnp.full((b, h, i_loc, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, i_loc, 1), jnp.float32)
    acc = jnp.zeros((b, h, i_loc, dv), jnp.float32)

    perm = [(d, (d + 1) % axis_size) for d in range(axis_size)]
    k_t, v_t, pad_t = k, v, pad_mask
    for t in range(axis_size):
        src = (s - t) % axis_size  # device the visiting chunk originated on

        logits = jnp.einsum("bhic,bhjc->bhij", qf, k_t, preferred_element_type=jnp.float32)
        logits = logits.astype(jnp.float32)
        allowed = None
        if pad_t is not None:
            allowed = ~pad_t[:, None, None, :]
        if causal:
            rows = s * i_loc + jnp.arange(i_loc)[:, None]
            cols = src * j_loc + jnp.arange(j_loc)[None, :]
            cm = (cols <= rows + offset)[None, None]
            allowed = cm if allowed is None else jnp.logical_and(allowed, cm)
        if allowed is not None:
            logits = jnp.where(allowed, logits, _MASK)

        m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        if allowed is not None:
            p = jnp.where(allowed, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhij,bhjc->bhic", p.astype(v_t.dtype), v_t, preferred_element_type=jnp.float32
        ).astype(jnp.float32)
        m = m_new

        if t + 1 < axis_size:
            k_t = jax.lax.ppermute(k_t, axis_name, perm)
            v_t = jax.lax.ppermute(v_t, axis_name, perm)
            if pad_t is not None:
                pad_t = jax.lax.ppermute(pad_t, axis_name, perm)

    safe_l = jnp.where(l > 0.0, l, 1.0)
    return (acc / safe_l).astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    axis_name: str = "seq",
    pad_mask: Optional[jnp.ndarray] = None,
    causal: bool = False,
) -> jnp.ndarray:
    """Ring attention over *global* arrays sharded on ``axis_name``.

    Applies ``shard_map`` itself: q/k/v are (re)sharded so their sequence
    dimension is split over the axis, every other mesh axis replicated.
    """
    if causal and k.shape[2] < q.shape[2]:
        raise ValueError("causal ring attention requires kv_len >= q_len")
    n_seq = mesh.shape[axis_name]
    if q.shape[2] % n_seq or k.shape[2] % n_seq:
        raise ValueError(
            f"q_len={q.shape[2]} and kv_len={k.shape[2]} must divide the "
            f"'{axis_name}' axis size {n_seq}"
        )

    seq_spec = P(None, None, axis_name, None)
    pad_spec = P(None, axis_name)
    in_specs = (seq_spec, seq_spec, seq_spec) + ((pad_spec,) if pad_mask is not None else ())
    body = functools.partial(
        _ring_body, axis_name=axis_name, axis_size=n_seq, causal=causal
    )
    sm = getattr(jax, "shard_map", None)
    if sm is not None:  # jax >= 0.6: top-level API, replication check is check_vma
        fn = sm(
            body, mesh=mesh, in_specs=in_specs, out_specs=seq_spec,
            check_vma=False,
        )
    else:  # older jax: experimental module, same check spelled check_rep
        from jax.experimental.shard_map import shard_map as _exp_shard_map

        fn = _exp_shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=seq_spec,
            check_rep=False,
        )
    args = (q, k, v) + ((pad_mask,) if pad_mask is not None else ())
    return fn(*args)


def _ring_body(q, k, v, pad_mask=None, *, axis_name, axis_size, causal):
    return ring_attention(
        q, k, v, axis_name=axis_name, axis_size=axis_size,
        pad_mask=pad_mask, causal=causal,
    )
