"""Parameter & batch partitioning rules.

Replaces the reference's FSDP wrap policy (``transformer_auto_wrap_policy``
over attention layers, reference ``perceiver/scripts/text/clm_fsdp.py:24-37``)
with declarative ``PartitionSpec`` rules — XLA GSPMD then emits the
all-gathers and reduce-scatters torch FSDP performs imperatively.

Two composable rule sets:

- **Tensor parallelism** (``model`` axis): attention head projections are
  sharded on the head dimension (q/k/v output, o input), the MLP on its
  hidden dimension. These are the canonical Megatron shardings, which make
  the two collectives per layer an all-reduce of activations.
- **FSDP** (``fsdp`` axis): every parameter's largest still-unsharded,
  evenly-divisible dimension is sharded. Parameters too small to split
  stay replicated (same effect as torch FSDP leaving small leaves in the
  root wrap unit).

The rules operate on flax param-path strings, so they apply uniformly to
every model family in :mod:`perceiver_io_tpu.models`.
"""
from __future__ import annotations

import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from perceiver_io_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_SEQ,
    BATCH_AXES,
)

# (path regex, dim) — dim of the kernel to shard over the `model` axis.
# Column-parallel (output dim): q/k/v projections, MLP up-projection.
# Row-parallel (input dim): attention output projection, MLP down-projection.
_TP_KERNEL_RULES: Tuple[Tuple[str, int], ...] = (
    (r"(q_proj|k_proj|v_proj)/kernel$", 1),
    (r"o_proj/kernel$", 0),
    (r"mlp/hidden/kernel$", 1),
    (r"mlp/out/kernel$", 0),
)

# Biases of column-parallel layers follow their kernel's output sharding;
# row-parallel biases stay replicated (added after the allreduce).
_TP_BIAS_RULES: Tuple[str, ...] = (
    r"(q_proj|k_proj|v_proj)/bias$",
    r"mlp/hidden/bias$",
)


def _tp_spec(path: str, shape: Tuple[int, ...], model_size: int) -> list:
    spec: list = [None] * len(shape)
    if model_size <= 1:
        return spec
    for pattern, dim in _TP_KERNEL_RULES:
        if re.search(pattern, path) and shape[dim] % model_size == 0:
            spec[dim] = AXIS_MODEL
            return spec
    for pattern in _TP_BIAS_RULES:
        if re.search(pattern, path) and shape[-1] % model_size == 0:
            spec[-1] = AXIS_MODEL
            return spec
    return spec


def infer_param_spec(
    path: str,
    value: Any,
    mesh: Mesh,
    *,
    min_fsdp_size: int = 2**14,
) -> P:
    """PartitionSpec for one parameter: TP rules first, then FSDP shards the
    largest remaining dimension. ``min_fsdp_size`` keeps tiny leaves (norms,
    biases) replicated — gathering them costs more than storing them."""
    shape = tuple(np.shape(value))
    spec = _tp_spec(path, shape, mesh.shape.get(AXIS_MODEL, 1))

    fsdp_size = mesh.shape.get(AXIS_FSDP, 1)
    if fsdp_size > 1 and np.size(value) >= min_fsdp_size:
        dims = sorted(range(len(shape)), key=lambda d: shape[d], reverse=True)
        for d in dims:
            if spec[d] is None and shape[d] % fsdp_size == 0:
                spec[d] = AXIS_FSDP
                break
    return P(*spec)


def _flatten_path(key_path) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in key_path
    )


def infer_param_specs(params, mesh: Mesh, *, min_fsdp_size: int = 2**14):
    """Pytree of PartitionSpecs matching ``params``."""
    return jax.tree_util.tree_map_with_path(
        lambda kp, v: infer_param_spec(
            _flatten_path(kp), v, mesh, min_fsdp_size=min_fsdp_size
        ),
        params,
    )


def param_shardings(params_or_specs, mesh: Mesh):
    """NamedShardings for a param pytree (or a pytree of PartitionSpecs)."""
    def to_sharding(leaf):
        spec = leaf if isinstance(leaf, P) else None
        if spec is None:
            raise TypeError("expected a pytree of PartitionSpec")
        return NamedSharding(mesh, spec)

    if all(isinstance(l, P) for l in jax.tree_util.tree_leaves(params_or_specs)):
        return jax.tree_util.tree_map(to_sharding, params_or_specs)
    specs = infer_param_specs(params_or_specs, mesh)
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs)


def shard_params(params, mesh: Mesh):
    """Place a (host or single-device) param pytree onto the mesh according
    to the inferred specs — the moment FSDP materializes its shards."""
    return jax.device_put(params, param_shardings(params, mesh))


# -- serving KV / slot-state rules (docs/serving.md "Sharded serving") ------
#
# The slot engine's persistent decode state (``serving/slots.py``) is the
# serving-side analogue of the param tree: named leaves with fixed layouts.
# The rules mirror the Megatron TP discipline above — attention heads (and
# everything keyed by them: dense per-slot caches, the paged pool's flat
# ``pool_k``/``pool_v``, the chunked-prefill staging caches) shard along
# ``model``; the slot/batch dimension shards along ``data``. Pool arrays are
# deliberately NOT data-sharded: block tables address one shared pool, so
# every data shard must see every page (sharing the pool across slots is the
# paged layout's whole point). A dimension that does not divide its axis
# falls back to replication on that dimension — same stance as the FSDP
# rule's small-leaf fallback.
#
# (name regex, per-dim axis template). Longest/most-specific first; matched
# against the leaf's path ("stack_k/0" for tuple entries).
SERVING_STATE_RULES: Tuple[Tuple[str, Tuple], ...] = (
    # (pool_tokens, heads, head_dim): shared across slots, heads sharded
    (r"^(pool_k|pool_v)$", (None, AXIS_MODEL, None)),
    # (pool_tokens, heads, 1) int8-layout dequant scales: they address by
    # the same (position, head) coordinates as the pool, so they shard
    # WITH their blocks along model (the trailing size-1 dim replicates)
    (r"^(scale_k|scale_v)$", (None, AXIS_MODEL, None)),
    # (1, heads, n, head_dim) batch-1 staging caches (chunked prefill)
    (r"^(stage_k|stage_v)$", (None, AXIS_MODEL, None, None)),
    # (slots, heads, n, head_dim) dense per-slot caches
    (r"^(cross_k|cross_v|stack_k|stack_v)(/\d+)?$",
     (AXIS_DATA, AXIS_MODEL, None, None)),
    # (slots, n) / (slots, vocab) / (slots, pages)
    (r"^(window|logits|table)$", (AXIS_DATA, None)),
    # (slots,) per-row vectors (and the decode step's token output)
    (r"^(pad|length|m|steps|tokens)$", (AXIS_DATA,)),
)


def serving_state_spec(name: str, shape: Tuple[int, ...], mesh: Mesh) -> P:
    """PartitionSpec for one slot-state leaf by name. Unknown names and
    non-divisible dimensions replicate — the safe default; the sharded
    serving layer validates the load-bearing divisibilities (slots % data,
    heads % model) loudly at engine construction instead."""
    for pattern, template in SERVING_STATE_RULES:
        if re.search(pattern, name):
            spec: list = [None] * len(shape)
            for dim, axis in enumerate(template[: len(shape)]):
                if axis is None:
                    continue
                size = mesh.shape.get(axis, 1)
                if size > 1 and shape[dim] % size == 0:
                    spec[dim] = axis
            return P(*spec)
    return P()


def serving_state_specs(state, mesh: Mesh):
    """Pytree of PartitionSpecs matching a slot-engine state dict."""
    return jax.tree_util.tree_map_with_path(
        lambda kp, v: serving_state_spec(
            _flatten_path(kp), tuple(np.shape(v)), mesh
        ),
        state,
    )


def batch_spec(
    mesh: Mesh, *, ndim: int = 2, shard_seq: bool = False, stacked_steps: bool = False
) -> P:
    """PartitionSpec for a batch array: leading dim over (data, fsdp), and
    optionally the sequence dim over ``seq`` (context parallelism).
    ``stacked_steps`` marks arrays with an extra leading steps dim — shape
    ``(n_steps, batch, ...)`` for the multi-step-in-jit train loop — which is
    scanned over, never sharded."""
    spec: list = [BATCH_AXES] + [None] * (ndim - 1)
    if stacked_steps:
        spec = [None, BATCH_AXES] + [None] * (ndim - 2)
    seq_dim = 2 if stacked_steps else 1
    if shard_seq and ndim > seq_dim and mesh.shape.get(AXIS_SEQ, 1) > 1:
        spec[seq_dim] = AXIS_SEQ
    return P(*spec)


def batch_sharding(
    mesh: Mesh, *, ndim: int = 2, shard_seq: bool = False, stacked_steps: bool = False
) -> NamedSharding:
    return NamedSharding(
        mesh, batch_spec(mesh, ndim=ndim, shard_seq=shard_seq, stacked_steps=stacked_steps)
    )


def shard_batch(batch, mesh: Mesh, *, shard_seq: bool = False, stacked_steps: bool = False):
    """Device-put a pytree of host batch arrays with batch-dim sharding.

    On multi-host pods, per-host arrays should instead be assembled with
    ``jax.make_array_from_process_local_data`` — see
    :mod:`perceiver_io_tpu.parallel.multihost`.
    """
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x,
            batch_sharding(
                mesh, ndim=np.ndim(x), shard_seq=shard_seq, stacked_steps=stacked_steps
            ),
        ),
        batch,
    )
