"""Sharded train-step factory.

One jitted function replaces the reference's whole strategy stack: Lightning
``training_step`` + DDP gradient allreduce + FSDP gather/scatter + fairscale
checkpointing (reference ``perceiver/model/core/lightning.py:44-58``,
``perceiver/scripts/text/clm_fsdp.py:40-83``). Sharding annotations on the
state and batch make XLA emit every collective; the same compiled step runs
on a single chip (degenerate mesh) or a pod.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding

from perceiver_io_tpu.parallel.partition import infer_param_specs


class TrainState(struct.PyTreeNode):
    """Step counter + params + optimizer state. The optimizer transformation
    itself is static (not a pytree leaf), mirroring optax convention."""

    step: jnp.ndarray
    params: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads: Any) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt_state,
        )

    @classmethod
    def create(cls, params: Any, tx: optax.GradientTransformation) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            tx=tx,
        )


def state_shardings(
    state_or_shapes: TrainState, mesh: Mesh, *, min_fsdp_size: int = 2**14
) -> TrainState:
    """Shardings for a TrainState (or its ``jax.eval_shape``): parameter rules
    apply equally to optimizer moments because optax state mirrors the param
    tree — an Adam ``mu`` leaf for ``.../q_proj/kernel`` carries that path
    suffix and picks up the same spec, giving ZeRO-style sharded optimizer
    state for free."""
    specs = infer_param_specs(state_or_shapes, mesh, min_fsdp_size=min_fsdp_size)
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs)


def create_train_state(
    init_params_fn: Callable[[], Any],
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    min_fsdp_size: int = 2**14,
    initial_params: Any = None,
) -> Tuple[TrainState, Any]:
    """Initialize a TrainState *directly sharded* on the mesh: params and
    optimizer state are materialized shard-by-shard under jit, so a model too
    big for one chip never exists unsharded (torch FSDP needs
    ``sync_module_states`` + meta-device tricks for the same effect).

    :param initial_params: concrete warm-start params. These are device_put
        onto the mesh and passed as a jit *argument* — closing over them would
        bake the whole parameter set into the executable as constants.
    :return: (sharded TrainState, matching sharding pytree).
    """
    if initial_params is not None:
        shapes = jax.eval_shape(lambda p: TrainState.create(p, tx), initial_params)
        shardings = state_shardings(shapes, mesh, min_fsdp_size=min_fsdp_size)
        params = jax.device_put(initial_params, shardings.params)
        with mesh:
            state = jax.jit(
                lambda p: TrainState.create(p, tx),
                in_shardings=(shardings.params,),
                out_shardings=shardings,
            )(params)
        return state, shardings

    def init_fn():
        return TrainState.create(init_params_fn(), tx)

    shapes = jax.eval_shape(init_fn)
    shardings = state_shardings(shapes, mesh, min_fsdp_size=min_fsdp_size)
    with mesh:
        state = jax.jit(init_fn, out_shardings=shardings)()
    return state, shardings


LossFn = Callable[..., Tuple[jnp.ndarray, dict]]


def make_train_step(
    loss_fn: LossFn,
    mesh: Mesh,
    shardings: TrainState,
    *,
    grad_clip_norm: Optional[float] = None,
    donate: bool = True,
    grad_accum_steps: int = 1,
    multi_steps: int = 1,
):
    """Build the jitted SPMD training step.

    :param loss_fn: ``(params, batch, rng) -> (loss, metrics)``; must average
        the loss over the *local* batch shard — sharding makes XLA produce the
        global mean's allreduce.
    :param grad_clip_norm: optional global-norm clipping *after* the gradient
        allreduce (matching the FSDP script's manual ``clip_grad_norm_``,
        reference ``clm_fsdp.py:59-67``); also logs the pre-clip grad norm.
    :param grad_accum_steps: gradient accumulation (the role of Lightning's
        ``accumulate_grad_batches``, which the reference's CLM/SAM runs use,
        reference ``examples/training/clm/train.py:50``) — the batch is split
        into this many equal microbatches along dim 0 and a ``lax.scan``
        inside the step averages their gradients before the single optimizer
        update; peak activation memory is one microbatch's. NOTE the batch
        semantics differ from Lightning: Lightning accumulates across N
        loader batches (multiplying the effective batch), this DIVIDES the
        given batch — pass the full effective batch. Averaging is
        mean-of-microbatch-means, the same semantics DDP+accumulation gives
        the reference (per-microbatch masked means weight microbatches
        equally even if their mask counts differ).
    :param multi_steps: with N > 1, the returned function instead runs N
        optimizer steps in ONE device program (``lax.scan`` over a stacked
        batch) — signature ``(state, batches, rngs) -> (state, metrics)``
        where every batch leaf has an extra leading N dim (shard with
        ``shard_batch(..., stacked_steps=True)``), ``rngs`` is N stacked
        keys, and every metric comes back stacked ``(N,)``. Amortizes the
        per-call host dispatch+fetch overhead (~tens of ms through a
        tunneled PJRT backend) over N steps; the TPU-native replacement for
        torch's per-step Python training loop.
    :return: jitted ``(state, batch, rng) -> (state, metrics)``. Batches must
        be placed with :func:`~perceiver_io_tpu.parallel.shard_batch` (their
        committed sharding propagates; ``in_shardings`` pins only the state so
        heterogeneous batch pytrees — 2-D tokens, 4-D images — all work).
    """
    if grad_accum_steps < 1:
        raise ValueError(f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
    if multi_steps < 1:
        raise ValueError(f"multi_steps must be >= 1, got {multi_steps}")

    def value_and_grads(params, batch, rng):
        if grad_accum_steps == 1:
            return jax.value_and_grad(loss_fn, has_aux=True)(params, batch, rng)

        def to_micro(x):
            if x.shape[0] % grad_accum_steps:
                raise ValueError(
                    f"batch dim {x.shape[0]} not divisible by "
                    f"grad_accum_steps={grad_accum_steps}"
                )
            return x.reshape(grad_accum_steps, x.shape[0] // grad_accum_steps, *x.shape[1:])

        micro = jax.tree_util.tree_map(to_micro, batch)
        keys = None if rng is None else jax.random.split(rng, grad_accum_steps)

        def body(g_sum, xs):
            mb, r = xs if keys is not None else (xs, None)
            (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, mb, r
            )
            return jax.tree_util.tree_map(jnp.add, g_sum, grads), (loss, metrics)

        g0 = jax.tree_util.tree_map(jnp.zeros_like, params)
        xs = (micro, keys) if keys is not None else micro
        g_sum, (losses, metrics) = jax.lax.scan(body, g0, xs)
        grads = jax.tree_util.tree_map(lambda g: g / grad_accum_steps, g_sum)
        metrics = jax.tree_util.tree_map(lambda m: jnp.mean(m, axis=0), metrics)
        return (jnp.mean(losses), metrics), grads

    def step(state: TrainState, batch, rng):
        (loss, metrics), grads = value_and_grads(state.params, batch, rng)
        if grad_clip_norm is not None:
            gnorm = optax.global_norm(grads)
            scale = jnp.minimum(1.0, grad_clip_norm / (gnorm + 1e-6))
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            metrics = {**metrics, "grad_norm": gnorm}
        state = state.apply_gradients(grads)
        return state, {"loss": loss, **metrics}

    if multi_steps == 1:
        return jax.jit(
            step,
            in_shardings=(shardings, None, None),
            out_shardings=(shardings, None),
            donate_argnums=(0,) if donate else (),
        )

    def multi(state: TrainState, batches, rngs):
        # One device program for `multi_steps` optimizer steps: the host
        # dispatches (and pays tunnel latency) once per block, not per step.
        return jax.lax.scan(lambda st, xs: step(st, *xs), state, (batches, rngs))

    return jax.jit(
        multi,
        in_shardings=(shardings, None, None),
        out_shardings=(shardings, None),
        donate_argnums=(0,) if donate else (),
    )


def make_eval_step(loss_fn: LossFn, mesh: Mesh, shardings: TrainState):
    """Jitted ``(state, batch) -> metrics`` with deterministic loss."""

    def step(state: TrainState, batch):
        loss, metrics = loss_fn(state.params, batch, None)
        return {"loss": loss, **metrics}

    return jax.jit(step, in_shardings=(shardings, None), out_shardings=None)
