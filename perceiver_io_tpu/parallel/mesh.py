"""Device mesh construction.

The mesh is the TPU-native replacement for the reference's
``torch.distributed`` process group (NCCL world created implicitly by
Lightning, reference ``perceiver/scripts/cli.py:33-34``): every collective —
gradient allreduce (DDP parity), parameter all-gather/reduce-scatter (FSDP
parity), metric reduction (``sync_dist`` parity) — is emitted by XLA from
sharding annotations over these named axes.

Axis semantics:

- ``data``: batch sharded, everything else replicated (DDP).
- ``fsdp``: batch *and* parameters/optimizer state sharded (ZeRO-3/FSDP).
  The ``data`` and ``fsdp`` axes jointly shard the batch.
- ``model``: tensor parallelism (heads / MLP hidden dim).
- ``seq``: sequence/context parallelism (ring attention over long inputs).

On multi-host pods the mesh should put ``data``/``fsdp`` on the outermost
(DCN) dimension and ``model``/``seq`` innermost so their heavier collectives
ride ICI — :func:`make_mesh` uses ``jax.experimental.mesh_utils`` device
assignment which handles this for TPU topologies.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"

AXIS_NAMES: Tuple[str, ...] = (AXIS_DATA, AXIS_FSDP, AXIS_MODEL, AXIS_SEQ)

#: Axes over which the *batch* dimension is sharded.
BATCH_AXES: Tuple[str, ...] = (AXIS_DATA, AXIS_FSDP)


@dataclasses.dataclass
class MeshConfig:
    """Parallelism degrees. ``-1`` for exactly one axis means "all remaining
    devices" (like the reference's ``--trainer.devices=-1``)."""

    data: int = -1
    fsdp: int = 1
    model: int = 1
    seq: int = 1

    def resolve(self, num_devices: int) -> "MeshConfig":
        sizes = dataclasses.asdict(self)
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {wild}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if num_devices % fixed:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = num_devices // fixed
        elif fixed > num_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices but only {num_devices} are available"
            )
        return MeshConfig(**sizes)

    @property
    def shape(self) -> Tuple[int, int, int, int]:
        return (self.data, self.fsdp, self.model, self.seq)


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    **axis_sizes: int,
) -> Mesh:
    """Build a 4-axis ``Mesh`` (data, fsdp, model, seq) over ``devices``.

    ``make_mesh()`` → all devices on the data axis (DDP parity).
    ``make_mesh(fsdp=8, data=1)`` → fully-sharded over 8 devices (FSDP parity).
    """
    if config is None:
        config = MeshConfig(**axis_sizes)
    elif axis_sizes:
        raise ValueError("pass either a MeshConfig or axis sizes, not both")
    devices = list(devices) if devices is not None else jax.devices()
    config = config.resolve(len(devices))
    devices = devices[: math.prod(config.shape)]  # fully-specified smaller mesh
    try:
        device_array = mesh_utils.create_device_mesh(
            config.shape, devices=np.asarray(devices)
        )
    except (ValueError, AssertionError):
        # Fallback for device sets mesh_utils cannot topology-optimize
        # (e.g. virtual CPU devices in tests).
        device_array = np.asarray(devices).reshape(config.shape)
    return Mesh(device_array, AXIS_NAMES)


def device_slice(
    count: int,
    *,
    offset: int = 0,
    devices: Optional[Sequence[jax.Device]] = None,
) -> list:
    """A contiguous device subset — the serving-fleet call shape: N replicas
    each own ``count`` devices at disjoint offsets (``serving/sharding.py``),
    so replicas=2 × mesh-of-4 coexist in one process instead of every mesh
    claiming ``jax.devices()`` whole. Validates the slice actually exists —
    an over-subscribed fleet must fail at construction, not alias devices
    silently."""
    devices = list(devices) if devices is not None else jax.devices()
    if count < 1:
        raise ValueError(f"device_slice count must be >= 1, got {count}")
    if offset < 0:
        raise ValueError(f"device_slice offset must be >= 0, got {offset}")
    if offset + count > len(devices):
        raise ValueError(
            f"device slice [{offset}, {offset + count}) overruns the "
            f"{len(devices)} available devices — shrink the mesh or the "
            "replica count (replicas x data x model devices must fit)"
        )
    return devices[offset:offset + count]


def single_device_mesh(device: Optional[jax.Device] = None,
                       *, index: int = 0) -> Mesh:
    """Degenerate 1-device mesh so the same sharded train step runs on one
    chip (all axes size 1 — every PartitionSpec collapses to replication).
    ``index`` picks the device when none is passed — the serving-replica
    form of "use this device subset" (:func:`device_slice`) at size 1."""
    device = device if device is not None else device_slice(1, offset=index)[0]
    return make_mesh(MeshConfig(data=1), devices=[device])
