"""Parallelism & distributed runtime — the TPU-native replacement for the
reference's Lightning strategy layer (DDP/FSDP over NCCL, reference
``perceiver/scripts/trainer.yaml:14``, ``perceiver/scripts/text/clm_fsdp.py``).

Design (SURVEY.md §2.5): one ``jax.sharding.Mesh`` with named axes

- ``data``  — pure data parallelism (batch sharding, gradient allreduce);
- ``fsdp``  — fully-sharded data parallelism: batch *and* parameters sharded,
  XLA GSPMD inserts the all-gather/reduce-scatter that torch FSDP does by
  hand;
- ``model`` — tensor parallelism (attention heads / MLP hidden);
- ``seq``   — sequence/context parallelism for long sequences.

All collectives are emitted by XLA from :class:`~jax.sharding.PartitionSpec`
annotations — there is no hand-written NCCL/MPI equivalent to port.
"""
from perceiver_io_tpu.parallel.mesh import MeshConfig, make_mesh, single_device_mesh
from perceiver_io_tpu.parallel.multihost import (
    global_batch,
    initialize,
    is_multihost,
    shard_or_assemble,
)
from perceiver_io_tpu.parallel.partition import (
    batch_sharding,
    batch_spec,
    infer_param_specs,
    param_shardings,
    shard_batch,
    shard_params,
)
from perceiver_io_tpu.parallel.ring import ring_attention, ring_attention_sharded
from perceiver_io_tpu.parallel.train_step import (
    TrainState,
    create_train_state,
    make_eval_step,
    make_train_step,
    state_shardings,
)

__all__ = [
    "MeshConfig",
    "make_mesh",
    "initialize",
    "is_multihost",
    "global_batch",
    "shard_or_assemble",
    "batch_sharding",
    "infer_param_specs",
    "param_shardings",
    "shard_batch",
    "shard_params",
    "ring_attention",
    "ring_attention_sharded",
    "TrainState",
    "create_train_state",
    "make_eval_step",
    "make_train_step",
    "state_shardings",
]
