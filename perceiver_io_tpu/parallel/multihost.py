"""Multi-host (pod) runtime.

The reference reaches multi-process scale through Lightning/torchrun: one
process per accelerator, NCCL process group, rank-sharded data loading
(reference ``perceiver/data/text/c4.py:56-79``). The TPU-native equivalent
is one process per *host*, each owning its local chips:

1. :func:`initialize` — bring up the JAX distributed runtime
   (``jax.distributed.initialize``). On TPU pods every argument is
   auto-detected from the TPU metadata; on CPU/GPU clusters pass the
   coordinator address + process ids explicitly.
2. Build the mesh over **all** devices (``jax.devices()`` is global after
   initialization); ``data``/``fsdp`` outermost so their collectives ride
   DCN while ``model``/``seq`` stay on ICI (see :mod:`.mesh`).
3. Each host loads its own slice of the data
   (:func:`perceiver_io_tpu.data.loader.host_shard_info` keys off
   ``jax.process_index()``) and assembles the **global** batch with
   :func:`global_batch`, which wraps
   ``jax.make_array_from_process_local_data`` — the host-local arrays
   become one logical ``jax.Array`` without any cross-host data movement.
4. The jitted train step is then identical single-host or multi-host: XLA
   GSPMD emits the cross-host collectives from the same PartitionSpecs.

:func:`shard_or_assemble` dispatches between the single-process
``shard_batch`` path and the multi-process :func:`global_batch` path, so
trainers call one function everywhere.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from perceiver_io_tpu.parallel.partition import batch_sharding, shard_batch


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> None:
    """Initialize the JAX distributed runtime (idempotent).

    On TPU pods call with no arguments — coordinator, process count and ids
    are discovered from the TPU environment. On other platforms (or CPU
    test clusters) pass them explicitly.

    Must run before the first use of the backend (``jax.devices()`` etc.);
    afterwards ``jax.devices()`` reports the global device set and
    ``jax.local_devices()`` this host's chips.
    """
    if is_initialized():
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = list(local_device_ids)
    jax.distributed.initialize(**kwargs)


def is_initialized() -> bool:
    """Whether the distributed runtime is up (single-process counts as no)."""
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:  # pragma: no cover - private-API drift
        return jax.process_count() > 1


def is_multihost() -> bool:
    return jax.process_count() > 1


def global_batch(batch, mesh, *, shard_seq: bool = False, stacked_steps: bool = False):
    """Assemble per-host batch arrays into global ``jax.Array``s.

    Every process passes its *local* slice (``local_batch = global_batch /
    process_count`` rows, from its own data-loader shard); the result is a
    single logical array laid out by the batch sharding, with each host's
    rows resident on its own devices — the TPU-native replacement for the
    reference's rank-local DataLoader + DDP gradient sync.

    With ``stacked_steps`` the leaves carry a leading ``(n_steps, ...)`` dim
    (multi-step-in-jit); the *batch* dim (dim 1) is the per-host one.
    """

    def assemble(x):
        x = np.asarray(x)
        sharding = batch_sharding(
            mesh, ndim=x.ndim, shard_seq=shard_seq, stacked_steps=stacked_steps
        )
        if stacked_steps:
            global_shape = (
                x.shape[0], x.shape[1] * jax.process_count()) + x.shape[2:]
        else:
            global_shape = (x.shape[0] * jax.process_count(),) + x.shape[1:]
        return jax.make_array_from_process_local_data(sharding, x, global_shape)

    return jax.tree_util.tree_map(assemble, batch)


def shard_or_assemble(batch, mesh, *, shard_seq: bool = False, stacked_steps: bool = False):
    """Single-process: ``shard_batch`` (device_put). Multi-process:
    :func:`global_batch` (process-local assembly)."""
    if is_multihost():
        return global_batch(batch, mesh, shard_seq=shard_seq, stacked_steps=stacked_steps)
    return shard_batch(batch, mesh, shard_seq=shard_seq, stacked_steps=stacked_steps)
