"""Block-paged KV pool: the host-side allocator behind the slot engine's
paged KV layout (``kv_layout="paged"``, docs/serving.md).

The dense slot state sizes every resident's cross-KV cache at the full
context length, so HBM cost is ``slots × max_context`` even when most
residents are short — the direct ceiling on slot count under mixed-length
traffic (ROADMAP open item 1; the "Ragged Paged Attention" TPU-serving
design in PAPERS.md is the kernel-side half of the fix). This module is
the pool-side half: ONE fixed device pool of KV blocks (``block_size``
token positions each) shared by every slot, with a per-slot **block
table** mapping token-index pages to pool blocks. A request only ever
consumes ``ceil((prompt + max_new) / block_size)`` blocks — its own
worst case, not the context's — so a pool sized for ``B`` dense residents
admits strictly more mixed-length ones.

Design rules (all pinned by ``tests/test_paged_kv.py``):

- **Block 0 is the null block.** It is never allocated; every unmapped
  table entry points at it, so device-side writes routed through the
  table for idle/retired rows (and prefill scatter of positions past a
  row's live length) land in dedicated trash that no masked read ever
  uses. The device pool therefore has ``num_blocks + 1`` blocks for a
  pool of capacity ``num_blocks``.
- **Reserve at admit, map lazily.** Admission reserves the request's
  whole worst-case block count up front (``reserve``), so a resident can
  NEVER hit pool exhaustion mid-decode — no preemption/swap machinery,
  and greedy output stays deterministic. Physical block ids are mapped
  page-by-page as positions actually fill (``ensure``): prompt pages at
  admit, one page per chunked-prefill call as the staged prefix grows,
  and the next page when a decode step crosses a block boundary. The
  free-list invariant ``free >= outstanding reservations`` makes the
  lazy ``ensure`` infallible.
- **Deterministic allocation order.** The free list is a min-heap;
  allocation always hands out the lowest free block id and ``release``
  returns ids to the heap — identical schedules produce identical block
  tables (and therefore identical compiled-program inputs), which the
  FakeClock-driven allocator drills rely on.
- **Zero-leak accounting.** ``release`` frees both the mapped blocks and
  the unconsumed reservation; ``in_use``/``reserved`` must both read 0
  when the engine is idle. Fragmentation is structurally bounded: blocks
  are fixed-size and interchangeable, so the only waste is internal
  (the tail of the last block per request — at most ``block_size - 1``
  positions per resident).

Observability (docs/observability.md): the owning engine publishes
``kv_pool_blocks`` / ``kv_pool_blocks_in_use`` / ``kv_pool_blocks_high_water``
gauges and ``kv_pool_block_allocs_total`` / ``kv_pool_block_frees_total``
counters from this allocator's accessors, plus the live
``kv_cache_resident_bytes`` gauge (allocated pages, not the analytic
worst case — that moved to ``kv_cache_capacity_bytes``).
"""
from __future__ import annotations

import heapq
from typing import Dict, List


class PoolExhausted(RuntimeError):
    """Raised by :meth:`KVPagePool.reserve` when the request's worst-case
    block count exceeds the currently unreserved pool — the engine's
    admission gate catches it and leaves the request queued."""


class KVPagePool:
    """Host-side block allocator + per-slot block tables for one engine.

    :param num_blocks: usable pool capacity in blocks (the null block is
        extra; the device pool holds ``num_blocks + 1`` blocks).
    :param block_size: token positions per block.
    :param slots: number of persistent decode slots (block-table rows).
    :param max_len: max token positions one slot can hold (the model
        context length) — fixes the block-table width.
    """

    def __init__(self, num_blocks: int, block_size: int, slots: int, max_len: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        import numpy as np

        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.slots = int(slots)
        self.pages_per_slot = -(-int(max_len) // self.block_size)
        # ids 1..num_blocks; 0 is the null block (see module docstring)
        self._free: List[int] = list(range(1, self.num_blocks + 1))
        heapq.heapify(self._free)
        self._table = np.zeros((self.slots, self.pages_per_slot), np.int32)
        self._mapped: Dict[int, List[int]] = {s: [] for s in range(self.slots)}
        self._reserved: Dict[int, int] = {s: 0 for s in range(self.slots)}
        self.high_water = 0
        self.allocs_total = 0
        self.frees_total = 0
        #: blocks freed per retirement route (``retire`` = ordinary EOS /
        #: max_new / deadline, ``cancelled`` = client-driven reclaim through
        #: the gateway's disconnect path, ``failover`` = engine fault) — the
        #: accounting that makes abandoned-resident leaks visible instead
        #: of folded into ordinary churn (docs/serving.md "Streaming")
        self.frees_by_cause: Dict[str, int] = {}

    # -- sizing -------------------------------------------------------------
    def blocks_needed(self, tokens: int) -> int:
        """Worst-case block count for a request holding ``tokens`` positions
        (prompt + max_new for the slot engine's scope)."""
        return -(-max(0, int(tokens)) // self.block_size)

    @property
    def in_use(self) -> int:
        """Blocks currently mapped to a slot (physically allocated)."""
        return self.num_blocks - len(self._free)

    @property
    def reserved(self) -> int:
        """Blocks committed to residents: mapped plus not-yet-mapped
        reservation balance. Admission must gate on this, not ``in_use`` —
        lazily-mapped pages are already spoken for."""
        return self.in_use + sum(self._reserved.values())

    @property
    def available(self) -> int:
        return self.num_blocks - self.reserved

    def can_reserve(self, blocks: int) -> bool:
        return blocks <= self.available

    # -- lifecycle ----------------------------------------------------------
    def reserve(self, slot: int, tokens: int) -> int:
        """Commit the worst-case block count for a request of ``tokens``
        total positions to ``slot``; returns the count. Raises
        :class:`PoolExhausted` when the pool cannot ever satisfy it right
        now (the caller keeps the request queued) and ``ValueError`` on a
        slot that already holds a reservation (engine bug, not load)."""
        if self._reserved[slot] or self._mapped[slot]:
            raise ValueError(f"slot {slot} already holds pool pages/reservation")
        need = self.blocks_needed(tokens)
        if need > self.pages_per_slot:
            raise ValueError(
                f"{tokens} tokens need {need} blocks but one slot maps at "
                f"most {self.pages_per_slot}"
            )
        if not self.can_reserve(need):
            raise PoolExhausted(
                f"need {need} blocks, {self.available} of {self.num_blocks} "
                "unreserved"
            )
        self._reserved[slot] = need
        return need

    def ensure(self, slot: int, tokens: int) -> bool:
        """Map physical blocks for every page covering positions
        ``[0, tokens)`` of ``slot``, consuming its reservation; returns True
        when any new block was mapped (the caller refreshes gauges and the
        device table). Infallible for positions within the reservation —
        the free-list invariant guarantees a block is available."""
        pages = self.blocks_needed(tokens)
        mapped = self._mapped[slot]
        changed = False
        while len(mapped) < pages:
            if self._reserved[slot] <= 0:
                raise ValueError(
                    f"slot {slot} mapping page {len(mapped)} past its "
                    "reservation — admission accounting bug"
                )
            block = heapq.heappop(self._free)  # lowest id first: deterministic
            self._reserved[slot] -= 1
            self._table[slot, len(mapped)] = block
            mapped.append(block)
            self.allocs_total += 1
            changed = True
        if changed:
            self.high_water = max(self.high_water, self.in_use)
        return changed

    def release(self, slot: int, cause: str = "retire") -> int:
        """Free ``slot``'s mapped blocks and drop its unconsumed
        reservation (retire/cancel/failover/timeout all route here);
        returns the number of blocks physically freed. ``cause`` feeds
        :attr:`frees_by_cause` so cancellation reclaims stay separable
        from ordinary retirement churn."""
        mapped = self._mapped[slot]
        freed = len(mapped)
        for block in mapped:
            heapq.heappush(self._free, block)
        self.frees_total += freed
        if freed:
            self.frees_by_cause[cause] = self.frees_by_cause.get(cause, 0) + freed
        mapped.clear()
        self._reserved[slot] = 0
        self._table[slot, :] = 0
        return freed

    def release_all(self) -> int:
        """Failover path: every slot's pages back to the free list."""
        return sum(self.release(s, cause="failover") for s in range(self.slots))

    # -- views --------------------------------------------------------------
    def table(self):
        """The ``(slots, pages_per_slot)`` int32 block table (a live view;
        the engine copies it to device each step it changed)."""
        return self._table

    def table_row(self, slot: int):
        return self._table[slot]

    def mapped_blocks(self, slot: int) -> int:
        return len(self._mapped[slot])

    def leaked(self) -> int:
        """Blocks neither free nor attributed to a slot — always 0 unless
        the allocator itself is buggy (pinned by the leak drills)."""
        return self.num_blocks - len(self._free) - sum(
            len(m) for m in self._mapped.values()
        )

    def utilization(self) -> float:
        return self.in_use / self.num_blocks

    def stats(self) -> dict:
        return {
            "blocks": self.num_blocks,
            "block_size": self.block_size,
            "pages_per_slot": self.pages_per_slot,
            "in_use": self.in_use,
            "reserved": self.reserved,
            "high_water": self.high_water,
            "allocs_total": self.allocs_total,
            "frees_total": self.frees_total,
            "frees_by_cause": dict(sorted(self.frees_by_cause.items())),
            "utilization": round(self.utilization(), 4),
        }
