"""Block-paged KV pool: the host-side allocator behind the slot engine's
paged KV layout (``kv_layout="paged"``, docs/serving.md).

The dense slot state sizes every resident's cross-KV cache at the full
context length, so HBM cost is ``slots × max_context`` even when most
residents are short — the direct ceiling on slot count under mixed-length
traffic (ROADMAP open item 1; the "Ragged Paged Attention" TPU-serving
design in PAPERS.md is the kernel-side half of the fix). This module is
the pool-side half: ONE fixed device pool of KV blocks (``block_size``
token positions each) shared by every slot, with a per-slot **block
table** mapping token-index pages to pool blocks. A request only ever
consumes ``ceil((prompt + max_new) / block_size)`` blocks — its own
worst case, not the context's — so a pool sized for ``B`` dense residents
admits strictly more mixed-length ones.

Design rules (all pinned by ``tests/test_paged_kv.py``):

- **Block 0 is the null block.** It is never allocated; every unmapped
  table entry points at it, so device-side writes routed through the
  table for idle/retired rows (and prefill scatter of positions past a
  row's live length) land in dedicated trash that no masked read ever
  uses. The device pool therefore has ``num_blocks + 1`` blocks for a
  pool of capacity ``num_blocks``.
- **Reserve at admit, map lazily.** Admission reserves the request's
  whole worst-case block count up front (``reserve``), so a resident can
  NEVER hit pool exhaustion mid-decode — no preemption/swap machinery,
  and greedy output stays deterministic. Physical block ids are mapped
  page-by-page as positions actually fill (``ensure``): prompt pages at
  admit, one page per chunked-prefill call as the staged prefix grows,
  and the next page when a decode step crosses a block boundary. The
  free-list invariant ``free >= outstanding reservations`` makes the
  lazy ``ensure`` infallible.
- **Deterministic allocation order.** The free list is a min-heap;
  allocation always hands out the lowest free block id and ``release``
  returns ids to the heap — identical schedules produce identical block
  tables (and therefore identical compiled-program inputs), which the
  FakeClock-driven allocator drills rely on.
- **Zero-leak accounting.** ``release`` frees both the mapped blocks and
  the unconsumed reservation; at engine idle ``in_use`` must equal the
  prefix index's ``cached_blocks`` (the retained prefix blocks — the only
  thing legitimately resident with no slot attached; 0 with the cache
  off) and :meth:`leaked` must read 0 — a page freed only on its LAST
  deref is referenced, never leaked mid-drill. Fragmentation is
  structurally bounded: blocks are fixed-size and interchangeable, so the
  only waste is internal (the tail of the last block per request — at
  most ``block_size - 1`` positions per resident).
- **Refcounted sharing (docs/serving.md "Prefix sharing").** Every
  allocated block carries a reference count. A block mapped by one slot
  has count 1 (the original, exclusive semantics); cross-request prefix
  sharing maps the SAME physical block into several slots' tables
  (:meth:`KVPagePool.map_shared`) and the :class:`PrefixBlockIndex`
  retains published prefix blocks across retirements, so ``release``
  becomes a *deref*: the block returns to the free heap only when its
  count drains to zero. A shared page is never written through —
  :meth:`KVPagePool.cow` swaps a fresh private block into the writing
  slot's table (copy-on-write; the owning engine performs the device-side
  page copy). ``frees_by_cause`` gains two causes on top of the
  retirement taxonomy: ``"shared"`` (a cached prefix block dropped by the
  index — LRU eviction under pool pressure, or a flush) and ``"cow"`` (a
  shared mapping's final deref through a copy-on-write replacement).
- **Host swap (docs/serving.md "Host-swap preemption").** A preemption
  victim under ``preemption="swap"`` gathers its pages to host memory
  (:class:`SwapBundle`) and releases them tagged
  ``frees_by_cause["swapped"]`` (:meth:`KVPagePool.extract`); restore
  (:meth:`KVPagePool.restore`) re-maps the bundle into whatever free
  blocks exist at readmission through the same block-table indirection —
  no retrace, and prefix-shared leading blocks travel by reference (one
  bundle retain), never by copy.

Observability (docs/observability.md): the owning engine publishes
``kv_pool_blocks`` / ``kv_pool_blocks_in_use`` / ``kv_pool_blocks_high_water``
gauges and ``kv_pool_block_allocs_total`` / ``kv_pool_block_frees_total``
counters from this allocator's accessors, plus the live
``kv_cache_resident_bytes`` gauge (allocated pages, not the analytic
worst case — that moved to ``kv_cache_capacity_bytes``), and the
``kv_prefix_*`` hit/miss/evict/shared-block families from the prefix
index (docs/serving.md "Prefix sharing").
"""
from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass
class SwapBundle:
    """Self-contained host-side image of one swapped-out victim
    (docs/serving.md "Host-swap preemption").

    ``payload`` holds host numpy copies of the victim's pool pages
    (``pool_k``/``pool_v`` gathered through its padded block-table row,
    plus int8 per-block scales under ``kv_layout="paged_int8"``) and a
    ``row`` dict of its per-slot state leaves. ``shared`` lists the
    leading prefix-shared block ids that were deref'd rather than copied —
    the bundle holds ONE retain on each (:meth:`KVPagePool.extract`), so
    their device content survives until restore re-references them or the
    bundle is dropped. Restore re-maps into whatever free blocks exist at
    readmission; nothing in the bundle names the original private ids.
    """

    request_id: int
    payload: dict
    shared: List[int]
    n_private: int
    #: resident token positions (prompt + generated) restore must re-map
    tokens: int
    emitted: List[int]
    m: int
    last_token_at: float
    bytes_moved: int


class PoolExhausted(RuntimeError):
    """Raised by :meth:`KVPagePool.reserve` when the request's worst-case
    block count exceeds the currently unreserved pool — the engine's
    admission gate catches it and leaves the request queued."""


class KVPagePool:
    """Host-side block allocator + per-slot block tables for one engine.

    :param num_blocks: usable pool capacity in blocks (the null block is
        extra; the device pool holds ``num_blocks + 1`` blocks).
    :param block_size: token positions per block.
    :param slots: number of persistent decode slots (block-table rows).
    :param max_len: max token positions one slot can hold (the model
        context length) — fixes the block-table width.
    """

    def __init__(self, num_blocks: int, block_size: int, slots: int, max_len: int):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        import numpy as np

        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.slots = int(slots)
        self.pages_per_slot = -(-int(max_len) // self.block_size)
        # ids 1..num_blocks; 0 is the null block (see module docstring)
        self._free: List[int] = list(range(1, self.num_blocks + 1))
        heapq.heapify(self._free)
        self._table = np.zeros((self.slots, self.pages_per_slot), np.int32)
        self._mapped: Dict[int, List[int]] = {s: [] for s in range(self.slots)}
        self._reserved: Dict[int, int] = {s: 0 for s in range(self.slots)}
        #: block id -> live reference count (slot mappings + prefix-index
        #: retains). Every allocated block appears here; a block is freed
        #: exactly when its count drains to 0, so
        #: ``num_blocks == len(_free) + len(_refcount)`` is the zero-leak
        #: invariant :meth:`leaked` checks.
        self._refcount: Dict[int, int] = {}
        self.high_water = 0
        self.allocs_total = 0
        self.frees_total = 0
        #: blocks mapped into a slot's table by reference (no allocation)
        self.shared_maps_total = 0
        #: derefs that left the block alive (another slot / the prefix
        #: index still holds it) — the non-free half of refcounted release
        self.shared_derefs_total = 0
        #: copy-on-write replacements performed (a fresh block swapped in
        #: for a shared mapping; the engine pays the device page copy)
        self.cow_swaps_total = 0
        #: blocks freed per retirement route (``retire`` = ordinary EOS /
        #: max_new / deadline, ``cancelled`` = client-driven reclaim through
        #: the gateway's disconnect path, ``failover`` = engine fault) — the
        #: accounting that makes abandoned-resident leaks visible instead
        #: of folded into ordinary churn (docs/serving.md "Streaming")
        self.frees_by_cause: Dict[str, int] = {}
        #: slot -> soft watermark (total pages the slot may EVER map —
        #: ``ceil((prompt + max_new) / block_size)``) for slots admitted
        #: through :meth:`reserve_lazy`. Lazy slots hold a hard reservation
        #: only for their prompt pages (+ headroom); decode pages past it
        #: allocate straight from the free heap, so :meth:`ensure` becomes
        #: FALLIBLE for them (:class:`PoolExhausted` = the engine's
        #: preemption trigger) instead of an accounting-bug ValueError.
        self._soft: Dict[int, int] = {}
        #: slot -> owner label (the engine's sanitized tenant label) for
        #: per-tenant pool attribution; cleared on :meth:`release`. The
        #: pool never interprets the label — it only sums mapped blocks
        #: per owner for :meth:`stats` (``in_use_by_owner``).
        self._owner: Dict[int, str] = {}

    # -- sizing -------------------------------------------------------------
    def blocks_needed(self, tokens: int) -> int:
        """Worst-case block count for a request holding ``tokens`` positions
        (prompt + max_new for the slot engine's scope)."""
        return -(-max(0, int(tokens)) // self.block_size)

    @property
    def in_use(self) -> int:
        """Blocks currently mapped to a slot (physically allocated)."""
        return self.num_blocks - len(self._free)

    @property
    def reserved(self) -> int:
        """Blocks committed to residents: mapped plus not-yet-mapped
        reservation balance. Admission must gate on this, not ``in_use`` —
        lazily-mapped pages are already spoken for."""
        return self.in_use + sum(self._reserved.values())

    @property
    def available(self) -> int:
        return self.num_blocks - self.reserved

    def can_reserve(self, blocks: int) -> bool:
        return blocks <= self.available

    # -- refcounts -----------------------------------------------------------
    def refcount(self, block: int) -> int:
        """Live references on an allocated block (0 for free blocks)."""
        return self._refcount.get(block, 0)

    def retain(self, block: int) -> None:
        """Add one reference to an allocated block (the prefix index's
        publish path); the block now survives its mapping slots' releases
        until the extra reference is dropped with :meth:`deref`."""
        if block not in self._refcount:
            raise ValueError(f"block {block} is not allocated")
        self._refcount[block] += 1

    def deref(self, block: int, cause: str = "retire") -> int:
        """Drop one reference; physically free the block when the count
        drains to zero. Returns 1 when the block was freed, else 0 —
        ``cause`` tags :attr:`frees_by_cause` only for the actual free
        (live derefs count under :attr:`shared_derefs_total`)."""
        count = self._refcount.get(block)
        if count is None:
            raise ValueError(f"block {block} is not allocated")
        if count > 1:
            self._refcount[block] = count - 1
            self.shared_derefs_total += 1
            return 0
        del self._refcount[block]
        heapq.heappush(self._free, block)
        self.frees_total += 1
        self.frees_by_cause[cause] = self.frees_by_cause.get(cause, 0) + 1
        return 1

    def _alloc(self) -> int:
        block = heapq.heappop(self._free)  # lowest id first: deterministic
        self._refcount[block] = 1
        self.allocs_total += 1
        return block

    # -- lifecycle ----------------------------------------------------------
    def reserve(self, slot: int, tokens: int, *, shared_blocks: int = 0) -> int:
        """Commit the worst-case block count for a request of ``tokens``
        total positions to ``slot``; returns the count reserved. Raises
        :class:`PoolExhausted` when the pool cannot ever satisfy it right
        now (the caller keeps the request queued) and ``ValueError`` on a
        slot that already holds a reservation (engine bug, not load).

        ``shared_blocks`` is the number of leading pages the caller will
        map BY REFERENCE to already-resident prefix blocks
        (:meth:`map_shared`): those pages allocate nothing, so they are
        excluded from the reservation — the capacity win prefix sharing
        exists for (docs/serving.md "Prefix sharing")."""
        if self._reserved[slot] or self._mapped[slot]:
            raise ValueError(f"slot {slot} already holds pool pages/reservation")
        total = self.blocks_needed(tokens)
        if total > self.pages_per_slot:
            raise ValueError(
                f"{tokens} tokens need {total} blocks but one slot maps at "
                f"most {self.pages_per_slot}"
            )
        if not 0 <= shared_blocks <= total:
            raise ValueError(
                f"shared_blocks {shared_blocks} out of range for a "
                f"{total}-block request"
            )
        need = total - shared_blocks
        if not self.can_reserve(need):
            raise PoolExhausted(
                f"need {need} blocks, {self.available} of {self.num_blocks} "
                "unreserved"
            )
        self._reserved[slot] = need
        return need

    def reserve_lazy(self, slot: int, prompt_tokens: int, total_tokens: int,
                     *, headroom: int = 0, shared_blocks: int = 0) -> int:
        """Optimistic admission: commit only the blocks the *prompt* needs
        (plus ``headroom`` decode blocks, clamped to the worst case), and
        record ``ceil(total_tokens / block_size)`` as a SOFT watermark —
        the reservation ledger the up-front path hard-commits becomes
        advisory. Returns the hard-committed count.

        Decode pages past the commitment allocate from the free heap when
        the resident actually crosses a block boundary; :meth:`ensure` on a
        lazy slot raises :class:`PoolExhausted` when that heap is dry — the
        signal the slot engine turns into a preemption instead of an
        admission-time head-of-line block (docs/serving.md "Preemption &
        priorities"). Raise semantics at admit mirror :meth:`reserve`:
        ``ValueError`` for structurally-infeasible or double bookings,
        :class:`PoolExhausted` when the committed need doesn't fit now.
        """
        if self._reserved[slot] or self._mapped[slot]:
            raise ValueError(f"slot {slot} already holds pool pages/reservation")
        total = self.blocks_needed(total_tokens)
        prompt = self.blocks_needed(prompt_tokens)
        if not 0 <= prompt <= total:
            raise ValueError(
                f"prompt_tokens {prompt_tokens} out of range for "
                f"{total_tokens} total tokens"
            )
        if total > self.pages_per_slot:
            raise ValueError(
                f"{total_tokens} tokens need {total} blocks but one slot "
                f"maps at most {self.pages_per_slot}"
            )
        if not 0 <= shared_blocks <= prompt:
            raise ValueError(
                f"shared_blocks {shared_blocks} out of range for a "
                f"{prompt}-prompt-block request"
            )
        if headroom < 0:
            raise ValueError(f"headroom must be >= 0, got {headroom}")
        # hard commitment: private prompt pages + headroom, never more than
        # the worst case would have taken (headroom can't over-reserve)
        need = min(prompt - shared_blocks + headroom, total - shared_blocks)
        if not self.can_reserve(need):
            raise PoolExhausted(
                f"need {need} blocks, {self.available} of {self.num_blocks} "
                "unreserved"
            )
        self._reserved[slot] = need
        self._soft[slot] = total
        return need

    def is_lazy(self, slot: int) -> bool:
        """True when ``slot`` was admitted through :meth:`reserve_lazy` —
        its :meth:`ensure` may raise :class:`PoolExhausted`."""
        return slot in self._soft

    @property
    def headroom_blocks(self) -> int:
        """Free blocks not spoken for by any hard reservation — the real
        distance to the next :class:`PoolExhausted` on a lazy slot's
        boundary crossing (the ``kv_pool_headroom_blocks`` gauge)."""
        return max(0, len(self._free) - sum(self._reserved.values()))

    def map_shared(self, slot: int, blocks: Sequence[int]) -> None:
        """Map already-resident blocks as ``slot``'s leading pages by
        reference (one retain each) — the prefix-sharing admit path. Must
        run right after :meth:`reserve` (the slot's table is still empty)
        and before any :meth:`ensure`; the shared pages were excluded from
        the reservation via ``reserve(..., shared_blocks=len(blocks))``."""
        mapped = self._mapped[slot]
        if mapped:
            raise ValueError(
                f"slot {slot} already maps {len(mapped)} pages; shared "
                "prefix pages must be the leading ones"
            )
        for block in blocks:
            self.retain(block)
            self._table[slot, len(mapped)] = block
            mapped.append(block)
            self.shared_maps_total += 1

    def page_shared(self, slot: int, page: int) -> bool:
        """True when ``slot``'s mapping at ``page`` is NOT exclusively
        owned (another slot or the prefix index also references the
        block) — the engine's write guard: such a page must be COW'd
        before any decode write could land on it."""
        mapped = self._mapped[slot]
        if page >= len(mapped):
            return False
        return self._refcount[mapped[page]] > 1

    def cow(self, slot: int, page: int, cause: str = "cow", *,
            use_reservation: bool = False) -> Tuple[int, int]:
        """Copy-on-write: replace ``slot``'s mapping at ``page`` with a
        fresh private block and deref the old one (tagged ``cause`` if
        that deref is its last). Returns ``(old_block, new_block)`` — the
        caller copies the page's device content before writing into it.

        ``use_reservation=True`` is the admit-time partial-block COW: that
        page was counted in the request's private need, so the swap
        consumes one reservation. The decode-path write guard passes
        False — the replaced page already consumed its reservation when it
        mapped, so the extra block comes from the free heap and must not
        eat into ANY slot's outstanding reservations
        (:class:`PoolExhausted` if it would)."""
        mapped = self._mapped[slot]
        if page >= len(mapped):
            raise ValueError(f"slot {slot} has no mapping at page {page}")
        if use_reservation and self._reserved[slot] > 0:
            self._reserved[slot] -= 1
        elif len(self._free) <= sum(self._reserved.values()):
            raise PoolExhausted(
                "copy-on-write needs a free block but every free block is "
                "reserved"
            )
        old = mapped[page]
        new = self._alloc()
        mapped[page] = new
        self._table[slot, page] = new
        self.cow_swaps_total += 1
        self.high_water = max(self.high_water, self.in_use)
        self.deref(old, cause=cause)
        return old, new

    def ensure(self, slot: int, tokens: int) -> bool:
        """Map physical blocks for every page covering positions
        ``[0, tokens)`` of ``slot``, consuming its reservation; returns True
        when any new block was mapped (the caller refreshes gauges and the
        device table). Infallible for positions within the reservation —
        the free-list invariant guarantees a block is available. Pages
        already mapped (privately or shared) are left untouched.

        Lazy slots (:meth:`reserve_lazy`) may map past their hard
        reservation up to the soft watermark, allocating from the free
        heap — but only from blocks no hard reservation has spoken for;
        when none remains this raises :class:`PoolExhausted` with the slot
        table UNCHANGED (no partial mapping), the engine's cue to preempt
        a victim and retry."""
        pages = self.blocks_needed(tokens)
        mapped = self._mapped[slot]
        soft = self._soft.get(slot)
        if soft is not None and pages > soft:
            raise ValueError(
                f"slot {slot} needs {pages} pages past its soft watermark "
                f"{soft} — admission accounting bug"
            )
        changed = False
        while len(mapped) < pages:
            from_reservation = self._reserved[slot] > 0
            if not from_reservation:
                if soft is None:
                    raise ValueError(
                        f"slot {slot} mapping page {len(mapped)} past its "
                        "reservation — admission accounting bug"
                    )
                if len(self._free) <= sum(self._reserved.values()):
                    raise PoolExhausted(
                        f"slot {slot} crossing a block boundary at page "
                        f"{len(mapped)} with no unreserved free block — "
                        "preempt a victim to continue"
                    )
            block = self._alloc()
            if from_reservation:
                self._reserved[slot] -= 1
            self._table[slot, len(mapped)] = block
            mapped.append(block)
            changed = True
        if changed:
            self.high_water = max(self.high_water, self.in_use)
        return changed

    def ensure_many(self, slot: int, tokens: int) -> bool:
        """Burst form of :meth:`ensure` — ATOMIC over a multi-block span.

        A speculative round can accept up to ``k+1`` tokens at once, so one
        call may need to map several fresh blocks. :meth:`ensure` maps
        page-by-page and checks the lazy-slot free-heap guard per page:
        correct for the one-crossing-per-step decode path, but a burst
        hitting exhaustion mid-span would leave the LEADING pages mapped —
        a partial mapping the preempt-and-retry loop would then double
        count. This wrapper pre-checks the WHOLE span against the
        unreserved free heap (reservation-consuming pages keep
        :attr:`headroom_blocks` unchanged, so lazy pages alone spend it)
        and only then delegates — on :class:`PoolExhausted` the slot table
        is untouched, and the block-id sequence is identical to ``n``
        single :meth:`ensure` calls (same min-heap order)."""
        pages = self.blocks_needed(tokens)
        mapped = self._mapped[slot]
        new_pages = max(0, pages - len(mapped))
        if new_pages == 0:
            return False
        soft = self._soft.get(slot)
        if soft is not None and pages > soft:
            raise ValueError(
                f"slot {slot} needs {pages} pages past its soft watermark "
                f"{soft} — admission accounting bug"
            )
        lazy_pages = max(0, new_pages - self._reserved[slot])
        if lazy_pages:
            if soft is None:
                raise ValueError(
                    f"slot {slot} mapping {lazy_pages} pages past its "
                    "reservation — admission accounting bug"
                )
            if lazy_pages > self.headroom_blocks:
                raise PoolExhausted(
                    f"slot {slot} needs {lazy_pages} unreserved free blocks "
                    f"for a {new_pages}-page burst but only "
                    f"{self.headroom_blocks} remain — preempt a victim to "
                    "continue"
                )
        return self.ensure(slot, tokens)

    def release(self, slot: int, cause: str = "retire") -> int:
        """Deref ``slot``'s mapped blocks and drop its unconsumed
        reservation (retire/cancel/failover/timeout all route here);
        returns the number of blocks PHYSICALLY freed — shared blocks
        whose count stays positive (other slots, the prefix index) remain
        resident and are counted under :attr:`shared_derefs_total`
        instead. ``cause`` feeds :attr:`frees_by_cause` so cancellation
        reclaims stay separable from ordinary retirement churn."""
        mapped = self._mapped[slot]
        freed = 0
        for block in mapped:
            freed += self.deref(block, cause=cause)
        mapped.clear()
        self._reserved[slot] = 0
        self._soft.pop(slot, None)
        self._owner.pop(slot, None)
        self._table[slot, :] = 0
        return freed

    def release_all(self) -> int:
        """Failover path: every slot's pages back to the free list."""
        return sum(self.release(s, cause="failover") for s in range(self.slots))

    # -- host swap (docs/serving.md "Host-swap preemption") ------------------
    def extract(self, slot: int, cause: str = "swapped") -> Tuple[List[int], List[int]]:
        """Swap-out bookkeeping for ``slot``: split its mapped blocks into
        the leading prefix-shared run (refcount > 1 — deref'd, never
        copied; the bundle takes ONE retain on each so the device content
        stays resident) and the private tail, then :meth:`release` the
        slot so the private blocks return to the free heap tagged
        ``frees_by_cause[cause]``. Returns ``(shared, private)`` block-id
        lists in page order. The caller must gather the device pages
        BEFORE calling this — once released, the private ids may be
        re-allocated by the very next admission.

        Shared blocks form a leading run by construction:
        :meth:`map_shared` only ever maps leading pages, and any later
        write through a shared page went through :meth:`cow` first."""
        blocks = list(self._mapped[slot])
        shared: List[int] = []
        for block in blocks:
            if self._refcount.get(block, 0) > 1:
                shared.append(block)
            else:
                break
        for block in shared:
            self.retain(block)
        private = blocks[len(shared):]
        self.release(slot, cause=cause)
        return shared, private

    def restore(self, slot: int, shared: Sequence[int], total_tokens: int,
                resident_tokens: int) -> List[int]:
        """Re-admit a swapped-out victim into ``slot``: reserve its FULL
        worst case (pessimistic readmission — the anti-thrash rule; the
        ``shared`` prefix blocks are excluded), re-map the shared run by
        reference, then map fresh private blocks covering
        ``resident_tokens`` positions from whatever the free heap holds
        now. Returns the fresh private block ids (page order) — the engine
        scatters the bundle's page payload into exactly these. The caller
        drops the bundle's retains on ``shared`` afterwards (the slot now
        holds its own references). Raises :class:`PoolExhausted` with the
        slot untouched when the worst case doesn't fit yet."""
        self.reserve(slot, total_tokens, shared_blocks=len(shared))
        if shared:
            self.map_shared(slot, shared)
        self.ensure(slot, resident_tokens)
        return list(self._mapped[slot][len(shared):])

    # -- views --------------------------------------------------------------
    def table(self):
        """The ``(slots, pages_per_slot)`` int32 block table (a live view;
        the engine copies it to device each step it changed)."""
        return self._table

    def table_row(self, slot: int):
        return self._table[slot]

    def set_owner(self, slot: int, owner: Optional[str]) -> None:
        """Tag ``slot``'s blocks with an owner label (the engine's
        sanitized tenant label) for per-tenant attribution in
        :meth:`stats`; ``None`` clears the tag. Cleared automatically on
        :meth:`release` — a freed slot carries no stale attribution."""
        if owner is None:
            self._owner.pop(slot, None)
        else:
            self._owner[slot] = str(owner)

    def in_use_by_owner(self) -> Dict[str, int]:
        """Mapped blocks summed per owner label; untagged slots with
        mapped blocks attribute to ``"default"``. Shared (refcounted)
        blocks count once per mapping — attribution, so a tenant holding a
        reference is charged for it even when another tenant shares the
        physical block."""
        held: Dict[str, int] = {}
        for slot, mapped in self._mapped.items():
            if not mapped:
                continue
            owner = self._owner.get(slot, "default")
            held[owner] = held.get(owner, 0) + len(mapped)
        return dict(sorted(held.items()))

    def mapped_blocks(self, slot: int) -> int:
        return len(self._mapped[slot])

    def slot_blocks(self, slot: int) -> Tuple[int, ...]:
        """The physical block ids mapped to ``slot``, page order — the
        prefix index publishes a retired-to-be slot's leading full prefix
        blocks from this view."""
        return tuple(self._mapped[slot])

    def leaked(self) -> int:
        """Blocks neither free nor carrying a live reference — always 0
        unless the allocator itself is buggy (pinned by the leak drills).
        Refcount-aware: a prefix block retained by the index after its
        donor retired is REFERENCED, not leaked — it frees on its last
        deref (the satellite accounting the refcount drills pin). The
        cross-check against per-slot attribution still holds through
        :meth:`refcount`: every mapped occurrence plus every index retain
        is one count."""
        return self.num_blocks - len(self._free) - len(self._refcount)

    def utilization(self) -> float:
        return self.in_use / self.num_blocks

    def stats(self) -> dict:
        mapped_refs = sum(len(m) for m in self._mapped.values())
        total_refs = sum(self._refcount.values())
        return {
            "blocks": self.num_blocks,
            "block_size": self.block_size,
            "pages_per_slot": self.pages_per_slot,
            "in_use": self.in_use,
            "reserved": self.reserved,
            "high_water": self.high_water,
            "allocs_total": self.allocs_total,
            "frees_total": self.frees_total,
            "frees_by_cause": dict(sorted(self.frees_by_cause.items())),
            "utilization": round(self.utilization(), 4),
            # always 0 unless the allocator is buggy; surfaced here so a
            # scale-down victim's post-mortem (the autoscaler's `retired`
            # records) carries its own zero-leak evidence
            "leaked": self.leaked(),
            # refcounted-sharing accounting (docs/serving.md "Prefix
            # sharing"): blocks referenced beyond their mapping slot,
            # reference totals (mapped occurrences + index retains), and
            # the shared map / live-deref / COW churn counters
            "shared_blocks": sum(1 for c in self._refcount.values() if c > 1),
            "refs_total": total_refs,
            "refs_retained": total_refs - mapped_refs,
            "shared_maps_total": self.shared_maps_total,
            "shared_derefs_total": self.shared_derefs_total,
            "cow_swaps_total": self.cow_swaps_total,
            # optimistic-admission accounting (docs/serving.md "Preemption
            # & priorities"): residents admitted lazily and the distance to
            # the next boundary-crossing PoolExhausted
            "lazy_slots": len(self._soft),
            "headroom_blocks": self.headroom_blocks,
            # per-tenant pool attribution (docs/observability.md
            # "Scheduler timeline & post-mortems"): mapped blocks summed
            # per owner label the engine tagged at admission
            "in_use_by_owner": self.in_use_by_owner(),
        }


class _PrefixNode:
    """One full prompt-prefix block in the radix index: the token ids it
    covers (its edge label from ``parent``), the physical pool block
    holding those positions' cross k/v, and the LRU stamp."""

    __slots__ = ("tokens", "block", "parent", "children", "last_used")

    def __init__(self, tokens: Tuple[int, ...], block: int,
                 parent: Optional["_PrefixNode"]):
        self.tokens = tokens
        self.block = block
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_PrefixNode"] = {}
        self.last_used = 0


class PrefixBlockIndex:
    """Radix/trie index over published full prompt-prefix blocks
    (docs/serving.md "Prefix sharing").

    Each node is ONE full block of ``block_size`` token ids, chained from
    the prompt start — node depth ``i`` covers absolute positions
    ``[i*block_size, (i+1)*block_size)``, whose cross k/v are per-position
    functions of (token id, absolute position) in the ``kv_norm``-side
    prefix region, so a published block's device content is bit-valid for
    ANY later prompt sharing that token prefix. Only blocks fully inside
    their donor's prefix region are ever published (latent-region values
    are boundary-dependent and get rewritten by migration), which is what
    makes shared pages immutable for their whole residency.

    The index holds one pool reference per published block
    (:meth:`KVPagePool.retain`), so cached prefixes survive their donor's
    retirement and are dropped — LRU leaves first, ``cause="shared"`` —
    only by :meth:`evict_lru` under pool pressure or :meth:`flush` on an
    engine state rebuild. All ordering is driven by a monotonic use
    counter, never wall time, so FakeClock drills replay bit-identically.
    """

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._root: Dict[Tuple[int, ...], _PrefixNode] = {}
        self._tick = 0
        self.cached_blocks = 0
        self.published_total = 0
        self.evicted_total = 0

    # -- lookup --------------------------------------------------------------
    def _touch(self, node: _PrefixNode) -> None:
        self._tick += 1
        node.last_used = self._tick

    def match(self, tokens) -> List[_PrefixNode]:
        """Longest chain of cached FULL blocks matching the prompt's
        leading token ids (LRU-touched). The caller clamps the usable
        span to its own prefix region."""
        bs = self.block_size
        out: List[_PrefixNode] = []
        children = self._root
        for i in range(len(tokens) // bs):
            key = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            node = children.get(key)
            if node is None:
                break
            self._touch(node)
            out.append(node)
            children = node.children
        return out

    def best_partial(self, matched: List[_PrefixNode], tokens) -> Tuple[Optional[_PrefixNode], int]:
        """The cached block extending ``matched`` whose token ids share
        the longest leading run with the prompt's next block — the
        divergent-mid-block COW donor. Returns ``(node, lcp_tokens)``;
        ``(None, 0)`` when nothing extends the chain. Ties break toward
        the most recently used node, then insertion order, so the choice
        is deterministic."""
        bs = self.block_size
        depth = len(matched)
        rest = tuple(int(t) for t in tokens[depth * bs:(depth + 1) * bs])
        if not rest:
            return None, 0
        children = matched[-1].children if matched else self._root
        best, best_lcp = None, 0
        for key, node in children.items():
            lcp = 0
            for a, b in zip(rest, key):
                if a != b:
                    break
                lcp += 1
            if lcp > best_lcp or (
                lcp == best_lcp and lcp > 0 and best is not None
                and node.last_used > best.last_used
            ):
                best, best_lcp = node, lcp
        if best is not None:
            self._touch(best)
        return best, best_lcp

    # -- publish -------------------------------------------------------------
    def insert(self, tokens, blocks: Sequence[int], pool: KVPagePool) -> int:
        """Publish ``blocks`` as the full prefix blocks covering
        ``tokens``' leading ids (block ``i`` holds positions
        ``[i*bs, (i+1)*bs)``); retains each NEWLY published block on the
        pool. Blocks whose token path is already cached are skipped — the
        first donor wins and later identical prefixes keep their private
        copies (no dedupe-in-place; docs/serving.md). Returns the number
        of blocks newly published."""
        bs = self.block_size
        children = self._root
        parent: Optional[_PrefixNode] = None
        published = 0
        for i, block in enumerate(blocks):
            key = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            if len(key) < bs:
                break
            node = children.get(key)
            if node is None:
                pool.retain(block)
                node = _PrefixNode(key, int(block), parent)
                children[key] = node
                self.cached_blocks += 1
                self.published_total += 1
                published += 1
            self._touch(node)
            parent = node
            children = node.children
        return published

    # -- eviction ------------------------------------------------------------
    def _leaves(self) -> List[_PrefixNode]:
        out = []
        stack = list(self._root.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            else:
                out.append(node)
        return out

    def _drop(self, node: _PrefixNode, pool: KVPagePool, cause: str) -> int:
        siblings = node.parent.children if node.parent is not None else self._root
        del siblings[node.tokens]
        self.cached_blocks -= 1
        self.evicted_total += 1
        return pool.deref(node.block, cause=cause)

    def evict_one(self, pool: KVPagePool, cause: str = "shared") -> Optional[int]:
        """Drop the least-recently-used LEAF (trie integrity: a parent is
        only evictable once childless). Returns the number of pool blocks
        physically freed (0 when the block is still mapped by a resident
        — it frees later on that resident's release), or None when the
        index is empty."""
        leaves = self._leaves()
        if not leaves:
            return None
        victim = min(leaves, key=lambda n: n.last_used)
        return self._drop(victim, pool, cause)

    def flush(self, pool: KVPagePool, cause: str = "shared") -> int:
        """Drop every cached block (deepest first). Mandatory whenever the
        device pool's CONTENT is rebuilt — executor-fault recovery,
        warmup's state blanking, a trace-env flag flip — because the
        index's blocks would otherwise describe zeroed or stale pages.
        Returns the number of pool blocks physically freed."""
        freed = 0
        while True:
            leaves = self._leaves()
            if not leaves:
                return freed
            for node in leaves:
                freed += self._drop(node, pool, cause)

    def stats(self) -> dict:
        return {
            "cached_blocks": self.cached_blocks,
            "published_total": self.published_total,
            "evicted_total": self.evicted_total,
        }
