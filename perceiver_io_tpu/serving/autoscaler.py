"""SLO-driven fleet elasticity: the burn-rate autoscaler closed loop.

PR 9's :class:`~perceiver_io_tpu.observability.slo.SLOMonitor` detects a
sustained burn but can only *tighten admission* — a flash crowd ends in
shedding, never in capacity. This module closes ROADMAP item 5's control
loop: a :class:`FleetAutoscaler` consumes the monitor's breach signal plus
the fleet's queue depth / slot occupancy and drives the
:class:`~perceiver_io_tpu.serving.FleetRouter`'s replica count between
``min_replicas`` and ``max_replicas`` — the deployment shape the
Gemma-on-TPU serving comparison (PAPERS.md) assumes: replica counts follow
load, and transitions are invisible to in-flight requests.

**The degradation ladder** (docs/reliability.md): the fleet's responses to
a breach are ORDERED, each rung engaging only when the previous one is not
enough:

1. ``tighten_admission`` — the router scales its effective ``max_pending``
   / deadline by ``slo_shed_factor`` while the monitor reports a breach
   (PR 9, already wired). The cheapest response: push back at the front
   door while the evidence accumulates.
2. ``scale_up`` — the burn (or raw queue pressure past ``queue_high`` ×
   total slot capacity) sustains for ``up_evidence`` consecutive polls and
   the up-cooldown has elapsed: spawn a replica through the engine factory
   (process-global executor caches mean it compiles nothing) — optionally
   with a larger slot count via the slot engine's warm-cache
   ``resize_slots`` path (``scale_up_slots``).
3. ``shed`` — at ``max_replicas`` and still breached: capacity is
   exhausted, rung 1's tightened admission is now the steady state and the
   sheds are the honest signal.
4. ``recover`` → cooldown-gated ``scale_down`` — the breach clears and the
   queue drains below ``queue_low`` × capacity for ``down_evidence``
   consecutive polls of FRESH evidence (the PR 9 stall-hold lesson: an
   empty window is a stalled system, not a healthy one — zero-sample polls
   never count), and ``down_cooldown_s`` has elapsed since the last scale
   action in EITHER direction: retire the least-loaded replica through
   :meth:`FleetRouter.remove_replica` — its in-flight work replays
   exactly-once on survivors (token-identical under greedy decoding), its
   pool pages return tagged ``cause="scale_down"``, and ``healthz`` stays
   ready throughout.

**Hysteresis**: per-direction cooldowns plus the evidence streaks mean a
blip cannot oscillate the fleet — one bad poll resets the healthy streak,
one good poll resets the breach streak, and the band between ``queue_low``
and ``queue_high`` resets BOTH (no fresh evidence either way). A total
outage holds the ladder where it is: the monitor's stall-hold keeps
``breached`` true with no fresh samples, so the autoscaler never reads
silence as recovery.

Everything runs on the fleet's injectable clock and is chaos-scriptable —
``fleet.scale_up`` (spawn failure: the autoscaler absorbs the raise,
counts ``fleet_scale_up_failed_total``, and holds its cooldown) and
``fleet.scale_down`` (replica crash mid-drain) — so the whole flash-crowd
acceptance drill replays bit-identically on CPU
(tests/test_elasticity.py).

Observability (docs/observability.md): ``autoscaler_evaluations_total`` /
``autoscaler_holds_total`` counters, ``autoscaler_ladder_rung`` /
``autoscaler_breach_streak`` / ``autoscaler_healthy_streak`` gauges,
``fleet_scale_up_total`` / ``fleet_scale_down_total`` /
``fleet_scale_up_failed_total`` on the fleet registry, and one
``autoscaler.scale_up`` / ``autoscaler.scale_down`` /
``autoscaler.spawn_failed`` / ``autoscaler.rung`` event per transition —
``obs report``'s elasticity section renders the scale-event timeline from
these.

Wiring: constructing the autoscaler installs it on the fleet
(``fleet.autoscaler``); :meth:`FleetRouter.step` polls it once per
scheduling pass, right after the SLO monitor and BEFORE the pass snapshots
the replica set — a scale-up serves the very pass that decided it. The
serve CLI builds it from the ``--serve.autoscale.*`` flag group.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

#: the ordered degradation ladder; ``autoscaler_ladder_rung`` publishes the
#: current index (0 = steady, nothing degraded)
LADDER = ("steady", "tighten_admission", "scale_up", "shed", "recover")

AUTOSCALER_COUNTERS = (
    "autoscaler_evaluations_total",
    "autoscaler_holds_total",
)


class FleetAutoscaler:
    """Closed-loop replica-count controller over one
    :class:`~perceiver_io_tpu.serving.FleetRouter` (module docstring for
    the ladder semantics).

    :param fleet: the router to control. The ctor installs itself as
        ``fleet.autoscaler``; :meth:`FleetRouter.step` then polls it once
        per scheduling pass.
    :param max_replicas: upper replica bound (rung 3 engages at it).
    :param min_replicas: lower bound — scale-down never goes below it, and
        healthy capacity below it (breaker-open replicas count as
        UNHEALTHY capacity) is itself a scale-up trigger.
    :param factory: engine factory for spawned replicas; default = the
        fleet's own first factory.
    :param up_cooldown_s / down_cooldown_s: per-direction hysteresis.
        The down cooldown gates on the last scale action in EITHER
        direction, so a scale-up is never immediately unwound.
    :param up_evidence / down_evidence: consecutive polls of fresh
        evidence required before acting in that direction.
    :param queue_high / queue_low: queue-depth watermarks as multiples of
        total healthy slot capacity — depth above ``queue_high`` ×
        capacity is pressure (scale-up trigger even without an SLO
        monitor), depth must fall below ``queue_low`` × capacity to count
        as healthy evidence for scale-down.
    :param scale_up_slots: optional slot count for replicas spawned on the
        scale-up path — applied through the slot engine's
        ``resize_slots`` warm-cache rebuild BEFORE the replica takes
        traffic (it is empty, so the rebuild is free of semantics).
    :param clock / registry / tracer: default to the fleet's own.
    """

    def __init__(self, fleet, *, max_replicas: int, min_replicas: int = 1,
                 factory: Optional[Callable[[], object]] = None,
                 up_cooldown_s: float = 15.0, down_cooldown_s: float = 60.0,
                 up_evidence: int = 2, down_evidence: int = 5,
                 queue_high: float = 1.0, queue_low: float = 0.25,
                 scale_up_slots: Optional[int] = None,
                 clock: Optional[Callable[[], float]] = None,
                 registry=None, tracer=None, flight_recorder=None):
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= min_replicas "
                f"({min_replicas})"
            )
        if up_evidence < 1 or down_evidence < 1:
            raise ValueError("evidence thresholds must be >= 1 polls")
        if up_cooldown_s < 0 or down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0 seconds")
        if not 0.0 <= queue_low <= queue_high:
            raise ValueError(
                f"need 0 <= queue_low ({queue_low}) <= queue_high "
                f"({queue_high})"
            )
        if scale_up_slots is not None and scale_up_slots < 1:
            raise ValueError(f"scale_up_slots must be >= 1, got {scale_up_slots}")
        self.fleet = fleet
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.factory = factory
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.up_evidence = int(up_evidence)
        self.down_evidence = int(down_evidence)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.scale_up_slots = scale_up_slots
        self._clock = clock if clock is not None else fleet._clock
        self.registry = registry if registry is not None else fleet.registry
        self.tracer = tracer if tracer is not None else fleet.tracer
        #: optional incident
        #: :class:`~perceiver_io_tpu.observability.FlightRecorder` — a
        #: ladder walk UP to scale_up/shed or a spawn failure dumps a
        #: bundle (docs/observability.md "Flight recorder & incident
        #: bundles"); defaults to the fleet's own when it has one
        self.flight_recorder = (
            flight_recorder if flight_recorder is not None
            else getattr(fleet, "flight_recorder", None)
        )
        self.registry.declare_counters(*AUTOSCALER_COUNTERS)
        self.rung = "steady"
        self._breach_streak = 0
        self._healthy_streak = 0
        # a fresh controller may act as soon as its evidence accumulates —
        # seed both cooldowns as already elapsed
        horizon = max(self.up_cooldown_s, self.down_cooldown_s)
        self._last_up_at = self._clock() - horizon
        self._last_down_at = self._clock() - horizon
        self.scale_ups = 0
        self.scale_downs = 0
        self.spawn_failures = 0
        #: post-mortem records of the last few scale-down victims (replica
        #: id, replayed in-flight count, final KV pool stats incl.
        #: ``frees_by_cause``) — the zero-leak evidence the acceptance
        #: drill and ``extras.elasticity`` read after the engine is gone
        self.retired: list = []
        self._publish_gauges()
        fleet.autoscaler = self

    # -- signal --------------------------------------------------------------
    def _capacity(self) -> int:
        """Total HEALTHY slot capacity: slots (1 for the bucket engine)
        summed over replicas that are closed-breaker and not draining — a
        breaker-open replica is unhealthy capacity, which is exactly why it
        can trigger a scale-up."""
        total = 0
        for r in self.fleet.replicas:
            if r.breaker.state != "closed" or r.draining:
                continue
            total += int(getattr(r.engine, "slots", 1))
        return total

    def _depth(self) -> int:
        return len(self.fleet._queue) + len(self.fleet._dispatched)

    # -- the control loop ----------------------------------------------------
    def poll(self) -> Optional[str]:
        """One control-loop evaluation (the fleet calls it per
        :meth:`~perceiver_io_tpu.serving.FleetRouter.step`). Returns the
        action taken — ``"scale_up"`` / ``"scale_down"`` /
        ``"spawn_failed"`` — or None."""
        self.registry.inc("autoscaler_evaluations_total")
        now = self._clock()
        fleet = self.fleet
        replicas = fleet.replicas
        healthy = sum(
            1 for r in replicas
            if r.breaker.state == "closed" and not r.draining
        )
        capacity = self._capacity()
        depth = self._depth()
        monitor = fleet.slo_monitor
        breached = monitor is not None and monitor.breached
        pressure = capacity == 0 or depth > self.queue_high * capacity
        relaxed = capacity > 0 and depth <= self.queue_low * capacity
        want_up = breached or pressure or healthy < self.min_replicas
        # fresh-evidence streaks (the hysteresis): one contrary poll resets
        # the other direction; the band between the watermarks resets BOTH
        if want_up:
            self._breach_streak += 1
            self._healthy_streak = 0
        elif relaxed:
            self._healthy_streak += 1
            self._breach_streak = 0
        else:
            self._breach_streak = 0
            self._healthy_streak = 0

        action = None
        if self._breach_streak >= self.up_evidence:
            if len(replicas) >= self.max_replicas:
                pass  # rung 3: capacity exhausted — shedding is the response
            elif now - self._last_up_at < self.up_cooldown_s:
                self.registry.inc("autoscaler_holds_total")
            else:
                action = self._scale_up(
                    "slo_breach" if breached
                    else ("unhealthy_capacity" if healthy < self.min_replicas
                          else "queue_pressure"),
                    depth=depth, capacity=capacity,
                )
        elif (
            self._healthy_streak >= self.down_evidence
            and len(replicas) > self.min_replicas
        ):
            if now - max(self._last_up_at, self._last_down_at) \
                    < self.down_cooldown_s:
                self.registry.inc("autoscaler_holds_total")
            else:
                action = self._scale_down(depth=depth, capacity=capacity)

        self._set_rung(self._compute_rung(breached, pressure, action))
        self._publish_gauges()
        return action

    def _scale_up(self, reason: str, *, depth: int, capacity: int
                  ) -> Optional[str]:
        fleet = self.fleet
        before = len(fleet.replicas)
        now = self._clock()
        try:
            replica = fleet.add_replica(self.factory)
        except Exception:
            # spawn failure (the fleet.scale_up chaos drill, or a genuinely
            # broken factory): already counted fleet_scale_up_failed_total
            # and evented by add_replica — hold the cooldown so a broken
            # image cannot spin the control loop, and retry after it
            self.spawn_failures += 1
            self._last_up_at = now
            self._breach_streak = 0
            if self.flight_recorder is not None:
                # the fleet needed capacity and could not get it — the
                # bundle preserves what the control loop saw at that moment
                self.flight_recorder.trigger(
                    "spawn_failed",
                    f"replica spawn failed while scaling up ({reason}; "
                    f"queue depth {depth}, capacity {capacity})",
                    reason=reason, queue_depth=depth, capacity=capacity,
                    replicas=before,
                )
            return "spawn_failed"
        if self.scale_up_slots is not None:
            resize = getattr(replica.engine, "resize_slots", None)
            if resize is not None and \
                    getattr(replica.engine, "slots", None) != self.scale_up_slots:
                # the replica is fresh and empty, so the warm-cache rebuild
                # is free of semantics; it has not taken a dispatch yet
                resize(self.scale_up_slots)
        self._last_up_at = now
        self._breach_streak = 0
        self.scale_ups += 1
        if self.tracer is not None:
            self.tracer.event(
                "autoscaler.scale_up", reason=reason,
                replica=replica.replica_id,
                replicas_before=before, replicas_after=before + 1,
                queue_depth=depth, capacity=capacity,
                slots=int(getattr(replica.engine, "slots", 1)),
            )
        return "scale_up"

    def _scale_down(self, *, depth: int, capacity: int) -> Optional[str]:
        fleet = self.fleet
        victim = fleet.scale_down_victim()
        if victim is None:
            # nothing eligible (e.g. every survivor-candidate is the last
            # healthy one, or open breakers still hold re-queued work) —
            # fresh evidence must accumulate again before the next attempt
            self.registry.inc("autoscaler_holds_total")
            self._healthy_streak = 0
            return None
        before = len(fleet.replicas)
        in_flight = len(victim.handles)
        removed = fleet.remove_replica(victim.replica_id)
        pool = getattr(removed.engine, "_pool", None)
        self.retired.append({
            "replica_id": removed.replica_id,
            "in_flight_replayed": in_flight,
            "pool": None if pool is None else pool.stats(),
        })
        if len(self.retired) > 8:
            self.retired.pop(0)
        self._last_down_at = self._clock()
        self._healthy_streak = 0
        self.scale_downs += 1
        if self.tracer is not None:
            self.tracer.event(
                "autoscaler.scale_down", replica=victim.replica_id,
                replicas_before=before, replicas_after=before - 1,
                in_flight_replayed=in_flight,
                queue_depth=depth, capacity=capacity,
            )
        return "scale_down"

    # -- the ladder ----------------------------------------------------------
    def _compute_rung(self, breached: bool, pressure: bool,
                      action: Optional[str]) -> str:
        n = len(self.fleet.replicas)
        if breached or pressure:
            if action == "scale_up":
                return "scale_up"
            if n >= self.max_replicas:
                return "shed"
            # rung 1 carries the load while scale-up evidence/cooldown
            # accumulates (the router's SLO tightening is already active)
            return "tighten_admission"
        in_down_cooldown = (
            self._clock() - max(self._last_up_at, self._last_down_at)
            < self.down_cooldown_s
        )
        if n > self.min_replicas and (
            action == "scale_down" or self._healthy_streak > 0
            or in_down_cooldown
        ):
            return "recover"
        return "steady"

    def _set_rung(self, rung: str) -> None:
        if rung != self.rung:
            if self.tracer is not None:
                self.tracer.event(
                    "autoscaler.rung", rung=rung, previous=self.rung,
                    index=LADDER.index(rung),
                )
            if (
                self.flight_recorder is not None
                and rung in ("scale_up", "shed")
                and LADDER.index(rung) > LADDER.index(self.rung)
            ):
                # the ladder walked UP past admission tightening: capacity
                # is being added (or is exhausted) — incident-worthy; the
                # recorder's per-kind cooldown keeps a long incident to
                # one bundle
                self.flight_recorder.trigger(
                    "autoscaler_escalation",
                    f"degradation ladder escalated {self.rung} -> {rung}",
                    rung=rung, previous=self.rung,
                    replicas=len(self.fleet.replicas),
                )
            self.rung = rung

    def _publish_gauges(self) -> None:
        self.registry.set_gauge("autoscaler_ladder_rung", LADDER.index(self.rung))
        self.registry.set_gauge("autoscaler_breach_streak", self._breach_streak)
        self.registry.set_gauge("autoscaler_healthy_streak", self._healthy_streak)

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        """JSON-able snapshot for ``serve_stats`` / bench records."""
        now = self._clock()
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "replicas": len(self.fleet.replicas),
            "rung": self.rung,
            "rung_index": LADDER.index(self.rung),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "spawn_failures": self.spawn_failures,
            "breach_streak": self._breach_streak,
            "healthy_streak": self._healthy_streak,
            "evaluations": int(
                self.registry.counter("autoscaler_evaluations_total")
            ),
            "holds": int(self.registry.counter("autoscaler_holds_total")),
            "up_cooldown_remaining_s": round(
                max(0.0, self.up_cooldown_s - (now - self._last_up_at)), 6
            ),
            "down_cooldown_remaining_s": round(
                max(0.0, self.down_cooldown_s
                    - (now - max(self._last_up_at, self._last_down_at))), 6
            ),
        }
