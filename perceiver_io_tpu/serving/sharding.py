"""Sharded serving runtime: run the slot engine over the parallelism mesh.

Training has had a 4-axis device mesh (``parallel/mesh.py``), declarative
``PartitionSpec`` rules (``parallel/partition.py``), and multihost wiring
since the first PRs — but every serving executor compiled single-device,
so a fleet could only scale by whole-chip replicas. This module is the
bridge (docs/serving.md "Sharded serving"): a :class:`ServingMeshSpec`
resolves to a 2-axis serving mesh (``data`` × ``model``; fsdp/seq pinned
at 1) over an explicit **device subset** (:func:`~perceiver_io_tpu.
parallel.mesh.device_slice` — N replicas × M-device replicas, the second
scaling axis), and a :class:`ServingSharding` places the slot engine's
whole working set onto it:

- **params** — the Megatron TP rules (``infer_param_specs``): q/k/v and
  MLP-up kernels column-parallel on ``model``, o/MLP-down row-parallel,
  everything replicated across ``data``.
- **slot state** — the serving rule set
  (:data:`~perceiver_io_tpu.parallel.partition.SERVING_STATE_RULES`):
  slots/batch along ``data``; attention heads — dense per-slot caches,
  the paged pool's flat ``pool_k``/``pool_v``, staging caches — along
  ``model``. The pool's token dimension stays UNsharded across ``data``:
  block tables address one shared pool, so every data shard must see
  every page (cross-slot sharing is the paged layout's point).

The executors themselves stay the slot engine's: they compile under
``jax.jit`` **over the mesh** — committed sharded inputs plus pinned
``out_shardings`` make XLA GSPMD partition the computation and emit the
collectives (head-parallel attends, the o-projection all-reduce — the
``sharded_flash_attention``/``sharded_paged_attention`` shapes from
SNIPPETS.md [1], derived instead of hand-written), and
:func:`~perceiver_io_tpu.ops.paged_attention.gather_constraint` keeps the
paged gather's dense view head-sharded so the attend computes shard-local.
GSPMD guarantees semantics for ANY sharding, so exactness degrades
gracefully: a degenerate 1-device mesh compiles the identical program
(byte-identical behavior, pinned), and a real multi-device mesh is greedy
token-identical to the unsharded engine (the o-projection partial-sum
order is the only float difference; pinned on an 8-virtual-device CPU
mesh by ``tests/test_sharding.py``).

Mesh geometry is part of executor identity: the spec's fingerprint folds
into every slot-engine cache key and the compile ledger's component
taxonomy (``mesh``), so a mesh flip REBUILDS and attributes instead of
silently reusing a single-device trace (docs/observability.md).
"""
from __future__ import annotations

import dataclasses
import weakref
from typing import Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from perceiver_io_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_SEQ,
    MeshConfig,
    device_slice,
    make_mesh,
)
from perceiver_io_tpu.parallel.partition import (
    infer_param_specs,
    serving_state_spec,
    serving_state_specs,
)


@dataclasses.dataclass(frozen=True)
class ServingMeshSpec:
    """Declarative serving-mesh geometry: ``data`` × ``model`` devices at
    ``device_offset`` into the process's device list. ``data`` shards the
    slot/batch dimension (slots must divide evenly), ``model`` the
    attention heads and KV caches (heads must divide evenly); fsdp/seq are
    pinned at 1 — serving holds no optimizer state and the slot engine's
    context fits one shard's HBM by construction (the paged pool is the
    context-scaling lever).

    ``device_offset`` is the fleet hook: replica i of an M-device fleet
    resolves at offset ``i*M`` so replicas own disjoint subsets
    (:func:`fleet_mesh_specs`)."""

    data: int = 1
    model: int = 1
    device_offset: int = 0

    def __post_init__(self):
        if self.data < 1 or self.model < 1:
            raise ValueError(
                f"mesh axis sizes must be >= 1, got data={self.data} "
                f"model={self.model}"
            )
        if self.device_offset < 0:
            raise ValueError(
                f"device_offset must be >= 0, got {self.device_offset}"
            )

    @property
    def num_devices(self) -> int:
        return self.data * self.model

    def resolve(self, devices: Optional[Sequence[jax.Device]] = None
                ) -> "ServingSharding":
        """Claim the device subset and build the resolved sharding layer."""
        subset = device_slice(
            self.num_devices, offset=self.device_offset, devices=devices
        )
        mesh = make_mesh(
            MeshConfig(data=self.data, fsdp=1, model=self.model, seq=1),
            devices=subset,
        )
        return ServingSharding(self, mesh)


class ServingSharding:
    """A resolved serving mesh: placement + out-sharding helpers for the
    slot engine's executors. Constructed via :meth:`ServingMeshSpec.resolve`
    (or :func:`as_serving_sharding` from an existing 4-axis ``Mesh`` whose
    fsdp/seq axes are 1)."""

    def __init__(self, spec: ServingMeshSpec, mesh: Mesh):
        self.spec = spec
        self.mesh = mesh
        self.data_size = int(mesh.shape.get(AXIS_DATA, 1))
        self.model_size = int(mesh.shape.get(AXIS_MODEL, 1))
        self.num_devices = int(np.prod(tuple(mesh.shape.values())))
        #: (allocator, group) when this sharding came from a
        #: :class:`MeshGroupAllocator` — see :meth:`release`
        self._allocator_claim = None

    def release(self) -> None:
        """Free this sharding's :class:`MeshGroupAllocator` group claim
        explicitly (idempotent; no-op for shardings resolved directly).
        ``Replica.restart`` calls it on the crashed engine's sharding
        before re-running the factory, so the rebuild reclaims the crashed
        group deterministically instead of waiting for the garbage
        collector to clear the weakref."""
        claim = self._allocator_claim
        if claim is None:
            return
        self._allocator_claim = None
        allocator, group = claim
        ref = allocator._claims.get(group)
        if ref is not None and ref() is self:
            del allocator._claims[group]

    # -- identity ------------------------------------------------------------
    def fingerprint(self) -> Tuple:
        """Executor-cache key component: axis sizes + the concrete device
        ids. Device ids matter — two fleet replicas with the SAME geometry
        on DISJOINT subsets must not share a compiled executor whose
        shardings bake in the other replica's devices."""
        return (
            "mesh", self.data_size, self.model_size,
            tuple(int(d.id) for d in self.mesh.devices.flat),
        )

    def describe(self) -> str:
        """Ledger-component / stats rendering: ``data x model @ devices``."""
        first = int(self.mesh.devices.flat[0].id)
        return (
            f"{self.data_size}x{self.model_size}"
            f"@{self.num_devices}dev+{first}"
        )

    # -- shardings -----------------------------------------------------------
    def named(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def state_shardings(self, state):
        """NamedSharding pytree for a slot-state dict (the serving rules)."""
        specs = serving_state_specs(state, self.mesh)
        return jax.tree_util.tree_map(self.named, specs)

    def leaf_sharding(self, name: str, shape: Tuple[int, ...]) -> NamedSharding:
        return self.named(serving_state_spec(name, tuple(shape), self.mesh))

    def table_sharding(self, slots: int, pages: int) -> NamedSharding:
        return self.leaf_sharding("table", (slots, pages))

    def tokens_sharding(self, slots: int) -> NamedSharding:
        return self.leaf_sharding("tokens", (slots,))

    def gathered_kv_spec(self) -> P:
        """Spec for the paged attend's transient dense (slots, heads, n, d)
        gather — slots along data, heads along model — applied inside
        :func:`~perceiver_io_tpu.ops.paged_attention.gather_kv` itself via
        :func:`~perceiver_io_tpu.ops.paged_attention.gather_constraint`
        (non-divisible dims dropped per shape, e.g. a batch-1 prefill
        gather) so NO gathered view materializes replicated — the decode
        step, the boundary step, and the prefill finalize alike."""
        return P(AXIS_DATA, AXIS_MODEL, None, None)

    # -- placement -----------------------------------------------------------
    def put_params(self, params):
        """Tensor-parallel param placement (``infer_param_specs``: Megatron
        TP rules on ``model``; fsdp is 1 so everything else replicates)."""
        specs = infer_param_specs(params, self.mesh)
        return jax.device_put(
            params, jax.tree_util.tree_map(self.named, specs)
        )

    def put_state(self, state):
        return jax.device_put(state, self.state_shardings(state))

    def put_leaf(self, name: str, value):
        return jax.device_put(
            value, self.leaf_sharding(name, np.shape(value))
        )


def as_serving_sharding(
    mesh: Union[None, ServingMeshSpec, ServingSharding, Mesh],
) -> Optional[ServingSharding]:
    """Coerce the slot engine's ``mesh=`` argument: None passes through
    (unsharded — today's exact code path), a spec resolves against the
    process's devices, an existing 4-axis ``Mesh`` is accepted when its
    fsdp/seq axes are 1 (the training-mesh reuse case)."""
    if mesh is None or isinstance(mesh, ServingSharding):
        return mesh
    if isinstance(mesh, ServingMeshSpec):
        return mesh.resolve()
    if isinstance(mesh, Mesh):
        shape = dict(mesh.shape)
        extra = {
            a: s for a, s in shape.items()
            if a not in (AXIS_DATA, AXIS_MODEL) and s > 1
        }
        if extra:
            raise ValueError(
                f"serving meshes use only ({AXIS_DATA!r}, {AXIS_MODEL!r}); "
                f"got extra axes {extra} — serving holds no optimizer state "
                f"to {AXIS_FSDP}-shard and no {AXIS_SEQ} ring"
            )
        spec = ServingMeshSpec(
            data=int(shape.get(AXIS_DATA, 1)),
            model=int(shape.get(AXIS_MODEL, 1)),
        )
        return ServingSharding(spec, mesh)
    raise TypeError(
        "mesh must be None, a ServingMeshSpec, a ServingSharding, or a "
        f"jax.sharding.Mesh, got {type(mesh).__name__}"
    )


def fleet_mesh_specs(
    spec: ServingMeshSpec,
    replicas: int,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Tuple[ServingMeshSpec, ...]:
    """Disjoint per-replica mesh specs: replica i at device offset
    ``spec.device_offset + i * spec.num_devices``. Validates the whole
    fleet fits the device budget up front (an over-subscribed fleet must
    fail at launch, not alias devices silently mid-scale-up)."""
    if replicas < 1:
        raise ValueError(f"replicas must be >= 1, got {replicas}")
    per = spec.num_devices
    # validate the LAST slice; earlier ones are subsets of the budget
    device_slice(
        per, offset=spec.device_offset + (replicas - 1) * per, devices=devices
    )
    return tuple(
        dataclasses.replace(spec, device_offset=spec.device_offset + i * per)
        for i in range(replicas)
    )


class MeshGroupAllocator:
    """Hands each engine spawn a disjoint device group — the
    engine-factory form the serve CLI uses: every factory call (initial
    spawn, crash rebuild, autoscaler scale-up) ``acquire()``s the first
    FREE group of ``spec.num_devices`` devices.

    A group is busy while an engine built on it is alive: claims are
    weakrefs to the resolved :class:`ServingSharding` the engine holds for
    its lifetime, plus an explicit :meth:`ServingSharding.release` —
    ``Replica.restart`` releases the crashed engine's claim *before*
    re-running its factory, so the rebuild reclaims the crashed group
    deterministically instead of aliasing a live replica's devices (and a
    retired engine whose claim was never released explicitly frees it
    through the weakref when it is collected). Only when every group is
    claimed does the allocator wrap round-robin (documented, not an
    error: CPU-virtual devices alias harmlessly; size real pods so
    ``max_replicas x num_devices <= len(jax.devices())``)."""

    def __init__(self, spec: ServingMeshSpec, *,
                 devices: Optional[Sequence[jax.Device]] = None):
        all_devices = list(devices) if devices is not None else jax.devices()
        self.spec = spec
        self.groups = max(
            1, (len(all_devices) - spec.device_offset) // spec.num_devices
        )
        self._devices = devices
        self._claims: dict = {}  # group index -> weakref to its ServingSharding
        self._wrap = 0

    def acquire(self) -> "ServingSharding":
        """Resolve the first free group (round-robin wrap when none is)."""
        free = [
            i for i in range(self.groups)
            if (ref := self._claims.get(i)) is None or ref() is None
        ]
        if free:
            group = free[0]
        else:
            group = self._wrap % self.groups
            self._wrap += 1
        spec = dataclasses.replace(
            self.spec,
            device_offset=self.spec.device_offset
            + group * self.spec.num_devices,
        )
        sharding = spec.resolve(self._devices)
        self._claims[group] = weakref.ref(sharding)
        sharding._allocator_claim = (self, group)
        return sharding


# ---------------------------------------------------------------- probe main
def _probe_main(argv: Optional[list] = None) -> int:
    """Self-contained sharded-serving probe (``python -m
    perceiver_io_tpu.serving.sharding``): build a tiny CLM, serve ragged
    greedy prompts through a slot engine on the requested mesh, print ONE
    JSON line — tokens/s, per-shard resident bytes, the emitted tokens
    (the parent's token-identity pin), compile count. ``bench.py
    extras.sharded_serving`` runs it twice (1-device vs 8-virtual-device
    CPU mesh, the device count injected via ``XLA_FLAGS`` in the child
    env) and A/Bs the records; ``make shard-bench`` is the one-command
    form."""
    import argparse
    import json
    import time

    parser = argparse.ArgumentParser(description=_probe_main.__doc__)
    parser.add_argument("--data", type=int, default=1)
    parser.add_argument("--model", type=int, default=1)
    parser.add_argument("--device-offset", type=int, default=0)
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--requests", type=int, default=8)
    parser.add_argument("--new-tokens", type=int, default=8)
    parser.add_argument("--kv-layout", default="dense",
                        choices=("dense", "paged", "paged_int8"))
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    import jax.numpy as jnp

    from perceiver_io_tpu.inference.generate import GenerationConfig
    from perceiver_io_tpu.inference.samplers import SamplingConfig
    from perceiver_io_tpu.models.text.clm import (
        CausalLanguageModel,
        CausalLanguageModelConfig,
    )
    # the canonical class, NOT this file's local binding: under
    # ``python -m perceiver_io_tpu.serving.sharding`` this module runs as
    # ``__main__`` while the engine isinstance-checks against the import
    # system's copy
    from perceiver_io_tpu.serving import (
        BucketTable,
        ServingMeshSpec as _CanonicalSpec,
        SlotServingEngine,
    )

    cfg = CausalLanguageModelConfig(
        vocab_size=93, max_seq_len=64, max_latents=16, num_channels=32,
        num_heads=4, num_self_attention_layers=2, cross_attention_dropout=0.0,
    )
    model = CausalLanguageModel(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 64), jnp.int32), 16
    )["params"]
    gen = GenerationConfig(
        max_new_tokens=args.new_tokens, num_latents=4,
        sampling=SamplingConfig(temperature=0.0),
    )
    spec = _CanonicalSpec(
        data=args.data, model=args.model, device_offset=args.device_offset
    )
    engine = SlotServingEngine(
        model, params, gen, BucketTable(prompt_lens=(16, 32), batch_sizes=(1,)),
        slots=args.slots, mesh=spec, kv_layout=args.kv_layout,
    )
    compiles = engine.warmup()
    rng = np.random.default_rng(args.seed)
    prompts = [
        rng.integers(1, 93, size=int(n)).astype(np.int32)
        for n in rng.integers(8, 28, size=args.requests)
    ]
    t0 = time.monotonic()
    outs = engine.serve(prompts)
    wall = time.monotonic() - t0
    stats = engine.stats()
    resident = int(stats.get("kv_pool", {}).get("resident_bytes", 0)) or int(
        engine.registry.gauge("kv_cache_resident_bytes") or 0
    )
    record = {
        "devices": len(jax.devices()),
        "mesh": {"data": args.data, "model": args.model},
        "kv_layout": engine.kv_layout,
        "compile_count": compiles,
        "tokens_generated": int(stats["tokens_generated"]),
        "tokens_per_s": round(stats["tokens_generated"] / max(wall, 1e-9), 2),
        "wall_s": round(wall, 3),
        "resident_bytes": resident,
        "per_shard_resident_bytes": resident // max(1, args.model),
        "tokens": [np.asarray(o).tolist() for o in outs],
    }
    print(json.dumps(record))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    raise SystemExit(_probe_main())
