"""Token-granular continuous batching: the persistent-slot decode engine.

The bucket engine (``serving/engine.py``) schedules at *generation*
granularity: a micro-batch is packed, a whole compiled ``generate()`` runs
to completion, and only then can queued requests join — a newly arrived
prompt waits a full batch of decoding, and a row that hits EOS early burns
its slot until the slowest row finishes. Both "Ragged Paged Attention"
(TPU serving kernels over ragged in-flight batches) and the compiler-first
O(1)-caching paper (PAPERS.md) land on the same fix: keep a **fixed-shape
resident decode state** and make scheduling **per token**.

This module is that engine. Serving splits into two compiled phases:

- **Prefill** — one executor per *prompt bucket* ``L``: right-align the
  prompt into the full decode window, run
  :func:`~perceiver_io_tpu.inference.generate._decode_prefill` at batch 1,
  and ``dynamic_update_slice`` the resulting KV caches + row state into
  slot ``s`` of the persistent multi-slot state. ``s`` is a traced scalar,
  so admitting into any slot reuses one program.
- **Decode** — exactly ONE fixed-shape executor advances all ``S`` slots by
  one token per call, using the per-row ``length``/``m`` vectors
  (:func:`~perceiver_io_tpu.inference.generate._slot_decode_step`) for
  ragged masking. No bucket grid on the decode path, no retracing as
  traffic mixes. When any active slot has filled its latent segment
  (``m == max_latents``), the engine switches to the **boundary variant**:
  a second executor that computes both the latent-growth step and the
  boundary-migration step (:func:`..generate._decode_step_boundary`) and
  selects per row — correct for mixed phases at ~2x step cost, used only
  while a boundary-phase row is resident.

``step()`` is a token-level scheduler: it retires slots immediately on
EOS / ``max_new_tokens`` / deadline expiry, refills freed slots
mid-generation by prefilling the next queued request into them, and keeps
the per-request trace alive across the slot lifecycle
(``serving.slot_assigned`` / ``serving.slot_retired`` events on the
request's trace; docs/observability.md).

**Chunked prefill** (``prefill_chunk=C``): a long-prompt admission is the
one remaining head-of-line stall — the full-window prefill runs between
two decode steps, so every resident slot's inter-token latency spikes by
the whole prompt's cost. With chunking, the prefix cross-k/v cache is
built ``C`` token positions at a time in a batch-1 *staging* buffer by ONE
bucket-independent chunk executor (traced offset/slot/m; a final *pure
finalize* call — the other ``lax.cond`` branch of the same program — runs
the latent attend + stack and inserts the finished row), one call per
:meth:`SlotServingEngine.step` interleaved with the resident decode steps
— the "Ragged Paged Attention" admission pattern (PAPERS.md). The
persistent state never holds a half-built row, so decode steps between
chunks stay oblivious.

**Prefix sharing** (``prefix_cache="on"``, paged layout only;
docs/serving.md "Prefix sharing"): a radix index over published full
prompt-prefix blocks lets an admission whose leading token ids match map
those pool blocks BY REFERENCE (per-block refcounts), copy-on-write at
the first divergent or partially-usable block, and prefill only the
un-shared suffix through the ``start_position``-taking shared executor —
a fully-hot system prompt admits with zero staged chunks, so TTFT
collapses to block-table writes plus the latent finalize. A shared page
is never written through (write routing + the COW guard), frees are
refcount-aware (a block returns to the pool on its LAST deref), and
unreferenced cached prefixes LRU-drop under pool pressure before any
admission is made to wait. Greedy output stays token-identical to the
unshared path (pinned by ``tests/test_prefix_cache.py``).

**Decode strategy** (``decode_strategy=...`` /
``PERCEIVER_DECODE_STRATEGY``): the boundary decode variant's
implementation — cached migration step vs full windowed recompute — is a
measured platform/shape choice (``inference/decode_strategy.py``; the
cached step loses to recompute on CPU, docs/benchmarks.md). Both are
exact, so greedy output stays token-identical either way; ``"auto"`` uses
the autotuner's memoized verdict (``warmup()`` measures it once when asked
explicitly).

**Speculative decoding** (``speculation="k<K>d<D>"`` /
``PERCEIVER_SPECULATION``; docs/serving.md "Speculative decoding"): a
self-draft proposer (the model's own first ``D`` self-attention layers,
``inference/speculative.py``) drafts ``K`` tokens per round and ONE
fixed-shape lane-batched verify forward scores all ``K+1`` positions; the
longest matching drafted prefix — ``n_e ∈ [1, K+1]`` tokens — advances
the persistent state in a single step. Greedy output stays
token-identical by the lane construction (each lane IS the window the
plain step would have seen), so speculation composes with every KV axis:
the verify executors pass the dense/paged/int8 caches through untouched
(recompute lanes never read them past the prefill), the pool maps each
round's worst-case burst atomically (``kv_pool.ensure_many`` —
multi-block crossings, lazy admission, and preemption victims behave as
``n_e`` sequential steps would), and every accepted token gets its own
``on_token`` delivery, ITL sample, and timeline event in index order.
Whether a round PAYS is measured
(``decode_strategy.autotune_speculation``) and persisted beside the
boundary/KV-layout/prefix-cache verdicts; ``"off"`` is byte-identical to
the pre-speculation engine.

Compile-count guarantee: at most ``len(prompt_buckets)`` prefill executors
plus one decode executor plus its boundary variant, plus ONE chunked-
prefill executor when ``prefill_chunk`` is set (``+2 -> +3``), plus the
draft + verify executor pair when ``speculation`` is on (``+2``) —
mixed-length traffic causes **zero** additional retraces after
:meth:`SlotServingEngine.warmup` (pinned by ``tests/test_slots.py`` /
``tests/test_decode_strategy.py`` / ``tests/test_speculative.py``).

Exactness: for greedy decoding the slot engine is token-identical to
unbucketed per-request ``generate()``, including requests admitted into
recycled slots mid-generation — each row's dynamic phase schedule (latent
growth while ``m < max_latents``, then boundary migration) reproduces the
static per-request plan exactly. Two scope restrictions keep that true,
enforced with precise errors at ``submit``:

- ``prompt_len + max_new_tokens <= max_seq_len`` — the sliding-window
  phase (semantically forced recompute, ``generate`` module docstring) has
  no incremental slot form; route longer generations to the bucket engine.
- ``prompt_len >= min(bucket_len, num_latents)`` — left pads must never
  occupy latent slots (the boundary cache's validity precondition; the
  bucket engine serves such prompts via its windowed-recompute demotion).

Fault tolerance mirrors the bucket engine (docs/reliability.md): bounded
queue backpressure, per-request deadlines checked every token (expiry
mid-generation retires the slot and ends the request's one terminal span
``timed_out``), per-request chaos hooks at admit time, executor-level
faults failing only resident requests while the queue survives.
"""
from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.inference import decode_strategy as decode_strategy_mod
from perceiver_io_tpu.inference import speculative as speculative_mod
from perceiver_io_tpu.inference.generate import (
    GenerationConfig,
    _decode_forward,
    _decode_prefill,
    _decode_step_boundary,
    _decode_step_boundary_paged,
    _prefill_chunk_kv,
    _prefill_finalize,
    _prefill_finalize_paged,
    _slot_decode_step,
    _slot_decode_step_paged,
    cached_executor,
    executor_cache_stats,
    ledger_model_id,
    model_fingerprint,
    register_executor_cache,
)
from perceiver_io_tpu.inference.samplers import apply_min_new_tokens, sample_logits
from perceiver_io_tpu.observability.timeline import tenant_label, tier_label
from perceiver_io_tpu.ops import paged_attention as paged_ops
from perceiver_io_tpu.serving.engine import ServeRequest, ServingEngine, _round_ms
from perceiver_io_tpu.serving.kv_pool import (
    KVPagePool,
    PoolExhausted,
    PrefixBlockIndex,
    SwapBundle,
)
from perceiver_io_tpu.serving.sharding import as_serving_sharding

#: preemption policies (docs/serving.md "Preemption & priorities" and
#: "Host-swap preemption"): ``off`` keeps reserve-worst-case admission;
#: ``recompute`` admits on prompt pages and replays preempted victims
#: from their original prompt (token-identical under greedy — no KV
#: state is saved or restored); ``swap`` gathers a victim's pool pages to
#: host memory and restores them at readmission, skipping prompt replay
#: entirely (pay transfer instead of recompute); ``auto`` picks swap vs
#: recompute per victim from the live post-mortem cost model.
PREEMPTION_MODES = ("off", "recompute", "swap", "auto")

_EXECUTOR_CACHE: dict = register_executor_cache({})


def _donate(*argnums: int) -> tuple:
    """Donate the persistent slot state into the executor (in-place cache
    update on device) — skipped on CPU, where donation is unimplemented and
    only produces a warning per compile."""
    return argnums if jax.default_backend() != "cpu" else ()


def _jit(fn, donate: tuple, out_shardings=None):
    """jit an executor body, optionally pinning its output shardings to the
    serving mesh (docs/serving.md "Sharded serving"). Pinning matters for
    trace stability, not just placement: the persistent state round-trips
    through every executor, so an output GSPMD re-sharded differently from
    its input would change the next call's committed-input signature and
    retrace. ``None`` (unsharded engine) is byte-for-byte today's
    ``jax.jit`` call."""
    if out_shardings is None:
        return jax.jit(fn, donate_argnums=donate)
    return jax.jit(fn, donate_argnums=donate, out_shardings=out_shardings)


_STATE_SHAPES: dict = {}  # (model key, param dtypes) -> (logits, cache) shapes


def _prefill_shapes(model, params):
    """ShapeDtypeStructs of one row's prefill outputs, via an abstract eval
    (no compile, no FLOPs). Tracing the flax module still costs hundreds of
    ms, so the result is memoized per (architecture, param dtypes) — engine
    construction and post-fault state rebuilds stay cheap."""
    key = (
        type(model).__qualname__, model_fingerprint(model),
        tuple(sorted({str(l.dtype) for l in jax.tree_util.tree_leaves(params)})),
    )
    hit = _STATE_SHAPES.get(key)
    if hit is not None:
        return hit
    n = model.max_seq_len

    def fn(p):
        window = jnp.zeros((1, n), jnp.int32)
        pad = jnp.zeros((1,), jnp.int32)
        return model.apply(
            {"params": p}, window, pad, jnp.asarray(1, jnp.int32),
            method=_decode_prefill,
        )

    logits_s, cache_s, _, _ = jax.eval_shape(fn, params)
    if len(_STATE_SHAPES) > 32:
        _STATE_SHAPES.clear()
    _STATE_SHAPES[key] = (logits_s, cache_s)
    return logits_s, cache_s


def _blank_state(model, params, slots: int, pad_token_id: int,
                 pool_tokens: Optional[int] = None,
                 quantized: bool = False) -> dict:
    """Zero-initialized persistent multi-slot decode state; KV-cache and
    logits shapes/dtypes track the model's computation dtype.

    ``pool_tokens`` selects the block-paged cross-KV layout
    (docs/serving.md): instead of per-slot dense ``cross_k/cross_v`` rows
    sized at the full context, the state holds ONE flat token-major pool
    ``pool_k/pool_v`` of that many positions, addressed through the
    engine's :class:`~perceiver_io_tpu.serving.kv_pool.KVPagePool` block
    tables. ``quantized`` (the ``paged_int8`` layout) stores the pool
    int8 and adds per-(position, head) f32 dequant scales ``scale_k/
    scale_v`` addressed by the same flat indices; a zero scale (every
    never-written position) dequantizes to exactly 0.0, so the blank
    pool reads as harmlessly as the exact layout's zeros. The
    latent-stack caches stay dense either way — they scale with
    ``max_latents`` (a model constant), not ``max_context``, so they are
    not part of the ``slots × max_context`` term the pool breaks."""
    n = model.max_seq_len
    logits_s, cache_s = _prefill_shapes(model, params)

    def z(sds):
        return jnp.zeros((slots,) + tuple(sds.shape[1:]), sds.dtype)

    state = {
        "window": jnp.full((slots, n), pad_token_id, jnp.int32),
        "pad": jnp.full((slots,), n, jnp.int32),
        "length": jnp.zeros((slots,), jnp.int32),
        "m": jnp.zeros((slots,), jnp.int32),
        "steps": jnp.zeros((slots,), jnp.int32),
        "logits": z(logits_s),
        "stack_k": tuple(z(s) for s in cache_s["stack_k"]),
        "stack_v": tuple(z(s) for s in cache_s["stack_v"]),
    }
    if pool_tokens is None:
        state["cross_k"] = z(cache_s["cross_k"])
        state["cross_v"] = z(cache_s["cross_v"])
    else:
        _, h, _, d = cache_s["cross_k"].shape
        pool_dtype = jnp.int8 if quantized else cache_s["cross_k"].dtype
        state["pool_k"] = jnp.zeros((pool_tokens, h, d), pool_dtype)
        state["pool_v"] = jnp.zeros((pool_tokens, h, d), pool_dtype)
        if quantized:
            state["scale_k"] = jnp.zeros((pool_tokens, h, 1), jnp.float32)
            state["scale_v"] = jnp.zeros((pool_tokens, h, 1), jnp.float32)
    return state


def _insert_row(state: dict, slot, *, window, pad, logits, cache, length, m,
                table_row=None, block_size: Optional[int] = None):
    """Insert one prefilled row (batch-1 caches + row state) into slot
    ``slot`` of the persistent multi-slot state — shared by the per-bucket
    prefill executor and the chunked-prefill finalize so the two admission
    paths cannot drift. ``slot`` and ``m`` may be traced scalars.

    Under the paged layout (``table_row`` given) the row's dense batch-1
    ``cross_k/cross_v`` scatter into the shared pool through the slot's
    block table: live positions land on the slot's mapped blocks, positions
    past them route to the null block (trash the masked attends never
    read)."""
    def upd(dst, src):
        return jax.lax.dynamic_update_slice(
            dst, src.astype(dst.dtype), (slot,) + (0,) * (dst.ndim - 1)
        )

    new = dict(state)
    if "cross_k" not in cache:
        # prefix-sharing finalize: the cross k/v already live in the pool
        # (shared blocks + the admission's own staged chunks) — only the
        # row state and the latent-stack caches get inserted here
        pass
    elif table_row is None:
        new["cross_k"] = upd(state["cross_k"], cache["cross_k"])
        new["cross_v"] = upd(state["cross_v"], cache["cross_v"])
    else:
        n = cache["cross_k"].shape[2]
        flat = paged_ops.flat_position_indices(table_row, block_size, n)
        # scatter_kv quantizes when the state carries scales (paged_int8)
        new["pool_k"], scale_k = paged_ops.scatter_kv(
            state["pool_k"], state.get("scale_k"), flat,
            cache["cross_k"][0].transpose(1, 0, 2),
        )
        new["pool_v"], scale_v = paged_ops.scatter_kv(
            state["pool_v"], state.get("scale_v"), flat,
            cache["cross_v"][0].transpose(1, 0, 2),
        )
        if scale_k is not None:
            new["scale_k"], new["scale_v"] = scale_k, scale_v
    new["stack_k"] = tuple(
        upd(d, s) for d, s in zip(state["stack_k"], cache["stack_k"])
    )
    new["stack_v"] = tuple(
        upd(d, s) for d, s in zip(state["stack_v"], cache["stack_v"])
    )
    new["window"] = upd(state["window"], window)
    new["pad"] = upd(state["pad"], pad)
    new["length"] = upd(state["length"], length.astype(jnp.int32))
    new["m"] = upd(state["m"], jnp.reshape(m, (1,)).astype(jnp.int32))
    new["steps"] = upd(state["steps"], jnp.zeros((1,), jnp.int32))
    new["logits"] = upd(state["logits"], logits)
    return new


def _build_prefill_executor(model, config: GenerationConfig, bucket_len: int,
                            block_size: Optional[int] = None,
                            out_shardings=None):
    """Prefill one request at prompt bucket ``bucket_len`` and insert its
    caches + row state into slot ``slot`` of the persistent state.
    ``block_size`` selects the paged layout: the executor additionally
    takes the slot's block-table row and scatters the cross cache into the
    shared pool instead of the dense slot row."""
    n = model.max_seq_len
    m0 = min(bucket_len, config.num_latents)

    def prefill(params, ids, pad_count):
        window = jnp.full((1, n), config.pad_token_id, ids.dtype)
        window = window.at[:, n - bucket_len:].set(ids)
        pad = pad_count.astype(jnp.int32) + (n - bucket_len)
        logits, cache, length, _ = model.apply(
            {"params": params}, window, pad, jnp.asarray(m0, jnp.int32),
            method=_decode_prefill,
        )
        return window, pad, logits, cache, length

    if block_size is None:
        def run(params, ids, pad_count, slot, state):
            window, pad, logits, cache, length = prefill(params, ids, pad_count)
            return _insert_row(
                state, slot, window=window, pad=pad, logits=logits,
                cache=cache, length=length, m=jnp.asarray(m0, jnp.int32),
            )

        return _jit(run, _donate(4), out_shardings)

    def run_paged(params, ids, pad_count, slot, table_row, state):
        window, pad, logits, cache, length = prefill(params, ids, pad_count)
        return _insert_row(
            state, slot, window=window, pad=pad, logits=logits, cache=cache,
            length=length, m=jnp.asarray(m0, jnp.int32),
            table_row=table_row, block_size=block_size,
        )

    return _jit(run_paged, _donate(5), out_shardings)


def _build_chunked_prefill_executor(model, config: GenerationConfig, chunk: int,
                                    block_size: Optional[int] = None,
                                    out_shardings=None):
    """ONE bucket-independent executor for chunked admission, two
    ``lax.cond`` branches in one compiled program. Stage calls project the
    ``kv_norm``-side cross k/v of ``chunk`` prefix token positions into a
    batch-1 staging cache
    (:func:`~perceiver_io_tpu.inference.generate._prefill_chunk_kv`); the
    final call runs ONLY the finalize — latent-side k/v, gathered
    cross-attention, the self-attention stack
    (:func:`~..generate._prefill_finalize`) — and inserts caches + row
    state into slot ``slot``. Keeping the branches disjoint matters for the
    tail latency the feature exists to cut: the finalize call must not
    also pay a chunk's staging math, or the admission's worst per-step
    stall creeps back toward the one-shot prefill's.

    ``offset``, ``m``, ``slot`` and ``is_final`` are traced, so every
    chunk of every prompt bucket reuses this single program — the
    compile-count bound grows by exactly one
    (``len(prompt_buckets) + 2 -> + 3``, pinned by tests)."""

    def run(params, tokens, offset, is_final, window, pad_count, m, slot,
            table_row, stage_k, stage_v, state):
        def stage(ops):
            stage_k, stage_v, state = ops
            k_c, v_c = model.apply(
                {"params": params}, tokens, offset, method=_prefill_chunk_kv
            )
            stage_k = jax.lax.dynamic_update_slice(
                stage_k, k_c.astype(stage_k.dtype), (0, 0, offset, 0)
            )
            stage_v = jax.lax.dynamic_update_slice(
                stage_v, v_c.astype(stage_v.dtype), (0, 0, offset, 0)
            )
            return stage_k, stage_v, state

        def fin(ops):
            stage_k, stage_v, state = ops
            logits, cache, length, _ = model.apply(
                {"params": params}, window, pad_count, m, stage_k, stage_v,
                method=_prefill_finalize,
            )
            state = _insert_row(
                state, slot, window=window, pad=pad_count, logits=logits,
                cache=cache, length=length, m=m,
                # paged layout: the finalized row's dense cross cache
                # scatters into the pool through the slot's block table
                # (live positions -> mapped blocks, the rest -> null block)
                table_row=None if block_size is None else table_row,
                block_size=block_size,
            )
            return stage_k, stage_v, state

        return jax.lax.cond(is_final, fin, stage, (stage_k, stage_v, state))

    return _jit(run, _donate(9, 10, 11), out_shardings)


def _build_shared_prefill_executor(model, config: GenerationConfig, chunk: int,
                                   block_size: int, out_shardings=None,
                                   gather_sharding=None):
    """The prefix-sharing admission executor (docs/serving.md "Prefix
    sharing"): ONE compiled program, two ``lax.cond`` branches, taking the
    admission's **start position** so shared prefix positions are never
    projected again.

    Stage calls project the ``kv_norm``-side cross k/v of ``chunk``
    prefix positions (:func:`~perceiver_io_tpu.inference.generate.
    _prefill_chunk_kv` — per-position math, identical values to the
    one-shot prefill) and scatter them STRAIGHT INTO THE POOL through the
    slot's block table; positions outside ``[lo, hi)`` — the un-shared
    prefix span — route to the null block, so a shared page is never
    written through (clamped chunk overruns land in trash, exactly the
    PR-8 write-routing discipline). The pool pages being written are the
    slot's own private/COW'd pages, invisible to every other slot's
    gathers, so interleaved decode steps never observe a half-built row.

    The final call runs :func:`~perceiver_io_tpu.inference.generate.
    _prefill_finalize_paged` — latent projections + pool gather + attend +
    stack — and inserts the finished row. ``offset``/``lo``/``hi``/``m``/
    ``slot`` are traced: one program serves every shared-span length of
    every prompt bucket, so the compile bound grows by exactly one."""

    def run(params, tokens, offset, is_final, window, pad_count, m, slot,
            table_row, lo, hi, state):
        table = table_row[None]

        def stage(state):
            k_c, v_c = model.apply(
                {"params": params}, tokens, offset, method=_prefill_chunk_kv
            )
            pos = offset + jnp.arange(chunk, dtype=jnp.int32)
            flat = paged_ops.flat_write_indices(table, pos[None, :], block_size)
            ok = (pos >= lo) & (pos < hi)
            flat = jnp.where(ok[None, :], flat, pos[None, :] % block_size)
            # scatter_kv quantizes when the state carries scales (paged_int8)
            pool_k, scale_k = paged_ops.scatter_kv(
                state["pool_k"], state.get("scale_k"), flat[0],
                k_c[0].transpose(1, 0, 2),
            )
            pool_v, scale_v = paged_ops.scatter_kv(
                state["pool_v"], state.get("scale_v"), flat[0],
                v_c[0].transpose(1, 0, 2),
            )
            out = {**state, "pool_k": pool_k, "pool_v": pool_v}
            if scale_k is not None:
                out["scale_k"], out["scale_v"] = scale_k, scale_v
            return out

        def fin(state):
            quant = "scale_k" in state
            scale_kwargs = (
                {"scale_k": state["scale_k"], "scale_v": state["scale_v"]}
                if quant else {}
            )
            outs = model.apply(
                {"params": params}, window, pad_count, m,
                state["pool_k"], state["pool_v"], table_row, block_size,
                method=_prefill_finalize_paged, **scale_kwargs,
            )
            if quant:
                (logits, pool_k, pool_v, scale_k, scale_v, cache, length,
                 m_out) = outs
                state = {**state, "pool_k": pool_k, "pool_v": pool_v,
                         "scale_k": scale_k, "scale_v": scale_v}
            else:
                logits, pool_k, pool_v, cache, length, m_out = outs
                state = {**state, "pool_k": pool_k, "pool_v": pool_v}
            return _insert_row(
                state, slot, window=window, pad=pad_count, logits=logits,
                cache=cache, length=length, m=m_out,
            )

        # trace-time: the finalize branch's pool gather stays head-sharded
        # on the serving mesh — lax.cond traces both branches inside the
        # context (docs/serving.md "Sharded serving")
        with paged_ops.gather_constraint(gather_sharding):
            return jax.lax.cond(is_final, fin, stage, state)

    return _jit(run, _donate(11), out_shardings)


def _build_page_copy_executor(block_size: int, out_shardings=None):
    """Copy one pool block's k/v content onto another — the device half of
    copy-on-write (``serving/kv_pool.py``): the host allocator swaps a
    fresh private block into the writing slot's table and this program
    makes its content identical to the shared source page before any
    write lands. ``src``/``dst`` are traced scalars: one compile covers
    every COW in the engine's lifetime."""

    def run(state, src, dst):
        idx_src = src * block_size + jnp.arange(block_size)
        idx_dst = dst * block_size + jnp.arange(block_size)
        pool_k = state["pool_k"].at[idx_dst].set(state["pool_k"][idx_src])
        pool_v = state["pool_v"].at[idx_dst].set(state["pool_v"][idx_src])
        out = {**state, "pool_k": pool_k, "pool_v": pool_v}
        if "scale_k" in state:
            # int8 layout: a COW'd page's dequant scales travel with its
            # content — already-quantized rows copy bit-exact, no requant
            out["scale_k"] = state["scale_k"].at[idx_dst].set(
                state["scale_k"][idx_src]
            )
            out["scale_v"] = state["scale_v"].at[idx_dst].set(
                state["scale_v"][idx_src]
            )
        return out

    return _jit(run, _donate(0), out_shardings)


def _build_swap_extract_executor(block_size: int):
    """Gather one victim's pool pages + per-slot row state for host swap
    (docs/serving.md "Host-swap preemption"). ``table_row`` is the slot's
    FULL padded block-table row and ``slot`` a traced scalar, so one
    compile covers every victim geometry: unmapped tail entries are 0 and
    gather null-block trash the restore routes right back to the null
    block. NOT donated — the resident state must survive the gather (the
    victim's neighbours keep decoding from it)."""

    def run(state, table_row, slot):
        flat = (
            table_row[:, None] * block_size + jnp.arange(block_size)[None, :]
        ).reshape(-1)
        out = {
            "pool_k": state["pool_k"][flat],
            "pool_v": state["pool_v"][flat],
        }
        if "scale_k" in state:
            out["scale_k"] = state["scale_k"][flat]
            out["scale_v"] = state["scale_v"][flat]
        row = {}
        for key in ("window", "pad", "length", "m", "steps", "logits"):
            row[key] = jax.lax.dynamic_index_in_dim(
                state[key], slot, axis=0, keepdims=False
            )
        row["stack_k"] = tuple(
            jax.lax.dynamic_index_in_dim(l, slot, axis=0, keepdims=False)
            for l in state["stack_k"]
        )
        row["stack_v"] = tuple(
            jax.lax.dynamic_index_in_dim(l, slot, axis=0, keepdims=False)
            for l in state["stack_v"]
        )
        out["row"] = row
        return out

    return jax.jit(run)


def _build_swap_restore_executor(block_size: int, out_shardings=None):
    """Scatter a :class:`~perceiver_io_tpu.serving.kv_pool.SwapBundle`'s
    payload back into the pool through the restored slot's NEW block-table
    row and re-insert its row state — the device half of swap-in. Pages
    below ``lo_blocks`` (the re-referenced prefix-shared run — their
    device content never left) and the unmapped tail route to the null
    block: a shared page is never written through, and the trash block
    absorbs the padding writes exactly as prefill scatter does. int8
    payloads restore bit-exact (no requant: content and scales travel
    together)."""

    def run(state, payload, table_row, slot, lo_blocks):
        pages = table_row.shape[0]
        pos = jnp.arange(pages * block_size)
        flat = (
            table_row[:, None] * block_size + jnp.arange(block_size)[None, :]
        ).reshape(-1)
        idx = jnp.where(pos >= lo_blocks * block_size, flat, pos % block_size)
        out = dict(state)
        out["pool_k"] = state["pool_k"].at[idx].set(
            payload["pool_k"].astype(state["pool_k"].dtype)
        )
        out["pool_v"] = state["pool_v"].at[idx].set(
            payload["pool_v"].astype(state["pool_v"].dtype)
        )
        if "scale_k" in state:
            out["scale_k"] = state["scale_k"].at[idx].set(
                payload["scale_k"].astype(state["scale_k"].dtype)
            )
            out["scale_v"] = state["scale_v"].at[idx].set(
                payload["scale_v"].astype(state["scale_v"].dtype)
            )

        def upd(dst, src):
            return jax.lax.dynamic_update_slice(
                dst,
                jnp.reshape(src, (1,) + dst.shape[1:]).astype(dst.dtype),
                (slot,) + (0,) * (dst.ndim - 1),
            )

        row = payload["row"]
        for key in ("window", "pad", "length", "m", "steps", "logits"):
            out[key] = upd(state[key], row[key])
        out["stack_k"] = tuple(
            upd(d, s) for d, s in zip(state["stack_k"], row["stack_k"])
        )
        out["stack_v"] = tuple(
            upd(d, s) for d, s in zip(state["stack_v"], row["stack_v"])
        )
        return out

    return _jit(run, _donate(0), out_shardings)


def _build_decode_executor(model, config: GenerationConfig, boundary: bool,
                           boundary_mode: str = "cached",
                           block_size: Optional[int] = None,
                           out_shardings=None, gather_sharding=None):
    """One fixed-shape token step over all slots: sample each row's next
    token from the resident logits, append it, advance every cache by one
    token. ``boundary=True`` additionally runs the boundary-phase step for
    rows whose latent segment is full and selects per row
    (``m == max_latents``) — the conservative mixed-phase variant, compiled
    once and used only while such a row is resident. ``boundary_mode``
    picks that step's implementation per the decode strategy
    (``inference/decode_strategy.py``): ``"cached"`` runs the cross-cache
    boundary-migration step, ``"recompute"`` the full windowed forward
    (exact either way; the winner is a measured platform/shape property —
    docs/benchmarks.md). Under recompute the boundary rows' cross caches go
    stale, which is safe: a row never leaves the boundary phase (the
    sliding-window phase is out of the slot engine's scope)."""
    n = model.max_seq_len
    max_latents = model.max_latents
    min_new = config.min_new_tokens if config.eos_token_id is not None else 0

    if block_size is not None:
        # Paged layout: same per-token schedule, but the cross caches live
        # in the shared block pool and the executor takes the (slots,
        # pages) block table as a per-call traced argument — the host
        # re-pushes it only when the allocator changed it, and no table
        # content ever retraces this program. The dense executor's per-row
        # ``where`` select between the base and boundary steps becomes
        # write ROUTING (``write_ok``): each live pool position is written
        # by exactly the step whose value the dense select would keep, so
        # live rows' logits stay bitwise identical to the dense layout.
        def _paged_body(params, state, table, rng):
            logits = state["logits"].astype(jnp.float32)
            logits = apply_min_new_tokens(
                logits, state["steps"][:, None], min_new, config.eos_token_id or 0
            )
            pad_positions = jnp.arange(n)[None, :] < state["pad"][:, None]
            token = sample_logits(
                rng, logits, config.sampling, state["window"], pad_positions
            )
            window = jnp.concatenate(
                [state["window"][:, 1:], token[:, None].astype(state["window"].dtype)],
                axis=1,
            )
            pad = jnp.maximum(state["pad"] - 1, 0)
            length, m = state["length"], state["m"]
            stack_cache = {
                "stack_k": list(state["stack_k"]), "stack_v": list(state["stack_v"]),
            }
            is_b = m >= max_latents
            write_ok = None
            if boundary and boundary_mode == "cached":
                write_ok = ~is_b  # boundary rows' appends belong to the
                # boundary step below (dense select semantics)
            quant = "scale_k" in state  # paged_int8: scales ride along
            scale_kwargs = (
                {"scale_k": state["scale_k"], "scale_v": state["scale_v"]}
                if quant else {}
            )
            outs = model.apply(
                {"params": params}, token, state["pool_k"], state["pool_v"],
                table, stack_cache, length, m, block_size, write_ok,
                method=_slot_decode_step_paged, **scale_kwargs,
            )
            if quant:
                logits_a, pool_k, pool_v, scale_k, scale_v, stack_a, _, _ = outs
            else:
                logits_a, pool_k, pool_v, stack_a, _, _ = outs
                scale_k = scale_v = None
            new_logits = logits_a
            stack_k, stack_v = stack_a["stack_k"], stack_a["stack_v"]
            if boundary and boundary_mode == "recompute":
                logits_b = model.apply(
                    {"params": params}, window, pad,
                    jnp.asarray(max_latents, jnp.int32),
                    method=_decode_forward,
                )
                new_logits = jnp.where(is_b[:, None], logits_b, logits_a)
            elif boundary:
                b_scale_kwargs = (
                    {"scale_k": scale_k, "scale_v": scale_v} if quant else {}
                )
                outs_b = model.apply(
                    {"params": params}, window, pad, pool_k, pool_v, table,
                    length, block_size, is_b,
                    method=_decode_step_boundary_paged, **b_scale_kwargs,
                )
                if quant:
                    logits_b, pool_k, pool_v, scale_k, scale_v, _ = outs_b
                else:
                    logits_b, pool_k, pool_v, _ = outs_b
                r4 = is_b[:, None, None, None]
                new_logits = jnp.where(is_b[:, None], logits_b, logits_a)
                # boundary rows' stack caches are stale by construction
                # (the boundary step recomputes the whole stack); keep
                # their old entries so latent rows' appends survive
                stack_k = [jnp.where(r4, old, a) for old, a in zip(state["stack_k"], stack_k)]
                stack_v = [jnp.where(r4, old, a) for old, a in zip(state["stack_v"], stack_v)]
            new_state = {
                "window": window,
                "pad": pad,
                "length": jnp.minimum(length + 1, n),  # idle slots saturate
                "m": jnp.minimum(m + 1, max_latents),
                "steps": state["steps"] + 1,
                "logits": new_logits.astype(state["logits"].dtype),
                "pool_k": pool_k, "pool_v": pool_v,
                "stack_k": tuple(stack_k), "stack_v": tuple(stack_v),
            }
            if quant:
                new_state["scale_k"], new_state["scale_v"] = scale_k, scale_v
            return new_state, token

        def run_paged(params, state, table, rng):
            # trace-time: every pool gather in the body (base step AND the
            # boundary variant) keeps its dense view slot/head-sharded on
            # the serving mesh (docs/serving.md "Sharded serving")
            with paged_ops.gather_constraint(gather_sharding):
                return _paged_body(params, state, table, rng)

        return _jit(run_paged, _donate(1), out_shardings)

    def run(params, state, rng):
        logits = state["logits"].astype(jnp.float32)
        # EOS unreachable until min_new_tokens — per-row step counts (the
        # scan path passes a scalar step; broadcasting handles the vector)
        logits = apply_min_new_tokens(
            logits, state["steps"][:, None], min_new, config.eos_token_id or 0
        )
        pad_positions = jnp.arange(n)[None, :] < state["pad"][:, None]
        token = sample_logits(
            rng, logits, config.sampling, state["window"], pad_positions
        )
        window = jnp.concatenate(
            [state["window"][:, 1:], token[:, None].astype(state["window"].dtype)],
            axis=1,
        )
        pad = jnp.maximum(state["pad"] - 1, 0)
        length, m = state["length"], state["m"]
        cache = {
            "cross_k": state["cross_k"], "cross_v": state["cross_v"],
            "stack_k": list(state["stack_k"]), "stack_v": list(state["stack_v"]),
        }
        logits_a, cache_a, _, _ = model.apply(
            {"params": params}, token, cache, length, m, method=_slot_decode_step
        )
        new_logits = logits_a
        cross_k, cross_v = cache_a["cross_k"], cache_a["cross_v"]
        stack_k, stack_v = cache_a["stack_k"], cache_a["stack_v"]
        if boundary and boundary_mode == "recompute":
            # Strategy-selected full recompute for boundary rows: the
            # windowed forward at m = max_latents (garbage for latent rows,
            # selected away). No cache writes — boundary rows never read
            # their cross cache again under this mode.
            logits_b = model.apply(
                {"params": params}, window, pad,
                jnp.asarray(max_latents, jnp.int32),
                method=_decode_forward,
            )
            is_b = m >= max_latents
            new_logits = jnp.where(is_b[:, None], logits_b, logits_a)
        elif boundary:
            logits_b, ck_b, cv_b, _ = model.apply(
                {"params": params}, window, pad,
                state["cross_k"], state["cross_v"], length,
                method=_decode_step_boundary,
            )
            is_b = m >= max_latents
            r4 = is_b[:, None, None, None]
            new_logits = jnp.where(is_b[:, None], logits_b, logits_a)
            cross_k = jnp.where(r4, ck_b, cross_k)
            cross_v = jnp.where(r4, cv_b, cross_v)
            # boundary rows' stack caches are stale by construction (the
            # boundary step recomputes the whole stack); keep their old
            # entries untouched so latent rows' appends survive the select
            stack_k = [jnp.where(r4, old, a) for old, a in zip(state["stack_k"], stack_k)]
            stack_v = [jnp.where(r4, old, a) for old, a in zip(state["stack_v"], stack_v)]
        new_state = {
            "window": window,
            "pad": pad,
            "length": jnp.minimum(length + 1, n),  # idle slots saturate
            "m": jnp.minimum(m + 1, max_latents),
            "steps": state["steps"] + 1,
            "logits": new_logits.astype(state["logits"].dtype),
            "cross_k": cross_k, "cross_v": cross_v,
            "stack_k": tuple(stack_k), "stack_v": tuple(stack_v),
        }
        return new_state, token

    return _jit(run, _donate(1), out_shardings)


def _build_spec_draft_executor(model, config: GenerationConfig, spec,
                               out_shardings=None):
    """Draft phase of one speculative round (docs/serving.md "Speculative
    decoding"): ``spec.k`` truncated-stack forwards propose ``(slots, k+1)``
    candidate tokens from the resident window/logits state —
    ``cand[:, 0]`` is the exact greedy token of the already-verified
    logits, the rest come from the ``spec.draft_layers``-deep self-draft
    (:func:`~perceiver_io_tpu.inference.speculative.propose_tokens`).
    Read-only over the state (NO donation — the verify executor consumes
    the same buffers right after), so the pair costs no extra state copy."""
    min_new = config.min_new_tokens if config.eos_token_id is not None else 0

    def run(params, state):
        return model.apply(
            {"params": params}, state["window"], state["pad"], state["m"],
            state["steps"], state["logits"], spec.k, spec.draft_layers,
            min_new, config.eos_token_id or 0,
            method=speculative_mod.propose_tokens,
        )

    return _jit(run, (), out_shardings)


def _build_spec_verify_executor(model, config: GenerationConfig, spec,
                                out_shardings=None):
    """Verify + accept + advance phase of one speculative round: ONE
    lane-batched full-model forward scores all ``k+1`` candidate positions
    (:func:`~perceiver_io_tpu.inference.speculative.verify_lanes` — lane
    ``j`` is bitwise the window the plain step would have seen after
    emitting ``j+1`` tokens, per row, in every phase regime), the longest
    matching drafted prefix is accepted, and the fixed-shape state
    advances by ``n_e ∈ [1, k+1]`` tokens in one donated step.

    The KV caches (dense cross or paged pool + scales, latent stacks) pass
    through UNTOUCHED: speculation decodes by windowed recompute, so cache
    content past what the prefill wrote is never read again — the same
    deliberate-staleness contract as the recompute boundary strategy, and
    the reason speculation composes with paged/int8/prefix-shared layouts
    without a cache-append variant per layout."""
    n = model.max_seq_len
    max_latents = model.max_latents
    min_new = config.min_new_tokens if config.eos_token_id is not None else 0

    def run(params, state, cand):
        lane_logits = model.apply(
            {"params": params}, state["window"], state["pad"], state["m"],
            cand, method=speculative_mod.verify_lanes,
        )
        n_e, next_logits = speculative_mod.accept_prefix(
            lane_logits, cand, state["steps"], min_new,
            config.eos_token_id or 0,
        )
        window, pad, m = speculative_mod.advance_window(
            state["window"], state["pad"], state["m"], cand, n_e, max_latents
        )
        new_state = dict(state)
        new_state.update(
            window=window,
            pad=pad,
            length=jnp.minimum(state["length"] + n_e, n),  # idle slots saturate
            m=m,
            steps=state["steps"] + n_e,
            logits=next_logits.astype(state["logits"].dtype),
        )
        return new_state, n_e

    return _jit(run, _donate(1), out_shardings)


@dataclasses.dataclass
class _Slot:
    """Host-side record of one resident request: the emitted tokens plus the
    mirrored per-row counters the scheduler needs without device reads."""

    req: ServeRequest
    slot: int
    max_new: int
    m: int  # mirrors state["m"][slot] for decode-variant choice
    emitted: List[int] = dataclasses.field(default_factory=list)
    #: engine-clock time this row's latest token materialized — the
    #: inter-token latency anchor (docs/observability.md)
    last_token_at: float = 0.0


@dataclasses.dataclass
class _PrefixPlan:
    """Host-side record of one admission's prefix-cache match
    (docs/serving.md "Prefix sharing"): the cached FULL blocks it maps by
    reference, the optional divergent/partially-usable block it
    copy-on-writes, and the resulting shared span ``shared_tokens`` —
    the start position the suffix-only prefill skips to."""

    nodes: list  # fully-shared _PrefixNode chain (mapped by reference)
    partial: Optional[object]  # COW donor node (divergent / clamped block)
    shared_tokens: int  # S: prefill projects only [S, prefix_len)
    bucket_len: int
    m0: int
    prefix_len: int


@dataclasses.dataclass
class _ChunkedAdmit:
    """Host-side record of one in-flight chunked admission: the reserved
    slot, the prepared window/row state, the chunk schedule, and the
    device-side staging caches the chunk executor accumulates into. The
    persistent slot state is untouched until the finalize call inserts the
    finished row, so interleaved decode steps can never observe a
    half-built cache."""

    req: ServeRequest
    slot: int
    bucket_len: int
    m0: int
    window: np.ndarray  # (1, n) right-aligned ids
    pad: np.ndarray  # (1,) left-pad count
    by_index: np.ndarray  # (n,) ids in token-index space (prompt then pad)
    offsets: List[int]  # staging-chunk start indices; one more pure
    # finalize call follows the last chunk
    chunk: int = 0  # staging-chunk size C this admission was scheduled with
    next_chunk: int = 0
    stage_k: object = None
    stage_v: object = None
    device_ms: float = 0.0  # summed per-chunk executor time
    #: prefix-cache match (None = unshared admission). Shared admissions
    #: stage straight into the pool through the shared prefill executor;
    #: ``lo``/``hi`` bound the writable span (docs/serving.md "Prefix
    #: sharing")
    plan: Optional[_PrefixPlan] = None
    lo: int = 0
    hi: int = 0


class SlotServingEngine(ServingEngine):
    """Token-granular scheduler over the persistent-slot decode state.

    Shares the bucket engine's whole request surface — ``submit`` /
    ``serve`` / ``step`` / ``run_until_idle`` / ``drain`` / ``stats`` /
    ``health``, bounded queue, deadlines, chaos hooks, metrics registry,
    tracer — but ``step()`` advances ONE TOKEN across all ``S`` slots
    instead of one whole micro-batch, admitting and retiring in flight.

    :param slots: number of persistent decode slots ``S`` (the decode
        executor's fixed batch dimension). The bucket table's
        ``batch_sizes`` are ignored; ``prompt_lens`` are the prefill grid.
    :param prefill_chunk: chunked-prefill chunk size (token positions per
        chunk-executor call). A request whose prefix exceeds it is admitted
        incrementally — one chunk per ``step()``, interleaved with resident
        decode steps, so a long admission no longer stalls resident slots'
        token cadence. ``None`` (default) keeps every admission on the
        single-call per-bucket prefill path.
    :param decode_strategy: boundary-phase decode strategy for the mixed
        boundary decode variant — ``"auto" | "cached" | "recompute"``.
        ``None`` defers to ``PERCEIVER_DECODE_STRATEGY`` then the measured
        registry (cached when untuned). ``warmup()`` runs the autotuner
        first when set to ``"auto"`` explicitly, so one deployment measures
        once and every variant compiles against the winner.
    :param kv_layout: cross-KV cache layout — ``"auto" | "dense" |
        "paged"`` (docs/serving.md "Block-paged KV"). ``dense`` keeps
        per-slot worst-case caches (the original layout); ``paged`` holds
        ONE shared block pool + per-slot block tables, so HBM scales with
        the pool size instead of ``slots × max_context`` and a long-tail
        workload admits more residents at the same budget. Both layouts
        are greedy token-identical (pinned by ``tests/test_paged_kv.py``).
        ``None`` defers to ``PERCEIVER_KV_LAYOUT`` then the measured
        registry (dense when untuned); an explicit ``"auto"`` makes
        ``warmup()`` run the kv-layout autotuner and rebuild onto the
        winner.
    :param kv_block_size: token positions per pool block (paged layout;
        default ``min(16, max_seq_len)``).
    :param kv_blocks: usable pool capacity in blocks (the null block is
        extra). Default sizes the pool at dense capacity
        (``slots * ceil(max_seq_len / kv_block_size)``); size it BELOW
        that to spend less HBM than dense while long-tail traffic still
        fills every slot — requests whose worst case cannot currently fit
        wait at the queue head (``kv_pool_admit_waits_total``), and
        requests that could never fit reject at submit.
    :param prefix_cache: cross-request prefix sharing — ``"auto" | "on" |
        "off"`` (docs/serving.md "Prefix sharing"; ``kv_layout="paged"``
        only). ``on`` keeps a radix index over published full
        prompt-prefix blocks: an admission whose leading token ids match
        maps those blocks by reference (per-block refcounts), copy-on-
        writes at the first divergent or partially-usable block, and
        prefills ONLY the un-shared suffix — a fully-hot system prompt
        collapses TTFT to block-table writes plus the latent finalize.
        Greedy output stays token-identical to the unshared path (pinned
        by ``tests/test_prefix_cache.py``). Unreferenced cached prefixes
        are LRU-dropped under pool pressure before an admission is made
        to wait. ``None`` defers to ``PERCEIVER_PREFIX_CACHE`` then the
        measured registry (off when unrecorded).
    :param preemption: optimistic KV admission + eviction under memory
        pressure — ``"off" | "recompute" | "swap" | "auto"``
        (docs/serving.md "Preemption & priorities" and "Host-swap
        preemption"; paged layouts only). ``"recompute"`` drops the
        up-front worst-case reservation: a request admits when its PROMPT
        pages fit (plus ``admit_headroom_blocks``), decode pages allocate
        lazily at each block-boundary crossing, and when a crossing finds
        the pool genuinely dry the engine preempts a victim —
        lowest-priority-first, then most-pages-held, then fewest-tokens-
        generated, never a higher tier — returning every page
        (``frees_by_cause["preempted"]``) and requeueing it for a
        token-identical greedy replay from its original prompt.
        ``"swap"`` keeps the same admission and victim policy but gathers
        the victim's pool pages (+ int8 scales) to host memory first
        (``frees_by_cause["swapped"]``); readmission restores them into
        whatever free blocks exist and resumes decoding at the
        pre-preemption position — no prompt replay, transfer instead of
        recompute, still greedy token-identical. ``"auto"`` arbitrates
        per victim: swap when the post-mortem cost model (measured decode
        step × tokens to replay vs victim bytes ÷ the calibrated
        ``swap_link_gbps``) scores transfer cheaper, recompute otherwise.
        ``"off"`` (default) keeps reserve-worst-case admission unchanged.
    :param swap_link_gbps: host-link bandwidth (decimal GB/s) for the
        post-mortem swap cost model and the ``auto`` arbitration. Default
        ``None`` reads the calibrated per-platform registry entry
        (``swap_entries``; every real swap refines it from measured
        transfer time) and falls back to a 16 GB/s prior.
    :param admit_headroom_blocks: extra decode blocks hard-committed per
        lazy admission (``preemption="recompute"`` only) — a small buffer
        that absorbs the first boundary crossings without triggering
        preemption; 0 (default) admits on prompt pages alone.
    :param mesh: serving parallelism mesh (docs/serving.md "Sharded
        serving") — a :class:`~perceiver_io_tpu.serving.sharding.
        ServingMeshSpec` (or resolved ``ServingSharding`` / 4-axis training
        ``Mesh`` with fsdp/seq at 1). Slots/batch shard along ``data``
        (``slots`` must divide evenly), attention heads and KV caches —
        dense per-slot AND the paged pool's flat ``pool_k``/``pool_v`` —
        along ``model`` (heads must divide evenly); params get the
        Megatron TP placement. Every executor compiles over the mesh with
        pinned output shardings; mesh geometry folds into the executor
        cache keys and the compile ledger's ``mesh`` component, so a mesh
        change rebuilds and attributes instead of reusing a stale
        single-device trace. A 1-device mesh reproduces the unsharded
        engine's behavior exactly, and greedy output on a real mesh stays
        token-identical (pinned by ``tests/test_sharding.py``). ``None``
        (default) keeps today's single-device path untouched.
    """

    def __init__(self, model, params, config: Optional[GenerationConfig] = None,
                 table=None, *, slots: int = 8,
                 prefill_chunk: Optional[int] = None,
                 decode_strategy: Optional[str] = None,
                 kv_layout: Optional[str] = None,
                 kv_block_size: Optional[int] = None,
                 kv_blocks: Optional[int] = None,
                 prefix_cache: Optional[str] = None,
                 preemption: Optional[str] = None,
                 admit_headroom_blocks: int = 0,
                 swap_link_gbps: Optional[float] = None,
                 speculation: Optional[str] = None,
                 mesh=None, **kwargs):
        super().__init__(
            model, params, config, table, decode_strategy=decode_strategy,
            **kwargs
        )
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
        if kv_layout is not None and kv_layout not in decode_strategy_mod.KV_LAYOUTS:
            raise ValueError(
                f"kv_layout must be one of {decode_strategy_mod.KV_LAYOUTS}, "
                f"got {kv_layout!r}"
            )
        if kv_block_size is not None and kv_block_size < 1:
            raise ValueError(f"kv_block_size must be >= 1, got {kv_block_size}")
        if kv_blocks is not None and kv_blocks < 1:
            raise ValueError(f"kv_blocks must be >= 1, got {kv_blocks}")
        self.slots = int(slots)
        self.prefill_chunk = (
            None if prefill_chunk is None
            else int(min(prefill_chunk, model.max_seq_len))
        )
        # -- serving mesh (docs/serving.md "Sharded serving") --------------
        # slots shard along `data`, heads/KV along `model`; params get the
        # TP placement once here (self.params stays the caller's unsharded
        # tree — the autotuners' probe engines compile their own unsharded
        # executors from it, keyed without the mesh component).
        self.sharding = as_serving_sharding(mesh)
        if self.sharding is not None and self.slots % self.sharding.data_size:
            raise ValueError(
                f"slots ({self.slots}) must divide evenly over the mesh "
                f"data axis ({self.sharding.data_size}): the decode "
                "executor's fixed batch dimension is slot-sharded"
            )
        self._exec_params = (
            self.sharding.put_params(self.params)
            if self.sharding is not None else self.params
        )
        self.registry.declare_counters(
            "serving_decode_steps_total",
            "serving_decode_rows_total",
            "serving_decode_rows_padded_total",
            "serving_prefills_total",
            "serving_prefill_chunks_total",
            "kv_pool_block_allocs_total",
            "kv_pool_block_frees_total",
            "kv_pool_admit_waits_total",
            "kv_prefix_hits_total",
            "kv_prefix_misses_total",
            "kv_prefix_shared_blocks_total",
            "kv_prefix_shared_tokens_total",
            "kv_prefix_cow_copies_total",
            "kv_prefix_evicted_blocks_total",
            "kv_prefix_published_blocks_total",
            "kv_quant_fallback_total",
            "kv_ragged_kernel_steps_total",
            "kv_preemptions_total",
            "kv_readmissions_total",
            "kv_swaps_total",
            "kv_swap_restores_total",
            "kv_swap_bytes_total",
            "spec_rounds_total",
            "spec_tokens_proposed_total",
            "spec_tokens_accepted_total",
            "spec_tokens_emitted_total",
        )
        self._slots: List[Optional[_Slot]] = [None] * self.slots
        self._admitting: Optional[_ChunkedAdmit] = None
        self._pinned_boundary_mode: Optional[str] = None
        # -- KV layout (docs/serving.md "Block-paged KV") ------------------
        # dense: per-slot worst-case cross caches (the original layout);
        # paged: one shared block pool + per-slot block tables. Resolution
        # mirrors the boundary strategy: explicit arg > PERCEIVER_KV_LAYOUT
        # > measured registry > dense. An explicit "auto" re-resolves at
        # warmup() after the kv-layout autotuner runs.
        self.kv_layout_requested = kv_layout
        #: True when the operator sized the pool explicitly — sizing IS a
        #: layout choice, so a dense resolution would silently discard the
        #: HBM budget the caller asked for; reject loudly instead, and skip
        #: the warmup auto-switch (a dense verdict must not drop the budget)
        self._kv_sized = kv_block_size is not None or kv_blocks is not None
        self.kv_block_size = int(
            min(kv_block_size or min(16, model.max_seq_len), model.max_seq_len)
        )
        #: usable pool capacity in blocks (null block excluded); default
        #: matches the dense layout's capacity so un-tuned paged serving
        #: admits exactly what dense would
        self.kv_blocks = int(kv_blocks or self.slots * self._pages_per_slot())
        resolved = decode_strategy_mod.resolve_kv_layout(kv_layout, model)
        if self._kv_sized and resolved not in decode_strategy_mod.PAGED_KV_LAYOUTS:
            raise ValueError(
                "kv_block_size/kv_blocks size the paged pool but the KV "
                f"layout resolved to {resolved!r} — the budget would be "
                "silently ignored; pass kv_layout='paged' or 'paged_int8' "
                "(sizing the pool is choosing the paged layout)"
            )
        # -- prefix cache (docs/serving.md "Prefix sharing") ---------------
        # cross-request copy-on-write sharing of hot prompt-prefix blocks;
        # only meaningful under the paged layout (sharing IS a block-table
        # property). Resolution mirrors the other axes: explicit arg >
        # PERCEIVER_PREFIX_CACHE > persisted registry > off.
        if prefix_cache is not None and \
                prefix_cache not in decode_strategy_mod.PREFIX_CACHE_MODES:
            raise ValueError(
                "prefix_cache must be one of "
                f"{decode_strategy_mod.PREFIX_CACHE_MODES}, got {prefix_cache!r}"
            )
        self.prefix_cache_requested = prefix_cache
        #: the resolved PREFERENCE (explicit > env > registry > off), kept
        #: apart from the ACTIVE state: kv_layout="auto" may only switch to
        #: paged at warmup, and the preference must survive that rebuild
        #: (the active self.prefix_cache is re-derived per _init_kv_state)
        self._prefix_pref = decode_strategy_mod.resolve_prefix_cache(
            prefix_cache, model
        )
        if prefix_cache == "on" and kv_layout != "auto" and \
                resolved not in decode_strategy_mod.PAGED_KV_LAYOUTS:
            raise ValueError(
                "prefix_cache='on' shares pool blocks between requests but "
                f"the KV layout resolved to {resolved!r} — prefix sharing "
                "requires kv_layout='paged' (or 'paged_int8'; dense slots "
                "have no block tables to share)"
            )
        # -- preemption (docs/serving.md "Preemption & priorities") --------
        # optimistic admission is a PAGED property: lazy pages need a block
        # pool to be lazy about. Same loud-reject discipline as prefix
        # sharing when the layout resolves dense.
        if preemption is not None and preemption not in PREEMPTION_MODES:
            raise ValueError(
                f"preemption must be one of {PREEMPTION_MODES}, "
                f"got {preemption!r}"
            )
        if admit_headroom_blocks < 0:
            raise ValueError(
                "admit_headroom_blocks must be >= 0, got "
                f"{admit_headroom_blocks}"
            )
        self.preemption = preemption or "off"
        self.admit_headroom_blocks = int(admit_headroom_blocks)
        if self.preemption != "off" and kv_layout != "auto" and \
                resolved not in decode_strategy_mod.PAGED_KV_LAYOUTS:
            raise ValueError(
                f"preemption={self.preemption!r} admits against the block "
                f"pool but the KV layout resolved to {resolved!r} — lazy "
                "pages need kv_layout='paged' (or 'paged_int8'; dense slots "
                "reserve their worst case by construction)"
            )
        # -- speculative decoding (docs/serving.md "Speculative decoding") -
        # draft/verify bursts are a DECODE property orthogonal to the KV
        # axes: exactness comes from the recompute lanes, so speculation
        # composes with dense, paged, int8, prefix sharing, and preemption
        # alike. Resolution mirrors the other measured axes: explicit arg >
        # PERCEIVER_SPECULATION > measured registry > off; the geometry is
        # validated HERE (greedy-only, draft a strict truncation) so a
        # misconfigured operator fails at construction, never mid-serving.
        if speculation is not None and \
                speculation not in decode_strategy_mod.SPECULATION_MODES:
            raise ValueError(
                "speculation must be one of "
                f"{decode_strategy_mod.SPECULATION_MODES}, got {speculation!r}"
            )
        self.speculation_requested = speculation
        self.speculation = decode_strategy_mod.resolve_speculation(
            speculation, model
        )
        self._spec = speculative_mod.parse_speculation(self.speculation)
        if self._spec is not None:
            speculative_mod.validate_spec(self._spec, model, self.config)
        if swap_link_gbps is not None and swap_link_gbps <= 0:
            raise ValueError(
                f"swap_link_gbps must be > 0, got {swap_link_gbps}"
            )
        #: modeled host-link bandwidth (decimal GB/s) for the preemption
        #: post-mortems' swap cost and the auto policy's per-victim
        #: arbitration. Resolution: explicit arg > the calibrated
        #: per-platform registry entry (``swap_entries`` in the strategy
        #: artifact — every real swap feeds a measured rate back through
        #: ``record_swap_gbps``) > a 16 GB/s prior. ROADMAP item 2's
        #: recompute-vs-swap crossover is measured against this rate.
        self.swap_link_gbps = float(
            swap_link_gbps
            if swap_link_gbps is not None
            else decode_strategy_mod.lookup_swap_gbps() or 16.0
        )
        #: preemption accounting: tier -> victims preempted at that tier
        #: (the kv_preemptions_total by-tier breakdown stats() reports)
        self._preempted_by_tier: Dict[int, int] = {}
        #: per-victim preemption post-mortems (docs/observability.md
        #: "Scheduler timeline & post-mortems"): actual recompute cost
        #: (tokens replayed x measured decode-step ms) vs the modeled
        #: host-swap cost (victim bytes / swap_link_gbps). Bounded ring;
        #: the running totals survive eviction.
        self._postmortems: Deque[dict] = deque(maxlen=256)
        self._postmortem_totals = {
            "count": 0, "swapped": 0, "tokens_discarded": 0,
            "pages_released": 0, "victim_bytes": 0,
            "recompute_est_ms": 0.0, "swap_est_ms": 0.0,
            "swap_measured_ms": 0.0,
        }
        #: per-tenant attribution (sanitized labels — observability.
        #: tenant_label): tokens generated and victims preempted; resident
        #: pool pages come live from _tenant_pages()
        self._tokens_by_tenant: Dict[str, int] = {}
        self._preempted_by_tenant: Dict[str, int] = {}
        self._tenant_gauge_keys: set = set()
        self._preempts_this_step = 0
        self._kv_counter_base = {"allocs": 0, "frees": 0}
        self._kv_waiting_id: Optional[int] = None  # last head counted waiting
        #: request_id -> host-side SwapBundle for swap-preempted victims
        #: awaiting readmission (docs/serving.md "Host-swap preemption");
        #: must exist before _init_kv_state (the rebuild path drops them)
        self._swap_bundles: Dict[int, SwapBundle] = {}
        self._init_kv_state(resolved)
        self._update_slot_gauges()

    def _pages_per_slot(self) -> int:
        """Block-table width: pages covering one slot's full context."""
        return -(-self.model.max_seq_len // self.kv_block_size)

    def _pool_tokens(self) -> int:
        """Device pool length in token positions: the usable blocks plus
        block 0, the null/trash block (``serving/kv_pool.py``)."""
        return (self.kv_blocks + 1) * self.kv_block_size

    # -- KV state/pool lifecycle --------------------------------------------
    def _init_kv_state(self, layout: str) -> None:
        """(Re)build the persistent device state and host allocator for
        ``layout`` ("dense" | "paged" | "paged_int8") and publish the
        capacity/resident gauges. Also the warmup-time layout-switch path
        (an explicit ``kv_layout="auto"`` re-resolving after the
        autotuner) — callers must guarantee no residents."""
        from perceiver_io_tpu.models.core.modules import trace_env_fingerprint

        # swapped-out bundles reference the OUTGOING pool's shared blocks
        # and its device content — a rebuild invalidates both, so drop them
        # while the old pool can still absorb the derefs (the queued
        # requests replay from their prompts: still token-identical)
        if getattr(self, "_swap_bundles", None) and \
                getattr(self, "_pool", None) is not None:
            for bundle in self._swap_bundles.values():
                self._release_bundle(bundle, cause="swapped")
            self._swap_bundles.clear()
        model, params = self.model, self.params
        self.kv_layout = layout
        if self.sharding is not None and self.sharding.model_size > 1:
            _, cache_shapes = _prefill_shapes(model, params)
            heads = int(cache_shapes["cross_k"].shape[1])
            if heads % self.sharding.model_size:
                raise ValueError(
                    f"attention heads ({heads}) must divide evenly over the "
                    f"mesh model axis ({self.sharding.model_size}): the KV "
                    "caches and head projections are head-sharded — shrink "
                    "the model axis or pad the head count"
                )
        if layout in decode_strategy_mod.PAGED_KV_LAYOUTS:
            self._pool: Optional[KVPagePool] = KVPagePool(
                self.kv_blocks, self.kv_block_size, self.slots, model.max_seq_len
            )
            self._state = self._place_state(_blank_state(
                model, params, self.slots, self.config.pad_token_id,
                pool_tokens=self._pool_tokens(),
                quantized=(layout == "paged_int8"),
            ))
            self._table_dev = self._place_table(self._pool.table())
            # a state rebuild zeroes the device pool, so the prefix index
            # starts (over) empty — stale entries must not describe pages
            # that no longer hold their values. The ACTIVE state re-derives
            # from the resolved preference here, so a warmup-time
            # auto-layout switch onto paged turns sharing on rather than
            # inheriting a stale off from the dense __init__ resolution.
            self.prefix_cache = "on" if self._prefix_pref == "on" else "off"
            self._prefix_index: Optional[PrefixBlockIndex] = (
                PrefixBlockIndex(self.kv_block_size)
                if self.prefix_cache == "on" else None
            )
        else:
            self._pool = None
            self._prefix_index = None
            self.prefix_cache = "off"
            self._state = self._place_state(_blank_state(
                model, params, self.slots, self.config.pad_token_id
            ))
            self._table_dev = None
        #: trace-env fingerprint the cached prefix blocks were computed
        #: under — a mid-process flag flip (fused QKV, flash knobs) changes
        #: the projection trace, so the index flushes rather than serve
        #: values from the other regime
        self._prefix_env = trace_env_fingerprint()
        # analytic worst-case slot-KV footprint: per-position byte cost
        # computed from the RESOLVED layout's pool dtype (int8 pools store
        # 1-byte entries plus f32 per-(position, head) dequant scales —
        # pretending bf16/f32 here would overstate capacity 2-4x and admit
        # too little) + the dense latent-stack caches — exact on every
        # backend, device memory_stats() or not (docs/observability.md)
        _, cache_s = _prefill_shapes(model, params)
        _, h, n, d = cache_s["cross_k"].shape
        pool_dtype = (
            self._state["pool_k"].dtype if self._pool is not None
            else cache_s["cross_k"].dtype
        )
        itemsize = jnp.dtype(pool_dtype).itemsize
        self._kv_token_bytes = 2 * h * d * itemsize  # k + v, per position
        #: int8 layouts carry one f32 scale per (position, head) per tensor;
        #: zero for exact layouts so downstream sums stay layout-agnostic
        self._kv_scale_token_bytes = (
            2 * h * jnp.dtype(jnp.float32).itemsize
            if "scale_k" in self._state else 0
        )
        self._kv_stack_bytes = sum(
            int(leaf.nbytes)
            for name in ("stack_k", "stack_v")
            for leaf in self._state[name]
        )
        if self._pool is not None:
            # paged capacity is what the POOL can hold (operators size it
            # via kv_blocks), not the dense worst case
            self._kv_capacity_bytes = (
                self.kv_blocks * self.kv_block_size
                * (self._kv_token_bytes + self._kv_scale_token_bytes)
                + self._kv_stack_bytes
            )
        else:
            self._kv_capacity_bytes = (
                self.slots * n * self._kv_token_bytes + self._kv_stack_bytes
            )
        self.registry.set_gauge("kv_cache_capacity_bytes", self._kv_capacity_bytes)
        if self._pool is not None:
            self.registry.set_gauge("kv_pool_blocks", self._pool.num_blocks)
            self.registry.set_gauge(
                "kv_pool_block_bytes", self.kv_block_size * self._kv_token_bytes
            )
            self.registry.set_gauge(
                "kv_pool_block_scale_bytes",
                self.kv_block_size * self._kv_scale_token_bytes,
            )
        from perceiver_io_tpu.ops import ragged_attention as ragged_mod
        self.registry.set_gauge(
            "kv_ragged_kernel_enabled",
            1 if (self._pool is not None and ragged_mod.kernel_enabled()) else 0,
        )
        if self.sharding is not None:
            # mesh geometry gauges (docs/observability.md): presence of
            # serving_mesh_devices is how `obs report` knows a mesh ran
            self.registry.set_gauge(
                "serving_mesh_devices", self.sharding.num_devices
            )
            self.registry.set_gauge("serving_mesh_data", self.sharding.data_size)
            self.registry.set_gauge("serving_mesh_model", self.sharding.model_size)
        self._update_kv_gauges()

    def _update_kv_gauges(self) -> None:
        """Publish the LIVE KV footprint: under the paged layout,
        ``kv_cache_resident_bytes`` counts allocated pages (+ the dense
        stack caches), updated on admit/retire/chunk progress; dense keeps
        resident == capacity (every slot row exists whether occupied or
        not). Pool gauges/counters ride along (docs/observability.md)."""
        from perceiver_io_tpu.observability import default_ledger

        pool = self._pool
        if pool is None:
            resident = self._kv_capacity_bytes
        else:
            resident = (
                pool.in_use * self.kv_block_size
                * (self._kv_token_bytes + self._kv_scale_token_bytes)
                + self._kv_stack_bytes
            )
            self.registry.set_gauge("kv_pool_blocks_in_use", pool.in_use)
            self.registry.set_gauge("kv_pool_blocks_reserved", pool.reserved)
            self.registry.set_gauge("kv_pool_blocks_high_water", pool.high_water)
            # distance to the next boundary-crossing PoolExhausted under
            # optimistic admission (docs/serving.md "Preemption &
            # priorities") — free blocks no hard reservation has claimed
            self.registry.set_gauge(
                "kv_pool_headroom_blocks", pool.headroom_blocks
            )
            if self._prefix_index is not None:
                self.registry.set_gauge(
                    "kv_prefix_cached_blocks", self._prefix_index.cached_blocks
                )
            # per-tenant attribution (docs/observability.md "Scheduler
            # timeline & post-mortems"): resident pool pages per tenant,
            # published as one gauge per (sanitized) tenant label. Gauges
            # for tenants that no longer hold pages drop to 0 rather than
            # lingering at their last value.
            live: Dict[str, int] = {}
            for tenant, held in self._tenant_pages().items():
                key = tenant_label(tenant)
                live[key] = live.get(key, 0) + held
            for key, held in live.items():
                self.registry.set_gauge(
                    f"kv_pool_tenant_blocks_in_use_{key}", held
                )
            for key in self._tenant_gauge_keys - set(live):
                self.registry.set_gauge(f"kv_pool_tenant_blocks_in_use_{key}", 0)
            self._tenant_gauge_keys |= set(live)
            base = self._kv_counter_base
            if pool.allocs_total > base["allocs"]:
                self.registry.inc(
                    "kv_pool_block_allocs_total", pool.allocs_total - base["allocs"]
                )
                base["allocs"] = pool.allocs_total
            if pool.frees_total > base["frees"]:
                self.registry.inc(
                    "kv_pool_block_frees_total", pool.frees_total - base["frees"]
                )
                base["frees"] = pool.frees_total
        self.registry.set_gauge("kv_cache_resident_bytes", resident)
        if self.sharding is not None:
            # the model-axis shard of the live KV bytes (heads are the
            # sharded dimension of both pool and dense caches); the data
            # axis divides the DENSE layout's slot rows further, but the
            # paged pool — the layout per-shard sizing matters for — is
            # shared across data shards (docs/observability.md)
            self.registry.set_gauge(
                "kv_cache_resident_bytes_per_shard",
                resident // self.sharding.model_size,
            )
        default_ledger().set_kv_cache_bytes(resident)

    def _place_state(self, state: dict) -> dict:
        """Place a freshly built slot state onto the serving mesh (identity
        when unsharded). Every state (re)build routes through here so the
        executors' committed-input signatures never drift."""
        return state if self.sharding is None else self.sharding.put_state(state)

    def _place_table(self, table) -> jnp.ndarray:
        if self.sharding is None:
            return jnp.asarray(table)
        return self.sharding.put_leaf("table", np.asarray(table))

    def _push_table(self) -> None:
        """Refresh the device copy of the block table after the allocator
        changed it (admit/chunk-progress/decode page crossing/retire). A
        (slots, pages) int32 transfer — tiny next to a decode step."""
        self._table_dev = self._place_table(self._pool.table())

    def _kv_release(self, slot: int, cause: str = "retire") -> None:
        """Return a retired/failed slot's pages to the pool and refresh
        gauges + device table. ``cause`` tags the pool's free accounting
        (``frees_by_cause`` in :meth:`KVPagePool.stats`): ordinary
        retirement vs a client-driven ``cancelled`` reclaim — the long-tail
        HBM-leak class the gateway's disconnect path exists to close."""
        if self._pool is not None:
            # push on UNMAP, not on physical free: a refcount-aware release
            # can free zero blocks (every page shared) yet still zero the
            # slot's table row, which the device copy must reflect
            had_pages = self._pool.mapped_blocks(slot) > 0
            self._pool.release(slot, cause=cause)
            if had_pages:
                self._push_table()
            self._update_kv_gauges()

    # -- executors -----------------------------------------------------------
    def _cache_key(self, kind: str, *extra):
        from perceiver_io_tpu.models.core.modules import trace_env_fingerprint

        # max_new_tokens is scheduled host-side (per-request retirement), so
        # it must NOT key the executors — requests overriding it share one
        # compiled program
        cfg = dataclasses.replace(self.config, max_new_tokens=0)
        # the paged pool's device shape (blocks x block size) specializes
        # every executor, so it must key them; dense keys stay identical to
        # the pre-paged ones
        kv = (
            (self.kv_layout, self.kv_block_size, self.kv_blocks)
            if self.kv_layout in decode_strategy_mod.PAGED_KV_LAYOUTS else ()
        )
        # mesh geometry (axis sizes + concrete device ids) specializes every
        # executor — shardings are baked into the compiled program, so a
        # mesh flip must rebuild, never reuse the other geometry's trace
        mesh_fp = () if self.sharding is None else self.sharding.fingerprint()
        return (
            kind, type(self.model).__qualname__, model_fingerprint(self.model),
            cfg, self.slots, trace_env_fingerprint(), *kv, *mesh_fp, *extra,
        )

    def _ledger_components(self, **extra) -> dict:
        """Named cache-key components for the compile ledger — the same
        knobs :meth:`_cache_key` folds into the tuple key, under the names
        retrace attribution diffs (docs/observability.md taxonomy). Only
        called on a cache MISS (the executor getters pass it as a thunk):
        the model-id hash and config normalization stay off the per-token
        hit path."""
        from perceiver_io_tpu.models.core.modules import trace_env_fingerprint

        cfg = dataclasses.replace(self.config, max_new_tokens=0)
        components = {
            "model": ledger_model_id(self.model),
            "config": cfg,
            "slots": self.slots,
            "trace_env": trace_env_fingerprint(),
            **extra,
        }
        if self.kv_layout in decode_strategy_mod.PAGED_KV_LAYOUTS:
            components["kv_layout"] = (
                f"{self.kv_layout}:{self.kv_blocks}x{self.kv_block_size}"
            )
        if self.sharding is not None:
            components["mesh"] = self.sharding.describe()
        return components

    def _kv_block_size_arg(self) -> Optional[int]:
        return (
            self.kv_block_size
            if self.kv_layout in decode_strategy_mod.PAGED_KV_LAYOUTS else None
        )

    # -- sharded-executor helpers (docs/serving.md "Sharded serving"). All
    # None on the unsharded engine; computed only inside cached_executor's
    # build thunks, so the per-dispatch hit path stays free of tree maps.
    def _state_out_shardings(self):
        if self.sharding is None:
            return None
        return self.sharding.state_shardings(self._state)

    def _decode_out_shardings(self):
        if self.sharding is None:
            return None
        return (
            self.sharding.state_shardings(self._state),
            self.sharding.tokens_sharding(self.slots),
        )

    def _chunk_out_shardings(self):
        if self.sharding is None:
            return None
        _, cache_s = _prefill_shapes(self.model, self.params)
        stage = self.sharding.leaf_sharding("stage_k", cache_s["cross_k"].shape)
        return (stage, stage, self.sharding.state_shardings(self._state))

    def _gather_sharding(self):
        """Constraint for the paged attend's transient dense gather."""
        if self.sharding is None or \
                self.kv_layout not in decode_strategy_mod.PAGED_KV_LAYOUTS:
            return None
        return self.sharding.named(self.sharding.gathered_kv_spec())

    def _prefill_executor(self, bucket_len: int):
        return cached_executor(
            _EXECUTOR_CACHE, self._cache_key("slot_prefill", bucket_len),
            lambda: _build_prefill_executor(
                self.model, self.config, bucket_len, self._kv_block_size_arg(),
                out_shardings=self._state_out_shardings(),
            ),
            ledger_site="slot_prefill",
            ledger_components=lambda: self._ledger_components(
                bucket_shape=f"1x{bucket_len}"
            ),
        )

    def _chunked_prefill_executor(self):
        return cached_executor(
            _EXECUTOR_CACHE,
            self._cache_key("slot_prefill_chunk", self.prefill_chunk),
            lambda: _build_chunked_prefill_executor(
                self.model, self.config, self.prefill_chunk,
                self._kv_block_size_arg(),
                out_shardings=self._chunk_out_shardings(),
            ),
            ledger_site="slot_prefill_chunk",
            ledger_components=lambda: self._ledger_components(
                chunk=self.prefill_chunk
            ),
        )

    def _shared_chunk_size(self) -> int:
        """Staging-chunk size for shared (prefix-cache hit) admissions:
        the configured ``prefill_chunk`` when set — so spread shared
        admissions share the schedule discipline — else a block-scaled
        default (the suffix past a hot prefix is short by construction)."""
        n = self.model.max_seq_len
        return int(self.prefill_chunk or min(n, max(self.kv_block_size, 16)))

    def _shared_prefill_executor(self):
        chunk = self._shared_chunk_size()
        return cached_executor(
            _EXECUTOR_CACHE,
            self._cache_key("slot_prefill_shared", chunk),
            lambda: _build_shared_prefill_executor(
                self.model, self.config, chunk, self.kv_block_size,
                out_shardings=self._state_out_shardings(),
                gather_sharding=self._gather_sharding(),
            ),
            ledger_site="slot_prefill_shared",
            ledger_components=lambda: self._ledger_components(chunk=chunk),
        )

    def _page_copy_executor(self):
        return cached_executor(
            _EXECUTOR_CACHE, self._cache_key("kv_page_copy"),
            lambda: _build_page_copy_executor(
                self.kv_block_size, out_shardings=self._state_out_shardings()
            ),
            ledger_site="kv_page_copy",
            ledger_components=lambda: self._ledger_components(),
        )

    def _swap_extract_executor(self):
        return cached_executor(
            _EXECUTOR_CACHE, self._cache_key("kv_swap_extract"),
            lambda: _build_swap_extract_executor(self.kv_block_size),
            ledger_site="kv_swap_extract",
            ledger_components=lambda: self._ledger_components(),
        )

    def _swap_restore_executor(self):
        return cached_executor(
            _EXECUTOR_CACHE, self._cache_key("kv_swap_restore"),
            lambda: _build_swap_restore_executor(
                self.kv_block_size, out_shardings=self._state_out_shardings()
            ),
            ledger_site="kv_swap_restore",
            ledger_components=lambda: self._ledger_components(),
        )

    def _boundary_mode(self) -> str:
        """Resolved boundary-phase strategy for the mixed decode variant
        (``decode_strategy`` ctor arg > env var > measured registry >
        cached), **pinned at first use**. Under recompute the resident
        boundary rows' cross caches are deliberately left stale, so a
        mid-serving registry change (a late autotune, a strategy file
        appearing) must not swap the executor under them — a fresh verdict
        applies from the next :meth:`warmup` (no residents there), not
        mid-flight. Pinning also keeps the per-token host path free of the
        env/file/fingerprint lookups ``resolve`` performs."""
        if self._pinned_boundary_mode is None:
            self._pinned_boundary_mode = decode_strategy_mod.resolve(
                self.decode_strategy, self.model
            ).boundary
        return self._pinned_boundary_mode

    def _decode_executor(self, boundary: bool):
        mode = self._boundary_mode() if boundary else "cached"
        return cached_executor(
            _EXECUTOR_CACHE, self._cache_key("slot_decode", boundary, mode),
            lambda: _build_decode_executor(
                self.model, self.config, boundary, mode,
                self._kv_block_size_arg(),
                out_shardings=self._decode_out_shardings(),
                gather_sharding=self._gather_sharding(),
            ),
            ledger_site="slot_decode",
            ledger_components=lambda: self._ledger_components(
                boundary=boundary, decode_strategy=mode
            ),
        )

    def _spec_cand_sharding(self):
        """Sharding for the draft executor's ``(slots, k+1)`` candidate
        block: slots along ``data`` like every per-row state leaf."""
        if self.sharding is None:
            return None
        return self.sharding.leaf_sharding(
            "window", (self.slots, self._spec.k + 1)
        )

    def _spec_draft_executor(self):
        spec = self._spec
        return cached_executor(
            _EXECUTOR_CACHE, self._cache_key("spec_draft", spec.mode),
            lambda: _build_spec_draft_executor(
                self.model, self.config, spec,
                out_shardings=self._spec_cand_sharding(),
            ),
            ledger_site="spec_draft",
            ledger_components=lambda: self._ledger_components(
                speculation=spec.mode
            ),
        )

    def _spec_verify_executor(self):
        spec = self._spec
        return cached_executor(
            _EXECUTOR_CACHE, self._cache_key("spec_verify", spec.mode),
            lambda: _build_spec_verify_executor(
                self.model, self.config, spec,
                out_shardings=self._decode_out_shardings(),
            ),
            ledger_site="spec_verify",
            ledger_components=lambda: self._ledger_components(
                speculation=spec.mode
            ),
        )

    # -- feasibility ---------------------------------------------------------
    def _pick_prompt_bucket(self, length: int, cfg: GenerationConfig) -> int:
        """Bucket choice plus the slot engine's scope checks (module
        docstring); called from ``submit`` so violations reject with a
        terminal span, never mid-schedule."""
        if dataclasses.replace(cfg, max_new_tokens=self.config.max_new_tokens) != self.config:
            raise ValueError(
                "slot engine requests must share the engine GenerationConfig "
                "(only max_new_tokens may differ per request): the decode "
                "executor is compiled once for one sampling/eos/latent plan"
            )
        if cfg.max_new_tokens < 1:
            # the decode loop always advances at least one token; a 0-token
            # request would retire with more emitted tokens than its result
            # can hold
            raise ValueError(
                f"max_new_tokens must be >= 1, got {cfg.max_new_tokens}"
            )
        cap = super()._pick_prompt_bucket(length, cfg)
        if length + cfg.max_new_tokens > self.model.max_seq_len:
            raise ValueError(
                f"prompt length {length} + max_new_tokens "
                f"{cfg.max_new_tokens} overruns the context "
                f"{self.model.max_seq_len}: the sliding-window phase has no "
                "slot form — use the bucket engine for this request"
            )
        if length < min(cap, cfg.num_latents):
            raise ValueError(
                f"prompt length {length} is shorter than the "
                f"{min(cap, cfg.num_latents)} latent positions its prompt "
                f"bucket ({cap}) assigns under num_latents="
                f"{cfg.num_latents}: left pads would occupy latent slots "
                "(boundary-cache precondition) — use the bucket engine for "
                "this request, or configure num_latents at or below the "
                "shortest served prompt"
            )
        return cap

    def check_feasible(self, prompt, config: Optional[GenerationConfig] = None
                       ) -> GenerationConfig:
        """Base feasibility plus KV-pool capacity (docs/serving.md): a
        request whose worst case ``prompt + max_new_tokens`` can NEVER fit
        the configured block pool rejects here — at submit, with its own
        precise reason — instead of camping at the queue head forever. A
        request that fits the pool but not its current free space is NOT
        rejected; it queues and admits when residents retire (counted
        ``kv_pool_admit_waits_total``)."""
        import numpy as np

        cfg = super().check_feasible(prompt, config)
        if self._pool is not None:
            tokens = int(np.asarray(prompt).size) + cfg.max_new_tokens
            need = self._pool.blocks_needed(tokens)
            # NOTE the never-fits bound is deliberately blind to the prefix
            # cache: a request's pages must all be DISTINCT resident blocks
            # simultaneously, shared or not, so sharing cannot relax the
            # single-request capacity. What sharing relaxes is the
            # CONCURRENT accounting — referenced blocks are excluded from
            # each admission's reservation in the scheduler's gate, so
            # hot-prefix residents pack where unshared ones would wait
            # (docs/serving.md "Prefix sharing"; the gate is where
            # feasibility accounts for shareable blocks).
            if need > self._pool.num_blocks:
                # byte figures from the RESOLVED layout's pool dtype (int8
                # positions cost 1 byte + f32 scales, not bf16/f32) so the
                # reason states the pool's TRUE capacity, not an assumed one
                per_block = self._pool.block_size * (
                    self._kv_token_bytes + self._kv_scale_token_bytes
                )
                raise ValueError(
                    f"request needs {need} KV blocks ({tokens} positions at "
                    f"block size {self._pool.block_size}, "
                    f"{need * per_block} bytes as {self.kv_layout!r}) but "
                    f"the pool holds {self._pool.num_blocks} blocks "
                    f"({self._pool.num_blocks * per_block} bytes): it can "
                    "never be admitted — raise kv_blocks "
                    "(--serve.kv_blocks) or route it to the dense layout / "
                    "bucket engine"
                )
        return cfg

    # -- prefix sharing (docs/serving.md "Prefix sharing") -------------------
    def _prefix_plan(self, prompt: np.ndarray,
                     cfg: GenerationConfig) -> Optional[_PrefixPlan]:
        """Match the prompt's leading token ids against the prefix index
        and clamp the usable span to this request's OWN prefix region
        ``[0, L - m0)`` — latent positions are boundary-normalized per
        request and migration rewrites from ``L - m0`` up, so only the
        kv_norm-side prefix is position/token-pure and safely shareable.
        Returns None on a miss (or when the cache is off/empty)."""
        index = self._prefix_index
        if index is None:
            return None
        from perceiver_io_tpu.models.core.modules import trace_env_fingerprint

        env = trace_env_fingerprint()
        if env != self._prefix_env:
            # a trace-env flip changes the projection programs; cached
            # values from the other regime must not cross it
            index.flush(self._pool)
            self._prefix_env = env
            self._update_kv_gauges()
        prompt = np.asarray(prompt).reshape(-1)
        L = int(prompt.size)
        bucket_len = self._pick_prompt_bucket(L, cfg)
        m0 = min(bucket_len, cfg.num_latents)
        prefix_len = L - m0
        bs = self.kv_block_size
        if prefix_len < 1 or not index.cached_blocks:
            return None
        nodes = index.match(prompt)
        max_full = prefix_len // bs
        full = nodes[:max_full]
        shared = len(full) * bs
        partial = None
        room = prefix_len - shared
        if room > 0:
            if len(nodes) > len(full):
                # the next cached block matches fully but straddles this
                # request's latent boundary: COW it, use the leading
                # ``room`` positions, let the finalize rewrite the rest
                partial, extra = nodes[len(full)], room
            else:
                partial, extra = index.best_partial(full, prompt[:prefix_len])
                if extra < 1:
                    partial = None
            if partial is not None:
                shared += extra
        if shared < 1:
            return None
        if self.prefill_chunk is None and \
                prefix_len - shared > 4 * self._shared_chunk_size():
            # small hit, long un-shared suffix, no operator chunk
            # discipline: the shared path would drain the whole suffix
            # inline as many fenced stage calls in ONE step — slower than
            # the single bucket-prefill call a miss dispatches, and a
            # resident-stalling spike. Treat it as a miss; with
            # prefill_chunk set the suffix spreads one chunk per step and
            # any hit pays off.
            return None
        return _PrefixPlan(
            nodes=full, partial=partial, shared_tokens=shared,
            bucket_len=bucket_len, m0=m0, prefix_len=prefix_len,
        )

    def _map_shared_prefix(self, req: ServeRequest, slot: int,
                           plan: _PrefixPlan) -> None:
        """Reserve + map a hit admission's pool pages: the fully-matched
        blocks by reference (excluded from the reservation), the partial
        block shared-then-COW'd (the device page copy runs before any
        write could land), and the worst-case remainder reserved
        privately. Counters + the ``serving.prefix_hit`` span event ride
        here so hit accounting is identical for inline and spread
        admissions."""
        pool = self._pool
        L = int(req.prompt.size)
        self._reserve_admit(
            slot, L, req.config.max_new_tokens, shared_blocks=len(plan.nodes),
            pessimistic=bool(req.preemptions),
        )
        blocks = [node.block for node in plan.nodes]
        if plan.partial is not None:
            blocks.append(plan.partial.block)
        pool.map_shared(slot, blocks)
        if plan.partial is not None:
            old, new = pool.cow(slot, len(plan.nodes), use_reservation=True)
            self._state = self._page_copy_executor()(
                self._state, np.int32(old), np.int32(new)
            )
            self.registry.inc("kv_prefix_cow_copies_total")
        # the shared/COW'd pages may already cover EVERY page this request
        # will ever touch, in which case no later ensure() maps anything —
        # the device table must reflect the new mappings before the first
        # decode gather, so push unconditionally here
        self._push_table()
        self.registry.inc("kv_prefix_hits_total")
        self.registry.inc(
            "kv_prefix_shared_blocks_total",
            len(plan.nodes) + (1 if plan.partial is not None else 0),
        )
        self.registry.inc("kv_prefix_shared_tokens_total", plan.shared_tokens)
        if self.tracer is not None:
            self.tracer.event(
                "serving.prefix_hit", trace_id=req.trace_id, slot=slot,
                shared_tokens=plan.shared_tokens,
                shared_blocks=len(plan.nodes),
                cow=plan.partial is not None,
            )

    def _publish_prefix(self, req: ServeRequest, slot: int) -> None:
        """Publish the admitted row's full prefix blocks into the index
        (first donor wins; already-cached paths are skipped). Runs after
        the prefill finished, so every published page holds final
        kv_norm-side values that the donor's own decode never rewrites
        (migration starts at ``prefix_len``)."""
        index = self._prefix_index
        if index is None:
            return
        cfg = req.config
        L = int(req.prompt.size)
        prefix_len = L - min(self._pick_prompt_bucket(L, cfg), cfg.num_latents)
        count = prefix_len // self.kv_block_size
        if count < 1:
            return
        published = index.insert(
            np.asarray(req.prompt).reshape(-1),
            self._pool.slot_blocks(slot)[:count], self._pool,
        )
        if published:
            self.registry.inc("kv_prefix_published_blocks_total", published)
            self._update_kv_gauges()

    def _evict_for(self, need: int) -> bool:
        """LRU-drop unreferenced cached prefixes until ``need`` blocks are
        reservable — the pool-pressure policy: cached prefixes are a
        best-effort accelerator and must never starve admissions. Returns
        True when the need is now reservable."""
        index = self._prefix_index
        while not self._pool.can_reserve(need):
            if index is None:
                return False
            freed = index.evict_one(self._pool)
            if freed is None:
                return False
            self.registry.inc("kv_prefix_evicted_blocks_total")
            if freed:
                self._update_kv_gauges()
        return True

    def _cow_guard(self, entry: _Slot, next_len: int) -> bool:
        """Write-routing guard: a shared page is NEVER written through.
        Before a decode step, COW any page the step's append/migration
        writes would land on while it is still shared. Structurally
        unreachable under the publish policy (shared spans end before
        ``prefix_len``; writes start at it) — kept as the enforced
        invariant, pinned by a synthetic drill in
        ``tests/test_prefix_cache.py``."""
        if self._prefix_index is None:
            return False
        bs = self.kv_block_size
        pages = {(next_len - 1) // bs}
        if entry.m >= self.model.max_latents:
            mig = next_len - 1 - self.model.max_latents
            if mig >= 0:
                pages.add(mig // bs)
        changed = False
        for page in sorted(pages):
            if self._pool.page_shared(entry.slot, page):
                old, new = self._pool.cow(entry.slot, page)
                self._state = self._page_copy_executor()(
                    self._state, np.int32(old), np.int32(new)
                )
                self.registry.inc("kv_prefix_cow_copies_total")
                changed = True
        return changed

    # -- preemption (docs/serving.md "Preemption & priorities") --------------
    def _reserve_admit(self, slot: int, prompt_tokens: int, max_new: int,
                       *, shared_blocks: int = 0,
                       pessimistic: bool = False) -> None:
        """One admission's pool reservation, policy-routed: the worst case
        up front (``preemption="off"``, or ``pessimistic`` — a replayed
        victim's anti-thrash guarantee) or lazily — prompt pages plus
        ``admit_headroom_blocks``, with ``prompt + max_new`` recorded as a
        soft watermark (:meth:`KVPagePool.reserve_lazy`)."""
        total = prompt_tokens + max_new
        if self.preemption == "off" or pessimistic:
            self._pool.reserve(slot, total, shared_blocks=shared_blocks)
        else:
            self._pool.reserve_lazy(
                slot, prompt_tokens, total,
                headroom=self.admit_headroom_blocks,
                shared_blocks=shared_blocks,
            )

    def _admit_need(self, req: ServeRequest, plan: Optional[_PrefixPlan],
                    bundle: Optional[SwapBundle] = None) -> int:
        """Blocks the admission gate must see reservable before ``req``
        admits: its worst case (minus referenced prefix blocks) under
        up-front reservation, or just its private prompt pages + headroom
        under optimistic admission — the tentpole's capacity win: peak
        concurrency sized by what residents USE, not what they might.

        Forward-progress exception: a request that has ALREADY been
        preempted (``req.preemptions > 0``) re-admits under its full worst
        case. Optimistic readmission livelocks — N long tails each
        re-entering on a 2-block prompt commit evict each other forever,
        nobody keeping decode progress. Pessimistic readmission makes the
        cycle terminate: every preemption moves one request from the
        optimistic class to the guaranteed class, a guaranteed resident's
        ``ensure`` draws only on its own reservation (it can never trip
        exhaustion), and each preemption's beneficiary keeps its tokens —
        so memory preemptions are bounded by the request count.

        A swap-preempted head (``bundle``) re-admits through
        :meth:`_restore_admit`: full worst case (it was preempted, so the
        pessimistic rule applies) minus the bundle's still-referenced
        prefix-shared blocks, which re-map by reference."""
        if bundle is not None:
            shared = len(bundle.shared)
        else:
            shared = len(plan.nodes) if plan is not None else 0
        tokens = int(req.prompt.size) + req.config.max_new_tokens
        total = self._pool.blocks_needed(tokens) - shared
        if self.preemption == "off" or req.preemptions:
            return total
        prompt = self._pool.blocks_needed(int(req.prompt.size)) - shared
        return min(prompt + self.admit_headroom_blocks, total)

    def _tenant_pages(self) -> Dict[Optional[str], int]:
        """Resident pool pages held per tenant (the in-flight chunked
        admission included) — the fairness signal victim selection uses:
        at equal priority, the tenant holding the most pages yields first,
        so one tenant's long tail cannot starve the rest."""
        pages: Dict[Optional[str], int] = {}
        for entry in self._active():
            t = entry.req.tenant
            pages[t] = pages.get(t, 0) + self._pool.mapped_blocks(entry.slot)
        if self._admitting is not None:
            t = self._admitting.req.tenant
            pages[t] = pages.get(t, 0) + self._pool.mapped_blocks(
                self._admitting.slot
            )
        return pages

    def _pick_victim(self, priority_cap: int, *, strict: bool,
                     exclude_slot: int = -1
                     ) -> Optional[Union[_Slot, _ChunkedAdmit]]:
        """Deterministic victim policy over residents AND the in-flight
        chunked admission: never a tier above ``priority_cap`` (above OR AT
        it when ``strict`` — admission-time preemption crosses tiers only,
        "interactive preempts batch, never vice versa"), then
        most-tenant-pages (fairness), most-pages-held (biggest relief),
        fewest-tokens-generated (cheapest replay), newest request."""
        tenant_pages = self._tenant_pages()

        def key(req: ServeRequest, slot: int, generated: int):
            return (
                req.priority,
                -tenant_pages.get(req.tenant, 0),
                -self._pool.mapped_blocks(slot),
                generated,
                -req.request_id,
            )

        def eligible(req: ServeRequest) -> bool:
            if req.priority > priority_cap:
                return False
            return not (strict and req.priority == priority_cap)

        best = None
        best_key = None
        for entry in self._active():
            if entry.slot == exclude_slot or not eligible(entry.req):
                continue
            k = key(entry.req, entry.slot, len(entry.emitted))
            if best_key is None or k < best_key:
                best, best_key = entry, k
        admit = self._admitting
        if admit is not None and admit.slot != exclude_slot \
                and eligible(admit.req):
            k = key(admit.req, admit.slot, 0)
            if best_key is None or k < best_key:
                best = admit
        return best

    def _preempt_victim(self, victim: Union[_Slot, _ChunkedAdmit], *,
                        beneficiary: Optional[int] = None) -> None:
        """Preempt one victim: retire its slot with EVERY page returned
        (a prefix-sharing victim only derefs published blocks, never frees
        them out from under other sharers) and requeue the request as a
        VOLUNTARY replay — status stays ``queued``, no failover-budget
        analog is charged.

        The page disposition is policy-routed per victim. ``recompute``
        discards the pages (``frees_by_cause["preempted"]``) and the
        emitted tokens; greedy re-decoding from the original prompt is
        token-identical (the bar ``tests/test_kv_preemption.py`` pins),
        and stream consumers see ``on_token`` indices restart at 0 on
        replay and dedupe, exactly like a fleet failover. ``swap``
        gathers the pages to a host :class:`SwapBundle` first
        (``frees_by_cause["swapped"]``); readmission restores them and
        decoding RESUMES at the pre-preemption position — same greedy
        tokens, paid in transfer instead of recompute
        (``tests/test_kv_swap.py``). ``auto`` picks per victim from the
        post-mortem cost model — both arms are priced from the SAME
        numbers the post-mortem records, so the policy can never choose
        the arm its own record scores worse. A mid-admission
        (:class:`_ChunkedAdmit`) victim has no finished row to save and
        always recomputes."""
        req = victim.req
        if isinstance(victim, _ChunkedAdmit):
            generated = 0
            self._admitting = None
        else:
            generated = len(victim.emitted)
            self._slots[victim.slot] = None
        pages = self._pool.mapped_blocks(victim.slot)
        # post-mortem cost model (docs/observability.md "Scheduler
        # timeline & post-mortems"), priced BEFORE the disposition so the
        # auto arbitration and the record read identical numbers: the
        # recompute cost the victim would pay (discarded tokens x the
        # measured decode-step ms) against the host-swap cost (victim
        # bytes / the calibrated link rate, one direction) — ROADMAP
        # item 2's crossover curve, measured instead of assumed.
        step_ms = self.registry.percentile("serving_decode_step_ms", 50.0) or 0.0
        victim_bytes = pages * self.kv_block_size * (
            self._kv_token_bytes + self._kv_scale_token_bytes
        )
        recompute_ms = generated * step_ms
        swap_ms = victim_bytes / (self.swap_link_gbps * 1e9) * 1e3
        mode = "recompute"
        if not isinstance(victim, _ChunkedAdmit) and (
            self.preemption == "swap"
            or (self.preemption == "auto" and swap_ms < recompute_ms)
        ):
            mode = "swap"
        if mode == "swap":
            swap_out = self._swap_out(victim)
        else:
            swap_out = None
            self._kv_release(victim.slot, cause="preempted")
        req.preemptions += 1
        req.started_at = None
        self._queue.append(req)  # the priority sort re-orders next pass
        self._preempts_this_step += 1
        self.registry.inc("kv_preemptions_total")
        tier = int(req.priority)
        # per-tier family (ledger's retrace_reason_* naming convention);
        # negative tiers spell the sign out — metric names can't hold '-'
        self.registry.inc(f"kv_preemptions_tier_{tier_label(tier)}_total")
        self._preempted_by_tier[tier] = self._preempted_by_tier.get(tier, 0) + 1
        tkey = tenant_label(req.tenant)
        self._preempted_by_tenant[tkey] = \
            self._preempted_by_tenant.get(tkey, 0) + 1
        pm = {
            "request_id": req.request_id,
            "tenant": req.tenant,
            "priority": tier,
            "slot": victim.slot,
            "mode": mode,
            # under swap nothing is actually discarded — the field keeps
            # the cost-model input (tokens replay WOULD have re-decoded)
            "tokens_discarded": generated,
            "pages_released": pages,
            "victim_bytes": int(victim_bytes),
            "decode_step_ms": round(step_ms, 3),
            "recompute_est_ms": round(recompute_ms, 3),
            "swap_est_ms": round(swap_ms, 3),
            # positive = swapping out would have been cheaper than replay
            "swap_advantage_ms": round(recompute_ms - swap_ms, 3),
        }
        if swap_out is not None:
            pm["swap_measured_ms"] = round(swap_out["ms"], 3)
        self._postmortems.append(pm)
        totals = self._postmortem_totals
        totals["count"] += 1
        totals["swapped"] += 1 if mode == "swap" else 0
        totals["tokens_discarded"] += generated
        totals["pages_released"] += pages
        totals["victim_bytes"] += int(victim_bytes)
        totals["recompute_est_ms"] += recompute_ms
        totals["swap_est_ms"] += swap_ms
        if swap_out is not None:
            totals["swap_measured_ms"] += swap_out["ms"]
        self._tl_event(
            "preempted", request_id=req.request_id, slot=victim.slot,
            tenant=req.tenant, priority=tier, mode=mode,
            tokens_discarded=generated, pages_released=pages,
            beneficiary=beneficiary,
        )
        self._update_slot_gauges()
        if self.tracer is not None:
            self.tracer.event(
                "serving.preempted", trace_id=req.trace_id, slot=victim.slot,
                priority=tier, tenant=req.tenant, mode=mode,
                pages_released=pages, tokens_discarded=generated,
                beneficiary=beneficiary,
            )
        if self._preempts_this_step == 2 and self.flight_recorder is not None:
            # two victims in ONE scheduling instant = a preemption storm:
            # the pool is thrashing, not absorbing a single long tail —
            # incident-worthy once per step (the recorder's cooldown bounds
            # a sustained storm further)
            pool = self._pool.stats()
            self.flight_recorder.trigger(
                "preemption_storm",
                f"{self._preempts_this_step} residents preempted in one "
                f"step: pool {pool['in_use']}/{pool['blocks']} blocks "
                "in use — sustained memory pressure, not a long tail",
                trace_ids=[req.trace_id] if req.trace_id else [],
                blocks=pool["blocks"],
                blocks_in_use=pool["in_use"],
            )

    def _swap_out(self, victim: _Slot) -> dict:
        """Device half of swap preemption (docs/serving.md "Host-swap
        preemption"): gather the victim's pool pages + row state to host
        numpy, release its blocks (``frees_by_cause["swapped"]``; leading
        prefix-shared blocks are deref'd with ONE bundle retain each, so
        their content stays device-resident), and park the
        :class:`SwapBundle` keyed by request id for readmission. The
        gather runs BEFORE the release — once freed, the private ids may
        be re-allocated by the very next admission. Returns
        ``{"bytes", "ms"}`` (the measured transfer, fed to
        :meth:`_calibrate_swap`)."""
        req = victim.req
        slot = victim.slot
        pool = self._pool
        # copy: release() zeroes the live table row under us
        row = np.array(pool.table_row(slot))
        t0 = self._clock()
        out = self._swap_extract_executor()(
            self._state, jnp.asarray(row), np.int32(slot)
        )
        # tree-wide np.asarray both fences the gather and lands it in host
        # memory — the device->host leg of the transfer being measured
        host = jax.tree_util.tree_map(np.asarray, out)
        wall = self._clock() - t0
        shared, private = pool.extract(slot, cause="swapped")
        self._push_table()
        self._update_kv_gauges()
        bytes_moved = int(sum(
            leaf.nbytes for leaf in jax.tree_util.tree_leaves(host)
        ))
        self._swap_bundles[req.request_id] = SwapBundle(
            request_id=req.request_id,
            payload=host,
            shared=shared,
            n_private=len(private),
            tokens=int(req.prompt.size) + len(victim.emitted),
            emitted=list(victim.emitted),
            m=int(victim.m),
            last_token_at=victim.last_token_at,
            bytes_moved=bytes_moved,
        )
        ms = wall * 1e3
        self.registry.inc("kv_swaps_total")
        self.registry.inc("kv_swap_bytes_total", bytes_moved)
        self.registry.observe("kv_swap_ms", ms)
        self._calibrate_swap(bytes_moved, wall)
        self._tl_event(
            "swapped", request_id=req.request_id, slot=slot,
            tenant=req.tenant, pages=len(shared) + len(private),
            shared_blocks=len(shared), bytes=bytes_moved, ms=_round_ms(ms),
        )
        if self.tracer is not None:
            self.tracer.event(
                "serving.swapped", trace_id=req.trace_id, slot=slot,
                pages=len(shared) + len(private), bytes=bytes_moved,
                ms=_round_ms(ms),
            )
        return {"bytes": bytes_moved, "ms": ms}

    def _restore_admit(self, req: ServeRequest, slot: int,
                       bundle: SwapBundle) -> None:
        """Readmit a swapped-out victim WITHOUT prompt replay: re-map its
        bundle into whatever free blocks exist now (pessimistic full
        worst-case reservation — the anti-thrash rule), scatter the host
        payload back through the new block-table row, and resume the
        resident at its pre-preemption position — emitted tokens, latent
        count, and inter-token anchor all restored, so the next decode
        step samples from the exact logits the victim was preempted with
        (greedy token-identity by construction) and its ITL telescopes
        across the swap gap. No new ``admitted`` event and no new
        first-token mark: the request's timeline keeps its original
        admission arc, joined by the ``swapped``/``restored`` legs."""
        pool = self._pool
        t0 = self._clock()
        req.started_at = t0
        self.registry.observe(
            "serving_queue_wait_ms", (t0 - req.submitted_at) * 1e3
        )
        self._note_readmitted(req, slot)
        total = int(req.prompt.size) + req.config.max_new_tokens
        try:
            pool.restore(slot, bundle.shared, total, bundle.tokens)
        except BaseException:
            # reserve raises with the pool untouched (restore's ensure is
            # reservation-backed, infallible) — the caller fails the
            # request, so the bundle's parking retains must drop here or
            # the shared blocks strand allocated forever
            self._release_bundle(bundle, cause="failover")
            raise
        pool.set_owner(slot, tenant_label(req.tenant))
        # the slot now holds its own references on the shared run — drop
        # the bundle's parking retains (live derefs, nothing freed)
        for block in bundle.shared:
            pool.deref(block, cause="swapped")
        self._push_table()
        self._update_kv_gauges()
        t1 = self._clock()
        payload = jax.tree_util.tree_map(jnp.asarray, bundle.payload)
        self._state = self._swap_restore_executor()(
            self._state, payload, jnp.asarray(pool.table_row(slot)),
            np.int32(slot), np.int32(len(bundle.shared)),
        )
        # fence: the host->device leg must finish inside the measurement
        np.asarray(self._state["length"])
        wall = self._clock() - t1
        ms = wall * 1e3
        self.registry.inc("kv_swap_restores_total")
        self.registry.inc("kv_swap_bytes_total", bundle.bytes_moved)
        self.registry.observe("kv_swap_ms", ms)
        self._calibrate_swap(bundle.bytes_moved, wall)
        self._slots[slot] = _Slot(
            req=req, slot=slot, max_new=req.config.max_new_tokens,
            m=int(bundle.m), emitted=list(bundle.emitted),
            last_token_at=bundle.last_token_at,
        )
        self._tl_event(
            "restored", request_id=req.request_id, slot=slot,
            tenant=req.tenant, pages=pool.mapped_blocks(slot),
            shared_blocks=len(bundle.shared), tokens_resident=bundle.tokens,
            bytes=bundle.bytes_moved, ms=_round_ms(ms),
        )
        if self.tracer is not None:
            self.tracer.event(
                "serving.restored", trace_id=req.trace_id, slot=slot,
                pages=pool.mapped_blocks(slot), tokens=bundle.tokens,
                bytes=bundle.bytes_moved, ms=_round_ms(ms),
            )

    def _calibrate_swap(self, bytes_moved: int, seconds: float) -> None:
        """Fold one measured transfer into the live link-rate model and
        the per-platform autotune registry (``swap_entries``, persisted
        beside ``spec_entries``): an exponential half-life keeps the rate
        current without letting one outlier transfer swing the auto
        policy. Zero-duration measurements (FakeClock drills) are skipped
        — deterministic tests keep the configured rate."""
        if seconds <= 0 or bytes_moved <= 0:
            return
        measured = bytes_moved / (seconds * 1e9)
        self.swap_link_gbps = round(
            0.5 * self.swap_link_gbps + 0.5 * measured, 6
        )
        decode_strategy_mod.record_swap_gbps(
            self.swap_link_gbps, bytes_moved=int(bytes_moved),
            last_transfer_ms=round(seconds * 1e3, 3),
        )

    def _release_bundle(self, bundle: SwapBundle, cause: str) -> None:
        """Drop one parked bundle's shared-block retains (its host payload
        goes with it). ``cause`` tags any resulting physical frees — the
        bundle may be the LAST reference to a prefix block whose index
        entry was evicted while the victim waited."""
        if self._pool is None:
            return
        for block in bundle.shared:
            self._pool.deref(block, cause=cause)
        if bundle.shared:
            self._update_kv_gauges()

    def _drop_bundle(self, request_id: int, cause: str) -> None:
        """Invalidate a parked swap bundle when its request leaves the
        queue by any path other than restore (cancel / evacuate /
        failover / chaos) — the zero-leak bar counts bundle retains."""
        bundle = self._swap_bundles.pop(request_id, None)
        if bundle is not None:
            self._release_bundle(bundle, cause=cause)

    def postmortems(self) -> dict:
        """The preemption post-mortem rollup (docs/observability.md
        "Scheduler timeline & post-mortems"): lifetime recompute-vs-swap
        totals plus the last few per-victim records. Public so the flight
        recorder sources it into incident bundles and BENCH's preemption
        probe can diff it per arm; also embedded in
        ``stats()["preemption"]["postmortems"]``."""
        totals = self._postmortem_totals
        return {
            "count": totals["count"],
            "swapped": totals["swapped"],
            "tokens_discarded": totals["tokens_discarded"],
            "pages_released": totals["pages_released"],
            "victim_bytes": totals["victim_bytes"],
            "recompute_est_ms": round(totals["recompute_est_ms"], 3),
            "swap_est_ms": round(totals["swap_est_ms"], 3),
            "swap_measured_ms": round(totals["swap_measured_ms"], 3),
            "swap_advantage_ms": round(
                totals["recompute_est_ms"] - totals["swap_est_ms"], 3
            ),
            "swap_link_gbps": self.swap_link_gbps,
            "swapped_waiting": len(self._swap_bundles),
            "recent": list(self._postmortems)[-8:],
        }

    def _preempt_lower_tier(self, head: ServeRequest) -> bool:
        """Admission-time preemption: a strictly-higher-tier head may
        evict lower tiers to get in ("interactive preempts batch"). Never
        fires within a tier — equal-priority admission waits FIFO, so
        steady same-tier load cannot thrash residents."""
        victim = self._pick_victim(head.priority, strict=True)
        if victim is None:
            return False
        self._preempt_victim(victim, beneficiary=head.request_id)
        return True

    def _reclaim_decode_page(self, entry: _Slot) -> str:
        """A resident crossing a block boundary found the pool dry — make
        room, cheapest first: LRU-drop an unreferenced cached prefix
        block, else preempt a victim at or below the resident's own tier,
        else (every other live request outranks it) the resident YIELDS —
        preempts itself so higher tiers keep their pages. Returns
        ``"reclaimed"`` (caller retries the mapping), ``"yielded"`` (the
        entry is gone; caller skips it), or ``"stuck"`` — structurally
        unreachable while check_feasible bounds single-request need, kept
        loud rather than assumed."""
        index = self._prefix_index
        while index is not None:
            freed = index.evict_one(self._pool)
            if freed is None:
                break
            self.registry.inc("kv_prefix_evicted_blocks_total")
            if freed:
                self._update_kv_gauges()
                return "reclaimed"
        victim = self._pick_victim(
            entry.req.priority, strict=False, exclude_slot=entry.slot
        )
        if victim is not None:
            self._preempt_victim(victim, beneficiary=entry.req.request_id)
            return "reclaimed"
        if self._admitting is not None or len(self._active()) > 1:
            # every other live request is a higher tier: yield this slot
            self._preempt_victim(entry, beneficiary=None)
            return "yielded"
        # forward-progress guarantee: the LAST resident is never preempted
        return "stuck"

    def _note_readmitted(self, req: ServeRequest, slot: int) -> None:
        """Admission-side half of the preempt/replay cycle: count and mark
        the re-admission of a previously-preempted request so its trace
        shows the full preempt -> requeue -> readmit arc."""
        if not req.preemptions:
            return
        self.registry.inc("kv_readmissions_total")
        self._tl_event(
            "readmitted", request_id=req.request_id, slot=slot,
            tenant=req.tenant, preemptions=req.preemptions,
        )
        if self.tracer is not None:
            self.tracer.event(
                "serving.readmitted", trace_id=req.trace_id, slot=slot,
                preemptions=req.preemptions,
            )

    # -- slot lifecycle ------------------------------------------------------
    def _update_slot_gauges(self) -> None:
        active = sum(1 for s in self._slots if s is not None)
        self.registry.set_gauge("serving_slots_active", active)
        self.registry.set_gauge("serving_slots_idle", self.slots - active)

    def _active(self) -> List[_Slot]:
        return [s for s in self._slots if s is not None]

    def pending(self) -> bool:
        return (
            bool(self._queue)
            or self._admitting is not None
            or any(s is not None for s in self._slots)
        )

    def _free_slot(self) -> Optional[int]:
        """Lowest unoccupied slot index, excluding the one reserved by an
        in-flight chunked admission."""
        reserved = self._admitting.slot if self._admitting is not None else -1
        for i, s in enumerate(self._slots):
            if s is None and i != reserved:
                return i
        return None

    def _chunk_eligible(self, req: ServeRequest,
                        plan: Optional[_PrefixPlan] = None) -> bool:
        """True when this request should be admitted chunk-by-chunk: chunked
        prefill is configured and the prompt's prefix spans more than one
        chunk (shorter prefixes gain nothing over the single-call bucket
        prefill, which stays the fast path for them). A prefix-cache hit
        shrinks the staged span to the UN-shared suffix — a hot prefix
        with a short suffix admits in one step even under chunking."""
        if self.prefill_chunk is None:
            return False
        cfg = req.config
        bucket_len = self._pick_prompt_bucket(int(req.prompt.size), cfg)
        prefix_len = int(req.prompt.size) - min(bucket_len, cfg.num_latents)
        if plan is not None:
            prefix_len -= plan.shared_tokens
        return prefix_len > self.prefill_chunk

    def _admit(self, req: ServeRequest, slot: int,
               plan: Optional[_PrefixPlan] = None) -> None:
        if plan is not None:
            # prefix-cache hit whose suffix fits one step: run the whole
            # shared admission (mapping, staged suffix chunks, finalize)
            # inline through the chunked-admit machinery — one code path
            # for inline and spread shared admissions. _start_chunked_admit
            # runs the FIRST executor call itself, so it sits inside the
            # try: a fault anywhere in the drain must clear the admission
            # record before step()'s prefill-fault handler rebuilds state,
            # or the next step() would advance a dead admission and
            # double-finish the request.
            try:
                self._start_chunked_admit(req, slot, plan)
                while self._admitting is not None:
                    self._advance_chunked_admit()
            except Exception:
                self._admitting = None
                raise  # step()'s prefill-fault handler releases via _fail_resident
            return
        cfg = req.config
        bucket_len = self._pick_prompt_bucket(int(req.prompt.size), cfg)
        ids = np.full((1, bucket_len), cfg.pad_token_id, np.int32)
        ids[0, bucket_len - req.prompt.size:] = req.prompt
        pad = np.asarray([bucket_len - req.prompt.size], np.int32)
        executor = self._prefill_executor(bucket_len)
        t0 = self._clock()
        # queue wait ends when the prefill STARTS (the bucket engine's
        # batch-assembly convention) — prefill time is its own histogram,
        # not queue wait
        req.started_at = t0
        self.registry.observe("serving_queue_wait_ms", (t0 - req.submitted_at) * 1e3)
        self._note_readmitted(req, slot)
        if self._pool is not None:
            # the scheduler's admission gate verified capacity; reserve the
            # worst case (or, under preemption, just the prompt + headroom —
            # except for a replayed victim, which re-admits pessimistically
            # so it can never be re-evicted by exhaustion) and map the
            # prompt's pages (decode steps map the rest page-by-page as
            # positions fill)
            self._reserve_admit(slot, int(req.prompt.size), cfg.max_new_tokens,
                                pessimistic=bool(req.preemptions))
            self._pool.set_owner(slot, tenant_label(req.tenant))
            self._pool.ensure(slot, int(req.prompt.size))
            self._push_table()
            self._update_kv_gauges()
            self._state = executor(
                self._exec_params, jnp.asarray(ids), jnp.asarray(pad),
                np.int32(slot), jnp.asarray(self._pool.table_row(slot)),
                self._state,
            )
        else:
            self._state = executor(
                self._exec_params, jnp.asarray(ids), jnp.asarray(pad),
                np.int32(slot), self._state,
            )
        # fetch one (tiny) output leaf: the executor is a single XLA program,
        # so this fences the whole prefill — without it, async dispatch (TPU)
        # would record ~0 here and bleed the real prefill cost into the next
        # decode step's histogram (same sync discipline as the bucket
        # engine's np.asarray before timing)
        np.asarray(self._state["length"])
        prefill_ms = (self._clock() - t0) * 1e3
        self.registry.observe("serving_prefill_ms", prefill_ms)
        self.registry.inc("serving_prefills_total")
        self.registry.inc("serving_prompt_tokens_real_total", int(req.prompt.size))
        self.registry.inc("serving_prompt_tokens_padded_total", bucket_len)
        self._slots[slot] = _Slot(
            req=req, slot=slot, max_new=cfg.max_new_tokens,
            m=min(bucket_len, cfg.num_latents),
        )
        self._tl_event(
            "admitted", request_id=req.request_id, slot=slot,
            tenant=req.tenant, priority=req.priority, chunks=0,
        )
        if self.tracer is not None:
            self.tracer.event(
                "serving.slot_assigned", trace_id=req.trace_id, slot=slot,
                bucket=bucket_len, prefill_ms=round(prefill_ms, 3),
            )
        if self._prefix_index is not None:
            self.registry.inc("kv_prefix_misses_total")
            self._publish_prefix(req, slot)

    def _start_chunked_admit(self, req: ServeRequest, slot: int,
                             plan: Optional[_PrefixPlan] = None) -> None:
        """Begin a chunked admission into ``slot``: build the row's window
        and chunk schedule host-side, allocate the batch-1 staging caches,
        and run the first chunk call (queue wait ends here — the bucket
        engine's prefill-starts convention). Subsequent chunks advance one
        per ``step()`` until the final call inserts the finished row.

        With a prefix-cache ``plan`` the admission is SHARED: cached
        blocks map by reference up front, the chunk schedule covers only
        the un-shared suffix ``[shared_tokens, prefix_len)``, staging goes
        straight into the slot's private pool pages through the shared
        prefill executor (no batch-1 staging caches), and a fully-hot
        prefix schedules zero chunks — just the finalize."""
        cfg = req.config
        n = self.model.max_seq_len
        L = int(req.prompt.size)
        bucket_len = self._pick_prompt_bucket(L, cfg)
        m0 = min(bucket_len, cfg.num_latents)
        window = np.full((1, n), cfg.pad_token_id, np.int32)
        window[0, n - L:] = req.prompt
        by_index = np.full((n,), cfg.pad_token_id, np.int32)
        by_index[:L] = req.prompt
        C = self._shared_chunk_size() if plan is not None else self.prefill_chunk
        # chunk starts cover the (un-shared) prefix token indices; starts
        # are clamped so a fixed-size chunk never runs past the cache (an
        # overrunning chunk re-covers earlier positions with identical
        # values — routed to the null block on the shared path — and
        # latent/future positions it grazes are overwritten by the
        # finalize / masked by length)
        start = plan.shared_tokens if plan is not None else 0
        if plan is not None:
            offsets = [min(o, n - C) for o in range(start, plan.prefix_len, C)]
        else:
            offsets = [min(o, n - C) for o in range(0, max(L - m0, 1), C)]
        t0 = self._clock()
        req.started_at = t0
        self.registry.observe("serving_queue_wait_ms", (t0 - req.submitted_at) * 1e3)
        self._note_readmitted(req, slot)
        stage_k = stage_v = None
        if self._pool is not None:
            self._pool.set_owner(slot, tenant_label(req.tenant))
        if plan is not None:
            # shared path: map the hit's pages (reserve excludes the
            # referenced blocks; the partial block COWs before any write)
            self._map_shared_prefix(req, slot, plan)
            self._update_kv_gauges()
        elif self._pool is not None:
            # worst-case (or lazy prompt-sized) reservation up front (the
            # admission gate checked capacity); pages map chunk-by-chunk as
            # the staged prefix grows
            self._reserve_admit(slot, L, cfg.max_new_tokens,
                                pessimistic=bool(req.preemptions))
            self._update_kv_gauges()
        if plan is None:
            _, cache_s = _prefill_shapes(self.model, self.params)
            stage_k = jnp.zeros(cache_s["cross_k"].shape, cache_s["cross_k"].dtype)
            stage_v = jnp.zeros(cache_s["cross_v"].shape, cache_s["cross_v"].dtype)
            if self.sharding is not None:
                # committed placement matching the chunk executor's pinned
                # output shardings — the first chunk call's input signature
                # must equal every later call's (AOT strictness)
                stage_k = self.sharding.put_leaf("stage_k", stage_k)
                stage_v = self.sharding.put_leaf("stage_v", stage_v)
        self._admitting = _ChunkedAdmit(
            req=req, slot=slot, bucket_len=bucket_len, m0=m0,
            window=window, pad=np.asarray([n - L], np.int32),
            by_index=by_index, offsets=offsets, chunk=C,
            stage_k=stage_k, stage_v=stage_v,
            plan=plan, lo=start,
            hi=plan.prefix_len if plan is not None else 0,
        )
        self._advance_chunked_admit()

    def _advance_chunked_admit(self) -> None:
        """Run the in-flight admission's next call: one staging chunk per
        ``step()``, then a pure finalize call (latent k/v + attend + stack,
        row inserted into the slot state). The finalize is its own call —
        not folded into the last chunk — so the admission's worst per-step
        stall is max(one chunk, one finalize), each well under the one-shot
        prefill."""
        admit = self._admitting
        req = admit.req
        C = admit.chunk
        i = admit.next_chunk
        final = i == len(admit.offsets)
        # the finalize branch ignores tokens/offset; reuse the first chunk's
        # slice so the call signature stays uniform
        off = 0 if final else admit.offsets[i]
        tokens = jnp.asarray(admit.by_index[off:off + C][None, :])
        if self._pool is not None:
            # "allocated on chunked-prefill progress": map the pages this
            # call's positions cover — every staged chunk extends the live
            # footprint; the finalize needs the whole prompt mapped before
            # its pool scatter. Shared admissions' referenced pages are
            # already in the table; ensure only extends past them.
            L = int(req.prompt.size)
            covered = L if final else min(off + C, L)
            if self._pool.ensure(admit.slot, covered):
                self._push_table()
            self._update_kv_gauges()
            table_row = jnp.asarray(self._pool.table_row(admit.slot))
        else:
            table_row = jnp.zeros((self._pages_per_slot(),), jnp.int32)
        t0 = self._clock()
        if admit.plan is not None:
            # shared admission: stage straight into the slot's private pool
            # pages; [lo, hi) bounds the writable span so shared pages are
            # never written through
            self._state = self._shared_prefill_executor()(
                self._exec_params, tokens, np.int32(off), np.bool_(final),
                jnp.asarray(admit.window), jnp.asarray(admit.pad),
                np.int32(admit.m0), np.int32(admit.slot), table_row,
                np.int32(admit.lo), np.int32(admit.hi), self._state,
            )
            # fence (host value fetch): the state dict is this program's
            # output, so one tiny leaf fences the whole call
            np.asarray(self._state["length"])
        else:
            executor = self._chunked_prefill_executor()
            admit.stage_k, admit.stage_v, self._state = executor(
                self._exec_params, tokens, np.int32(off), np.bool_(final),
                jnp.asarray(admit.window), jnp.asarray(admit.pad),
                np.int32(admit.m0), np.int32(admit.slot), table_row,
                admit.stage_k, admit.stage_v, self._state,
            )
            # fence the call (host value fetch — same sync discipline as the
            # bucket prefill path) so the chunk/stall histograms are real
            if final:
                np.asarray(self._state["length"])
            else:
                np.asarray(admit.stage_k[0, 0, 0, 0])
        chunk_ms = (self._clock() - t0) * 1e3
        admit.device_ms += chunk_ms
        admit.next_chunk += 1
        # the ms histogram covers every call (the finalize's stall is part of
        # the max(chunk, finalize) bound); the chunk counter covers staging
        # calls only, so it totals the per-admission serving_prefill_chunks
        self.registry.observe("serving_prefill_chunk_ms", chunk_ms)
        if not final:
            self.registry.inc("serving_prefill_chunks_total")
        self._tl_event(
            "chunks", request_id=req.request_id, slot=admit.slot,
            chunk=i, final=final, ms=round(chunk_ms, 3),
        )
        if self.tracer is not None:
            self.tracer.event(
                "serving.prefill_chunk", trace_id=req.trace_id, slot=admit.slot,
                chunk=i, offset=off, final=final, ms=round(chunk_ms, 3),
            )
        if final:
            self._admitting = None
            self.registry.observe("serving_prefill_ms", admit.device_ms)
            self.registry.observe("serving_prefill_chunks", len(admit.offsets))
            self.registry.inc("serving_prefills_total")
            self.registry.inc(
                "serving_prompt_tokens_real_total", int(req.prompt.size)
            )
            self.registry.inc(
                "serving_prompt_tokens_padded_total", admit.bucket_len
            )
            self._slots[admit.slot] = _Slot(
                req=req, slot=admit.slot, max_new=req.config.max_new_tokens,
                m=admit.m0,
            )
            self._tl_event(
                "admitted", request_id=req.request_id, slot=admit.slot,
                tenant=req.tenant, priority=req.priority,
                chunks=len(admit.offsets),
            )
            if self.tracer is not None:
                self.tracer.event(
                    "serving.slot_assigned", trace_id=req.trace_id,
                    slot=admit.slot, bucket=admit.bucket_len,
                    prefill_ms=round(admit.device_ms, 3),
                    chunks=len(admit.offsets),
                )
            if self._prefix_index is not None:
                if admit.plan is None:
                    self.registry.inc("kv_prefix_misses_total")
                # publish this row's full prefix blocks (a hit publishes
                # its EXTENSION blocks — conversation-history growth)
                self._publish_prefix(req, admit.slot)

    def _retire(self, entry: _Slot, status: str, *, error: Optional[str] = None,
                kv_cause: Optional[str] = None) -> None:
        if status == "ok":
            pad_id = entry.req.config.pad_token_id
            out = np.full((entry.max_new,), pad_id, np.int32)
            out[: len(entry.emitted)] = entry.emitted
            entry.req.result = out
        self._finish(entry.req, status, error=error)
        self._slots[entry.slot] = None
        # pool free-cause taxonomy (kv_pool.frees_by_cause): client-driven
        # reclaim, engine-fault reclaim, and fleet scale-down evacuation
        # (kv_cause override) stay separable from ordinary
        # EOS/max_new/deadline churn
        cause = kv_cause or {
            "cancelled": "cancelled", "failed": "failover",
        }.get(status, "retire")
        self._kv_release(entry.slot, cause=cause)
        if self.tracer is not None:
            self.tracer.event(
                "serving.slot_retired", trace_id=entry.req.trace_id,
                slot=entry.slot, status=status, decode_steps=len(entry.emitted),
            )

    def _fail_resident(self, error: str) -> int:
        """Executor-level fault: every resident request fails, the queue
        survives, and the (possibly donated-away) device state is rebuilt."""
        failed = 0
        for entry in self._active():
            self._retire(entry, "failed", error=error)
            failed += 1
        if self._pool is not None:
            # parked swap bundles reference pool blocks about to be blanked
            # — their queued requests fall back to replay-from-prompt
            # (still token-identical), and the retains must drop while the
            # pool's refcounts are still live
            for rid in list(self._swap_bundles):
                self._drop_bundle(rid, cause="failover")
            self._pool.release_all()
            if self._prefix_index is not None:
                # the device pool is about to be blanked: cached prefix
                # blocks would describe zeroed pages — drop them all
                self._prefix_index.flush(self._pool)
            self._push_table()
            self._update_kv_gauges()
            pool_tokens = self._pool_tokens()
        else:
            pool_tokens = None
        self._state = self._place_state(_blank_state(
            self.model, self.params, self.slots, self.config.pad_token_id,
            pool_tokens=pool_tokens,
            quantized=(self.kv_layout == "paged_int8"),
        ))
        self._update_slot_gauges()
        return failed

    # -- cancellation --------------------------------------------------------
    def cancel(self, request_id: int) -> bool:
        """Token-granular cancellation — the gateway's client-disconnect
        retirement route (docs/serving.md "Streaming"). Works at every
        stage of the request lifecycle and reclaims capacity IMMEDIATELY
        (within the current scheduling instant, i.e. before the next
        ``step()`` runs — the zero-leak bar the chaos drill pins):

        - **resident** — the slot retires ``cancelled`` right now: the slot
          frees for the next queued admission and, under the paged layout,
          every pool page (mapped + reserved) returns to the
          :class:`~perceiver_io_tpu.serving.kv_pool.KVPagePool` tagged
          ``cancelled``. Surviving residents are untouched — per-row
          independence means their token streams cannot shift (pinned).
        - **mid chunked admission** — the in-flight admission is dropped
          before its row ever enters the slot state; staging caches are
          garbage-by-construction and the reserved pages return.
        - **queued** — base-class behavior (leaves the queue).

        Exactly one terminal ``serving.request`` span (status
        ``cancelled``) plus one ``serving.cancelled`` event end the trace.
        Returns True when the request was found live."""
        admit = self._admitting
        if admit is not None and admit.req.request_id == request_id:
            self._admitting = None
            self._kv_release(admit.slot, cause="cancelled")
            if self.tracer is not None:
                self.tracer.event(
                    "serving.cancelled", trace_id=admit.req.trace_id,
                    stage="admitting", slot=admit.slot, tokens_emitted=0,
                )
            self._finish(admit.req, "cancelled")
            return True
        for entry in self._active():
            if entry.req.request_id == request_id:
                if self.tracer is not None:
                    self.tracer.event(
                        "serving.cancelled", trace_id=entry.req.trace_id,
                        stage="resident", slot=entry.slot,
                        tokens_emitted=len(entry.emitted),
                    )
                self._retire(entry, "cancelled")
                self._update_slot_gauges()
                return True
        if super().cancel(request_id):
            # a queued swap victim leaves with its parked bundle: the
            # shared-block retains return tagged like every other
            # cancellation reclaim
            self._drop_bundle(request_id, cause="cancelled")
            return True
        return False

    def evacuate(self, cause: str = "scale_down") -> int:
        """Withdraw every live request at once — the fleet scale-down path
        (docs/serving.md "Elasticity"), token-granular: the in-flight
        chunked admission drops (its staging caches are
        garbage-by-construction), every RESIDENT slot retires immediately
        with its pool pages (mapped + reserved) returned tagged ``cause``
        in the pool's ``frees_by_cause`` accounting — the zero-leak bar the
        scale-down drill pins — and queued requests leave through the base
        path. Per-row independence means nothing here could have shifted
        another engine's tokens; the fleet has already replayed this work
        on survivors, token-identical under greedy decoding."""
        evacuated = 0
        admit = self._admitting
        if admit is not None:
            self._admitting = None
            self._kv_release(admit.slot, cause=cause)
            if self.tracer is not None:
                self.tracer.event(
                    "serving.cancelled", trace_id=admit.req.trace_id,
                    stage="admitting", slot=admit.slot, tokens_emitted=0,
                    cause=cause,
                )
            self._finish(admit.req, "cancelled", error=f"evacuated ({cause})")
            evacuated += 1
        for entry in self._active():
            if self.tracer is not None:
                self.tracer.event(
                    "serving.cancelled", trace_id=entry.req.trace_id,
                    stage="resident", slot=entry.slot,
                    tokens_emitted=len(entry.emitted), cause=cause,
                )
            self._retire(
                entry, "cancelled", error=f"evacuated ({cause})",
                kv_cause=cause,
            )
            evacuated += 1
        # queued swap victims leave through the base path below — their
        # parked bundles' retains return tagged with the evacuation cause
        # (the scale-down drill's zero-leak bar counts them)
        for rid in list(self._swap_bundles):
            self._drop_bundle(rid, cause=cause)
        self._update_slot_gauges()
        return evacuated + super().evacuate(cause)

    def resize_slots(self, new_slots: int) -> int:
        """Grow or shrink the persistent decode state to ``new_slots`` —
        the autoscaler's slot-count elasticity knob (docs/serving.md
        "Elasticity"), riding the SAME rebuild-from-warm-cache path a
        warmup-time kv-layout switch uses: the device state (and, under the
        paged layout, the pool — re-scaled to the new slot count unless the
        operator sized it explicitly) is rebuilt blank via
        ``_init_kv_state``, while the executor caches are process-global —
        a slot count this process has compiled before costs ZERO fresh
        compiles, an unseen one compiles exactly the slot-specialized
        executors (decode pair + chunk/shared variants). Requires an idle
        engine (no residents, no in-flight admission) — resizing under
        traffic would decode residents from zeroed caches; drain or
        evacuate first. Queued requests survive (host-side numpy, no device
        state). Returns the previous slot count."""
        if new_slots < 1:
            raise ValueError(f"slots must be >= 1, got {new_slots}")
        if self.sharding is not None and new_slots % self.sharding.data_size:
            raise ValueError(
                f"slots ({new_slots}) must divide evenly over the mesh "
                f"data axis ({self.sharding.data_size})"
            )
        if any(s is not None for s in self._slots) or self._admitting is not None:
            raise RuntimeError(
                "resize_slots() with requests resident in slots would "
                "corrupt their decode state; drain() or evacuate() first"
            )
        old = self.slots
        if new_slots == old:
            return old
        self.slots = int(new_slots)
        self._slots = [None] * self.slots
        if not self._kv_sized:
            # default pool sizing tracks the slot count (dense-equivalent
            # capacity); an operator-sized pool is a fixed HBM budget and
            # must not silently change under a resize
            self.kv_blocks = self.slots * self._pages_per_slot()
        self._init_kv_state(self.kv_layout)
        self._update_slot_gauges()
        return old

    # -- the token-level scheduler ------------------------------------------
    def step(self) -> int:
        """Advance serving by ONE TOKEN: expire deadlines (queued, resident,
        and mid-admission), advance an in-flight chunked admission by one
        chunk, refill free slots from the queue, run one fixed-shape decode
        step over all slots, and retire rows that just finished
        (EOS / max_new_tokens). Returns the number of requests disposed of
        this call; ``pending()`` — not the return value — says whether more
        work remains (a mid-generation step legitimately disposes of 0).
        """
        return self._run_pass(self._step_pass)

    def _tl_record(self, t0: float, t1: float) -> None:
        """Slot-engine per-pass timeline record: the bucket shape plus the
        slot occupancy vector, real-vs-padded decode rows, KV pool
        occupancy, and per-tenant resident pages."""
        draft, self._tl_draft = self._tl_draft, None
        marks, self._tl_marks = self._tl_marks or {}, None
        phases = {"total": round((t1 - t0) * 1e3, 3)}
        if "admit_done_s" in marks:
            phases["admit"] = round((marks["admit_done_s"] - t0) * 1e3, 3)
        if "decode_ms" in marks:
            phases["decode"] = round(marks["decode_ms"], 3)
        if "token_at_s" in marks:
            phases["account"] = round((t1 - marks["token_at_s"]) * 1e3, 3)
        rec = {
            "engine": "slots",
            "t_start_s": round(t0, 6),
            "t_end_s": round(t1, 6),
            "queue_depth": len(self._queue),
            "slots": [
                None if s is None else s.req.request_id for s in self._slots
            ],
            "phases_ms": phases,
        }
        if "rows_active" in marks:
            active = int(marks["rows_active"])
            rec["rows"] = {
                "total": self.slots, "real": active,
                "padded": self.slots - active,
            }
        if self._pool is not None:
            rec["pool"] = {
                "in_use": self._pool.in_use,
                "reserved": self._pool.reserved,
                "headroom": self._pool.headroom_blocks,
            }
            tenants: Dict[str, int] = {}
            for tenant, held in self._tenant_pages().items():
                key = tenant_label(tenant)
                tenants[key] = tenants.get(key, 0) + held
            if tenants:
                rec["tenants"] = dict(sorted(tenants.items()))
        rec.update(draft or {})
        self.timeline.append(rec)

    def _step_pass(self) -> int:
        disposed = self._expire_overdue()
        if self._swap_bundles:
            # a parked bundle whose request left the queue by a path that
            # bypasses the drop hooks (deadline expiry while queued) must
            # not strand its shared-block retains
            queued = {r.request_id for r in self._queue}
            for rid in [r for r in self._swap_bundles if r not in queued]:
                self._drop_bundle(rid, cause="retire")
        now = self._clock()
        for entry in self._active():
            req = entry.req
            if req.deadline_at is not None and now >= req.deadline_at:
                self._retire(
                    entry, "timed_out",
                    error=f"deadline exceeded after {len(entry.emitted)} of "
                          f"{entry.max_new} tokens",
                )
                disposed += 1
        ran_chunk_call = False
        if self._admitting is not None:
            admit = self._admitting
            req = admit.req
            if req.deadline_at is not None and now >= req.deadline_at:
                self._admitting = None
                self._kv_release(admit.slot)
                self._finish(
                    req, "timed_out",
                    error=f"deadline exceeded after {admit.next_chunk} of "
                          f"{len(admit.offsets)} prefill chunks",
                )
                disposed += 1
            else:
                final = admit.next_chunk == len(admit.offsets)
                shared = admit.plan is not None
                ran_chunk_call = True
                try:
                    self._advance_chunked_admit()
                except Exception as e:
                    # on CPU an UNSHARED chunk fault only poisons the
                    # batch-1 staging caches; a SHARED stage call writes
                    # pool pages through the live state on every backend,
                    # and with donation live (non-CPU) the shared slot
                    # state was donated into the failed call too — as does
                    # a finalize fault either way
                    self._admitting = None
                    self._kv_release(admit.slot)
                    self._finish(req, "failed", error=f"{type(e).__name__}: {e}")
                    disposed += 1
                    if final or shared or _donate(0):
                        return disposed + self._fail_resident(
                            "chunked-prefill fault poisoned the slot state: "
                            f"{type(e).__name__}: {e}"
                        )
        self._preempts_this_step = 0
        if self._queue and (
            self.preemption != "off" or any(r.priority for r in self._queue)
        ):
            # priority-ordered admission (stable: request_id keeps FIFO
            # within a tier, and puts a preempted request's replay back at
            # its original submission order). Pure-FIFO workloads with the
            # default tier skip the sort entirely — byte-identical cost.
            self._queue.sort(key=lambda r: (-r.priority, r.request_id))
        while self._queue:
            slot = self._free_slot()
            if slot is None:
                break
            head = self._queue[0]
            # a swap-preempted head restores from its parked bundle: no
            # prefix plan (its pages carry the prefix content already) and
            # no chunk lane (restore is one scatter, not a prefill)
            bundle = self._swap_bundles.get(head.request_id)
            plan = None
            if self._pool is not None and bundle is None:
                try:
                    plan = self._prefix_plan(head.prompt, head.config)
                except Exception:
                    plan = None  # infeasible heads fail in _admit as before

            def lane_blocked(plan_now):
                try:
                    is_chunked = self._chunk_eligible(head, plan_now)
                except Exception:
                    is_chunked = False
                # FIFO: both the spread-chunk path and a shared admission's
                # inline drain use the single chunked-admit lane, and the
                # lane runs at most one call per step (a finalize -> first-
                # chunk handoff in one step would stall residents past the
                # documented max(chunk, finalize) bound)
                if (is_chunked or plan_now is not None) and self._admitting is not None:
                    return True, is_chunked
                # an inline shared drain is also lane work: it must not run
                # in the same step the lane already ran a call (finalize ->
                # inline-drain handoff would stall residents past the bound)
                return (
                    (is_chunked or plan_now is not None) and ran_chunk_call,
                    is_chunked,
                )

            # lane check BEFORE the evicting gate: a head that cannot admit
            # this step anyway must not flush cached prefixes to make room
            # it cannot yet use
            blocked, chunked = (
                (False, False) if bundle is not None else lane_blocked(plan)
            )
            if blocked:
                break
            if self._pool is not None:
                # pool admission gate: the head waits (FIFO — later
                # requests must not starve it) until retirements free its
                # worst-case block count. check_feasible already rejected
                # requests that could NEVER fit, so this wait terminates.
                # Counted once per WAITING REQUEST, not per scheduler poll
                # (a long-blocked head is one wait, however many steps it
                # spans). Prefix sharing shrinks the need by the
                # referenced blocks, and under pressure unreferenced
                # cached prefixes LRU-drop BEFORE the head is made to
                # wait; each eviction can invalidate the match, so the
                # plan re-derives until the need is reservable or the
                # cache is dry. Under optimistic admission the need
                # shrinks to the head's PROMPT pages + headroom
                # (_admit_need), and a strictly-higher-tier head may
                # preempt lower tiers to get in ("interactive preempts
                # batch") — equal tiers still wait FIFO, so steady
                # same-tier load cannot thrash residents.
                while True:
                    need = self._admit_need(head, plan, bundle)
                    if self._pool.can_reserve(need):
                        break
                    if not self._evict_for(need) and not (
                        self.preemption != "off"
                        and self._preempt_lower_tier(head)
                    ):
                        break
                    if bundle is not None:
                        continue
                    try:
                        plan = self._prefix_plan(head.prompt, head.config)
                    except Exception:
                        plan = None
                if not self._pool.can_reserve(need):
                    if self._kv_waiting_id != head.request_id:
                        self._kv_waiting_id = head.request_id
                        self.registry.inc("kv_pool_admit_waits_total")
                        if self.flight_recorder is not None:
                            # pool exhaustion is incident-worthy exactly
                            # once per waiting request (the counter's own
                            # once-per-wait discipline), and the recorder's
                            # cooldown bounds a thrashing pool further
                            pool = self._pool.stats()
                            self.flight_recorder.trigger(
                                "pool_exhausted",
                                f"admission stalled: request "
                                f"{head.request_id} needs {int(need)} pool "
                                f"blocks, {pool['blocks'] - pool['reserved']}"
                                f" of {pool['blocks']} unreserved",
                                trace_ids=(
                                    [head.trace_id] if head.trace_id else []
                                ),
                                request_id=head.request_id,
                                blocks_needed=int(need),
                                blocks=pool["blocks"],
                                blocks_reserved=pool["reserved"],
                            )
                    break
                # eviction may have shrunk the plan and flipped the head
                # onto the (busy) chunk lane — re-check before admitting
                blocked, chunked = (
                    (False, False) if bundle is not None else lane_blocked(plan)
                )
                if blocked:
                    break
            req = self._queue.pop(0)
            if self._apply_request_chaos(req):
                self._drop_bundle(req.request_id, cause="failover")
                disposed += 1
                continue
            if bundle is not None:
                del self._swap_bundles[req.request_id]
                try:
                    self._restore_admit(req, slot, bundle)
                except Exception as e:
                    # the restore scatter donates the slot state (non-CPU)
                    # and may have half-written the pool either way —
                    # _fail_resident releases every slot's pages, which
                    # covers whatever pool.restore had re-mapped
                    self._finish(req, "failed", error=f"{type(e).__name__}: {e}")
                    return disposed + 1 + self._fail_resident(
                        "swap-restore fault poisoned the slot state: "
                        f"{type(e).__name__}: {e}"
                    )
                continue
            if chunked:
                try:
                    self._start_chunked_admit(req, slot, plan)
                except Exception as e:
                    # first chunk: staging-only fault on CPU; with donation
                    # live the slot state went into the failed call too —
                    # and a shared first call writes pool pages directly
                    self._admitting = None
                    self._kv_release(slot)
                    self._finish(req, "failed", error=f"{type(e).__name__}: {e}")
                    disposed += 1
                    if plan is not None or _donate(0):
                        return disposed + self._fail_resident(
                            "chunked-prefill fault poisoned the slot state: "
                            f"{type(e).__name__}: {e}"
                        )
                continue
            try:
                self._admit(req, slot, plan)
            except Exception as e:  # prefill fault: this request + residents
                self._finish(req, "failed", error=f"{type(e).__name__}: {e}")
                return disposed + 1 + self._fail_resident(
                    f"prefill fault poisoned the slot state: {type(e).__name__}: {e}"
                )
        self._update_slot_gauges()
        self._tl_mark_clock("admit_done_s")
        active = self._active()
        if not active:
            return disposed

        self._rng, key = jax.random.split(self._rng)
        t0 = self._clock()
        try:
            fault = self._chaos.hit("serving.batch") if self._chaos else None
            if fault is not None and fault.kind == "error":
                raise fault.make_error()
            if self._pool is not None:
                # map the page each active row's NEXT write lands on (a
                # block-boundary crossing maps one fresh block), then
                # refresh the device table. Reservation makes this
                # infallible under preemption="off"; under optimistic
                # admission a dry pool raises PoolExhausted and a victim
                # yields its pages instead (the boundary-crossing preempt
                # path; kv.exhaust chaos scripts that pressure
                # deterministically — consulted once per decode step).
                forced = None
                if self._chaos is not None and self.preemption != "off":
                    forced = self._chaos.hit("kv.exhaust")
                changed = False
                for entry in active:
                    if self._slots[entry.slot] is not entry:
                        continue  # preempted as an earlier row's victim
                    # speculative bursts map this round's WORST-CASE accepted
                    # span up front (atomically — ensure_many), so a
                    # mid-burst boundary crossing can never strand a
                    # half-mapped row; clamped to the request's remaining
                    # budget so a retiring row maps nothing it cannot emit
                    burst = 1 if self._spec is None else max(
                        1, min(self._spec.k + 1,
                               entry.max_new - len(entry.emitted))
                    )
                    next_len = (
                        int(entry.req.prompt.size) + len(entry.emitted) + burst
                    )
                    while True:
                        try:
                            if forced is not None and forced.kind == "error":
                                forced = None
                                raise PoolExhausted(
                                    "chaos: kv.exhaust scripted pool pressure"
                                )
                            if burst > 1:
                                changed |= self._pool.ensure_many(
                                    entry.slot, next_len
                                )
                            else:
                                changed |= self._pool.ensure(entry.slot, next_len)
                            # write-routing invariant: COW any still-shared
                            # page this step's append/migration would write
                            # through
                            changed |= self._cow_guard(entry, next_len)
                            break
                        except PoolExhausted as e:
                            if self.preemption == "off":
                                raise
                            outcome = self._reclaim_decode_page(entry)
                            if outcome == "yielded":
                                break
                            if outcome == "stuck":
                                raise RuntimeError(
                                    "preemption found no victim and no "
                                    "evictable prefix for the sole "
                                    "resident — single-request "
                                    "feasibility was checked at submit: "
                                    f"{e}"
                                ) from e
                if changed:
                    self._push_table()
                    self._update_kv_gauges()
                active = self._active()
                if not active:
                    # every resident yielded this step (an all-preempted
                    # instant): nothing to decode; the requeued replays
                    # admit next step
                    return disposed
            # armed by a serving_decode_step_ms p95 regression on a PRIOR
            # step: this step (dispatch + host-sync fence) runs under the
            # profiler capture; the step-number read (a registry lock) only
            # happens when a capture actually fires
            with self._device_capture(
                step=lambda: int(self.registry.counter("serving_decode_steps_total"))
            ):
                if self._spec is not None:
                    # speculative round: draft then verify, one fixed-shape
                    # dispatch each; the verify's lanes handle latent growth
                    # AND the m == max_latents boundary per row, so no
                    # boundary-variant executor choice exists on this path
                    cand = self._spec_draft_executor()(
                        self._exec_params, self._state
                    )
                    self._state, n_e = self._spec_verify_executor()(
                        self._exec_params, self._state, cand
                    )
                    cand = np.asarray(cand)
                    n_e = np.asarray(n_e)  # host sync: the scheduling point
                else:
                    boundary = any(
                        s.m >= self.model.max_latents for s in active
                    )
                    executor = self._decode_executor(boundary)
                    if self._pool is not None:
                        self._state, tokens = executor(
                            self._exec_params, self._state, self._table_dev, key
                        )
                    else:
                        self._state, tokens = executor(
                            self._exec_params, self._state, key
                        )
                    tokens = np.asarray(tokens)  # host sync: the scheduling point
        except Exception as e:
            self.registry.observe(
                "serving_decode_step_ms", (self._clock() - t0) * 1e3
            )
            return disposed + self._fail_resident(f"{type(e).__name__}: {e}")
        decode_ms = (self._clock() - t0) * 1e3
        self.registry.observe("serving_decode_step_ms", decode_ms)
        self._tl_mark("decode_ms", decode_ms)
        self._tl_mark("rows_active", len(active))
        if self.profiler_trigger is not None:
            self.profiler_trigger.observe(decode_ms)
        self.registry.inc("serving_decode_steps_total")
        if self._pool is not None:
            from perceiver_io_tpu.ops import ragged_attention as ragged_mod
            if ragged_mod.kernel_enabled():
                # decode steps served by the ragged paged-attention kernel
                # (vs the gather-to-dense reference) — docs/observability.md
                self.registry.inc("kv_ragged_kernel_steps_total")
        self.registry.inc("serving_decode_rows_total", self.slots)
        self.registry.inc("serving_decode_rows_padded_total", self.slots - len(active))
        eos = self.config.eos_token_id
        # Per-request token-latency accounting (docs/observability.md): the
        # np.asarray fence above materialized every slot's token, so all
        # active rows share this step's completion instant — TTFT for rows
        # that just emitted their first token (submit → that instant, queue
        # wait and prefill included), inter-token latency for the rest
        # (previous token's instant → this one, so a long admission or a
        # boundary-variant step shows up in every RESIDENT row's ITL).
        # A speculative round emits its whole accepted burst at this ONE
        # instant: the burst's first token carries the round's latency,
        # the rest sample 0.0 ms ITL — each emitted token still gets its
        # own sample, so TTFT + Σ ITL telescopes exactly to the stream
        # span, burst or not (pinned under FakeClock).
        token_at = self._clock()
        self._tl_mark("token_at_s", token_at)
        tier_tokens: Dict[str, int] = {}
        tenant_tokens: Dict[str, int] = {}
        emitted_this_step = 0
        for entry in active:
            if self._spec is None:
                row_tokens = [int(tokens[entry.slot])]
            else:
                # accepted burst, truncated host-side at EOS/max_new below
                # exactly as n_e sequential steps would have stopped
                row_tokens = [
                    int(t) for t in cand[entry.slot, : int(n_e[entry.slot])]
                ]
            for token in row_tokens:
                first = not entry.emitted
                entry.emitted.append(token)
                emitted_this_step += 1
                if entry.req.on_token is not None:
                    # incremental streaming: the fence above materialized
                    # this token, so the sink (the gateway's per-stream
                    # queue) gets it the same instant the scheduler does —
                    # burst tokens flush one callback per index, in order
                    self._emit_token(entry.req, len(entry.emitted) - 1, token)
                entry.m = min(entry.m + 1, self.model.max_latents)
                if first:
                    ttft_ms = (token_at - entry.req.ttft_from_s) * 1e3
                    self._observe_token_latency("serving_ttft_ms", ttft_ms)
                    if self.timeline is not None:
                        self._tl_event(
                            "tokens", request_id=entry.req.request_id,
                            slot=entry.slot, first=True,
                            ttft_ms=round(ttft_ms, 3),
                        )
                    if self.tracer is not None:
                        self.tracer.event(
                            "serving.first_token", trace_id=entry.req.trace_id,
                            slot=entry.slot, ttft_ms=round(ttft_ms, 3),
                        )
                else:
                    itl_ms = (token_at - entry.last_token_at) * 1e3
                    self._observe_token_latency("serving_inter_token_ms", itl_ms)
                    if self.timeline is not None:
                        self._tl_event(
                            "tokens", request_id=entry.req.request_id,
                            slot=entry.slot, first=False,
                            itl_ms=round(itl_ms, 3),
                        )
                entry.last_token_at = token_at
                # per-tier / per-tenant token attribution, batched to one
                # registry/dict bump per label per step (hot-path discipline)
                tkey = tier_label(entry.req.priority)
                tier_tokens[tkey] = tier_tokens.get(tkey, 0) + 1
                nkey = tenant_label(entry.req.tenant)
                tenant_tokens[nkey] = tenant_tokens.get(nkey, 0) + 1
                if (eos is not None and token == eos) or len(entry.emitted) >= entry.max_new:
                    self._retire(entry, "ok")
                    disposed += 1
                    break
        self.registry.inc("serving_tokens_generated_total", emitted_this_step)
        if self._spec is not None:
            # acceptance telemetry (docs/observability.md "spec_*"): the
            # measured signal autotune_speculation gates on, and the live
            # regression alarm a fleet watches after enabling speculation
            accepted = int(sum(int(n_e[e.slot]) - 1 for e in active))
            self.registry.inc("spec_rounds_total")
            self.registry.inc(
                "spec_tokens_proposed_total", self._spec.k * len(active)
            )
            self.registry.inc("spec_tokens_accepted_total", accepted)
            self.registry.inc("spec_tokens_emitted_total", emitted_this_step)
            if self.timeline is not None:
                self._tl_event(
                    "spec_round", rows=len(active),
                    proposed=self._spec.k * len(active),
                    accepted=accepted, emitted=emitted_this_step,
                )
        for tkey, n in tier_tokens.items():
            self.registry.inc(f"serving_tokens_tier_{tkey}_total", n)
        for nkey, n in tenant_tokens.items():
            self._tokens_by_tenant[nkey] = \
                self._tokens_by_tenant.get(nkey, 0) + n
        self._update_slot_gauges()
        return disposed

    def run_until_idle(self) -> int:
        served = 0
        while self.pending():
            served += self.step()
        return served

    def drain(self) -> int:
        """Graceful shutdown, token-granular (API parity with
        :meth:`ServingEngine.drain` — the serve CLI and the fleet router's
        rolling restart call one method on either engine instead of
        hand-rolling ``while pending(): step()`` loops): stop accepting
        submissions, then run every QUEUED request, the in-flight chunked
        admission, and every RESIDENT slot to completion — a resident row
        mid-generation finishes its remaining tokens rather than being
        dropped. The base implementation already does the right thing
        through the overridden :meth:`run_until_idle`; this override exists
        to document (and pin, ``tests/test_fleet.py``) the token-granular
        contract. Returns the number of requests disposed of; idempotent."""
        return super().drain()

    # -- ahead-of-time warmup ------------------------------------------------
    def warmup(self, config: Optional[GenerationConfig] = None) -> int:
        """Compile every executor the engine can ever dispatch — one prefill
        per feasible prompt bucket, the decode executor, its boundary
        variant, (when ``prefill_chunk`` is set) the one chunked-prefill
        executor, and (when ``speculation`` is on) the draft + verify pair —
        then wipe the warmup garbage from the slot state.
        Returns the number of fresh executor builds; after it, mixed-length
        traffic compiles nothing (pinned by tests).

        When ``decode_strategy="auto"`` was requested explicitly, the
        boundary autotuner runs first
        (:func:`~perceiver_io_tpu.inference.decode_strategy.autotune_boundary`
        — its cached-vs-recompute probe compiles two small generation
        executors, counted in the return value), so the boundary variant is
        compiled against the measured winner and steady-state traffic never
        retraces."""
        if config is not None and dataclasses.replace(
            config, max_new_tokens=self.config.max_new_tokens
        ) != self.config:
            raise ValueError(
                "slot engine warmup config must match the engine config "
                "(only max_new_tokens may differ)"
            )
        if any(s is not None for s in self._slots) or self._admitting is not None:
            # warmup ends by blanking the device state; doing that under
            # resident requests would silently decode them from zeroed caches
            raise RuntimeError(
                "warmup() with requests resident in slots would corrupt "
                "their decode state; warm up before traffic or after drain()"
            )
        cfg = self.config
        before = executor_cache_stats()["misses"]
        if self.decode_strategy == "auto":
            decode_strategy_mod.autotune_boundary(self.model, self.params)
        if self.kv_layout_requested == "auto" and not self._kv_sized:
            # measure dense-vs-paged decode at the bound shape once per
            # process (memoized; the probe's own executor compiles count in
            # the return value), then rebuild onto the winner BEFORE
            # compiling the grid — no residents here, so the switch is
            # free. Skipped when the operator sized the pool explicitly:
            # sizing is a layout choice, and a dense verdict would discard
            # the budget. The probe engines published THEIR footprints on
            # the process-global ledger gauge, so re-publish ours after.
            verdict = decode_strategy_mod.autotune_kv_layout(
                self.model, self.params, block_size=self.kv_block_size,
            )
            if verdict != self.kv_layout:
                self._init_kv_state(verdict)
            else:
                self._update_kv_gauges()
            entry = decode_strategy_mod.kv_entry(self.model)
            gate = (entry or {}).get("quant_gate")
            if gate is not None and not gate.get("passed", False):
                # the quality gate vetoed int8 at this shape — the verdict
                # degraded to exact "paged"/dense; surface it on a counter
                # so a fleet rollout notices quality-driven fallbacks
                self.registry.inc("kv_quant_fallback_total")
            if self.prefix_cache_requested == "on" and \
                    self.kv_layout not in decode_strategy_mod.PAGED_KV_LAYOUTS:
                # the ctor deferred this check for kv_layout="auto" (the
                # autotuner could still pick paged); it didn't — an
                # explicit sharing request must not be dropped silently
                raise ValueError(
                    "prefix_cache='on' requires a paged kv_layout but the "
                    "kv-layout autotuner resolved dense at this shape — "
                    "pass kv_layout='paged' explicitly to share prefixes"
                )
        # no residents here (checked above), so re-resolving is safe: the
        # boundary variant compiles against the freshest verdict
        self._pinned_boundary_mode = None
        paged = self._pool is not None
        pages = self._pages_per_slot()
        # an all-zero table routes every warmup write to the null block and
        # every gather to its (finite) trash — the executors trace the same
        # programs live traffic dispatches
        row0 = jnp.zeros((pages,), jnp.int32)
        max_prefix = self.model.max_prefix_len
        for bucket_len in self.table.prompt_lens:
            if bucket_len - min(bucket_len, cfg.num_latents) > max_prefix:
                continue
            ids = jnp.full((1, bucket_len), cfg.pad_token_id, jnp.int32)
            pad = jnp.zeros((1,), jnp.int32)
            if paged:
                self._state = self._prefill_executor(bucket_len)(
                    self._exec_params, ids, pad, np.int32(0), row0, self._state
                )
            else:
                self._state = self._prefill_executor(bucket_len)(
                    self._exec_params, ids, pad, np.int32(0), self._state
                )
        if self.prefill_chunk is not None:
            n = self.model.max_seq_len
            _, cache_s = _prefill_shapes(self.model, self.params)
            sk = jnp.zeros(cache_s["cross_k"].shape, cache_s["cross_k"].dtype)
            sv = jnp.zeros(cache_s["cross_v"].shape, cache_s["cross_v"].dtype)
            if self.sharding is not None:
                # match the live admission path's committed staging
                # placement (AOT signature discipline)
                sk = self.sharding.put_leaf("stage_k", sk)
                sv = self.sharding.put_leaf("stage_v", sv)
            tokens = jnp.full((1, self.prefill_chunk), cfg.pad_token_id, jnp.int32)
            window = jnp.full((1, n), cfg.pad_token_id, jnp.int32)
            pad = jnp.zeros((1,), jnp.int32)
            m0 = np.int32(min(cfg.num_latents, self.model.max_latents))
            executor = self._chunked_prefill_executor()
            for final in (False, True):  # one program: lax.cond traces both
                sk, sv, self._state = executor(
                    self._exec_params, tokens, np.int32(0), np.bool_(final),
                    window, pad, m0, np.int32(0), row0, sk, sv, self._state,
                )
        if self._prefix_index is not None:
            # prefix-sharing executors: the shared (suffix-only) prefill —
            # both lax.cond branches of one program — and the COW page
            # copy, so the first hot admission compiles nothing
            C = self._shared_chunk_size()
            tokens = jnp.full((1, C), cfg.pad_token_id, jnp.int32)
            window = jnp.full((1, self.model.max_seq_len), cfg.pad_token_id,
                              jnp.int32)
            pad = jnp.zeros((1,), jnp.int32)
            m0 = np.int32(min(cfg.num_latents, self.model.max_latents))
            executor = self._shared_prefill_executor()
            for final in (False, True):
                self._state = executor(
                    self._exec_params, tokens, np.int32(0), np.bool_(final),
                    window, pad, m0, np.int32(0), row0,
                    np.int32(0), np.int32(0), self._state,
                )
            self._state = self._page_copy_executor()(
                self._state, np.int32(0), np.int32(0)
            )
        for boundary in (False, True):
            self._rng, key = jax.random.split(self._rng)
            if paged:
                # placed like the live _table_dev (AOT signature discipline)
                table0 = self._place_table(
                    np.zeros((self.slots, pages), np.int32)
                )
                self._state, _ = self._decode_executor(boundary)(
                    self._exec_params, self._state, table0, key
                )
            else:
                self._state, _ = self._decode_executor(boundary)(
                    self._exec_params, self._state, key
                )
        if self._spec is not None:
            # the speculative round's pair (+2 on the compile bound): the
            # lane verify handles both phases per row, so no boundary
            # variant exists on this path
            cand0 = self._spec_draft_executor()(self._exec_params, self._state)
            self._state, _ = self._spec_verify_executor()(
                self._exec_params, self._state, cand0
            )
        if paged and self.preemption in ("swap", "auto"):
            # the host-swap pair (+2 on the compile bound): one dummy
            # extract/restore round trip on the all-zero table (null-block
            # trash both ways), so the first real victim compiles nothing
            out0 = self._swap_extract_executor()(
                self._state, row0, np.int32(0)
            )
            self._state = self._swap_restore_executor()(
                self._state, out0, row0, np.int32(0), np.int32(0)
            )
        if self._prefix_index is not None:
            # the state blank below zeroes the device pool; cached blocks
            # must not survive it
            self._prefix_index.flush(self._pool)
            self._update_kv_gauges()
        # parked swap bundles (possible when warmup is re-run after
        # traffic drained mid-queue) reference pool content the blank
        # below zeroes — their requests fall back to replay-from-prompt
        for rid in list(self._swap_bundles):
            self._drop_bundle(rid, cause="retire")
        self._state = self._place_state(_blank_state(
            self.model, self.params, self.slots, cfg.pad_token_id,
            pool_tokens=self._pool_tokens() if paged else None,
            quantized=(self.kv_layout == "paged_int8"),
        ))
        return executor_cache_stats()["misses"] - before

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        out = super().stats()
        counts = self.registry.counters()
        rows = counts.get("serving_decode_rows_total", 0)
        padded = counts.get("serving_decode_rows_padded_total", 0)
        reg = self.registry
        out.update({
            "engine": "slots",
            "slots": self.slots,
            "slots_active": sum(1 for s in self._slots if s is not None),
            "decode_steps": int(counts.get("serving_decode_steps_total", 0)),
            "prefills": int(counts.get("serving_prefills_total", 0)),
            "slot_occupancy": round((rows - padded) / max(1.0, rows), 4),
            "decode_rows_padding_waste": round(padded / max(1.0, rows), 4),
            "decode_step_ms": {
                "p50": _round_ms(reg.percentile("serving_decode_step_ms", 50.0)),
                "p95": _round_ms(reg.percentile("serving_decode_step_ms", 95.0)),
            },
            "prefill_chunk": self.prefill_chunk,
            "prefill_chunks": int(counts.get("serving_prefill_chunks_total", 0)),
            "prefill_chunk_ms": {
                "p50": _round_ms(reg.percentile("serving_prefill_chunk_ms", 50.0)),
                "p95": _round_ms(reg.percentile("serving_prefill_chunk_ms", 95.0)),
            },
            "decode_strategy_boundary": self._boundary_mode(),
            "kv_layout": self.kv_layout,
        })
        out["speculation"] = {"mode": self.speculation}
        if self._spec is not None:
            rounds = int(counts.get("spec_rounds_total", 0))
            proposed = int(counts.get("spec_tokens_proposed_total", 0))
            accepted = int(counts.get("spec_tokens_accepted_total", 0))
            emitted = int(counts.get("spec_tokens_emitted_total", 0))
            out["speculation"].update({
                "k": self._spec.k,
                "draft_layers": self._spec.draft_layers,
                "rounds": rounds,
                "proposed": proposed,
                "accepted": accepted,
                "emitted": emitted,
                # the autotuner's gate signal: drafted tokens the verify
                # kept, over drafted tokens proposed
                "acceptance_rate": round(accepted / max(1, proposed), 4),
                "tokens_per_round": round(emitted / max(1, rounds), 4),
            })
        if self.sharding is not None:
            out["mesh"] = {
                "data": self.sharding.data_size,
                "model": self.sharding.model_size,
                "devices": self.sharding.num_devices,
                "spec": self.sharding.describe(),
            }
        if self._pool is not None:
            out["kv_pool"] = {
                **self._pool.stats(),
                "layout": self.kv_layout,
                "dtype": str(jnp.dtype(self._state["pool_k"].dtype)),
                "admit_waits": int(counts.get("kv_pool_admit_waits_total", 0)),
                "resident_bytes": int(
                    self.registry.gauge("kv_cache_resident_bytes") or 0
                ),
                "capacity_bytes": self._kv_capacity_bytes,
                "block_bytes": self.kv_block_size * self._kv_token_bytes,
                "block_scale_bytes":
                    self.kv_block_size * self._kv_scale_token_bytes,
                "quant_fallbacks": int(
                    counts.get("kv_quant_fallback_total", 0)
                ),
            }
            out["preemption"] = {
                "mode": self.preemption,
                "admit_headroom_blocks": self.admit_headroom_blocks,
                "preemptions": int(counts.get("kv_preemptions_total", 0)),
                "readmissions": int(counts.get("kv_readmissions_total", 0)),
                # host-swap disposition (docs/serving.md "Host-swap
                # preemption"): victims swapped out / bundles restored /
                # bytes moved both directions / victims parked right now
                "swaps": int(counts.get("kv_swaps_total", 0)),
                "swap_restores": int(counts.get("kv_swap_restores_total", 0)),
                "swap_bytes": int(counts.get("kv_swap_bytes_total", 0)),
                "swapped_waiting": len(self._swap_bundles),
                "swap_link_gbps": self.swap_link_gbps,
                "by_tier": dict(sorted(self._preempted_by_tier.items())),
                "by_tenant": dict(sorted(self._preempted_by_tenant.items())),
                "headroom_blocks": self._pool.headroom_blocks,
                # per-victim recompute-vs-swap post-mortems
                # (docs/observability.md "Scheduler timeline &
                # post-mortems"): the measured crossover evidence ROADMAP
                # item 2's host-swap policy starts from
                "postmortems": self.postmortems(),
            }
            out["prefix_cache"] = {"enabled": self._prefix_index is not None}
            if self._prefix_index is not None:
                hits = int(counts.get("kv_prefix_hits_total", 0))
                misses = int(counts.get("kv_prefix_misses_total", 0))
                out["prefix_cache"].update({
                    "hits": hits,
                    "misses": misses,
                    "hit_ratio": round(hits / max(1, hits + misses), 4),
                    "shared_blocks": int(
                        counts.get("kv_prefix_shared_blocks_total", 0)
                    ),
                    "shared_tokens": int(
                        counts.get("kv_prefix_shared_tokens_total", 0)
                    ),
                    "cow_copies": int(
                        counts.get("kv_prefix_cow_copies_total", 0)
                    ),
                    "evicted": int(
                        counts.get("kv_prefix_evicted_blocks_total", 0)
                    ),
                    "published": int(
                        counts.get("kv_prefix_published_blocks_total", 0)
                    ),
                    **self._prefix_index.stats(),
                })
        # per-tenant attribution rollup (sanitized labels): resident pool
        # pages + generated tokens + preemption victims per tenant — the
        # fleet router sums these across replicas, and the serve CLI's
        # serve_stats carries the fleet-level rollup
        pages_by_tenant: Dict[str, int] = {}
        if self._pool is not None:
            for tenant, held in self._tenant_pages().items():
                key = tenant_label(tenant)
                pages_by_tenant[key] = pages_by_tenant.get(key, 0) + held
        tenant_keys = (
            set(pages_by_tenant) | set(self._tokens_by_tenant)
            | set(self._preempted_by_tenant)
        )
        if tenant_keys:
            out["tenants"] = {
                key: {
                    "blocks_in_use": pages_by_tenant.get(key, 0),
                    "tokens": self._tokens_by_tenant.get(key, 0),
                    "preemptions": self._preempted_by_tenant.get(key, 0),
                }
                for key in sorted(tenant_keys)
            }
        return out

    def health(self) -> dict:
        out = super().health()
        out["slots"] = self.slots
        out["slots_active"] = sum(1 for s in self._slots if s is not None)
        out["admitting"] = self._admitting is not None
        out["kv_layout"] = self.kv_layout
        out["prefix_cache"] = self.prefix_cache
        out["preemption"] = self.preemption
        out["speculation"] = self.speculation
        out["mesh"] = (
            None if self.sharding is None else self.sharding.describe()
        )
        return out
