"""Shape-bucketed serving engine: continuous micro-batching over the
compiled generation executors.

``generate()`` compiles one executor per exact ``(batch, prompt_len,
num_latents, s1, s2)`` plan, and ``TextGenerationPipeline`` pads each
caller's batch to its own max width — so ragged real traffic causes
unbounded retracing and tiny fixed batches. This engine is the first
load-path layer between "a jitted ``generate()``" and "a service":

- **Bucketing** — every prompt is padded up to a static
  ``(batch_size, prompt_len)`` grid (:class:`~.buckets.BucketTable`), so
  all traffic lands on at most ``len(table)`` pre-compilable executors
  (plus the phase-plan split, see :meth:`ServingEngine.warmup`).
- **Continuous micro-batching** — queued requests are packed FIFO into the
  next bucket slot via the existing left-pad path (``prompt_pad_count``);
  unfilled rows are dummy pad rows whose outputs are discarded; results are
  split back per request.
- **Warmup** — :meth:`ServingEngine.warmup` compiles every bucket before
  traffic is accepted.
- **Observability** — the executor cache's hit/miss/evict counters
  (``generate.executor_cache_stats``) plus queue-wait percentiles surface
  in :meth:`ServingEngine.stats`, so residual retracing is measured, never
  silent.

Exactness: generation is left-pad invariant (padded keys are masked out of
every softmax; ``tests/test_generate.py`` pins padded == unpadded against
the torch reference), so for greedy decoding the bucketed output is
token-identical to the unbucketed path. The effective latent count is
clamped by the bucket width (``min(bucket_len, config.num_latents)``)
exactly as the unbucketed pipeline clamps it by the batch's max width —
keep ``config.num_latents`` at or below the shortest served prompt if
per-request calls must match bit-for-bit.

The engine is deliberately synchronous and single-owner: ``submit()``
enqueues, ``step()`` drains one micro-batch, ``serve()`` is submit-all +
drain. An async front end (HTTP/RPC) drives the same queue from its own
loop; device work already serializes inside each compiled executor.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.inference.generate import (
    GenerationConfig,
    executor_cache_stats,
    generate,
)
from perceiver_io_tpu.serving.buckets import BucketTable


@dataclass
class ServeRequest:
    """One queued prompt and, after its micro-batch ran, its result row."""

    request_id: int
    prompt: np.ndarray  # (len,) int32, unpadded
    config: GenerationConfig
    submitted_at: float
    started_at: Optional[float] = None
    result: Optional[np.ndarray] = None  # (max_new_tokens,) ids, pad after EOS

    @property
    def done(self) -> bool:
        return self.result is not None


class ServingEngine:
    """Request queue + scheduler over the bucketed generation executors.

    :param model: an ``AutoregressiveSequenceModel`` (CLM / symbolic audio).
    :param params: its parameter tree.
    :param config: default :class:`GenerationConfig` (per-request override
        via ``submit(..., config=...)``; only identical-config requests are
        packed into one micro-batch).
    :param table: the bucket grid; defaults to a powers-of-two grid up to
        the model's context length (:meth:`BucketTable.for_model`).
    :param rng: base PRNG key; each micro-batch uses a fresh split.
    """

    def __init__(self, model, params, config: Optional[GenerationConfig] = None,
                 table: Optional[BucketTable] = None, *, rng: Optional[jax.Array] = None):
        self.model = model
        self.params = params
        self.config = config or GenerationConfig()
        self.table = table or BucketTable.for_model(model)
        too_long = [L for L in self.table.prompt_lens if L > model.max_seq_len]
        if too_long:
            raise ValueError(
                f"prompt buckets {too_long} exceed the model context "
                f"length {model.max_seq_len}"
            )
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._queue: List[ServeRequest] = []
        self._next_id = 0
        self._cache0 = executor_cache_stats()
        self._waits_ms: List[float] = []
        self._batches = 0
        self._requests = 0
        self._tokens_generated = 0
        self._real_prompt_tokens = 0
        self._padded_prompt_tokens = 0

    # -- queue front --------------------------------------------------------
    def submit(self, prompt, config: Optional[GenerationConfig] = None) -> ServeRequest:
        """Enqueue one prompt (1-D token ids); returns its request handle."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("cannot serve an empty prompt")
        cfg = config or self.config
        self._pick_prompt_bucket(int(prompt.size), cfg)  # fail fast, not mid-batch
        req = ServeRequest(self._next_id, prompt, cfg, time.monotonic())
        self._next_id += 1
        self._queue.append(req)
        self._requests += 1
        return req

    def serve(self, prompts: Sequence, config: Optional[GenerationConfig] = None,
              *, rng: Optional[jax.Array] = None) -> List[np.ndarray]:
        """Submit every prompt, drain the queue, return results in order."""
        if rng is not None:
            self._rng = rng
        reqs = [self.submit(p, config) for p in prompts]
        self.run_until_idle()
        return [r.result for r in reqs]

    def run_until_idle(self) -> int:
        """Drain the whole queue; returns the number of requests served."""
        served = 0
        while True:
            n = self.step()
            if n == 0:
                return served
            served += n

    # -- scheduler ----------------------------------------------------------
    def _pick_prompt_bucket(self, length: int, cfg: GenerationConfig) -> int:
        """Smallest prompt bucket that fits ``length`` AND the model's
        prefix capacity under ``cfg`` (``generate`` rejects plans whose
        nominal prefix ``L - min(L, num_latents)`` exceeds
        ``max_prefix_len``)."""
        max_prefix = self.model.max_prefix_len
        for cap in self.table.prompt_lens:
            if cap < length:
                continue
            if cap - min(cap, cfg.num_latents) > max_prefix:
                continue
            return cap
        raise ValueError(
            f"no feasible prompt bucket for length {length} with "
            f"num_latents={cfg.num_latents}: buckets {self.table.prompt_lens} "
            f"must satisfy len <= {self.model.max_seq_len} and "
            f"len - num_latents <= max_prefix_len={max_prefix}"
        )

    def step(self) -> int:
        """Run ONE micro-batch: the queue head plus following requests with
        the same config, packed FIFO into the next bucket slot. Returns the
        number of real requests served (0 = queue empty)."""
        if not self._queue:
            return 0
        cfg = self._queue[0].config
        picked: List[ServeRequest] = []
        rest: List[ServeRequest] = []
        for req in self._queue:
            if len(picked) < self.table.batch_sizes[-1] and req.config == cfg:
                picked.append(req)
            else:
                rest.append(req)
        self._queue = rest

        b = self.table.batch_bucket(len(picked))
        length = self._pick_prompt_bucket(max(r.prompt.size for r in picked), cfg)
        ids = np.full((b, length), cfg.pad_token_id, np.int32)
        # Dummy filler rows claim zero pads — a full-width "prompt" of pad-id
        # tokens whose output is computed and dropped. Zero, not length-1:
        # ``generate`` enables the cached prefix-growth phase only when EVERY
        # row's pad count fits the nominal prefix (``phase2_ok``), so a
        # max-padded filler would silently demote an underfilled micro-batch
        # to the slow windowed-recompute plan. Attention is per-row; filler
        # content never touches real rows.
        pad_count = np.zeros((b,), np.int32)
        now = time.monotonic()
        for i, req in enumerate(picked):
            ids[i, length - req.prompt.size:] = req.prompt
            pad_count[i] = length - req.prompt.size
            req.started_at = now
            self._waits_ms.append((now - req.submitted_at) * 1e3)

        self._rng, key = jax.random.split(self._rng)
        out = np.asarray(
            generate(
                self.model, self.params, jnp.asarray(ids), cfg,
                rng=key, prompt_pad_count=jnp.asarray(pad_count),
            )
        )
        for i, req in enumerate(picked):
            req.result = out[i]
        self._batches += 1
        self._tokens_generated += len(picked) * cfg.max_new_tokens
        self._real_prompt_tokens += sum(int(r.prompt.size) for r in picked)
        self._padded_prompt_tokens += b * length
        return len(picked)

    # -- ahead-of-time warmup ----------------------------------------------
    def warmup(self, config: Optional[GenerationConfig] = None) -> int:
        """Compile every feasible bucket before accepting traffic; returns
        the number of fresh executor compiles.

        Each ``(batch, prompt_len)`` cell is driven through ``generate``
        with BOTH phase plans it can map to at serve time: zero left pads
        (prefix-growth cache eligible) and maximal left pads (pad overflow
        beyond the nominal prefix disables phase 2, a different static
        plan). Cells infeasible under ``config`` (prefix capacity) are
        skipped — serve-time scheduling skips them identically."""
        cfg = config or self.config
        before = executor_cache_stats()["misses"]
        max_prefix = self.model.max_prefix_len
        for b, length in self.table.grid():
            nominal_prefix = length - min(length, cfg.num_latents)
            if nominal_prefix > max_prefix:
                continue
            pad_variants = {0}
            if length - 1 > nominal_prefix:
                pad_variants.add(length - 1)
            for pad in pad_variants:
                ids = jnp.full((b, length), cfg.pad_token_id, jnp.int32)
                pad_count = jnp.full((b,), pad, jnp.int32)
                generate(self.model, self.params, ids, cfg,
                         rng=jax.random.PRNGKey(0), prompt_pad_count=pad_count)
        return executor_cache_stats()["misses"] - before

    # -- observability ------------------------------------------------------
    def stats(self) -> dict:
        """Serving counters since engine construction. ``compiles`` is the
        executor-cache miss delta — the engine assumes it owns the process's
        generation traffic over its lifetime (true for the CLI, bench probe,
        and tests)."""
        cache_now = executor_cache_stats()
        # clamp at 0: reset_executor_caches() mid-lifetime rewinds the global
        # counters below this engine's construction-time snapshot
        cache = {k: max(0, cache_now[k] - self._cache0[k]) for k in cache_now}
        waits = sorted(self._waits_ms)

        def pct(p: float) -> Optional[float]:
            if not waits:
                return None
            return round(waits[min(len(waits) - 1, int(round(p / 100.0 * (len(waits) - 1))))], 3)

        return {
            "requests": self._requests,
            "batches": self._batches,
            "queued": len(self._queue),
            "compiles": cache["misses"],
            "executor_cache": cache,
            "queue_wait_ms": {"p50": pct(50.0), "p95": pct(95.0)},
            "tokens_generated": self._tokens_generated,
            "prompt_padding_efficiency": round(
                self._real_prompt_tokens / max(1, self._padded_prompt_tokens), 4
            ),
            "bucket_grid": {
                "prompt_lens": list(self.table.prompt_lens),
                "batch_sizes": list(self.table.batch_sizes),
            },
        }
