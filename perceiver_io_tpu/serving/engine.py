"""Shape-bucketed serving engine: continuous micro-batching over the
compiled generation executors.

``generate()`` compiles one executor per exact ``(batch, prompt_len,
num_latents, s1, s2)`` plan, and ``TextGenerationPipeline`` pads each
caller's batch to its own max width — so ragged real traffic causes
unbounded retracing and tiny fixed batches. This engine is the first
load-path layer between "a jitted ``generate()``" and "a service":

- **Bucketing** — every prompt is padded up to a static
  ``(batch_size, prompt_len)`` grid (:class:`~.buckets.BucketTable`), so
  all traffic lands on at most ``len(table)`` pre-compilable executors
  (plus the phase-plan split, see :meth:`ServingEngine.warmup`).
- **Continuous micro-batching** — queued requests are packed FIFO into the
  next bucket slot via the existing left-pad path (``prompt_pad_count``);
  unfilled rows are dummy pad rows whose outputs are discarded; results are
  split back per request.
- **Warmup** — :meth:`ServingEngine.warmup` compiles every bucket before
  traffic is accepted.
- **Observability** (docs/observability.md) — every counter lives on a
  :class:`~perceiver_io_tpu.observability.MetricsRegistry` under canonical
  Prometheus-style names (``serving_requests_completed_total``, ...), with
  queue-wait / batch-assembly / device-execute histograms; an optional
  :class:`~perceiver_io_tpu.observability.Tracer` threads one trace per
  request through ``submit → queued → batched → executed → split/complete``
  so every submitted request ends in exactly one terminal
  ``serving.request`` span (status ``ok``/``shed``/``timed_out``/
  ``failed``/``rejected``). The executor cache's hit/miss/evict counters
  (``generate.executor_cache_stats``) surface in
  :meth:`ServingEngine.stats` too, so residual retracing is measured,
  never silent.

Exactness: generation is left-pad invariant (padded keys are masked out of
every softmax; ``tests/test_generate.py`` pins padded == unpadded against
the torch reference), so for greedy decoding the bucketed output is
token-identical to the unbucketed path. The effective latent count is
clamped by the bucket width (``min(bucket_len, config.num_latents)``)
exactly as the unbucketed pipeline clamps it by the batch's max width —
keep ``config.num_latents`` at or below the shortest served prompt if
per-request calls must match bit-for-bit.

The engine is deliberately synchronous and single-owner: ``submit()``
enqueues, ``step()`` drains one micro-batch, ``serve()`` is submit-all +
drain. An async front end (HTTP/RPC) drives the same queue from its own
loop; device work already serializes inside each compiled executor.

Fault tolerance (docs/reliability.md): the queue is bounded (``max_queue``
→ :class:`~perceiver_io_tpu.reliability.QueueFull` backpressure + a shed
counter), requests carry deadlines (expired ones complete ``timed_out``
instead of occupying a bucket slot), a failing request or executor marks
only its own request(s) ``failed`` while the rest of the queue drains,
``drain()`` is the graceful-shutdown path, and ``health()`` is the
readiness snapshot a front end polls. All failure paths are drilled by the
deterministic chaos harness (``reliability.chaos``) via the optional
``chaos`` / ``clock`` hooks.
"""
from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.inference.generate import (
    GenerationConfig,
    executor_cache_stats,
    generate,
)
from perceiver_io_tpu.observability import MetricsRegistry, Tracer
from perceiver_io_tpu.reliability import QueueFull
from perceiver_io_tpu.serving.buckets import BucketTable

#: shared no-op capture context for unarmed dispatches (nullcontext is
#: stateless and re-enterable, so one instance serves every step)
_NULL_CAPTURE = contextlib.nullcontext()


class _SafeCapture:
    """A profiler capture that cannot fail the dispatch it observes: enter
    and exit errors (an already-active profiler session, an unwritable
    capture dir) degrade to no capture instead of surfacing inside the
    engine's executor-failure handler — which would terminally fail every
    resident request over telemetry."""

    __slots__ = ("_ctx",)

    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        try:
            return self._ctx.__enter__()
        except Exception:
            self._ctx = None
            return None

    def __exit__(self, *exc):
        if self._ctx is None:
            return False
        try:
            return self._ctx.__exit__(*exc)
        except Exception:
            return False  # never replace the dispatch's own exception

#: The shared health-snapshot schema contract (docs/serving.md): every
#: ``health()`` in the serving layer — both engines, the fleet's per-replica
#: snapshot, and the FleetRouter itself — exposes AT LEAST these keys, so a
#: supervisor (the fleet router, a load balancer probe) reads any of them
#: uniformly. Implementations may add keys (the slot engine adds ``slots``/
#: ``slots_active``; a Replica adds breaker state) but never drop these.
#: Pinned by the contract test in ``tests/test_fleet.py``.
HEALTH_KEYS = frozenset({
    "ready", "accepting", "queue_depth", "max_queue", "oldest_wait_ms",
    "completed", "shed", "timed_out", "failed", "cancelled",
})

#: canonical registry counter names -> the legacy ``stats()`` keys they
#: replace (kept as deprecation aliases; docs/observability.md)
STAT_ALIASES = {
    "serving_requests_submitted_total": "requests",
    "serving_requests_completed_total": "completed",
    "serving_requests_shed_total": "shed",
    "serving_requests_timed_out_total": "timed_out",
    "serving_requests_failed_total": "failed",
    "serving_requests_rejected_total": "rejected",
    "serving_requests_cancelled_total": "cancelled",
    "serving_batches_total": "batches",
    "serving_tokens_generated_total": "tokens_generated",
}


def _round_ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 3)


@dataclass
class ServeRequest:
    """One queued prompt and, after its micro-batch ran, its outcome.

    ``status`` is ``"queued"`` until the scheduler disposes of the request:
    ``"ok"`` (``result`` holds the generated row), ``"timed_out"`` (deadline
    expired before a bucket slot ran it), ``"cancelled"`` (the caller
    withdrew it via :meth:`ServingEngine.cancel` — the streaming gateway's
    client-disconnect path), or ``"failed"`` (``error`` holds the reason;
    its micro-batch peers are unaffected).
    """

    request_id: int
    prompt: np.ndarray  # (len,) int32, unpadded
    config: GenerationConfig
    submitted_at: float
    deadline_at: Optional[float] = None  # absolute, in engine-clock seconds
    started_at: Optional[float] = None
    result: Optional[np.ndarray] = None  # (max_new_tokens,) ids, pad after EOS
    status: str = "queued"  # queued | ok | timed_out | cancelled | failed
    error: Optional[str] = None
    #: per-request trace ID (None when the engine has no tracer) — the join
    #: key between the serve CLI's JSON lines and events.jsonl
    trace_id: Optional[str] = None
    #: TTFT measurement anchor on the engine clock — defaults to
    #: ``submitted_at``. The fleet router backdates it to the FLEET submit
    #: time at dispatch (and the HTTP gateway to the SOCKET accept
    #: instant), so time-to-first-token stays the user-facing number
    #: (front door → first token) instead of resetting at each replica
    #: handoff. Queue-wait / request-latency accounting keeps using
    #: ``submitted_at`` — those attribute THIS engine's share.
    ttft_anchor_s: Optional[float] = None
    #: optional per-request incremental token sink (docs/serving.md
    #: "Streaming"): called ``on_token(index, token_id)`` the moment a REAL
    #: token for this request materializes — per token step on the slot
    #: engine, once per token at batch completion on the bucket engine
    #: (batch granularity). Indices restart at 0 when a fleet failover
    #: replays the request; greedy determinism makes the replayed prefix
    #: identical, so stream consumers dedupe by index. A raising sink is
    #: isolated (``serving_token_sink_errors_total``), never failing the
    #: request it observes.
    on_token: Optional[Callable[[int, int], None]] = None
    #: scheduling tier (docs/serving.md "Preemption & priorities"): HIGHER
    #: int = more important. The slot engine admits higher tiers first and
    #: — under optimistic KV admission — preempts strictly-lower tiers
    #: when the pool runs dry ("interactive preempts batch, never vice
    #: versa"). 0 (default) keeps pure FIFO; the bucket engine stores but
    #: ignores it.
    priority: int = 0
    #: tenant label for per-tenant resident-page fairness under preemption
    #: (victim selection prefers the tenant holding the most pool pages at
    #: equal priority). None = untagged.
    tenant: Optional[str] = None
    #: times this request was preempted (pages returned, requeued for a
    #: token-identical greedy replay) — ``serving.readmitted`` span events
    #: and the replay dedupe contract key off it
    preemptions: int = 0

    @property
    def ttft_from_s(self) -> float:
        return self.submitted_at if self.ttft_anchor_s is None else self.ttft_anchor_s

    @property
    def done(self) -> bool:
        return self.status != "queued"


class ServingEngine:
    """Request queue + scheduler over the bucketed generation executors.

    :param model: an ``AutoregressiveSequenceModel`` (CLM / symbolic audio).
    :param params: its parameter tree.
    :param config: default :class:`GenerationConfig` (per-request override
        via ``submit(..., config=...)``; only identical-config requests are
        packed into one micro-batch).
    :param table: the bucket grid; defaults to a powers-of-two grid up to
        the model's context length (:meth:`BucketTable.for_model`).
    :param rng: base PRNG key; each micro-batch uses a fresh split.
    :param max_queue: bounded-queue depth; ``submit`` past it raises
        :class:`QueueFull` and counts a shed. None = unbounded (offline use).
    :param default_deadline_s: deadline applied to requests submitted without
        an explicit ``deadline_s``; expired requests complete ``timed_out``.
    :param clock: monotonic time source. Tests and the chaos harness pass a
        :class:`~perceiver_io_tpu.reliability.FakeClock` so deadline expiry
        is deterministic; production uses the default ``time.monotonic``.
    :param chaos: optional fault-injection registry
        (:class:`~perceiver_io_tpu.reliability.ChaosRegistry`); None skips
        every hook.
    :param registry: metrics registry the engine's counters/histograms live
        on. Defaults to a private one (two engines must not double-count);
        pass a shared registry for unified export (the serve CLI does).
    :param tracer: optional span tracer — one trace per request, one
        terminal ``serving.request`` span per submission, one
        ``serving.batch`` span per micro-batch. None skips every span site.
    :param profiler_trigger: optional
        :class:`~perceiver_io_tpu.observability.ProfilerTrigger` watching
        the serving device path (this engine feeds it per-batch
        ``serving_device_execute_ms``; the slot engine feeds per-token
        ``serving_decode_step_ms``). When a p95 regression arms it, the
        NEXT device dispatch runs under a ``jax.profiler`` capture —
        the serve-side twin of the trainer wiring (docs/observability.md).
    :param decode_strategy: per-phase decode strategy forwarded to every
        ``generate()`` dispatch — ``"auto" | "cached" | "recompute"``
        (``inference/decode_strategy.py``). ``None`` defers to
        ``PERCEIVER_DECODE_STRATEGY`` then the measured registry. With an
        explicit ``"auto"``, :meth:`warmup` runs the boundary autotuner
        first so the deployment measures once and compiles against the
        winner.
    """

    def __init__(self, model, params, config: Optional[GenerationConfig] = None,
                 table: Optional[BucketTable] = None, *, rng: Optional[jax.Array] = None,
                 max_queue: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 chaos=None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 profiler_trigger=None,
                 decode_strategy: Optional[str] = None):
        from perceiver_io_tpu.inference import decode_strategy as _strategy

        if decode_strategy is not None and decode_strategy not in _strategy.MODES:
            raise ValueError(
                f"decode_strategy must be one of {_strategy.MODES}, "
                f"got {decode_strategy!r}"
            )
        self.decode_strategy = decode_strategy
        self.model = model
        self.params = params
        self.config = config or GenerationConfig()
        self.table = table or BucketTable.for_model(model)
        too_long = [L for L in self.table.prompt_lens if L > model.max_seq_len]
        if too_long:
            raise ValueError(
                f"prompt buckets {too_long} exceed the model context "
                f"length {model.max_seq_len}"
            )
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        self.default_deadline_s = default_deadline_s
        self._clock = clock
        self._chaos = chaos
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._queue: List[ServeRequest] = []
        self._next_id = 0
        self._accepting = True
        self._cache0 = executor_cache_stats()
        # One source of truth for every counter/histogram (the old private
        # _completed/_shed/... ints). stats() reads these back and also
        # exposes the legacy key names as aliases.
        self.registry = registry if registry is not None else MetricsRegistry(clock=clock)
        self.registry.declare_counters(
            *STAT_ALIASES,
            "serving_prompt_tokens_real_total",
            "serving_prompt_tokens_padded_total",
            "serving_decode_rows_total",
            "serving_decode_rows_padded_total",
        )
        self.tracer = tracer
        self.profiler_trigger = profiler_trigger
        #: optional mirror for the per-token latency histograms
        #: (``serving_ttft_ms`` / ``serving_inter_token_ms``,
        #: docs/observability.md): called with ``(name, value_ms)`` after
        #: the engine's own registry observes. The fleet router installs
        #: one per replica so fleet-scope percentiles exist beside the
        #: per-replica ones, and an
        #: :class:`~perceiver_io_tpu.observability.slo.SLOMonitor`'s
        #: ``sink`` plugs in the same way.
        self.latency_sink: Optional[Callable[[str, float], None]] = None
        #: optional incident
        #: :class:`~perceiver_io_tpu.observability.FlightRecorder` — the
        #: slot engine fires its ``pool_exhausted`` seam when an admission
        #: stalls on KV pool blocks (docs/observability.md "Flight
        #: recorder & incident bundles"); None skips the seam, the same
        #: contract as ``tracer``/``chaos``
        self.flight_recorder = None
        #: optional scheduler step timeline
        #: (:class:`~perceiver_io_tpu.observability.StepTimeline`,
        #: docs/observability.md "Scheduler timeline & post-mortems"):
        #: when attached, every ``step()`` pass appends one structured
        #: record — admissions / token emissions / terminal dispositions
        #: this pass plus per-phase wall ms on the engine clock. None
        #: skips the seam entirely, the same contract as ``tracer``.
        self.timeline = None
        self._tl_draft: Optional[dict] = None  # per-pass event accumulator
        self._tl_marks: Optional[dict] = None  # per-pass phase marks

    # -- scheduler timeline seams -------------------------------------------
    def _tl_event(self, kind: str, **fields) -> None:
        """Accumulate one timeline event under ``kind`` for the pass in
        flight (or the NEXT pass for out-of-band calls like ``cancel()``
        between steps — deterministic either way)."""
        if self.timeline is None:
            return
        if self._tl_draft is None:
            self._tl_draft = {}
        self._tl_draft.setdefault(kind, []).append(fields)

    def _tl_mark(self, key: str, value) -> None:
        if self._tl_marks is not None:
            self._tl_marks[key] = value

    def _tl_mark_clock(self, key: str) -> None:
        """Phase-boundary clock mark — reads the clock ONLY when a pass is
        being recorded, so a timeline-less engine's step stays byte-
        identical (FakeClock drills included)."""
        if self._tl_marks is not None:
            self._tl_marks[key] = self._clock()

    def _run_pass(self, pass_fn):
        """Run one scheduler pass, appending its timeline record on every
        exit path (early returns and raises included)."""
        if self.timeline is None:
            return pass_fn()
        t0 = self._clock()
        self._tl_marks = {}
        try:
            return pass_fn()
        finally:
            self._tl_record(t0, self._clock())

    def _tl_record(self, t0: float, t1: float) -> None:
        """Build and append the bucket engine's per-pass record; the slot
        engine overrides this with its occupancy/pool shape."""
        draft, self._tl_draft = self._tl_draft, None
        marks, self._tl_marks = self._tl_marks or {}, None
        phases = {"total": round((t1 - t0) * 1e3, 3)}
        for key in ("assemble_ms", "execute_ms"):
            if key in marks:
                phases[key[: -len("_ms")]] = round(marks[key], 3)
        rec = {
            "engine": "bucket",
            "t_start_s": round(t0, 6),
            "t_end_s": round(t1, 6),
            "queue_depth": len(self._queue),
            "phases_ms": phases,
        }
        rec.update(draft or {})
        self.timeline.append(rec)

    def _observe_token_latency(self, name: str, value_ms: float) -> None:
        """One TTFT / inter-token observation: engine registry first (the
        scope ``stats()`` reads), then the optional mirror (replica → fleet
        scope, SLO monitor)."""
        self.registry.observe(name, value_ms)
        if self.latency_sink is not None:
            self.latency_sink(name, value_ms)

    def _device_capture(self, *, step=None):
        """Context for one device dispatch: a profiler capture when the
        trigger armed on the previous observation, else a shared no-op — so
        the capture shows a representative regressed dispatch, not the blip
        that armed it (the trainer-loop convention). ``step`` may be a
        zero-arg callable, evaluated only when a capture actually runs —
        keeps step-number bookkeeping off the unarmed per-token path."""
        trigger = self.profiler_trigger
        if trigger is None or not trigger.armed:
            return _NULL_CAPTURE
        try:
            return _SafeCapture(
                trigger.capture(step=step() if callable(step) else step)
            )
        except Exception:
            return _NULL_CAPTURE

    # -- queue front --------------------------------------------------------
    def submit(self, prompt, config: Optional[GenerationConfig] = None,
               *, deadline_s: Optional[float] = None,
               ttft_anchor_s: Optional[float] = None,
               on_token: Optional[Callable[[int, int], None]] = None,
               priority: int = 0, tenant: Optional[str] = None
               ) -> ServeRequest:
        """Enqueue one prompt (1-D token ids); returns its request handle.

        Raises ``ValueError`` for infeasible prompts (empty, or longer than
        the largest bucket / prefix capacity) at submit time — never inside
        bucket packing — and :class:`QueueFull` when the bounded queue is at
        ``max_queue`` (the request is shed and counted, not enqueued).
        ``ttft_anchor_s`` backdates the TTFT measurement to an earlier
        instant on the same clock (the fleet router passes its front-door
        submit time; the HTTP gateway its socket-accept time — see
        :class:`ServeRequest`). ``on_token`` installs the request's
        incremental token sink (:attr:`ServeRequest.on_token`).
        ``priority`` (higher = more important) and ``tenant`` tag the
        request for the slot engine's priority-ordered admission and
        preemption victim policy (docs/serving.md "Preemption &
        priorities"); this bucket engine stores them untouched.
        """
        if not self._accepting:
            raise RuntimeError("engine is draining; new submissions rejected")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        cfg = config or self.config
        try:
            self.check_feasible(prompt, cfg)
        except ValueError as e:
            # infeasible submissions still get a terminal span + counter so
            # the CLI's per-line error records join against events.jsonl
            self.registry.inc("serving_requests_rejected_total")
            e.trace_id = self._terminal_event("rejected", error=str(e))
            raise
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            self.registry.inc("serving_requests_shed_total")
            exc = QueueFull(
                f"queue depth {len(self._queue)} is at max_queue="
                f"{self.max_queue}; request shed — drain with step() or "
                "retry after backoff"
            )
            exc.trace_id = self._terminal_event(
                "shed", queue_depth=len(self._queue)
            )
            raise exc
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        now = self._clock()
        req = ServeRequest(
            self._next_id, prompt, cfg, now,
            deadline_at=None if deadline_s is None else now + deadline_s,
            trace_id=self.tracer.new_trace_id() if self.tracer else None,
            ttft_anchor_s=ttft_anchor_s,
            on_token=on_token,
            priority=int(priority), tenant=tenant,
        )
        self._next_id += 1
        self._queue.append(req)
        self.registry.inc("serving_requests_submitted_total")
        return req

    def check_feasible(self, prompt, config: Optional[GenerationConfig] = None
                       ) -> GenerationConfig:
        """Raise the precise ``ValueError`` this engine's ``submit`` would
        raise for an infeasible prompt (empty, longer than the largest
        bucket, or — via the subclass's ``_pick_prompt_bucket`` — out of the
        slot engine's scope), WITHOUT touching the queue or emitting spans;
        returns the resolved config. The fleet router shares it for
        fleet-level admission, so a request that no replica could ever serve
        rejects at the front door instead of bouncing between replicas.
        The slot engine's override additionally gates on KV-pool capacity
        (a single request's pages must all fit the pool, a physical bound
        prefix sharing cannot relax); the scheduler's admission gate is
        where shareable blocks enter the accounting — referenced prefix
        blocks are excluded from each admission's reservation, so
        hot-prefix residents pack concurrently (docs/serving.md "Prefix
        sharing"). Fleet replicas keep independent caches; replay after a
        failover re-prefills through the survivor's own index."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        cfg = config or self.config
        if prompt.size == 0:
            raise ValueError("cannot serve an empty prompt")
        if prompt.size > self.table.prompt_lens[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the largest bucket "
                f"{self.table.prompt_lens[-1]}; extend the bucket table or "
                "truncate the prompt"
            )
        self._pick_prompt_bucket(int(prompt.size), cfg)  # fail fast, not mid-batch
        return cfg

    def _terminal_event(self, status: str, **attrs) -> Optional[str]:
        """Emit a terminal ``serving.request`` span for a submission that
        never became a queue entry (shed / rejected); returns its trace ID
        so the raising path can attach it to the exception."""
        if self.tracer is None:
            return None
        trace_id = self.tracer.new_trace_id()
        self.tracer.event("serving.request", trace_id=trace_id, status=status, **attrs)
        return trace_id

    def serve(self, prompts: Sequence, config: Optional[GenerationConfig] = None,
              *, rng: Optional[jax.Array] = None) -> List[Optional[np.ndarray]]:
        """Submit every prompt, drain the queue, return results in order.

        This batch convenience API is STRICT about failures: a ``failed``
        request (a real executor error, which ``step()`` isolates instead of
        propagating) re-raises here so callers like the bucketed pipeline
        surface the root cause instead of crashing on a None row. A
        ``timed_out`` request's slot holds None (only reachable when the
        engine has deadlines configured). Use ``submit``/``step``/``drain``
        directly for per-request fault handling."""
        if rng is not None:
            self._rng = rng
        reqs = [self.submit(p, config) for p in prompts]
        self.run_until_idle()
        failed = [r for r in reqs if r.status == "failed"]
        if failed:
            raise RuntimeError(
                f"{len(failed)} of {len(reqs)} served requests failed; "
                f"first error: {failed[0].error}"
            )
        return [r.result for r in reqs]

    def pending(self) -> bool:
        """True while a call to :meth:`step` has work to do. The bucket
        engine's unit of work is a whole micro-batch, so this is just queue
        depth; the slot engine overrides it to include resident slots (its
        ``step`` legitimately disposes of nothing mid-generation). Drive
        drain loops off this, not off ``step()``'s return value."""
        return bool(self._queue)

    def run_until_idle(self) -> int:
        """Drain the whole queue; returns the number of requests disposed of
        (completed + timed out + failed)."""
        served = 0
        while True:
            n = self.step()
            if n == 0:
                return served
            served += n

    def drain(self) -> int:
        """Graceful shutdown: stop accepting submissions, run every queued
        request to completion, return the number disposed of. Idempotent —
        a second call is a no-op returning 0."""
        self._accepting = False
        return self.run_until_idle()

    # -- streaming -----------------------------------------------------------
    def _emit_token(self, req: ServeRequest, index: int, token: int) -> None:
        """Deliver one token to the request's incremental sink. A raising
        sink (a torn-down stream consumer) is isolated and counted — the
        request it observes must finish normally."""
        try:
            req.on_token(index, token)
        except Exception:
            self.registry.inc("serving_token_sink_errors_total")

    def cancel(self, request_id: int) -> bool:
        """Withdraw one request — the streaming gateway's client-disconnect
        retirement route (docs/serving.md). A queued request leaves the
        queue and finishes ``cancelled`` (one terminal span, a
        ``serving.cancelled`` event, ``serving_requests_cancelled_total``).
        The bucket engine schedules whole micro-batches, so a request
        already packed into a running batch cannot be interrupted — it
        completes and the caller discards the result; the slot engine
        overrides this with token-granular mid-generation cancellation.
        Returns True when the request was found live and cancelled."""
        for i, req in enumerate(self._queue):
            if req.request_id == request_id:
                del self._queue[i]
                if self.tracer is not None:
                    self.tracer.event(
                        "serving.cancelled", trace_id=req.trace_id,
                        stage="queued", tokens_emitted=0,
                    )
                self._finish(req, "cancelled")
                return True
        return False

    def evacuate(self, cause: str = "scale_down") -> int:
        """Withdraw EVERY live request at once — the fleet scale-down path
        (docs/serving.md "Elasticity"): the router has already failed this
        engine's work over to survivors, so the local copies are stale and
        must be retired immediately rather than decoded to completion.
        Each finishes ``cancelled`` with one terminal span (the ``cause``
        attribute separates a scale-down evacuation from a client
        disconnect in the events stream). The bucket engine only holds
        queued work between steps; the slot engine overrides this to also
        retire residents and return their KV pool pages tagged ``cause``.
        Returns the number of requests evacuated."""
        evacuated = 0
        queued, self._queue = list(self._queue), []
        for req in queued:
            if self.tracer is not None:
                self.tracer.event(
                    "serving.cancelled", trace_id=req.trace_id,
                    stage="queued", tokens_emitted=0, cause=cause,
                )
            self._finish(req, "cancelled", error=f"evacuated ({cause})")
            evacuated += 1
        return evacuated

    # -- fault disposition ---------------------------------------------------
    def _finish(self, req: ServeRequest, status: str, *, error: Optional[str] = None) -> None:
        req.status = status
        req.error = error
        self._tl_event(
            "finished", request_id=req.request_id, status=status,
            tenant=req.tenant, priority=req.priority,
        )
        if status == "ok":
            self.registry.inc("serving_requests_completed_total")
        elif status == "timed_out":
            self.registry.inc("serving_requests_timed_out_total")
        elif status == "cancelled":
            self.registry.inc("serving_requests_cancelled_total")
        elif status == "failed":
            self.registry.inc("serving_requests_failed_total")
        now = self._clock()
        latency_s = now - req.submitted_at
        self.registry.observe("serving_request_latency_ms", latency_s * 1e3)
        if self.tracer is not None:
            # the request's ONE terminal span: submit time -> disposition.
            # The latency was measured on the ENGINE clock; backdate in the
            # tracer's own clock domain so the span duration stays correct
            # even when the two clocks differ (FakeClock engine + wall-clock
            # tracer, or vice versa).
            span = self.tracer.start_span(
                "serving.request", trace_id=req.trace_id,
                start_s=self.tracer.now() - latency_s,
                request_id=req.request_id,
                prompt_len=int(req.prompt.size),
            )
            self.tracer.end_span(
                span, status=status, **({"error": error} if error else {})
            )

    def _apply_request_chaos(self, req: ServeRequest) -> bool:
        """Run the per-request chaos hook (``serving.request``); returns True
        when the fault disposed of the request — an injected error fails it,
        a hang advances the injectable clock and times it out if that burned
        through its deadline. Shared by both engines' schedulers so fault
        semantics cannot drift between them."""
        if self._chaos is None:
            return False
        fault = self._chaos.hit("serving.request", req.request_id)
        if fault is None:
            return False
        if fault.kind == "error":
            self._finish(req, "failed", error=str(fault.make_error()))
            return True
        if fault.kind == "hang":
            # A hung request stalls its slot: advance the injectable clock
            # (FakeClock; a real monotonic clock can't be moved) and re-check
            # the deadline it just burned through.
            advance = getattr(self._clock, "advance", None)
            if advance is not None:
                advance(fault.delay_s)
            if req.deadline_at is not None and self._clock() >= req.deadline_at:
                self._finish(
                    req, "timed_out",
                    error=f"hung for {fault.delay_s}s past its deadline",
                )
                return True
        return False

    def _expire_overdue(self) -> int:
        """Complete every queue entry past its deadline as ``timed_out`` so
        expired requests never occupy a bucket slot."""
        now = self._clock()
        live: List[ServeRequest] = []
        expired = 0
        for req in self._queue:
            if req.deadline_at is not None and now >= req.deadline_at:
                self._finish(
                    req, "timed_out",
                    error=f"deadline exceeded after {now - req.submitted_at:.3f}s in queue",
                )
                expired += 1
            else:
                live.append(req)
        self._queue = live
        return expired

    # -- scheduler ----------------------------------------------------------
    def _pick_prompt_bucket(self, length: int, cfg: GenerationConfig) -> int:
        """Smallest prompt bucket that fits ``length`` AND the model's
        prefix capacity under ``cfg`` (``generate`` rejects plans whose
        nominal prefix ``L - min(L, num_latents)`` exceeds
        ``max_prefix_len``)."""
        max_prefix = self.model.max_prefix_len
        for cap in self.table.prompt_lens:
            if cap < length:
                continue
            if cap - min(cap, cfg.num_latents) > max_prefix:
                continue
            return cap
        raise ValueError(
            f"no feasible prompt bucket for length {length} with "
            f"num_latents={cfg.num_latents}: buckets {self.table.prompt_lens} "
            f"must satisfy len <= {self.model.max_seq_len} and "
            f"len - num_latents <= max_prefix_len={max_prefix}"
        )

    def step(self) -> int:
        """Run ONE micro-batch: the queue head plus following requests with
        the same config, packed FIFO into the next bucket slot. Returns the
        number of requests disposed of — completed, timed out, or failed
        (0 = queue empty).

        Fault isolation: requests past their deadline finish ``timed_out``
        before packing; a chaos-injected per-request fault finishes only
        that request ``failed``; an exception out of the executor (real or
        injected) fails every request in this micro-batch but leaves the
        rest of the queue intact.
        """
        return self._run_pass(self._step_pass)

    def _step_pass(self) -> int:
        disposed = self._expire_overdue()
        if not self._queue:
            return disposed
        cfg = self._queue[0].config
        picked: List[ServeRequest] = []
        rest: List[ServeRequest] = []
        for req in self._queue:
            if len(picked) >= self.table.batch_sizes[-1] or req.config != cfg:
                rest.append(req)
                continue
            if self._apply_request_chaos(req):
                disposed += 1
                continue
            picked.append(req)
        self._queue = rest
        if not picked:
            return disposed

        b = self.table.batch_bucket(len(picked))
        length = self._pick_prompt_bucket(max(r.prompt.size for r in picked), cfg)
        assemble_t0 = self._clock()
        ids = np.full((b, length), cfg.pad_token_id, np.int32)
        # Dummy filler rows claim zero pads — a full-width "prompt" of pad-id
        # tokens whose output is computed and dropped. Zero, not length-1:
        # ``generate`` enables the cached prefix-growth phase only when EVERY
        # row's pad count fits the nominal prefix (``phase2_ok``), so a
        # max-padded filler would silently demote an underfilled micro-batch
        # to the slow windowed-recompute plan. Attention is per-row; filler
        # content never touches real rows.
        pad_count = np.zeros((b,), np.int32)
        now = self._clock()
        for i, req in enumerate(picked):
            ids[i, length - req.prompt.size:] = req.prompt
            pad_count[i] = length - req.prompt.size
            req.started_at = now
            self.registry.observe(
                "serving_queue_wait_ms", (now - req.submitted_at) * 1e3
            )

        self._rng, key = jax.random.split(self._rng)
        batch_index = int(self.registry.inc("serving_batches_total"))
        assemble_ms = (self._clock() - assemble_t0) * 1e3
        self.registry.observe("serving_batch_assembly_ms", assemble_ms)
        self._tl_mark("assemble_ms", assemble_ms)
        if self.timeline is not None:
            for req in picked:
                self._tl_event(
                    "admitted", request_id=req.request_id,
                    tenant=req.tenant, priority=req.priority,
                    bucket=[b, length],
                )
        batch_span = None
        if self.tracer is not None:
            batch_span = self.tracer.start_span(
                "serving.batch", batch_index=batch_index, size=len(picked),
                bucket=[b, length], assemble_ms=round(assemble_ms, 3),
                trace_ids=[r.trace_id for r in picked],
            )
        execute_t0 = self._clock()
        try:
            batch_fault = self._chaos.hit("serving.batch") if self._chaos else None
            if batch_fault is not None and batch_fault.kind == "error":
                raise batch_fault.make_error()
            with self._device_capture(step=batch_index):
                out = np.asarray(
                    generate(
                        self.model, self.params, jnp.asarray(ids), cfg,
                        rng=key, prompt_pad_count=jnp.asarray(pad_count),
                        decode_strategy=self.decode_strategy,
                    )
                )
        except Exception as e:
            # Executor failure: this micro-batch fails, the queue survives.
            self.registry.observe(
                "serving_device_execute_ms", (self._clock() - execute_t0) * 1e3
            )
            if batch_span is not None:
                self.tracer.end_span(
                    batch_span, status="failed", error=f"{type(e).__name__}: {e}"
                )
            for req in picked:
                self._finish(req, "failed", error=f"{type(e).__name__}: {e}")
            return disposed + len(picked)
        # np.asarray above materialized the result, so this is device time
        # plus dispatch — the per-batch execute phase of the trace.
        execute_ms = (self._clock() - execute_t0) * 1e3
        self.registry.observe("serving_device_execute_ms", execute_ms)
        self._tl_mark("execute_ms", execute_ms)
        if self.profiler_trigger is not None:
            self.profiler_trigger.observe(execute_ms)
        if batch_span is not None:
            self.tracer.end_span(batch_span, execute_ms=round(execute_ms, 3))
        # Per-request token-latency accounting (docs/observability.md): the
        # bucket engine is batch-granular — every token of the micro-batch
        # materializes at the np.asarray fence above — so TTFT is submit →
        # batch completion and inter-token latency is the amortized device
        # time per generated token, ONE sample per request (a per-token
        # observation would just repeat the same amortized value). The slot
        # engine records both per real token step.
        done_at = self._clock()
        itl_ms = execute_ms / max(1, cfg.max_new_tokens)
        for i, req in enumerate(picked):
            req.result = out[i]
            if req.on_token is not None:
                # batch-granular streaming: the whole row materialized at
                # the fence above, so the sink gets every real token now —
                # the row up to and including the first EOS (pad after EOS
                # is filler, never a generated token)
                toks = out[i].tolist()
                eos = cfg.eos_token_id
                if eos is not None and eos in toks:
                    toks = toks[: toks.index(eos) + 1]
                for idx, t in enumerate(toks):
                    self._emit_token(req, idx, int(t))
            ttft_ms = (done_at - req.ttft_from_s) * 1e3
            self._observe_token_latency("serving_ttft_ms", ttft_ms)
            self._observe_token_latency("serving_inter_token_ms", itl_ms)
            if self.timeline is not None:
                self._tl_event(
                    "tokens", request_id=req.request_id, first=True,
                    ttft_ms=round(ttft_ms, 3), itl_ms=round(itl_ms, 3),
                    batch_granular=True,
                )
            if self.tracer is not None:
                self.tracer.event(
                    "serving.first_token", trace_id=req.trace_id,
                    ttft_ms=round(ttft_ms, 3),
                    inter_token_ms=round(itl_ms, 3), batch_granular=True,
                )
            self._finish(req, "ok")
        self.registry.inc(
            "serving_tokens_generated_total", len(picked) * cfg.max_new_tokens
        )
        self.registry.inc(
            "serving_prompt_tokens_real_total",
            sum(int(r.prompt.size) for r in picked),
        )
        self.registry.inc("serving_prompt_tokens_padded_total", b * length)
        # decode-row accounting, comparable with the slot engine's: every
        # row of every decode step, split real vs batch-padding filler —
        # the padding-waste ratio the serve bench A/B reports
        self.registry.inc("serving_decode_rows_total", b * cfg.max_new_tokens)
        self.registry.inc(
            "serving_decode_rows_padded_total",
            (b - len(picked)) * cfg.max_new_tokens,
        )
        return disposed + len(picked)

    # -- ahead-of-time warmup ----------------------------------------------
    def warmup(self, config: Optional[GenerationConfig] = None) -> int:
        """Compile every feasible bucket before accepting traffic; returns
        the number of fresh executor compiles.

        Each ``(batch, prompt_len)`` cell is driven through ``generate``
        with BOTH phase plans it can map to at serve time: zero left pads
        (prefix-growth cache eligible) and maximal left pads (pad overflow
        beyond the nominal prefix disables phase 2, a different static
        plan). Cells infeasible under ``config`` (prefix capacity) are
        skipped — serve-time scheduling skips them identically."""
        cfg = config or self.config
        before = executor_cache_stats()["misses"]
        if self.decode_strategy == "auto":
            # measure the boundary winner ONCE before compiling the grid, so
            # every warmed executor is the plan steady-state traffic uses
            # (the probe's two small generation compiles count in the return)
            from perceiver_io_tpu.inference import decode_strategy as _strategy

            _strategy.autotune_boundary(self.model, self.params)
        max_prefix = self.model.max_prefix_len
        for b, length in self.table.grid():
            nominal_prefix = length - min(length, cfg.num_latents)
            if nominal_prefix > max_prefix:
                continue
            pad_variants = {0}
            if length - 1 > nominal_prefix:
                pad_variants.add(length - 1)
            for pad in pad_variants:
                ids = jnp.full((b, length), cfg.pad_token_id, jnp.int32)
                pad_count = jnp.full((b,), pad, jnp.int32)
                generate(self.model, self.params, ids, cfg,
                         rng=jax.random.PRNGKey(0), prompt_pad_count=pad_count,
                         decode_strategy=self.decode_strategy)
        return executor_cache_stats()["misses"] - before

    # -- observability ------------------------------------------------------
    def stats(self) -> dict:
        """Serving counters since engine construction, read back from the
        metrics registry (the one source of truth). Every counter appears
        under its canonical registry name (``serving_*_total``) AND its
        legacy short key (``completed``, ``shed``, ... — deprecation
        aliases; see ``STAT_ALIASES`` / docs/observability.md).

        ``compiles`` is the executor-cache miss delta — the engine assumes
        it owns the process's generation traffic over its lifetime (true for
        the CLI, bench probe, and tests)."""
        cache_now = executor_cache_stats()
        # clamp at 0: reset_executor_caches() mid-lifetime rewinds the global
        # counters below this engine's construction-time snapshot
        cache = {k: max(0, cache_now[k] - self._cache0[k]) for k in cache_now}
        reg = self.registry
        # one consistent read, not 16 separate ones: a scrape thread polling
        # stats() mid-step must still see alias == canonical for every pair
        # (counters(), not snapshot() — no histogram sorting under the lock)
        counts = reg.counters()
        counters = {
            alias: int(counts.get(name, 0)) for name, alias in STAT_ALIASES.items()
        }
        counters.update(
            {name: int(counts.get(name, 0)) for name in STAT_ALIASES}
        )
        real = counts.get("serving_prompt_tokens_real_total", 0)
        padded = counts.get("serving_prompt_tokens_padded_total", 0)
        # compile-ledger rollup (docs/observability.md): the full per-key
        # compile/memory table stays on default_ledger().snapshot() — the
        # serve CLI embeds it in serve_stats; stats() carries the summary
        # so a poller sees compile cost and retrace reasons without the
        # per-record bulk
        from perceiver_io_tpu.observability import default_ledger

        ledger = default_ledger().rollup()
        out = {
            **counters,
            "queued": len(self._queue),
            "compiles": cache["misses"],
            "executor_cache": cache,
            "compile_ledger": ledger,
            # registry.percentile is the LOCKED accessor — stats() may be
            # polled from a scrape thread while the owner thread observes
            "queue_wait_ms": {
                "p50": _round_ms(reg.percentile("serving_queue_wait_ms", 50.0)),
                "p95": _round_ms(reg.percentile("serving_queue_wait_ms", 95.0)),
            },
            # the SLO-facing token latencies (docs/observability.md): TTFT
            # and inter-token latency, per-token on the slot engine,
            # batch-amortized on this one
            "ttft_ms": {
                "p50": _round_ms(reg.percentile("serving_ttft_ms", 50.0)),
                "p95": _round_ms(reg.percentile("serving_ttft_ms", 95.0)),
            },
            "inter_token_ms": {
                "p50": _round_ms(reg.percentile("serving_inter_token_ms", 50.0)),
                "p95": _round_ms(reg.percentile("serving_inter_token_ms", 95.0)),
            },
            "prompt_padding_efficiency": round(real / max(1, padded), 4),
            "bucket_grid": {
                "prompt_lens": list(self.table.prompt_lens),
                "batch_sizes": list(self.table.batch_sizes),
            },
        }
        if self.timeline is not None:
            # scheduler-timeline rollup (docs/observability.md "Scheduler
            # timeline & post-mortems"): pass/event totals over the ring
            out["timeline"] = self.timeline.summary()
        return out

    def health(self) -> dict:
        """Readiness snapshot for a serving front end: ``ready`` means the
        engine accepts a submission right now (not draining, queue below
        ``max_queue``). Cheap — no device work, no cache reads."""
        now = self._clock()
        depth = len(self._queue)
        reg = self.registry
        return {
            "ready": self._accepting
            and (self.max_queue is None or depth < self.max_queue),
            "accepting": self._accepting,
            "queue_depth": depth,
            "max_queue": self.max_queue,
            "oldest_wait_ms": round(
                max((now - r.submitted_at) for r in self._queue) * 1e3, 3
            ) if self._queue else 0.0,
            "completed": int(reg.counter("serving_requests_completed_total")),
            "shed": int(reg.counter("serving_requests_shed_total")),
            "timed_out": int(reg.counter("serving_requests_timed_out_total")),
            "failed": int(reg.counter("serving_requests_failed_total")),
            "cancelled": int(reg.counter("serving_requests_cancelled_total")),
        }
