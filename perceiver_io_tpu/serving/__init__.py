"""Serving layer (docs/serving.md): two engines over the compiled
generation executors.

- :class:`ServingEngine` — shape bucketing + continuous micro-batching at
  *generation* granularity: ragged traffic lands on a small pre-compilable
  executor grid instead of retracing per exact shape.
- :class:`SlotServingEngine` — token-granular continuous batching over a
  persistent fixed-shape multi-slot decode state: per-token scheduling,
  immediate EOS/deadline retirement, mid-generation slot refill, one
  decode executor for all traffic.

Both are hardened for load (docs/reliability.md): bounded queue with
:class:`QueueFull` backpressure, per-request deadlines, per-request error
isolation, graceful ``drain()``, and a ``health()`` readiness snapshot.
"""
from perceiver_io_tpu.reliability import QueueFull
from perceiver_io_tpu.serving.buckets import BucketTable
from perceiver_io_tpu.serving.engine import ServeRequest, ServingEngine
from perceiver_io_tpu.serving.slots import SlotServingEngine

__all__ = [
    "BucketTable",
    "QueueFull",
    "ServeRequest",
    "ServingEngine",
    "SlotServingEngine",
]
