"""Serving layer (docs/serving.md): two engines over the compiled
generation executors.

- :class:`ServingEngine` — shape bucketing + continuous micro-batching at
  *generation* granularity: ragged traffic lands on a small pre-compilable
  executor grid instead of retracing per exact shape.
- :class:`SlotServingEngine` — token-granular continuous batching over a
  persistent fixed-shape multi-slot decode state: per-token scheduling,
  immediate EOS/deadline retirement, mid-generation slot refill, one
  decode executor for all traffic.
- :class:`FleetRouter` — N supervised engine replicas behind one
  router: load-aware dispatch, per-replica health/circuit breakers,
  crash/hang failure detection, and exactly-once failover that replays
  in-flight requests from their prompts (token-identical under greedy
  decoding).
- :class:`FleetAutoscaler` — the SLO-driven elasticity closed loop
  (docs/serving.md "Elasticity"): burn-rate breaches and queue pressure
  drive the fleet's replica count between min/max bounds through an
  ordered degradation ladder, with zero-downtime scale-down (in-flight
  work replays exactly-once on survivors, pool pages return tagged
  ``scale_down``).
- :class:`StreamingGateway` — the stdlib-only asyncio HTTP/1.1 front
  end: per-token SSE / JSON-lines streaming out of ``step()``,
  socket-anchored TTFT, and client-disconnect cancellation that frees
  slots and KV pool pages mid-generation.
- :class:`ServingMeshSpec` — the sharded serving runtime (docs/serving.md
  "Sharded serving"): the slot engine's executors compile over a
  ``data`` × ``model`` device mesh (slots sharded along data, attention
  heads + KV caches — dense and the paged pool — along model), turning
  "N replicas" into "N replicas × M-device replicas"
  (:func:`~perceiver_io_tpu.serving.sharding.fleet_mesh_specs` hands each
  replica a disjoint device subset).

All are hardened for load (docs/reliability.md): bounded queue with
:class:`QueueFull` backpressure, per-request deadlines, per-request error
isolation, graceful ``drain()``, and a ``health()`` readiness snapshot
sharing one schema (:data:`~perceiver_io_tpu.serving.engine.HEALTH_KEYS`).
"""
from perceiver_io_tpu.reliability import QueueFull
from perceiver_io_tpu.serving.autoscaler import LADDER, FleetAutoscaler
from perceiver_io_tpu.serving.buckets import BucketTable
from perceiver_io_tpu.serving.engine import HEALTH_KEYS, ServeRequest, ServingEngine
from perceiver_io_tpu.serving.fleet import (
    CircuitBreaker,
    FleetRequest,
    FleetRouter,
    Replica,
)
from perceiver_io_tpu.serving.gateway import StreamingGateway
from perceiver_io_tpu.serving.kv_pool import KVPagePool, PoolExhausted, PrefixBlockIndex
from perceiver_io_tpu.serving.sharding import (
    MeshGroupAllocator,
    ServingMeshSpec,
    ServingSharding,
    fleet_mesh_specs,
)
from perceiver_io_tpu.serving.slots import SlotServingEngine

__all__ = [
    "BucketTable",
    "CircuitBreaker",
    "FleetAutoscaler",
    "FleetRequest",
    "FleetRouter",
    "LADDER",
    "HEALTH_KEYS",
    "KVPagePool",
    "PrefixBlockIndex",
    "PoolExhausted",
    "QueueFull",
    "Replica",
    "ServeRequest",
    "ServingEngine",
    "MeshGroupAllocator",
    "ServingMeshSpec",
    "ServingSharding",
    "SlotServingEngine",
    "StreamingGateway",
    "fleet_mesh_specs",
]
