"""Serving layer — shape bucketing + continuous micro-batching over the
compiled generation executors (docs/serving.md). The first load-path layer
between "a jitted ``generate()``" and "a service": ragged traffic lands on
a small pre-compilable executor grid instead of retracing per exact shape.

Hardened for load (docs/reliability.md): bounded queue with
:class:`QueueFull` backpressure, per-request deadlines, per-request error
isolation, graceful ``drain()``, and a ``health()`` readiness snapshot.
"""
from perceiver_io_tpu.reliability import QueueFull
from perceiver_io_tpu.serving.buckets import BucketTable
from perceiver_io_tpu.serving.engine import ServeRequest, ServingEngine

__all__ = ["BucketTable", "QueueFull", "ServeRequest", "ServingEngine"]
