"""Supervised serving fleet: replica health, failover, and exactly-once
request recovery.

One engine is one fault domain: a poisoned executor, a hung device
dispatch, or a crashed replica loses every in-flight request with no
recovery path. Production serving stacks (PAPERS.md: the Gemma-on-TPU
serving comparison) treat replica supervision and failover as table
stakes; this module is that layer — a :class:`FleetRouter` that owns N
engine replicas behind a common :class:`Replica` wrapper, dispatches by
load-aware policy, and supervises them:

- **Failure detection** — every ``Replica.step`` is supervised: a raised
  exception is a **crash** (the replica is rebuilt from its factory — the
  process-restart model; warm executor caches make this cheap), a step
  whose wall time exceeds ``step_timeout_s`` on the injectable clock is
  **hung** (the replica is presumed dead but may still be computing), and
  both are chaos-scriptable at the ``fleet.replica_step.<r>`` /
  ``fleet.dispatch`` hook sites (``reliability.chaos``) so every drill
  replays bit-identically on CPU. Hang detection is **post-hoc and
  in-line**: the single-threaded router measures a step AFTER it returns,
  so it catches slow-but-returning dispatches (which is also what the
  chaos ``hang`` fault models) — a step that never returns blocks the
  router itself and needs out-of-process supervision (the
  ``longrun --phase-timeout`` watchdog pattern; the async front-end of
  ROADMAP item 3 is the natural home for an off-thread supervisor).
- **Circuit breaker** — per replica, ``closed → open`` after
  ``breaker_threshold`` *consecutive* failures, ``open → half_open`` after
  ``breaker_cooldown_s`` on the shared clock, ``half_open → closed`` on a
  successful probe step (at most one probe request is outstanding while
  half-open) and back to ``open`` on a failed one. An open replica
  receives no dispatches and is not stepped.
- **Exactly-once recovery** — on replica failure every in-flight request
  is re-queued (``fleet_failover_total`` / ``fleet_redispatch_total``)
  and **replayed from its prompt** on a surviving replica, with backoff
  from a :class:`~perceiver_io_tpu.reliability.RetryPolicy` (optionally
  jittered by an injected rng so a redispatch storm spreads out).
  Completion is deduplicated by fleet request id: the first copy to
  finish wins, late duplicates — e.g. a hung-but-alive replica finishing
  its copy after reintegration — are counted
  (``fleet_duplicate_results_total``) and dropped. Greedy decode is
  deterministic (chaos-drilled bit-identical on CPU), so a recovered
  output is **token-identical** to the no-fault run — pinned by
  ``tests/test_fleet.py``.
- **Fleet-level admission** — the per-engine bounded-queue / deadline
  shedding lifts to the whole fleet: ``max_pending`` bounds queued +
  dispatched requests (:class:`~perceiver_io_tpu.reliability.QueueFull`
  past it), ``default_deadline_s`` expires requests that wait too long,
  and infeasible prompts reject at the fleet front door via the engines'
  shared :meth:`~perceiver_io_tpu.serving.engine.ServingEngine.check_feasible`.
- **Graceful operations** — ``drain()`` stops admission and finishes all
  in-flight work; ``rolling_restart()`` cycles replicas one at a time
  (drain one, rebuild it from the factory, reintegrate) while the rest
  keep serving.
- **Elasticity** (docs/serving.md "Elasticity") — the replica set is no
  longer fixed for the life of the process: :meth:`FleetRouter.add_replica`
  spawns a replica from the engine factory (process-global executor
  caches mean it compiles nothing after the first warmup) and
  :meth:`FleetRouter.remove_replica` retires one with ZERO dropped
  in-flight requests — its live dispatches fail over through the
  exactly-once replay path (token-identical under greedy decoding) and
  its engine is evacuated so every KV pool page returns tagged
  ``cause="scale_down"``. Both transitions are chaos-scriptable
  (``fleet.scale_up`` spawn failure / ``fleet.scale_down`` crash
  mid-drain) and driven in production by the
  :class:`~perceiver_io_tpu.serving.autoscaler.FleetAutoscaler` closed
  loop, polled once per :meth:`FleetRouter.step`. All per-replica
  bookkeeping keys by ``replica_id`` (never by position), so replicas
  appearing and disappearing mid-run cannot corrupt attribution.

The router mirrors the engines' request surface — ``submit`` / ``serve``
/ ``step`` / ``pending`` / ``run_until_idle`` / ``drain`` / ``warmup`` /
``stats`` / ``health`` — so the serve CLI (``--serve.replicas``) and any
front end drive a fleet exactly like a single engine. With one replica
and ``failover=False`` the fleet layer is behavior-identical (greedy
outputs and accounting) to driving the engine directly.

Observability (docs/observability.md): ``fleet_replicas_healthy`` gauge,
``fleet_failover_total`` / ``fleet_redispatch_total`` /
``fleet_breaker_open_total`` counters (among others, all declared up
front), a ``fleet_request_latency_ms`` histogram, and one terminal
``fleet.request`` span per submission carrying the completing replica id
— ``obs report`` renders the fleet section from these.

Clock discipline: the router, every breaker, and every replica engine
must share ONE clock (the factories close over it), or deadline handoff
and hang detection mix time bases. Tests pass a
:class:`~perceiver_io_tpu.reliability.FakeClock`; production uses the
default ``time.monotonic``.
"""
from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from perceiver_io_tpu.inference.generate import GenerationConfig
from perceiver_io_tpu.observability import MetricsRegistry, Tracer
from perceiver_io_tpu.reliability import QueueFull, RetryPolicy

#: counters declared at construction so exports show the full fleet schema
#: before the first failure (docs/observability.md)
FLEET_COUNTERS = (
    "fleet_requests_submitted_total",
    "fleet_requests_completed_total",
    "fleet_requests_shed_total",
    "fleet_requests_timed_out_total",
    "fleet_requests_failed_total",
    "fleet_requests_rejected_total",
    "fleet_requests_cancelled_total",
    "fleet_dispatch_total",
    "fleet_failover_total",
    "fleet_redispatch_total",
    "fleet_breaker_open_total",
    "fleet_replica_failures_total",
    "fleet_replica_restarts_total",
    "fleet_duplicate_results_total",
    "fleet_slo_shed_total",
    "fleet_scale_up_total",
    "fleet_scale_down_total",
    "fleet_scale_up_failed_total",
)


@dataclasses.dataclass
class FleetRequest:
    """One fleet-level request: the durable identity that survives replica
    failures. ``status`` is ``queued`` (awaiting dispatch, possibly gated by
    redispatch backoff) or ``dispatched`` (an engine copy is in flight)
    until the terminal disposition: ``ok`` / ``timed_out`` / ``failed``.
    ``replica_id`` is the replica whose copy completed it (None until
    then); ``dispatches`` counts dispatch attempts — 1 for an undisturbed
    request, more after failover."""

    request_id: int
    prompt: np.ndarray  # (len,) int32, unpadded
    config: Optional[GenerationConfig]
    submitted_at: float
    deadline_at: Optional[float] = None
    status: str = "queued"  # queued | dispatched | ok | timed_out | failed
    result: Optional[np.ndarray] = None
    error: Optional[str] = None
    trace_id: Optional[str] = None
    replica_id: Optional[int] = None
    dispatches: int = 0
    not_before: float = 0.0  # redispatch backoff gate, fleet-clock seconds
    #: replica the last failed attempt ran on — the re-dispatch AVOIDS it
    #: when any other replica is available, so a retry never bounces
    #: straight back onto the executor that just failed it
    last_replica_id: Optional[int] = None
    #: TTFT anchor handed through to the engine at dispatch (the HTTP
    #: gateway passes its socket-accept instant); defaults to the fleet's
    #: own ``submitted_at`` — see ``serving.engine.ServeRequest``
    ttft_anchor_s: Optional[float] = None
    #: per-request incremental token sink, forwarded to the engine copy at
    #: every dispatch (failover replays re-fire indices from 0; greedy
    #: determinism makes the replayed prefix identical, so stream
    #: consumers dedupe by index — docs/serving.md "Streaming")
    on_token: Optional[Callable[[int, int], None]] = None
    #: scheduling tier + tenant label, forwarded to the engine copy at
    #: every dispatch (docs/serving.md "Preemption & priorities"): the
    #: fleet dispatches higher tiers first, and a preemption-enabled slot
    #: engine uses them for victim selection + per-tenant page fairness
    priority: int = 0
    tenant: Optional[str] = None

    @property
    def done(self) -> bool:
        return self.status not in ("queued", "dispatched")


class CircuitBreaker:
    """Per-replica circuit breaker: ``closed → open → half_open → closed``.

    ``record_failure`` opens after ``failure_threshold`` *consecutive*
    failures (or instantly from half-open — a failed probe); ``poll``
    advances ``open → half_open`` once ``cooldown_s`` has elapsed on the
    injectable clock; ``record_success`` resets the failure run and closes
    a half-open breaker. Pure host-side state on an injectable clock, so
    every transition is deterministic under ``reliability.FakeClock``.
    """

    def __init__(self, *, failure_threshold: int = 3, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self.state = "closed"  # closed | open | half_open
        self.consecutive_failures = 0
        self.opened_at: Optional[float] = None
        self.opened_total = 0

    def record_failure(self) -> bool:
        """Count one failure; returns True when THIS call opened the
        breaker (a half-open probe failure re-opens it and counts again)."""
        self.consecutive_failures += 1
        if self.state == "half_open" or (
            self.state == "closed"
            and self.consecutive_failures >= self.failure_threshold
        ):
            self.state = "open"
            self.opened_at = self._clock()
            self.opened_total += 1
            return True
        return False

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state == "half_open":
            self.state = "closed"
            self.opened_at = None

    def poll(self) -> str:
        """Current state, advancing ``open → half_open`` when the cooldown
        has elapsed — the reintegration-probe gate."""
        if (
            self.state == "open"
            and self._clock() - self.opened_at >= self.cooldown_s
        ):
            self.state = "half_open"
        return self.state


class Replica:
    """One supervised engine replica: the engine (rebuilt from ``factory``
    on crash), its circuit breaker, the fleet-request-id → engine-handle
    map, and the chaos-scriptable supervised ``step``.

    Works over either engine — :class:`~..engine.ServingEngine` or
    :class:`~..slots.SlotServingEngine` — through the shared request
    surface and health schema (``serving.engine.HEALTH_KEYS``).
    """

    def __init__(self, factory: Callable[[], object], replica_id: int, *,
                 clock: Callable[[], float] = time.monotonic, chaos=None,
                 breaker: Optional[CircuitBreaker] = None,
                 latency_mirror: Optional[Callable[[str, float], None]] = None):
        self.factory = factory
        self.replica_id = int(replica_id)
        self._clock = clock
        self._chaos = chaos
        #: installed as the engine's ``latency_sink`` (rebuilds included):
        #: every per-token TTFT / inter-token observation the replica's
        #: engine records on its PRIVATE registry is mirrored here too, so
        #: the router gets fleet-scope ``serving_ttft_ms`` /
        #: ``serving_inter_token_ms`` percentiles (and the SLO monitor its
        #: samples) without collapsing the per-replica attribution
        self.latency_mirror = latency_mirror
        self.breaker = breaker if breaker is not None else CircuitBreaker(clock=clock)
        self.engine = factory()
        self._install_latency_mirror()
        #: fleet request id -> engine ServeRequest handle. Entries persist
        #: across a HUNG failover (the slow copy may still complete — the
        #: dedupe path) and are cleared by :meth:`restart` (a crashed
        #: process loses its work).
        self.handles: Dict[int, object] = {}
        self.restarts = 0
        self.draining = False
        self.last_step_wall_s = 0.0

    @property
    def chaos_site(self) -> str:
        return f"fleet.replica_step.{self.replica_id}"

    def step(self) -> int:
        """One supervised engine step. The ``fleet.replica_step.<r>`` chaos
        hook fires first: ``error`` raises (a scripted crash — the router
        catches it), ``hang`` advances the shared injectable clock by
        ``delay_s`` so the step's wall time trips the router's
        ``step_timeout_s`` (resident deadlines burn through the stall too,
        exactly as they would on a real wedged replica)."""
        t0 = self._clock()
        if self._chaos is not None:
            fault = self._chaos.hit(self.chaos_site)
            if fault is not None:
                if fault.kind == "error":
                    raise fault.make_error()
                if fault.kind == "hang":
                    advance = getattr(self._clock, "advance", None)
                    if advance is not None:
                        advance(fault.delay_s)
        disposed = self.engine.step()
        self.last_step_wall_s = self._clock() - t0
        return disposed

    def _install_latency_mirror(self) -> None:
        if self.latency_mirror is not None and hasattr(self.engine, "latency_sink"):
            self.engine.latency_sink = self.latency_mirror

    def restart(self) -> None:
        """Rebuild the engine from the factory — the crashed-process model:
        queued and resident engine work is lost (the router already failed
        it over), the executor caches are process-global so the fresh
        engine compiles nothing new. A sharded replica's mesh-group claim
        is released BEFORE the factory runs, so the rebuild reclaims the
        CRASHED group instead of aliasing a live replica's devices
        (``serving/sharding.py`` ``MeshGroupAllocator``); the crashed
        engine itself stays installed until the factory returns, so a
        spawn failure leaves the replica degraded-but-present, never
        holding ``engine=None``."""
        sharding = getattr(self.engine, "sharding", None)
        release = getattr(sharding, "release", None)
        if release is not None:
            release()
        self.engine = self.factory()
        self._install_latency_mirror()
        self.handles.clear()
        self.restarts += 1

    def collect(self) -> List[Tuple[int, object]]:
        """Pop and return every finished ``(fleet_request_id, handle)``."""
        done = [(fid, h) for fid, h in self.handles.items() if h.done]
        for fid, _ in done:
            del self.handles[fid]
        return done

    def health(self) -> dict:
        """The engine's health snapshot (shared schema,
        ``serving.engine.HEALTH_KEYS``) plus the supervision fields the
        router adds — a strict superset, so anything that can probe an
        engine can probe a replica."""
        out = self.engine.health()
        out.update({
            "replica_id": self.replica_id,
            "breaker": self.breaker.state,
            "consecutive_failures": self.breaker.consecutive_failures,
            "in_flight": len(self.handles),
            "restarts": self.restarts,
            "draining": self.draining,
        })
        return out


class FleetRouter:
    """Load-aware router + supervisor over N engine replicas (module
    docstring for the full design).

    :param engine_factories: one zero-arg engine factory per replica
        (``[make_engine] * n`` for a homogeneous fleet). Factories are
        re-invoked to rebuild crashed replicas, must build engines sharing
        the fleet ``clock``, and should build engines WITHOUT their own
        ``max_queue``/``default_deadline_s`` — admission is fleet-level.
        For a SHARDED fleet (docs/serving.md "Sharded serving") each
        factory owns a disjoint device subset: build them over
        :func:`~perceiver_io_tpu.serving.sharding.fleet_mesh_specs` (fixed
        per-replica offsets) or ``acquire()`` from a
        :class:`~perceiver_io_tpu.serving.sharding.MeshGroupAllocator`
        inside one shared factory (what the serve CLI does), so crash
        rebuilds and autoscaler spawns keep landing on disjoint groups —
        the N replicas × M-device replicas scaling shape.
    :param clock: the fleet's (and every breaker's) monotonic time source.
    :param chaos: optional :class:`~perceiver_io_tpu.reliability.ChaosRegistry`
        consulted at ``fleet.dispatch`` / ``fleet.replica_step.<r>``.
    :param registry: metrics registry for the ``fleet_*`` families;
        defaults to a private one.
    :param tracer: optional span tracer — one terminal ``fleet.request``
        span per submission (replica id attached), ``fleet.dispatch`` /
        ``fleet.replica_failed`` / ``fleet.breaker_*`` events.
    :param max_pending: fleet-wide bound on queued + dispatched requests;
        ``submit`` past it sheds with :class:`QueueFull`.
    :param default_deadline_s: fleet-level deadline; the remaining budget
        is handed to the engine at dispatch time, so replicas enforce it
        token-granularly.
    :param step_timeout_s: wall-time deadline on one supervised replica
        step; a slower step marks the replica hung. None disables hang
        detection (CPU-fallback default — a cold compile inside the first
        step would otherwise trip it).
    :param failover: re-dispatch a failed replica's in-flight requests
        (True) or fail them terminally (False — the single-engine
        behavior).
    :param breaker_threshold / breaker_cooldown_s: circuit-breaker knobs,
        applied per replica.
    :param redispatch_policy: backoff between a request's dispatch
        attempts; its ``max_retries`` bounds failovers per request. The
        default retries 3 times immediately; set ``jitter`` + the policy's
        base to spread a redispatch storm (``redispatch_seed`` feeds the
        deterministic rng).
    :param slo_monitor: optional
        :class:`~perceiver_io_tpu.observability.slo.SLOMonitor` — the
        telemetry-driven admission loop (docs/observability.md). The
        router feeds it: every replica's per-token TTFT / inter-token
        observations (via the latency mirror) and the fleet's terminal
        dispositions (via ``watch_counters`` over the fleet registry), and
        polls it once per :meth:`step`. While the monitor reports a
        breach, admission TIGHTENS deterministically: the effective
        ``max_pending`` and default deadline scale by ``slo_shed_factor``,
        so sustained burn sheds load at the front door instead of letting
        the queue push latency further past target. Extra sheds caused by
        the tightened bound are counted ``fleet_slo_shed_total`` (they
        also count in the ordinary shed counter).
    :param slo_shed_factor: the tightening multiplier in ``(0, 1]``.
    """

    def __init__(self, engine_factories: Sequence[Callable[[], object]], *,
                 clock: Callable[[], float] = time.monotonic,
                 chaos=None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 max_pending: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 step_timeout_s: Optional[float] = None,
                 failover: bool = True,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 redispatch_policy: Optional[RetryPolicy] = None,
                 redispatch_seed: int = 0,
                 slo_monitor=None,
                 slo_shed_factor: float = 0.5,
                 flight_recorder=None):
        factories = list(engine_factories)
        if not factories:
            raise ValueError("a fleet needs at least one engine factory")
        if max_pending is not None and max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        if step_timeout_s is not None and step_timeout_s <= 0:
            raise ValueError(f"step_timeout_s must be > 0, got {step_timeout_s}")
        self._clock = clock
        self._chaos = chaos
        self.registry = registry if registry is not None else MetricsRegistry(clock=clock)
        self.tracer = tracer
        self.max_pending = max_pending
        self.default_deadline_s = default_deadline_s
        self.step_timeout_s = step_timeout_s
        self.failover = bool(failover)
        self.redispatch_policy = (
            redispatch_policy if redispatch_policy is not None
            else RetryPolicy(max_retries=3, backoff_base_s=0.0)
        )
        if not 0.0 < slo_shed_factor <= 1.0:
            raise ValueError(
                f"slo_shed_factor must be in (0, 1], got {slo_shed_factor}"
            )
        self.slo_monitor = slo_monitor
        self.slo_shed_factor = float(slo_shed_factor)
        #: optional incident
        #: :class:`~perceiver_io_tpu.observability.FlightRecorder`
        #: (docs/observability.md "Flight recorder & incident bundles"):
        #: a replica failure or a breaker opening dumps a bounded bundle
        #: with the victims' trace ids attached — the moments the span
        #: firehose alone cannot reconstruct after sampling
        self.flight_recorder = flight_recorder
        self._rng = random.Random(redispatch_seed)
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_cooldown_s = float(breaker_cooldown_s)
        #: the factory scale-up spawns from when none is passed explicitly
        #: (a homogeneous fleet's one factory)
        self._default_factory = factories[0]
        #: replicas keyed by replica_id — NEVER by list position: ids are
        #: handed out monotonically and survive removals, so per-replica
        #: bookkeeping (dispatch maps, completion attribution, chaos sites)
        #: stays correct while the autoscaler adds/retires replicas mid-run
        self._replicas: Dict[int, Replica] = {}
        self._next_replica_id = 0
        #: optional :class:`~perceiver_io_tpu.serving.autoscaler.FleetAutoscaler`
        #: polled once per :meth:`step` (the autoscaler's ctor installs it)
        self.autoscaler = None
        for f in factories:
            self._spawn_replica(f)
        if slo_monitor is not None:
            # error-rate dimension: fed from the fleet's own disposition
            # counters, diffed per poll — the router never sees engine
            # tokens, but it IS the one source of terminal fleet states
            slo_monitor.watch_counters(self.registry.counters, prefix="fleet")
        self._queue: List[FleetRequest] = []
        self._dispatched: Dict[int, FleetRequest] = {}
        #: every non-terminal request (queued OR dispatched), by id — the
        #: dedupe lookup: a completed engine copy must find its fleet
        #: request even while it sits re-queued behind a redispatch
        #: backoff gate, or a first-copy-wins completion would be dropped
        #: as a duplicate and replayed for nothing
        self._inflight: Dict[int, FleetRequest] = {}
        self._next_id = 0
        self._accepting = True
        self._last_step_activity = False
        self._completed_by_replica: Dict[int, int] = {
            r.replica_id: 0 for r in self._replicas.values()
        }
        self.registry.declare_counters(*FLEET_COUNTERS)
        self._update_gauges()

    @property
    def replicas(self) -> List[Replica]:
        """Live replicas in ``replica_id`` order (ids are monotonic, so
        this is also spawn order)."""
        return [self._replicas[rid] for rid in sorted(self._replicas)]

    def _spawn_replica(self, factory: Callable[[], object]) -> Replica:
        """Build one replica on the next monotonic id (ids are never
        reused: a chaos script or span keyed ``fleet.replica_step.<r>``
        must stay unambiguous across scale churn)."""
        rid = self._next_replica_id
        self._next_replica_id += 1
        replica = Replica(
            factory, rid, clock=self._clock, chaos=self._chaos,
            breaker=CircuitBreaker(
                failure_threshold=self._breaker_threshold,
                cooldown_s=self._breaker_cooldown_s, clock=self._clock,
            ),
            latency_mirror=self._mirror_token_latency,
        )
        self._replicas[rid] = replica
        return replica

    @property
    def last_step_made_progress(self) -> bool:
        """False when the most recent :meth:`step` found nothing steppable
        (every replica open/idle) — drive loops use it to yield instead of
        hot-spinning on breaker cooldowns (the serve CLI does)."""
        return self._last_step_activity

    def _mirror_token_latency(self, name: str, value_ms: float) -> None:
        """Every replica engine's ``latency_sink``: fleet-scope TTFT / ITL
        histograms on the router registry, plus the SLO monitor's latency
        dimensions (docs/observability.md — engine, replica, and fleet
        scope are three registries observing the same samples)."""
        self.registry.observe(name, value_ms)
        if self.slo_monitor is not None:
            self.slo_monitor.sink(name, value_ms)

    def _effective_admission(self) -> Tuple[Optional[int], Optional[float]]:
        """``(max_pending, default_deadline_s)`` as currently enforced:
        the configured bounds, scaled by ``slo_shed_factor`` while the SLO
        monitor reports a breach — telemetry-driven shedding, deterministic
        because the monitor's windows run on the injectable clock."""
        limit, deadline = self.max_pending, self.default_deadline_s
        if self.slo_monitor is not None and self.slo_monitor.breached:
            if limit is not None:
                limit = max(1, int(limit * self.slo_shed_factor))
            if deadline is not None:
                deadline = deadline * self.slo_shed_factor
        return limit, deadline

    # -- queue front --------------------------------------------------------
    def submit(self, prompt, config: Optional[GenerationConfig] = None,
               *, deadline_s: Optional[float] = None,
               ttft_anchor_s: Optional[float] = None,
               on_token: Optional[Callable[[int, int], None]] = None,
               priority: int = 0, tenant: Optional[str] = None
               ) -> FleetRequest:
        """Enqueue one prompt fleet-wide; returns its durable handle.

        Mirrors the engine contract: ``ValueError`` for prompts no replica
        could ever serve (validated via the engines' shared
        ``check_feasible``, so slot-engine scope limits apply fleet-wide),
        :class:`QueueFull` past ``max_pending`` — both carry a
        ``trace_id`` and a terminal span, like the engines' rejections.
        While the SLO monitor reports a sustained burn, the effective
        ``max_pending`` and default deadline are tightened by
        ``slo_shed_factor`` (:meth:`_effective_admission`).
        ``ttft_anchor_s`` / ``on_token`` / ``priority`` / ``tenant`` are
        handed to the engine copy at every dispatch (:class:`FleetRequest`);
        higher-priority requests dispatch first, and a preemption-enabled
        slot engine uses the tier + tenant for victim selection
        (docs/serving.md "Preemption & priorities").
        """
        if not self._accepting:
            raise RuntimeError("fleet is draining; new submissions rejected")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        try:
            self.replicas[0].engine.check_feasible(prompt, config)
        except ValueError as e:
            self.registry.inc("fleet_requests_rejected_total")
            e.trace_id = self._terminal_event("rejected", error=str(e))
            raise
        max_pending, default_deadline_s = self._effective_admission()
        in_flight = len(self._queue) + len(self._dispatched)
        if max_pending is not None and in_flight >= max_pending:
            # a shed is attributed to the SLO tightening only when the
            # CONFIGURED bound would have admitted it — genuine overload
            # sheds during a breach stay ordinary sheds (and keep feeding
            # the monitor's error dimension, which excludes slo_shed)
            tightened = (
                max_pending != self.max_pending
                and in_flight < self.max_pending
            )
            self.registry.inc("fleet_requests_shed_total")
            if tightened:
                self.registry.inc("fleet_slo_shed_total")
            exc = QueueFull(
                f"fleet has {in_flight} requests in flight, at max_pending="
                f"{max_pending}"
                + (f" (tightened from {self.max_pending} by SLO burn)"
                   if tightened else "")
                + "; request shed — drain with step() or retry after backoff"
            )
            exc.trace_id = self._terminal_event(
                "shed", in_flight=in_flight, slo_tightened=tightened,
            )
            raise exc
        if deadline_s is None:
            deadline_s = default_deadline_s
        now = self._clock()
        req = FleetRequest(
            self._next_id, prompt, config, now,
            deadline_at=None if deadline_s is None else now + deadline_s,
            trace_id=self.tracer.new_trace_id() if self.tracer else None,
            ttft_anchor_s=ttft_anchor_s,
            on_token=on_token,
            priority=int(priority), tenant=tenant,
        )
        self._next_id += 1
        self._queue.append(req)
        self._inflight[req.request_id] = req
        self.registry.inc("fleet_requests_submitted_total")
        return req

    def serve(self, prompts: Sequence, config: Optional[GenerationConfig] = None
              ) -> List[Optional[np.ndarray]]:
        """Submit every prompt, drain, return results in order — the strict
        batch convenience (failed requests re-raise, the engine contract)."""
        reqs = [self.submit(p, config) for p in prompts]
        self.run_until_idle()
        failed = [r for r in reqs if r.status == "failed"]
        if failed:
            raise RuntimeError(
                f"{len(failed)} of {len(reqs)} fleet requests failed; "
                f"first error: {failed[0].error}"
            )
        return [r.result for r in reqs]

    def pending(self) -> bool:
        """True while the FLEET has undispatched or in-flight requests.
        Stale engine copies already decided by dedupe don't count — they
        retire on their own while other work drives steps, or vanish with
        the next restart."""
        return bool(self._queue) or bool(self._dispatched)

    def cancel(self, request_id: int) -> bool:
        """Withdraw one fleet request — the gateway's client-disconnect
        route, lifted to the fleet: a queued request leaves the queue; a
        dispatched request's LIVE engine copy is cancelled on its replica
        (the slot engine frees the slot and returns its pool pages
        immediately), and the fleet request finalizes ``cancelled``
        exactly once (``fleet_requests_cancelled_total``, one terminal
        ``fleet.request`` span). Stale copies on hung replicas retire on
        their own and fall into the ordinary duplicate-dedupe accounting.
        Returns True when the request was found live."""
        req = self._inflight.get(request_id)
        if req is None or req.done:
            return False
        if req.status == "dispatched" and req.replica_id is not None:
            replica = self._replicas.get(req.replica_id)
            if replica is None:
                # the dispatch target was scaled away; its in-flight work
                # was already failed over, so the request is queued — just
                # finalize the withdrawal
                self._finalize(req, "cancelled", replica_id=None)
                return True
            handle = replica.handles.get(req.request_id)
            if handle is not None and handle.done:
                # the engine copy already finished; the next collect sweep
                # finalizes the fleet request with its REAL disposition — a
                # finished generation must not be recast as a cancellation
                # (the single-engine cancel() handles this race the same
                # way: found-but-done returns False)
                return False
            replica.handles.pop(req.request_id, None)
            if handle is not None:
                try:
                    replica.engine.cancel(handle.request_id)
                except Exception:
                    pass  # a wedged replica must not block the withdrawal
        self._finalize(req, "cancelled", replica_id=req.replica_id)
        return True

    def run_until_idle(self) -> int:
        served = 0
        while self.pending():
            before = self._clock()
            n = self.step()
            served += n
            if n == 0 and not self._last_step_activity:
                # nothing was steppable or dispatchable this pass — even
                # dispatched requests can be unreachable when their replica's
                # breaker is open, so in-flight work alone is no progress
                # guarantee
                if self._clock() == before:
                    # Pending work, nothing steppable or dispatchable, and a
                    # frozen clock: only breaker cooldowns / backoff gates
                    # could unblock us, and a frozen clock never elapses
                    # them. Raise instead of spinning forever — a FakeClock
                    # driver must advance the clock (or call step() itself).
                    raise RuntimeError(
                        "fleet stalled: pending work but every replica is "
                        "unavailable and the clock is not advancing — "
                        "advance the FakeClock past the breaker cooldown / "
                        "redispatch backoff, or drive step() manually"
                    )
                # real clock, waiting on a breaker cooldown or a redispatch
                # backoff gate: yield instead of hot-spinning the drain loop
                # at 100% CPU for up to breaker_cooldown_s
                time.sleep(0.005)
        return served

    def drain(self) -> int:
        """Graceful shutdown: stop accepting, run every fleet request to a
        terminal state, then drain each reachable replica engine (stale
        deduped copies finish too, so duplicate accounting closes).
        Idempotent."""
        self._accepting = False
        served = self.run_until_idle()
        for replica in self.replicas:
            if replica.breaker.poll() == "open":
                continue
            replica.engine.drain()
            self._collect(replica)
        return served

    def warmup(self, config: Optional[GenerationConfig] = None) -> int:
        """Warm every replica; the executor caches are process-global, so
        replica 0 compiles the grid and the rest reuse it. Returns total
        fresh compiles."""
        return sum(r.engine.warmup(config) for r in self.replicas)

    # -- internals ----------------------------------------------------------
    def _terminal_event(self, status: str, **attrs) -> Optional[str]:
        if self.tracer is None:
            return None
        trace_id = self.tracer.new_trace_id()
        self.tracer.event("fleet.request", trace_id=trace_id, status=status, **attrs)
        return trace_id

    def _update_gauges(self) -> None:
        replicas = self._replicas.values()
        healthy = sum(1 for r in replicas if r.breaker.state == "closed")
        self.registry.set_gauge("fleet_replicas_healthy", healthy)
        self.registry.set_gauge("fleet_replicas", len(self._replicas))
        self.registry.set_gauge(
            "fleet_replicas_draining", sum(1 for r in replicas if r.draining)
        )

    def _finalize(self, req: FleetRequest, status: str, *,
                  result: Optional[np.ndarray] = None,
                  error: Optional[str] = None,
                  replica_id: Optional[int] = None) -> None:
        """The request's ONE terminal disposition — every submission that
        entered the queue passes here exactly once (dedupe guards the
        duplicate-completion paths), emitting the one terminal
        ``fleet.request`` span with the completing replica id attached.
        Removes the request from EVERY tracking structure — a stale copy's
        completion can finalize a request that sits re-queued behind a
        redispatch backoff gate, so the queue must forget it too."""
        self._dispatched.pop(req.request_id, None)
        self._inflight.pop(req.request_id, None)
        if req.status == "queued":
            self._queue = [r for r in self._queue if r.request_id != req.request_id]
        req.status = status
        req.result = result
        req.error = error
        req.replica_id = replica_id
        if status == "ok":
            self.registry.inc("fleet_requests_completed_total")
            if replica_id is not None:
                self._completed_by_replica[replica_id] = (
                    self._completed_by_replica.get(replica_id, 0) + 1
                )
        elif status == "timed_out":
            self.registry.inc("fleet_requests_timed_out_total")
        elif status == "cancelled":
            self.registry.inc("fleet_requests_cancelled_total")
        elif status == "failed":
            self.registry.inc("fleet_requests_failed_total")
        latency_s = self._clock() - req.submitted_at
        self.registry.observe("fleet_request_latency_ms", latency_s * 1e3)
        if self.tracer is not None:
            span = self.tracer.start_span(
                "fleet.request", trace_id=req.trace_id,
                start_s=self.tracer.now() - latency_s,
                request_id=req.request_id, prompt_len=int(req.prompt.size),
                replica=replica_id, dispatches=req.dispatches,
            )
            self.tracer.end_span(
                span, status=status, **({"error": error} if error else {})
            )

    def _expire_overdue(self) -> int:
        """Fleet-level deadline shedding for undispatched requests (the
        engines enforce deadlines for dispatched copies from the remaining
        budget handed over at dispatch)."""
        now = self._clock()
        live: List[FleetRequest] = []
        expired = 0
        for req in self._queue:
            if req.deadline_at is not None and now >= req.deadline_at:
                self._finalize(
                    req, "timed_out",
                    error=f"deadline exceeded after "
                          f"{now - req.submitted_at:.3f}s in the fleet queue",
                )
                expired += 1
            else:
                live.append(req)
        self._queue = live
        return expired

    def _charge_breaker(self, replica: Replica) -> bool:
        """Count one replica failure; returns True when it OPENED the
        breaker — the caller must then fail over the replica's in-flight
        work (:meth:`_failover_inflight`), because an open replica is no
        longer stepped and would strand its dispatched requests."""
        self.registry.inc("fleet_replica_failures_total")
        opened = replica.breaker.record_failure()
        if opened:
            self.registry.inc("fleet_breaker_open_total")
            if self.tracer is not None:
                self.tracer.event(
                    "fleet.breaker_open", replica=replica.replica_id,
                    consecutive_failures=replica.breaker.consecutive_failures,
                )
            if self.flight_recorder is not None:
                self.flight_recorder.trigger(
                    "breaker_open",
                    f"replica {replica.replica_id} breaker opened after "
                    f"{replica.breaker.consecutive_failures} consecutive "
                    "failures",
                    trace_ids=self._inflight_trace_ids(replica),
                    replica=replica.replica_id,
                )
        self._update_gauges()
        return opened

    def _inflight_trace_ids(self, replica: Replica) -> List[str]:
        """Trace ids of the fleet requests whose live dispatch sits on
        ``replica`` — the join evidence an incident bundle carries."""
        return [
            self._dispatched[fid].trace_id
            for fid in replica.handles
            if fid in self._dispatched
            and self._dispatched[fid].trace_id is not None
        ]

    def _requeue(self, req: FleetRequest, error: str, *,
                 avoid_replica_id: Optional[int] = None,
                 voluntary: bool = False) -> int:
        """Failover path: return the request to the fleet queue for
        re-dispatch (replayed from its prompt), or fail it terminally when
        its dispatch budget (``1 + redispatch_policy.max_retries``) is
        spent. ``avoid_replica_id`` records where the failed attempt ran so
        the next dispatch prefers anywhere else. ``voluntary`` marks a
        retirement requeue (scale-down): the withdrawn dispatch did not
        fail, so it is REFUNDED — no budget charge, no terminal
        budget-exhaustion, no backoff delay — a replica retiring must not
        be able to drop a request whose genuine failovers already spent
        the budget. Returns 1 when this call disposed of the request."""
        self._dispatched.pop(req.request_id, None)
        req.status = "queued"
        req.replica_id = None
        if avoid_replica_id is not None:
            req.last_replica_id = avoid_replica_id
        if voluntary:
            req.dispatches = max(0, req.dispatches - 1)
            self.registry.inc("fleet_redispatch_total")
            req.not_before = self._clock()
            self._queue.append(req)
            return 0
        if req.dispatches >= 1 + self.redispatch_policy.max_retries:
            self._finalize(
                req, "failed",
                error=f"failover budget exhausted after {req.dispatches} "
                      f"dispatch attempts; last error: {error}",
            )
            return 1
        self.registry.inc("fleet_redispatch_total")
        req.not_before = self._clock() + self.redispatch_policy.delay_s(
            req.dispatches - 1, rng=self._rng
        )
        # append only; _dispatch_pending sorts once per pass (FIFO by id),
        # so a failure with many victims doesn't pay one sort per victim
        self._queue.append(req)
        return 0

    def _pick_replica(self, req: FleetRequest,
                      loads: Dict[Replica, int]) -> Optional[Replica]:
        """Least-loaded dispatchable replica (ties → lowest id) from the
        pass's pre-scanned ``loads`` map. Open breakers and draining
        replicas are excluded; a half-open replica is eligible only for a
        single probe request at a time; a replica still holding a STALE
        copy of this request (hung, failed over, not yet retired) is
        excluded — re-dispatching there would overwrite the stale handle
        and leave an untracked duplicate running (the stale copy's own
        completion can still win via the dedupe sweep). The replica the
        request LAST FAILED on is only chosen when nothing else is
        available, so a retry doesn't bounce straight back onto a poisoned
        executor. Breaker state and handle sets are re-read live (they
        change as the pass dispatches and charges faults); only the engine
        health scan is cached."""
        best = None
        best_load = None
        last_resort = None
        last_resort_load = None
        for replica, load in loads.items():
            if replica.draining:
                continue
            if req.request_id in replica.handles:
                continue
            state = replica.breaker.poll()
            if state == "open":
                continue
            if state == "half_open" and replica.handles:
                continue
            if replica.replica_id == req.last_replica_id:
                if last_resort_load is None or load < last_resort_load:
                    last_resort, last_resort_load = replica, load
                continue
            if best_load is None or load < best_load:
                best, best_load = replica, load
        return best if best is not None else last_resort

    def _dispatch_pending(self) -> int:
        """Place queued requests on replicas, oldest first. Returns the
        number of requests terminally disposed of while trying (expired
        before dispatch, or out of failover budget).

        One ``engine.health()`` scan per replica per PASS (not per queued
        request): this runs on the per-token ``step()`` path, and with the
        queue at ``max_pending`` an O(queue x replicas) health scan would
        dominate the host work. Loads are maintained locally as the pass
        places requests."""
        if not self._queue:
            return 0
        disposed = 0
        now = self._clock()
        # the ONE sort site: requeues since the last pass appended without
        # sorting; dispatch order is priority tier first (higher tiers
        # reach an engine — and its preemption machinery — sooner), FIFO
        # by original submission id within a tier
        self._queue.sort(key=lambda r: (-r.priority, r.request_id))
        pending = self._queue
        self._queue = []
        loads: Dict[Replica, int] = {}
        for replica in self.replicas:
            h = replica.engine.health()
            if h["ready"]:
                loads[replica] = (
                    int(h["queue_depth"])
                    + int(h.get("slots_active") or 0)
                    + (1 if h.get("admitting") else 0)
                )
        for req in pending:
            if req.not_before > now:
                self._queue.append(req)
                continue
            replica = self._pick_replica(req, loads)
            if replica is None:
                self._queue.append(req)
                continue
            fault = self._chaos.hit("fleet.dispatch") if self._chaos else None
            if fault is not None and fault.kind == "error":
                # a failed dispatch RPC: charges the chosen replica's
                # breaker, the request retries under backoff; if the charge
                # OPENED the breaker, the replica's other in-flight work
                # must fail over too (open replicas are not stepped)
                req.dispatches += 1
                opened = self._charge_breaker(replica)
                disposed += self._requeue(
                    req, str(fault.make_error()),
                    avoid_replica_id=replica.replica_id,
                )
                if opened:
                    disposed += self._failover_inflight(
                        replica, "breaker_open",
                        f"opened by dispatch fault: {fault.make_error()}",
                    )
                continue
            remaining = None
            if req.deadline_at is not None:
                remaining = req.deadline_at - now
                if remaining <= 0:
                    self._finalize(
                        req, "timed_out",
                        error="deadline expired before dispatch",
                    )
                    disposed += 1
                    continue
            try:
                # ttft_anchor_s: TTFT is user-facing — measured from the
                # FLEET front door (or further back, at the gateway's
                # socket accept), so fleet queue wait (and failover
                # replays) stay inside the number the SLO judges
                handle = replica.engine.submit(
                    req.prompt, req.config, deadline_s=remaining,
                    ttft_anchor_s=(
                        req.submitted_at if req.ttft_anchor_s is None
                        else req.ttft_anchor_s
                    ),
                    on_token=req.on_token,
                    priority=req.priority, tenant=req.tenant,
                )
            except QueueFull:
                self._queue.append(req)  # engine backpressure: wait, not a fault
                continue
            except ValueError as e:
                # only reachable with heterogeneous replicas (fleet-level
                # check_feasible ran against replica 0)
                self._finalize(req, "failed", error=f"{type(e).__name__}: {e}")
                disposed += 1
                continue
            except Exception as e:
                req.dispatches += 1
                opened = self._charge_breaker(replica)
                disposed += self._requeue(
                    req, f"{type(e).__name__}: {e}",
                    avoid_replica_id=replica.replica_id,
                )
                if opened:
                    disposed += self._failover_inflight(
                        replica, "breaker_open",
                        f"opened by dispatch fault: {type(e).__name__}: {e}",
                    )
                continue
            req.dispatches += 1
            req.status = "dispatched"
            req.replica_id = replica.replica_id
            replica.handles[req.request_id] = handle
            self._dispatched[req.request_id] = req
            loads[replica] += 1
            self.registry.inc("fleet_dispatch_total")
            if self.tracer is not None:
                self.tracer.event(
                    "fleet.dispatch", trace_id=req.trace_id,
                    replica=replica.replica_id, attempt=req.dispatches,
                )
        return disposed

    def _failover_inflight(self, replica: Replica, reason: str,
                           error: str) -> int:
        """Fail over (or, with failover disabled, terminally fail) every
        request whose LIVE dispatch sits on ``replica`` — called whenever
        the replica becomes unreachable: a step failure, or its breaker
        opening from the dispatch-fault path (an open replica is not
        stepped, so leaving requests on it would strand them for the whole
        cooldown). Returns terminal dispositions caused here."""
        victims = sorted(
            (
                self._dispatched[fid]
                for fid in list(replica.handles)
                if fid in self._dispatched
                and self._dispatched[fid].replica_id == replica.replica_id
            ),
            key=lambda r: r.request_id,
        )
        disposed = 0
        if victims:
            if self.failover:
                self.registry.inc("fleet_failover_total")
                for req in victims:
                    disposed += self._requeue(
                        req, f"replica {replica.replica_id} {reason}: {error}",
                        avoid_replica_id=replica.replica_id,
                    )
            else:
                for req in victims:
                    self._finalize(
                        req, "failed",
                        error=f"replica {replica.replica_id} {reason} "
                              f"(failover disabled): {error}",
                    )
                    disposed += 1
        return disposed

    def _on_replica_failure(self, replica: Replica, reason: str,
                            error: str) -> int:
        """Replica-level step failure: charge the breaker, fail over (or
        fail) its in-flight requests, rebuild a crashed replica. Returns
        terminal dispositions caused here."""
        if self.flight_recorder is not None:
            # capture BEFORE the failover sweep mutates the dispatch maps:
            # the bundle's trace ids name the victims as they were
            self.flight_recorder.trigger(
                "replica_failure",
                f"replica {replica.replica_id} {reason}: {error}",
                trace_ids=self._inflight_trace_ids(replica),
                replica=replica.replica_id, failure_reason=reason,
                in_flight=len(replica.handles),
            )
        self._charge_breaker(replica)
        if self.tracer is not None:
            self.tracer.event(
                "fleet.replica_failed", replica=replica.replica_id,
                reason=reason, error=error, in_flight=len(replica.handles),
            )
        disposed = self._failover_inflight(replica, reason, error)
        if reason == "crash":
            # the crashed-process model: rebuild now so reintegration
            # probes a live engine; its handles (and any stale copies) die
            # with it
            replica.restart()
            self.registry.inc("fleet_replica_restarts_total")
            if self.tracer is not None:
                self.tracer.event(
                    "fleet.replica_restarted", replica=replica.replica_id,
                    reason=reason,
                )
        self._update_gauges()
        return disposed

    def _collect(self, replica: Replica) -> int:
        """Sweep the replica's finished engine handles into fleet terminal
        states, with exactly-once dedupe by fleet request id: the first
        completed copy wins; a late duplicate (the request already done, or
        no longer tracked) is counted and dropped. A stale copy's non-ok
        outcome never decides a request that has a live dispatch
        elsewhere."""
        disposed = 0
        for fid, handle in replica.collect():
            # look up in the full in-flight map, not just the dispatched
            # one: a hung replica's completed copy must still win for a
            # request waiting re-queued behind its redispatch backoff
            req = self._inflight.get(fid)
            if req is None or req.done:
                self.registry.inc("fleet_duplicate_results_total")
                continue
            if handle.status == "ok":
                self._finalize(
                    req, "ok", result=handle.result,
                    replica_id=replica.replica_id,
                )
                disposed += 1
            elif req.replica_id != replica.replica_id:
                # stale non-ok copy (the request is queued for re-dispatch
                # or live on another replica): the live dispatch decides
                continue
            elif handle.status == "timed_out":
                self._finalize(
                    req, "timed_out", error=handle.error,
                    replica_id=replica.replica_id,
                )
                disposed += 1
            else:  # engine-level failure (poisoned executor, request fault)
                if self.failover:
                    # charge the replica: a poisoned executor failing every
                    # request must open its breaker instead of silently
                    # burning each request's failover budget. A genuinely
                    # bad REQUEST charges one failure per replica it visits,
                    # which the replica's next clean pass resets — only a
                    # replica failing repeatedly accumulates to threshold.
                    opened = self._charge_breaker(replica)
                    disposed += self._requeue(
                        req,
                        f"engine fault on replica {replica.replica_id}: "
                        f"{handle.error}",
                        avoid_replica_id=replica.replica_id,
                    )
                    if opened:
                        disposed += self._failover_inflight(
                            replica, "breaker_open",
                            f"opened by engine fault: {handle.error}",
                        )
                else:
                    self._finalize(
                        req, "failed", error=handle.error,
                        replica_id=replica.replica_id,
                    )
                    disposed += 1
        return disposed

    # -- the supervised scheduler -------------------------------------------
    def step(self) -> int:
        """One fleet scheduling pass: expire overdue queued requests,
        dispatch what can be placed, then give every reachable replica one
        supervised engine step and sweep its completions. Returns the
        number of fleet requests terminally disposed of; drive drain loops
        off :meth:`pending` (a mid-generation pass legitimately disposes of
        nothing)."""
        if self.slo_monitor is not None:
            # one burn evaluation per scheduling pass: breach/recovery
            # transitions (and the admission tightening they gate) happen
            # here, on the shared clock, never mid-submit
            self.slo_monitor.poll()
        if self.autoscaler is not None:
            # the elasticity control loop runs HERE, before the pass
            # snapshots the replica set: a scale-up serves this very pass,
            # a scale-down's failed-over work re-dispatches below
            self.autoscaler.poll()
        disposed = self._expire_overdue()
        disposed += self._dispatch_pending()
        stepped_any = False
        # snapshot: an autoscaler poll (above) may have added/removed
        # replicas, and the next poll can again — never iterate the live map
        for replica in self.replicas:
            state = replica.breaker.poll()
            if state == "open":
                continue
            if not (replica.engine.pending() or replica.handles):
                continue
            was_half_open = state == "half_open"
            stepped_any = True
            try:
                replica.step()
            except Exception as e:
                disposed += self._on_replica_failure(
                    replica, "crash", f"{type(e).__name__}: {e}"
                )
                continue
            if (
                self.step_timeout_s is not None
                and replica.last_step_wall_s >= self.step_timeout_s
            ):
                disposed += self._on_replica_failure(
                    replica, "hung",
                    f"step wall time {replica.last_step_wall_s:.3f}s >= "
                    f"step_timeout_s={self.step_timeout_s}",
                )
                continue
            # collect BEFORE judging the pass: an engine-level fault swept
            # up here charges the breaker, and that charge must not be
            # erased by crediting the same pass as a success
            fails_before = replica.breaker.consecutive_failures
            opens_before = replica.breaker.opened_total
            disposed += self._collect(replica)
            if (
                replica.breaker.consecutive_failures == fails_before
                and replica.breaker.opened_total == opens_before
            ):
                replica.breaker.record_success()
                if was_half_open:
                    self._update_gauges()
                    if self.tracer is not None:
                        self.tracer.event(
                            "fleet.breaker_close", replica=replica.replica_id
                        )
        self._last_step_activity = stepped_any
        self._update_gauges()
        return disposed

    # -- elasticity ---------------------------------------------------------
    def add_replica(self, factory: Optional[Callable[[], object]] = None
                    ) -> Replica:
        """Scale up by one replica, spawned from ``factory`` (default: the
        fleet's first constructor factory — the homogeneous case). The
        executor caches are process-global, so after one warmup pass a new
        replica compiles nothing and serves its first dispatch immediately.

        The ``fleet.scale_up`` chaos site fires first (execution-count
        keyed): an ``error`` fault models a SPAWN FAILURE — the factory's
        process never comes up — counted ``fleet_scale_up_failed_total``
        and re-raised for the caller (the autoscaler absorbs it and holds
        its cooldown, so a broken image cannot spin the control loop)."""
        if self._chaos is not None:
            fault = self._chaos.hit("fleet.scale_up")
            if fault is not None and fault.kind == "error":
                self.registry.inc("fleet_scale_up_failed_total")
                if self.tracer is not None:
                    self.tracer.event(
                        "autoscaler.spawn_failed",
                        error=str(fault.make_error()),
                        replicas=len(self._replicas),
                    )
                raise fault.make_error()
        replica = self._spawn_replica(
            factory if factory is not None else self._default_factory
        )
        self._completed_by_replica.setdefault(replica.replica_id, 0)
        self.registry.inc("fleet_scale_up_total")
        self._update_gauges()
        return replica

    def scale_down_victim(self) -> Optional[Replica]:
        """The replica :meth:`remove_replica` should retire next: the
        LEAST-LOADED eligible one (ties → highest id, so the founding
        replicas persist). Excluded: draining replicas, the last healthy
        replica (never drop below min-healthy — ``healthz`` must stay
        ready), and any replica whose breaker is not closed while it still
        holds engine handles — its in-flight work was re-queued at
        failover, and the stale copies must be left to retire through the
        duplicate-dedupe sweep, not evacuated into accounting limbo.
        Returns None when nothing is eligible."""
        replicas = list(self._replicas.values())
        healthy = [
            r for r in replicas if r.breaker.state == "closed" and not r.draining
        ]
        best = None
        best_key = None
        for replica in replicas:
            if replica.draining:
                continue
            if replica.breaker.state != "closed" and replica.handles:
                continue
            if replica in healthy and len(healthy) <= 1:
                continue  # the last healthy replica keeps the fleet ready
            load = len(replica.handles)
            try:
                h = replica.engine.health()
                load += int(h["queue_depth"]) + int(h.get("slots_active") or 0)
            except Exception:
                pass  # a wedged engine is still a fine victim
            key = (load, -replica.replica_id)
            if best_key is None or key < best_key:
                best, best_key = replica, key
        return best

    def remove_replica(self, replica_id: int) -> Replica:
        """Scale down by one replica with ZERO dropped in-flight requests
        — the rolling_restart() discipline applied to retirement:

        1. the replica stops receiving dispatches (``draining``),
        2. its live in-flight requests fail over through the exactly-once
           replay path — survivors replay them from their prompts,
           token-identical under greedy decoding,
        3. its engine is **evacuated**: every stale engine-side copy is
           withdrawn and every KV pool page (mapped + reserved) returns to
           the pool tagged ``cause="scale_down"`` — the zero-leak
           accounting the acceptance drill pins,
        4. the replica leaves the fleet; its id is never reused.

        The ``fleet.scale_down`` chaos site fires after the failover
        (execution-count keyed): an ``error`` fault models the replica
        CRASHING MID-DRAIN — the evacuation never runs (a dead process
        frees its memory by dying), the failure is charged, and the
        removal completes; the failed-over work is already safe.

        Returns the removed :class:`Replica` (its engine still inspectable
        — tests read the pool's ``frees_by_cause``). Refuses to remove the
        last healthy non-draining replica (``healthz`` stays ready
        throughout, never below min-healthy)."""
        replica = self._replicas.get(replica_id)
        if replica is None:
            raise KeyError(f"no replica {replica_id} in the fleet")
        others_healthy = sum(
            1 for r in self._replicas.values()
            if r.replica_id != replica_id
            and r.breaker.state == "closed" and not r.draining
        )
        if others_healthy == 0 and replica.breaker.state == "closed":
            raise ValueError(
                f"removing replica {replica_id} would leave no healthy "
                "replica — the fleet must stay ready through a scale-down "
                "(scale_down_victim() never picks this one)"
            )
        replica.draining = True
        # always replay — even with failover=False: scale-down is a
        # voluntary retirement, not a failure, so its in-flight work moves
        # to survivors through the same exactly-once requeue path
        victims = sorted(
            (
                self._dispatched[fid]
                for fid in list(replica.handles)
                if fid in self._dispatched
                and self._dispatched[fid].replica_id == replica.replica_id
            ),
            key=lambda r: r.request_id,
        )
        if victims:
            self.registry.inc("fleet_failover_total")
            for req in victims:
                self._requeue(
                    req, f"replica {replica_id} retiring (fleet scale-down)",
                    avoid_replica_id=replica_id, voluntary=True,
                )
        crashed = None
        if self._chaos is not None:
            crashed = self._chaos.hit("fleet.scale_down")
            if crashed is not None and crashed.kind != "error":
                crashed = None
        if crashed is not None:
            # crash mid-drain: the process died before a clean evacuation;
            # its in-flight work is already re-queued above, so the drill
            # only costs the failure accounting
            self.registry.inc("fleet_replica_failures_total")
            if self.tracer is not None:
                self.tracer.event(
                    "fleet.replica_failed", replica=replica_id,
                    reason="scale_down_crash",
                    error=str(crashed.make_error()), in_flight=0,
                )
        else:
            evacuate = getattr(replica.engine, "evacuate", None)
            if evacuate is not None:
                evacuate(cause="scale_down")
        replica.handles.clear()
        del self._replicas[replica_id]
        self.registry.inc("fleet_scale_down_total")
        self._update_gauges()
        return replica

    # -- operations ---------------------------------------------------------
    def rolling_restart(self) -> int:
        """Zero-downtime maintenance: one replica at a time — stop
        dispatching to it, finish its in-flight work (the rest of the fleet
        keeps serving, new submissions included), rebuild it from its
        factory, reintegrate. An open (already failed) replica is rebuilt
        immediately. Returns the number of replicas restarted."""
        restarted = 0
        for replica in self.replicas:
            replica.draining = True
            restarts_before = replica.restarts
            try:
                while (
                    (replica.engine.pending() or replica.handles)
                    and replica.breaker.poll() != "open"
                ):
                    self.step()
                if replica.restarts == restarts_before:
                    # a crash during the drain loop already rebuilt it (and
                    # counted the restart) — don't discard the fresh engine
                    replica.restart()
                    self.registry.inc("fleet_replica_restarts_total")
                    if self.tracer is not None:
                        self.tracer.event(
                            "fleet.replica_restarted",
                            replica=replica.replica_id,
                            reason="rolling_restart",
                        )
                restarted += 1
            finally:
                replica.draining = False
        self._update_gauges()
        return restarted

    # -- observability ------------------------------------------------------
    def _prefix_cache_rollup(self) -> Optional[dict]:
        """Summed per-replica prefix-cache hit accounting, or None when no
        replica shares prefixes (docs/serving.md "Prefix sharing")."""
        regs: dict = {}
        for r in self.replicas:
            if getattr(r.engine, "_prefix_index", None) is not None:
                regs[id(r.engine.registry)] = r.engine.registry
        if not regs:
            return None
        hits = sum(int(reg.counter("kv_prefix_hits_total")) for reg in regs.values())
        misses = sum(
            int(reg.counter("kv_prefix_misses_total")) for reg in regs.values()
        )
        return {
            "hits": hits,
            "misses": misses,
            "hit_ratio": round(hits / max(1, hits + misses), 4),
        }

    def stats(self) -> dict:
        """Fleet counters (canonical ``fleet_*`` names AND the short
        convenience keys), per-replica completion attribution, and each
        replica's own ``stats()`` — the serve CLI's ``serve_stats`` record
        for a fleet run."""
        counts = self.registry.counters()

        def c(name: str) -> int:
            return int(counts.get(name, 0))

        reg = self.registry
        out = {name: c(name) for name in FLEET_COUNTERS}
        out.update({
            "engine": "fleet",
            "replicas": len(self._replicas),
            "failover": self.failover,
            "submitted": c("fleet_requests_submitted_total"),
            "completed": c("fleet_requests_completed_total"),
            "shed": c("fleet_requests_shed_total"),
            "timed_out": c("fleet_requests_timed_out_total"),
            "failed": c("fleet_requests_failed_total"),
            "rejected": c("fleet_requests_rejected_total"),
            "cancelled": c("fleet_requests_cancelled_total"),
            "queued": len(self._queue),
            "dispatched": len(self._dispatched),
            "dispatches": c("fleet_dispatch_total"),
            "failovers": c("fleet_failover_total"),
            "redispatches": c("fleet_redispatch_total"),
            "breaker_opens": c("fleet_breaker_open_total"),
            "replica_failures": c("fleet_replica_failures_total"),
            "replica_restarts": c("fleet_replica_restarts_total"),
            "duplicate_results_ignored": c("fleet_duplicate_results_total"),
            "replicas_healthy": sum(
                1 for r in self._replicas.values() if r.breaker.state == "closed"
            ),
            "scale_ups": c("fleet_scale_up_total"),
            "scale_downs": c("fleet_scale_down_total"),
            "scale_up_failures": c("fleet_scale_up_failed_total"),
            "autoscaler": (
                None if self.autoscaler is None else self.autoscaler.stats()
            ),
            "completed_by_replica": {
                str(k): v for k, v in sorted(self._completed_by_replica.items())
            },
            "request_latency_ms": {
                "p50": reg.percentile("fleet_request_latency_ms", 50.0),
                "p95": reg.percentile("fleet_request_latency_ms", 95.0),
            },
            # fleet-scope token latencies, mirrored from every replica's
            # engine (docs/observability.md)
            "ttft_ms": {
                "p50": reg.percentile("serving_ttft_ms", 50.0),
                "p95": reg.percentile("serving_ttft_ms", 95.0),
            },
            "inter_token_ms": {
                "p50": reg.percentile("serving_inter_token_ms", 50.0),
                "p95": reg.percentile("serving_inter_token_ms", 95.0),
            },
            "slo": None if self.slo_monitor is None else self.slo_monitor.stats(),
            "slo_sheds": c("fleet_slo_shed_total"),
            # fleet-wide prefix-sharing rollup (docs/serving.md "Prefix
            # sharing"): replicas keep INDEPENDENT caches — a failover
            # replay re-prefills on the survivor and re-hits whatever that
            # replica's own index holds — so the fleet view is the sum of
            # per-replica hit accounting (deduped by registry: replicas
            # sharing one registry already aggregate), not a shared cache's
            "prefix_cache": self._prefix_cache_rollup(),
            "per_replica": [
                {
                    "replica_id": r.replica_id,
                    "breaker": r.breaker.state,
                    "restarts": r.restarts,
                    "in_flight": len(r.handles),
                    "engine": r.engine.stats(),
                }
                for r in self.replicas
            ],
        })
        # fleet-wide per-tenant rollup (docs/observability.md "Scheduler
        # timeline & post-mortems"): replicas attribute independently —
        # pool pages, generated tokens, and preemption victims per tenant
        # — so the fleet view is the field-wise sum, the same shape each
        # replica's engine stats() reports
        tenants: dict = {}
        for rep in out["per_replica"]:
            for key, fields in (rep["engine"].get("tenants") or {}).items():
                agg = tenants.setdefault(key, {})
                for field, value in fields.items():
                    agg[field] = agg.get(field, 0) + value
        if tenants:
            out["tenants"] = {k: tenants[k] for k in sorted(tenants)}
        return out

    def health(self) -> dict:
        """Fleet readiness under the shared health schema
        (``serving.engine.HEALTH_KEYS``) plus per-replica snapshots —
        ``ready`` means a submission would be accepted right now AND at
        least one replica's breaker is closed to run it.

        ``replicas`` / ``replicas_healthy`` / ``draining`` are COUNTS (the
        ``/healthz`` payload a load balancer or autoscaler dashboard reads
        — docs/serving.md "Elasticity"); the per-replica snapshots live
        under ``replica_detail``. ``ready`` is pinned to stay true across
        a rolling restart and an autoscale transition: survivors keep
        serving while one replica drains."""
        now = self._clock()
        depth = len(self._queue) + len(self._dispatched)
        reg = self.registry
        replicas = list(self._replicas.values())
        healthy = sum(1 for r in replicas if r.breaker.state == "closed")
        # admission as currently ENFORCED — under SLO tightening, "ready"
        # flips false at the reduced bound, so a well-behaved front end
        # backs off before tripping the shed counter
        max_pending, _ = self._effective_admission()
        return {
            "ready": self._accepting and healthy > 0
            and (max_pending is None or depth < max_pending),
            "accepting": self._accepting,
            "queue_depth": depth,
            "max_queue": self.max_pending,
            "oldest_wait_ms": round(
                max((now - r.submitted_at) for r in self._queue) * 1e3, 3
            ) if self._queue else 0.0,
            "completed": int(reg.counter("fleet_requests_completed_total")),
            "shed": int(reg.counter("fleet_requests_shed_total")),
            "timed_out": int(reg.counter("fleet_requests_timed_out_total")),
            "failed": int(reg.counter("fleet_requests_failed_total")),
            "cancelled": int(reg.counter("fleet_requests_cancelled_total")),
            "replicas": len(replicas),
            "replicas_healthy": healthy,
            "draining": sum(1 for r in replicas if r.draining),
            "replica_detail": [r.health() for r in self.replicas],
        }
