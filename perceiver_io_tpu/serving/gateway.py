"""Async HTTP/SSE streaming gateway: the serving stack's network front end.

Until this module the repo's serving story stopped at a Python API and a
batch-in/batch-out CLI — PR 9's load generator drives engines in-process,
so socket-anchored TTFT, per-connection streaming, and client-abandonment
behavior were unmeasured and unbuilt (ROADMAP item 3). The Gemma-on-TPU
serving paper (PAPERS.md) is the deployment-shape reference: tokens stream
to clients *as they decode* and front-end latency is judged *at the
socket*; the Ragged Paged Attention paper motivates why mid-stream
cancellation must return pool pages promptly — abandoned residents are the
long-tail HBM leak.

:class:`StreamingGateway` is a **stdlib-only** (``asyncio``, no new
dependencies — the ``observability/report.py`` discipline) HTTP/1.1 server
multiplexing thousands of concurrent connections onto ONE engine — either
engine, or a whole :class:`~perceiver_io_tpu.serving.FleetRouter` — all of
which the gateway drives through the shared request surface from a single
driver task, preserving the engines' single-owner contract:

- ``POST /v1/generate`` — body ``{"prompt": str | "prompt_ids": [int],
  "max_new_tokens"?: int, "stream"?: "sse"|"jsonl", "deadline_s"?: s,
  "priority"?: int in [-100, 100], "tenant"?: str}``. ``priority`` and
  ``tenant`` feed the slot engine's preemption tiers and per-tenant
  fairness accounting (docs/serving.md "Preemption & priorities").
  Each generated token is flushed the moment the slot engine's ``step()``
  materializes it (the per-request ``on_token`` sink,
  :class:`~perceiver_io_tpu.serving.engine.ServeRequest`; batch-granular
  on the bucket engine), framed as Server-Sent Events (``data: {...}``)
  or JSON-lines, EOF-terminated (``Connection: close``). The final record
  carries ``{"done": true, "status": ..., "trace_id": ...}``.
- ``GET /healthz`` — the engine's shared health snapshot
  (``serving.engine.HEALTH_KEYS``); HTTP 200 while ``ready``, 503
  otherwise — load-balancer probe semantics.
- ``GET /metrics`` — the registry in Prometheus exposition format.

**Socket-anchored TTFT**: the accept instant is passed to
``submit(ttft_anchor_s=...)``, so the SLO-judged ``serving_ttft_ms``
includes network/gateway queue time (the fleet router then carries the
anchor through failover replays). The gateway's own
``gateway_socket_ttft_ms`` histogram measures accept → first token byte
*written to the socket* — the delta between the two is the response-path
overhead ``obs report``'s gateway section surfaces.

**Cancellation-safe slot retirement**: a client disconnect (socket EOF or
a failed write) propagates as ``engine.cancel(request_id)`` — a new
retirement route that frees the slot, returns every
:class:`~perceiver_io_tpu.serving.kv_pool.KVPagePool` page (tagged
``cancelled`` in the pool's free accounting), and ends the request trace
with a terminal ``cancelled`` span — without perturbing surviving
requests' tokens (per-row independence, pinned by
``tests/test_gateway.py``). The ``gateway.disconnect.<stream>`` chaos site
(``reliability.chaos``) scripts mass abandonment deterministically: the
drill asserts zero slot/page leak and survivor token-identity.

Disposition accounting closes: every accepted stream ends exactly one way
— ``gateway_streams_completed_total + gateway_streams_cancelled_total ==
gateway_streams_total`` (rejected submissions count
``gateway_streams_rejected_total`` and never become streams).

Determinism note: greedy decoding means the byte stream a client receives
is a pure function of its prompt — the gateway adds concurrency, not
entropy — so HTTP-served outputs are token-identical to in-process
``generate()`` (the acceptance pin, including fleet-routed and paged-KV
configurations). On fleet failover the replayed copy re-emits indices
from 0; the per-stream ``sent`` cursor dedupes, so the wire sees each
index exactly once.
"""
from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import json
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from perceiver_io_tpu.observability.timeline import tenant_label
from perceiver_io_tpu.reliability import QueueFull

#: stream framings the gateway speaks
STREAM_MODES = ("sse", "jsonl")

#: request-body cap — a generate request is a prompt plus a few scalars;
#: anything bigger is a malformed or hostile client (answered 413, never
#: buffered)
MAX_BODY_BYTES = 1 << 20

#: counters declared at construction so exports show the full gateway
#: schema before the first connection (docs/observability.md)
GATEWAY_COUNTERS = (
    "gateway_connections_total",
    "gateway_streams_total",
    "gateway_streams_completed_total",
    "gateway_streams_cancelled_total",
    "gateway_streams_rejected_total",
    "gateway_bytes_sent_total",
)

_CONTENT_TYPES = {"sse": "text/event-stream", "jsonl": "application/x-ndjson"}


@dataclasses.dataclass
class _Stream:
    """Host-side record of one in-flight token stream: the engine handle,
    the per-stream token queue the ``on_token`` sink feeds, and the wire
    cursor (``sent``) that dedupes failover replays."""

    stream_id: int
    handle: object  # ServeRequest | FleetRequest
    queue: "asyncio.Queue"
    accepted_at: float
    mode: str = "sse"
    sent: int = 0
    bytes_sent: int = 0
    disconnected: bool = False
    finalized: bool = False  # terminal sentinel enqueued
    #: the stream reached exactly one of completed/cancelled — the
    #: disposition invariant's bookkeeping bit (a handler torn down by
    #: server shutdown settles in its finally block)
    counted: bool = False


class StreamingGateway:
    """Asyncio HTTP/1.1 front end over one engine or fleet (module
    docstring for the protocol).

    :param engine: anything with the shared request surface — ``submit`` /
        ``step`` / ``pending`` / ``cancel`` / ``health`` / ``drain`` (both
        engines and the :class:`~perceiver_io_tpu.serving.FleetRouter`).
        The gateway becomes the engine's single driver: nothing else may
        call ``step()`` while it runs.
    :param host / port: bind address; ``port=0`` picks an ephemeral port
        (read it back from :attr:`port` after :meth:`run_in_thread`).
    :param stream: default framing, ``"sse"`` or ``"jsonl"`` (per-request
        override via the body's ``"stream"`` field).
    :param encode / decode: optional tokenizer hooks. ``encode(str) ->
        ids`` enables the ``"prompt"`` text field; ``decode([id]) -> str``
        adds a ``"text"`` field to every token record. Without ``encode``,
        only ``"prompt_ids"`` is accepted.
    :param registry: metrics registry for the ``gateway_*`` families;
        defaults to the engine's own registry so one scrape covers both.
    :param tracer: optional span tracer — one ``gateway.request`` event
        per stream on the request's trace (the events.jsonl join).
    :param chaos: optional :class:`~perceiver_io_tpu.reliability.ChaosRegistry`
        consulted at ``gateway.disconnect.<stream>`` once per outgoing
        token — the scripted mass-abandonment drill.
    :param clock: monotonic time source shared with the engine (the TTFT
        anchor and the engine's latency accounting must share a time base).
    :param slo_monitor: optional
        :class:`~perceiver_io_tpu.observability.SLOMonitor`, polled once
        per driver pass (skipped when the engine is a fleet — the router
        polls its own monitor inside ``step()``).
    :param snapshot_writer: optional cadence-gated
        :class:`~perceiver_io_tpu.observability.SnapshotWriter`, offered a
        write once per driver pass.
    :param max_streams: shut the server down after this many streams reach
        a terminal state (None = serve until :meth:`close`) — the CLI's
        scriptable-run knob.
    :param idle_sleep_s: driver nap while the engine has no pending work.
    """

    def __init__(self, engine, *, host: str = "127.0.0.1", port: int = 0,
                 stream: str = "sse",
                 encode: Optional[Callable] = None,
                 decode: Optional[Callable] = None,
                 registry=None, tracer=None, chaos=None,
                 clock: Callable[[], float] = time.monotonic,
                 slo_monitor=None, snapshot_writer=None,
                 flight_recorder=None,
                 max_streams: Optional[int] = None,
                 idle_sleep_s: float = 0.002,
                 mass_disconnect_threshold: int = 3,
                 mass_disconnect_window_s: float = 5.0):
        if stream not in STREAM_MODES:
            raise ValueError(
                f"stream must be one of {STREAM_MODES}, got {stream!r}"
            )
        if max_streams is not None and max_streams < 1:
            raise ValueError(f"max_streams must be >= 1, got {max_streams}")
        self.engine = engine
        self.host = host
        self.port = int(port)  # rebound to the real port after start()
        self.stream_mode = stream
        self._encode = encode
        self._decode = decode
        self.registry = registry if registry is not None else engine.registry
        self.tracer = tracer
        self._chaos = chaos
        self._clock = clock
        self.slo_monitor = slo_monitor
        self.snapshot_writer = snapshot_writer
        #: optional incident
        #: :class:`~perceiver_io_tpu.observability.FlightRecorder` —
        #: ``mass_disconnect_threshold`` client disconnects inside
        #: ``mass_disconnect_window_s`` fire its ``mass_disconnect`` seam
        #: (docs/observability.md "Flight recorder & incident bundles"):
        #: one abandoned stream is churn, a burst is an incident
        self.flight_recorder = flight_recorder
        from perceiver_io_tpu.observability.flight_recorder import DisconnectWatch

        self._disconnect_watch = DisconnectWatch(
            threshold=mass_disconnect_threshold,
            window_s=mass_disconnect_window_s, clock=clock,
        )
        #: accepted streams per sanitized tenant label (the wire half of
        #: the per-tenant attribution the engines carry in their stats())
        self._streams_by_tenant: Dict[str, int] = {}
        self.max_streams = max_streams
        self.idle_sleep_s = float(idle_sleep_s)
        # the fleet router polls its own monitor per step(); polling it
        # here too would double-diff the disposition counters
        self._poll_slo = (
            slo_monitor is not None
            and getattr(engine, "slo_monitor", None) is not slo_monitor
        )
        self.registry.declare_counters(*GATEWAY_COUNTERS)
        self.registry.set_gauge("gateway_connections_active", 0)
        self.registry.set_gauge("gateway_streams_active", 0)
        self._streams: Dict[int, _Stream] = {}  # engine request id -> stream
        self._next_stream_id = 1
        self._finished_streams = 0
        self._active_connections = 0
        self.driver_errors: List[str] = []
        self._server = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_event: Optional[asyncio.Event] = None
        self._stopping = False
        self._startup_error: Optional[BaseException] = None

    # -- lifecycle -----------------------------------------------------------
    async def start(self) -> None:
        """Bind the listening socket (call from the serving event loop)."""
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve(self) -> None:
        """Run the driver + server until :meth:`close` (or ``max_streams``)
        stops it. ``start()`` must have run. (``run_in_thread`` creates
        ``_stop_event`` BEFORE signalling readiness, so an immediate
        ``close()`` from the caller is never a lost wakeup.)"""
        if self._stop_event is None:
            self._stop_event = asyncio.Event()
        driver = asyncio.ensure_future(self._drive())
        try:
            await self._stop_event.wait()
        finally:
            self._stopping = True
            driver.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await driver
            self._server.close()
            # bounded wait only: on Python >= 3.12.1 wait_closed() blocks
            # until every connection HANDLER returns, and a handler mid-
            # stream (its client still connected, its terminal sentinel
            # never coming — the driver is dead) would deadlock shutdown.
            # Handlers left running are cancelled when the loop exits;
            # their finally blocks settle the disposition invariant
            # (cancel the engine request + count the stream).
            with contextlib.suppress(asyncio.TimeoutError, TimeoutError):
                await asyncio.wait_for(self._server.wait_closed(), timeout=2.0)

    def run_in_thread(self) -> "StreamingGateway":
        """Start the gateway on its own event loop in a daemon thread and
        return once the socket is bound (``self.port`` is then real). The
        engine is driven ONLY from that thread — the single-owner contract
        holds; callers interact over HTTP (or via :meth:`close`)."""
        started = threading.Event()

        async def _main():
            try:
                await self.start()
            except BaseException as e:  # bind failure -> surface in caller
                self._startup_error = e
                started.set()
                return
            self._loop = asyncio.get_running_loop()
            # the stop event must exist before the caller unblocks: a
            # close() issued right after run_in_thread() returns has to
            # find something to set, or it would silently leak the thread
            self._stop_event = asyncio.Event()
            started.set()
            await self.serve()

        self._thread = threading.Thread(
            target=lambda: asyncio.run(_main()), daemon=True,
            name="perceiver-gateway",
        )
        self._thread.start()
        if not started.wait(timeout=30.0):
            raise RuntimeError("gateway failed to start within 30s")
        if self._startup_error is not None:
            raise RuntimeError(
                f"gateway failed to bind {self.host}:{self.port}: "
                f"{self._startup_error}"
            )
        return self

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the gateway thread exits (``max_streams`` reached or
        :meth:`close` called elsewhere); returns False on timeout."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def close(self) -> None:
        """Stop the server and driver; idempotent, thread-safe. In-flight
        streams are torn down with the loop; the ENGINE keeps its state —
        the caller decides whether to ``drain()`` or ``cancel`` leftovers."""
        loop = self._loop
        if loop is not None and self._stop_event is not None:
            with contextlib.suppress(RuntimeError):
                loop.call_soon_threadsafe(self._stop_event.set)
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=10.0)

    # -- the driver ----------------------------------------------------------
    async def _drive(self) -> None:
        """THE engine drive loop: one ``step()`` per pass while work is
        pending, then a flush of newly-terminal streams. Runs in the same
        event loop as every connection handler, so ``on_token`` sinks
        (plain ``put_nowait``) and ``cancel()`` calls never race the
        scheduler — asyncio's cooperative scheduling is the lock."""
        while not self._stopping:
            worked = False
            if self.engine.pending():
                try:
                    self.engine.step()
                    worked = True
                except Exception as e:  # engine isolates its own faults;
                    # a scheduler bug must not kill every open connection —
                    # but a PERSISTENT fault (pending stays true, step keeps
                    # raising) must not hot-spin the loop either: leave
                    # worked False so the pass backs off by idle_sleep_s,
                    # and bound the error log
                    if len(self.driver_errors) < 100:
                        self.driver_errors.append(f"{type(e).__name__}: {e}")
            if self._poll_slo:
                self.slo_monitor.poll()
            if self.snapshot_writer is not None:
                self.snapshot_writer.maybe_write()
            if self.flight_recorder is not None:
                # the flight recorder's periodic "before" evidence rides
                # the same per-pass cadence hook as the snapshot writer
                self.flight_recorder.maybe_record()
            self._flush_terminal()
            # yield so handlers drain their queues between steps; nap when
            # idle instead of hot-spinning the loop
            await asyncio.sleep(0 if worked else self.idle_sleep_s)

    def _flush_terminal(self) -> None:
        """Enqueue the terminal sentinel for every stream whose engine
        handle reached a terminal state since the last pass."""
        for stream in list(self._streams.values()):
            if not stream.finalized and stream.handle.done:
                stream.finalized = True
                stream.queue.put_nowait(None)

    # -- http plumbing -------------------------------------------------------
    async def _read_http(self, reader):
        request_line = await reader.readline()
        if not request_line:
            return None
        parts = request_line.decode("latin-1").split()
        if len(parts) < 2:
            return None
        method, path = parts[0].upper(), parts[1]
        headers = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            key, _, value = line.decode("latin-1").partition(":")
            headers[key.strip().lower()] = value.strip()
        body = b""
        try:
            length = int(headers.get("content-length", 0) or 0)
        except ValueError:
            return None  # malformed head: drop the connection, nothing to answer
        if length > MAX_BODY_BYTES:
            # don't buffer an attacker-sized body; body=None marks oversize
            return method, path, headers, None
        if length > 0:
            body = await reader.readexactly(length)
        return method, path, headers, body

    async def _write(self, writer, data: bytes,
                     stream: Optional[_Stream] = None) -> bool:
        """One counted socket write; False when the peer is gone."""
        try:
            writer.write(data)
            await writer.drain()
        except (ConnectionError, OSError):
            return False
        self.registry.inc("gateway_bytes_sent_total", len(data))
        if stream is not None:
            stream.bytes_sent += len(data)
        return True

    async def _respond(self, writer, status: int, payload: dict,
                       extra_headers: str = "") -> None:
        """One-shot JSON response (errors, healthz) with Content-Length.
        ``extra_headers`` is pre-formatted ``Name: value\\r\\n`` lines."""
        body = (json.dumps(payload, default=str) + "\n").encode()
        head = (
            f"HTTP/1.1 {status} {_REASONS.get(status, 'OK')}\r\n"
            "Content-Type: application/json\r\n"
            f"{extra_headers}"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n\r\n"
        ).encode("latin-1")
        await self._write(writer, head + body)

    async def _on_connection(self, reader, writer) -> None:
        self.registry.inc("gateway_connections_total")
        self._active_connections += 1
        self.registry.set_gauge(
            "gateway_connections_active", self._active_connections
        )
        try:
            parsed = await self._read_http(reader)
            if parsed is None:
                return
            method, path, headers, body = parsed
            if body is None:  # oversized Content-Length, never buffered
                await self._respond(
                    writer, 413,
                    {"error": f"request body exceeds {MAX_BODY_BYTES} bytes"},
                )
            elif path == "/healthz" and method == "GET":
                health = self.engine.health()
                # uniform LB payload (docs/serving.md "Elasticity"): a
                # fleet reports its real replica counts (pinned to stay
                # 200-ready across rolling restarts and autoscale
                # transitions); a single engine is its own fleet of one
                health.setdefault("replicas", 1)
                health.setdefault(
                    "replicas_healthy", 1 if health.get("ready") else 0
                )
                health.setdefault(
                    "draining", 0 if health.get("accepting", True) else 1
                )
                await self._respond(
                    writer, 200 if health.get("ready") else 503, health
                )
            elif path == "/metrics" and method == "GET":
                from perceiver_io_tpu.observability import to_prometheus_text

                text = to_prometheus_text(self.registry).encode()
                head = (
                    "HTTP/1.1 200 OK\r\n"
                    "Content-Type: text/plain; version=0.0.4\r\n"
                    f"Content-Length: {len(text)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode("latin-1")
                await self._write(writer, head + text)
            elif path == "/v1/generate":
                if method != "POST":
                    await self._respond(
                        writer, 405, {"error": "use POST /v1/generate"}
                    )
                else:
                    await self._handle_generate(reader, writer, body)
            else:
                await self._respond(writer, 404, {"error": f"no route {path}"})
        except (ConnectionError, OSError, asyncio.IncompleteReadError,
                asyncio.LimitOverrunError, ValueError):
            # the peer vanished mid-request, or sent a head the reader
            # refuses (oversized request/header line past the StreamReader
            # limit raises ValueError/LimitOverrunError): nothing to answer
            pass
        finally:
            self._active_connections -= 1
            self.registry.set_gauge(
                "gateway_connections_active", self._active_connections
            )
            with contextlib.suppress(Exception):
                writer.close()

    # -- the streaming endpoint ----------------------------------------------
    def _base_config(self):
        """The engine's default GenerationConfig — the template per-request
        ``max_new_tokens`` overrides are built from (fleet: replica 0's)."""
        cfg = getattr(self.engine, "config", None)
        if cfg is None and hasattr(self.engine, "replicas"):
            cfg = self.engine.replicas[0].engine.config
        return cfg

    def _max_new_limit(self, base) -> int:
        """Upper bound on a remote ``max_new_tokens`` override: an
        unauthenticated client must not be able to size device buffers —
        cap at a few context lengths (the slot engine additionally rejects
        prompt + max_new past ONE context at submit)."""
        model = getattr(self.engine, "model", None)
        if model is None and hasattr(self.engine, "replicas"):
            model = self.engine.replicas[0].engine.model
        ctx = getattr(model, "max_seq_len", 0) or 0
        return max(4 * ctx, int(base.max_new_tokens), 1)

    def _parse_generate(self, body: bytes):
        """Validated (prompt_ids, config, mode, deadline_s) from the
        request body; raises ValueError with a client-facing message."""
        try:
            payload = json.loads(body.decode() or "{}")
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise ValueError(f"request body is not valid JSON: {e}")
        if not isinstance(payload, dict):
            raise ValueError("request body must be a JSON object")
        ids = payload.get("prompt_ids")
        if ids is None:
            text = payload.get("prompt")
            if text is None:
                raise ValueError('body needs "prompt" or "prompt_ids"')
            if self._encode is None:
                raise ValueError(
                    'no tokenizer configured: send "prompt_ids" instead of '
                    '"prompt"'
                )
            ids = self._encode(text)
        try:
            prompt = np.asarray(ids, np.int32).reshape(-1)
        except (TypeError, ValueError, OverflowError):
            raise ValueError('"prompt_ids" must be a flat list of token ids')
        mode = payload.get("stream", self.stream_mode)
        if mode not in STREAM_MODES:
            raise ValueError(f'"stream" must be one of {STREAM_MODES}')
        cfg = None
        max_new = payload.get("max_new_tokens")
        if max_new is not None:
            if isinstance(max_new, bool) or not isinstance(max_new, (int, float)):
                raise ValueError('"max_new_tokens" must be a number')
            base = self._base_config()
            if base is None:
                raise ValueError("engine exposes no config to override")
            limit = self._max_new_limit(base)
            if not 1 <= int(max_new) <= limit:
                raise ValueError(
                    f'"max_new_tokens" must be in [1, {limit}] on this '
                    "deployment"
                )
            cfg = dataclasses.replace(base, max_new_tokens=int(max_new))
        deadline_s = payload.get("deadline_s")
        if deadline_s is not None:
            if isinstance(deadline_s, bool) or not isinstance(
                deadline_s, (int, float)
            ):
                raise ValueError('"deadline_s" must be a number of seconds')
            deadline_s = float(deadline_s)
        # scheduling tier + tenant tag (docs/serving.md "Preemption &
        # priorities"): clamped to a small signed range — an
        # unauthenticated client must not be able to claim an unbounded
        # tier any more than it can size device buffers
        priority = payload.get("priority", 0)
        if isinstance(priority, bool) or not isinstance(priority, int):
            raise ValueError('"priority" must be an integer')
        if not -100 <= priority <= 100:
            raise ValueError('"priority" must be in [-100, 100]')
        tenant = payload.get("tenant")
        if tenant is not None:
            if not isinstance(tenant, str) or not tenant or len(tenant) > 128:
                raise ValueError(
                    '"tenant" must be a non-empty string of at most 128 '
                    "characters"
                )
        return prompt, cfg, mode, deadline_s, priority, tenant

    def _event_bytes(self, record: dict, mode: str) -> bytes:
        line = json.dumps(record)
        if mode == "sse":
            return f"data: {line}\n\n".encode()
        return (line + "\n").encode()

    def _cancel_stream(self, stream: _Stream) -> None:
        """Client-disconnect propagation: withdraw the engine request (slot
        + pool pages freed, terminal ``cancelled`` span). A request that
        already finished server-side counts as a completed stream — the
        work was done; only the delivery was abandoned."""
        stream.disconnected = True
        cancelled = False
        try:
            cancelled = self.engine.cancel(stream.handle.request_id)
        except Exception:
            pass
        stream.counted = True
        if cancelled:
            self.registry.inc("gateway_streams_cancelled_total")
            if (
                self.flight_recorder is not None
                and self._disconnect_watch.note()
            ):
                self.flight_recorder.trigger(
                    "mass_disconnect",
                    f"{self._disconnect_watch.threshold} client disconnects "
                    f"within {self._disconnect_watch.window_s}s "
                    f"(stream {stream.stream_id} last)",
                    trace_ids=(
                        [stream.handle.trace_id]
                        if stream.handle.trace_id else []
                    ),
                    stream_id=stream.stream_id,
                    threshold=self._disconnect_watch.threshold,
                    window_s=self._disconnect_watch.window_s,
                )
        else:
            self.registry.inc("gateway_streams_completed_total")

    async def _handle_generate(self, reader, writer, body: bytes) -> None:
        accepted_at = self._clock()  # the socket-accept TTFT anchor
        try:
            prompt, cfg, mode, deadline_s, priority, tenant = \
                self._parse_generate(body)
        except ValueError as e:
            self.registry.inc("gateway_streams_rejected_total")
            await self._respond(writer, 400, {"error": str(e)})
            return
        queue: asyncio.Queue = asyncio.Queue()

        def on_token(index: int, token: int) -> None:
            queue.put_nowait((index, token))

        try:
            handle = self.engine.submit(
                prompt, cfg, deadline_s=deadline_s,
                ttft_anchor_s=accepted_at, on_token=on_token,
                priority=priority, tenant=tenant,
            )
        except QueueFull as e:
            # backpressure maps to 503 + Retry-After: the engine already
            # counted the shed and emitted its terminal span
            self.registry.inc("gateway_streams_rejected_total")
            await self._respond(
                writer, 503,
                {"error": str(e), "trace_id": getattr(e, "trace_id", None)},
                extra_headers="Retry-After: 1\r\n",
            )
            return
        except ValueError as e:
            self.registry.inc("gateway_streams_rejected_total")
            await self._respond(
                writer, 400,
                {"error": str(e), "trace_id": getattr(e, "trace_id", None)},
            )
            return
        except Exception as e:
            # an engine-side bug must answer 500, not kill the handler with
            # a bare connection reset
            self.registry.inc("gateway_streams_rejected_total")
            await self._respond(writer, 500, {"error": f"{type(e).__name__}: {e}"})
            return

        stream = _Stream(
            stream_id=self._next_stream_id, handle=handle, queue=queue,
            accepted_at=accepted_at, mode=mode,
        )
        self._next_stream_id += 1
        self._streams[handle.request_id] = stream
        self.registry.inc("gateway_streams_total")
        # per-tenant wire attribution (docs/observability.md "Scheduler
        # timeline & post-mortems"): accepted streams per sanitized tenant
        # label, rolled up into stats() beside the engine's page/token view
        tkey = tenant_label(tenant)
        self._streams_by_tenant[tkey] = self._streams_by_tenant.get(tkey, 0) + 1
        self.registry.set_gauge("gateway_streams_active", len(self._streams))
        head = (
            "HTTP/1.1 200 OK\r\n"
            f"Content-Type: {_CONTENT_TYPES[mode]}\r\n"
            "Cache-Control: no-cache\r\n"
            "Connection: close\r\n\r\n"
        ).encode("latin-1")
        eof_task = asyncio.ensure_future(reader.read())
        try:
            if not await self._write(writer, head, stream):
                self._cancel_stream(stream)
                return
            await self._stream_tokens(writer, stream, eof_task)
        finally:
            if not stream.counted:
                # the handler was torn down mid-stream (server shutdown /
                # max_streams while this one was in flight): settle the
                # disposition invariant — cancel the engine request (its
                # client can never read the rest) and count the stream, so
                # completed + cancelled == accepted still closes
                self._cancel_stream(stream)
            eof_task.cancel()
            with contextlib.suppress(
                asyncio.CancelledError, ConnectionError, OSError
            ):
                await eof_task
            self._streams.pop(handle.request_id, None)
            self.registry.set_gauge(
                "gateway_streams_active", len(self._streams)
            )
            self._finished_streams += 1
            if self.tracer is not None:
                # the stream's one gateway.request event, on the SAME trace
                # as the engine's serving.request span — the events.jsonl
                # join between wire-level and engine-level accounting
                self.tracer.event(
                    "gateway.request",
                    trace_id=getattr(handle, "trace_id", None),
                    stream_id=stream.stream_id, mode=mode,
                    status="cancelled" if stream.disconnected else handle.status,
                    tokens=stream.sent, bytes=stream.bytes_sent,
                )
            if (
                self.max_streams is not None
                and self._finished_streams >= self.max_streams
                and self._stop_event is not None
            ):
                self._stop_event.set()

    async def _stream_tokens(self, writer, stream: _Stream, eof_task) -> None:
        """Pump the stream's token queue onto the socket until the terminal
        sentinel — or the client disconnects (EOF on the read side, a
        failed write, or a scripted ``gateway.disconnect`` fault)."""
        chaos_site = f"gateway.disconnect.{stream.stream_id}"
        while True:
            get_task = asyncio.ensure_future(stream.queue.get())
            done, _ = await asyncio.wait(
                {get_task, eof_task}, return_when=asyncio.FIRST_COMPLETED
            )
            if get_task not in done:
                # the client closed its end before the stream finished
                get_task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await get_task
                self._cancel_stream(stream)
                return
            item = get_task.result()
            if item is None:  # terminal: the engine disposed of the request
                break
            index, token = item
            if index < stream.sent:
                continue  # failover replay: already on the wire
            if self._chaos is not None:
                fault = self._chaos.hit(chaos_site)
                if fault is not None and fault.kind == "error":
                    # scripted abandonment: the client "vanishes" before
                    # this token is written
                    self._cancel_stream(stream)
                    return
            record = {"index": index, "token": int(token)}
            if self._decode is not None:
                try:
                    record["text"] = self._decode([int(token)])
                except Exception:
                    pass  # undecodable id: the raw token still streams
            first = stream.sent == 0
            if not await self._write(
                writer, self._event_bytes(record, stream.mode), stream
            ):
                self._cancel_stream(stream)
                return
            if first:
                self.registry.observe(
                    "gateway_socket_ttft_ms",
                    (self._clock() - stream.accepted_at) * 1e3,
                )
            stream.sent += 1
        handle = stream.handle
        terminal = {
            "done": True,
            "status": handle.status,
            "request_id": handle.request_id,
            "trace_id": getattr(handle, "trace_id", None),
        }
        if handle.error:
            terminal["error"] = handle.error
        # a failed final flush (client gone at the last instant) still
        # counts completed: the server-side work reached a terminal state
        await self._write(writer, self._event_bytes(terminal, stream.mode), stream)
        stream.counted = True
        self.registry.inc("gateway_streams_completed_total")

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        counts = self.registry.counters()

        def c(name: str) -> int:
            return int(counts.get(name, 0))

        return {
            "address": f"{self.host}:{self.port}",
            "stream_mode": self.stream_mode,
            "connections": c("gateway_connections_total"),
            "streams": c("gateway_streams_total"),
            "streams_completed": c("gateway_streams_completed_total"),
            "streams_cancelled": c("gateway_streams_cancelled_total"),
            "streams_rejected": c("gateway_streams_rejected_total"),
            "bytes_sent": c("gateway_bytes_sent_total"),
            "socket_ttft_ms": {
                "p50": self.registry.percentile("gateway_socket_ttft_ms", 50.0),
                "p95": self.registry.percentile("gateway_socket_ttft_ms", 95.0),
            },
            "driver_errors": len(self.driver_errors),
            "streams_by_tenant": dict(sorted(self._streams_by_tenant.items())),
            # prefix sharing (docs/serving.md "Prefix sharing"): a client
            # disconnect's cancellation reclaim is refcount-aware — the
            # cancelled stream's SHARED pages deref (cached prefixes
            # survive for the next hot admission) while its private pages
            # free immediately, both within the cancel instant
            "engine_prefix_cache": getattr(self.engine, "prefix_cache", None),
        }


_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 413: "Payload Too Large",
    500: "Internal Server Error", 503: "Service Unavailable",
}
