"""Static shape-bucket grid for the serving engine.

The generation path compiles one executor per exact static plan
(``generate._generation_executor``), so ragged real traffic — every caller
with its own batch width and prompt length — causes unbounded retracing at
~1.5 s per miss. The fix TPU serving stacks converge on (PAPERS.md: the
"Ragged Paged Attention" TPU-serving paper, the Gemma-on-TPU serving
comparison) is to pad every request up to a small static grid of
``(batch_size, prompt_len)`` shapes: at most ``len(table)`` executors ever
exist, all pre-compilable ahead of traffic, and the padding waste stays
under 2x with powers-of-two rounding.

:class:`BucketTable` is that grid — pure shape arithmetic, no model or jax
dependency. Feasibility against a concrete model (context length, prefix
capacity) is the engine's job (``engine.ServingEngine``).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Tuple


def _pow2_span(lo: int, hi: int) -> Tuple[int, ...]:
    """Powers of two starting at ``lo``, ending with ``hi`` itself (the last
    bucket covers the range exactly even when ``hi`` is not a power of two)."""
    vals = []
    v = max(1, int(lo))
    while v < hi:
        vals.append(v)
        v *= 2
    vals.append(int(hi))
    return tuple(vals)


@dataclass(frozen=True)
class BucketTable:
    """Grid of compile shapes: every served micro-batch is padded to one
    ``(batch_size, prompt_len)`` cell.

    Both axes must be strictly increasing; a request rounds *up* to the
    smallest bucket that fits (:meth:`prompt_bucket`, :meth:`batch_bucket`).
    """

    prompt_lens: Tuple[int, ...]
    batch_sizes: Tuple[int, ...]

    def __post_init__(self):
        for name in ("prompt_lens", "batch_sizes"):
            vals = tuple(int(v) for v in getattr(self, name))
            if not vals or any(v <= 0 for v in vals) or vals != tuple(sorted(set(vals))):
                raise ValueError(
                    f"{name} must be a non-empty, positive, strictly "
                    f"increasing sequence, got {getattr(self, name)!r}"
                )
            object.__setattr__(self, name, vals)

    @classmethod
    def for_model(cls, model, *, max_batch_size: int = 8, min_prompt_len: int = 16) -> "BucketTable":
        """Power-of-two grid up to the model's context length."""
        n = int(model.max_seq_len)
        return cls(
            prompt_lens=_pow2_span(min(min_prompt_len, n), n),
            batch_sizes=_pow2_span(1, max_batch_size),
        )

    def prompt_bucket(self, length: int) -> int:
        """Smallest prompt bucket >= ``length``; raises when none fits."""
        for cap in self.prompt_lens:
            if cap >= length:
                return cap
        raise ValueError(
            f"prompt length {length} exceeds the largest bucket "
            f"{self.prompt_lens[-1]}; extend the bucket table"
        )

    def batch_bucket(self, n: int) -> int:
        """Smallest batch bucket >= ``n``, else the largest bucket (the
        caller chunks oversized groups across micro-batches)."""
        for cap in self.batch_sizes:
            if cap >= n:
                return cap
        return self.batch_sizes[-1]

    def grid(self) -> Iterator[Tuple[int, int]]:
        """All (batch_size, prompt_len) cells — the warmup compile set."""
        for b in self.batch_sizes:
            for length in self.prompt_lens:
                yield b, length

    def __len__(self) -> int:
        return len(self.prompt_lens) * len(self.batch_sizes)
