# TPU-host image for perceiver_io_tpu — the role the reference's Dockerfile
# plays for its CUDA/torch stack (reference Dockerfile:1), re-based on the
# JAX TPU wheel. On a Cloud TPU VM the libtpu runtime is injected by the
# `jax[tpu]` extra; the same image runs CPU-only for tests.
FROM python:3.12-slim

WORKDIR /app

RUN apt-get update \
    && apt-get install -y --no-install-recommends build-essential \
    && rm -rf /var/lib/apt/lists/*

COPY pyproject.toml README.md ./
COPY perceiver_io_tpu ./perceiver_io_tpu

# TPU runtime: jax[tpu] pulls libtpu from the Google releases index.
RUN pip install --no-cache-dir \
    --find-links https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    "jax[tpu]" \
    && pip install --no-cache-dir ".[text,vision,audio]"

COPY tests ./tests
COPY examples ./examples
COPY bench.py Makefile ./

CMD ["python", "-c", "import jax, perceiver_io_tpu; print(jax.devices())"]
