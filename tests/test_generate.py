"""Generation tests.

``TestReferenceParity`` is the strongest oracle: greedy decode must produce
the *exact* token sequence the torch reference produces through HF
``GenerationMixin`` (reference ``perceiver/model/text/clm/huggingface.py``),
with the same weights, across all three window phases (latent growth →
prefix growth → sliding window). The remaining tests cover samplers and the
boundary validation the reference tests in
``tests/causal_language_model_generate_test.py:23-68``.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from tests._reference import load_reference

import perceiver_io_tpu.convert as convert
from perceiver_io_tpu.inference import SamplingConfig, generate
from perceiver_io_tpu.inference.generate import GenerationConfig
from perceiver_io_tpu.inference.samplers import NEG_INF, apply_top_k, apply_top_p
from perceiver_io_tpu.models.text.clm import CausalLanguageModel, CausalLanguageModelConfig

ref = load_reference()
pytestmark = pytest.mark.skipif(ref is None, reason="reference tree unavailable")

KW = dict(
    vocab_size=32,
    max_seq_len=16,
    max_latents=8,
    num_channels=16,
    num_heads=2,
    num_self_attention_layers=2,
    cross_attention_dropout=0.5,  # inactive at inference
    init_scale=0.1,
)


@pytest.fixture(scope="module")
def models():
    torch.manual_seed(0)
    t_model = ref.clm.CausalLanguageModel(ref.clm.CausalLanguageModelConfig(**KW)).eval()
    j_config = CausalLanguageModelConfig(**KW)
    j_model = CausalLanguageModel(config=j_config)
    params = convert.import_causal_language_model(t_model.state_dict(), j_config)
    return t_model, j_model, params


def reference_generate(t_model, input_ids, num_latents, max_new_tokens, **gen_kwargs):
    """Drive the reference HF wrapper's generate loop (greedy by default)."""
    import importlib

    from transformers import GenerationMixin

    hf = importlib.import_module("perceiver.model.text.clm.huggingface")

    # transformers >= 4.50 no longer mixes GenerationMixin into
    # PreTrainedModel; the reference targets the old behavior. Restore it.
    class Wrapper(hf.PerceiverCausalLanguageModel, GenerationMixin):
        pass

    config = hf.PerceiverCausalLanguageModelConfig(t_model.config)
    config.is_decoder = True
    # appease the newer GenerationMixin (the reference has no KV cache)
    config.use_cache = False
    config.num_hidden_layers = t_model.config.num_self_attention_layers
    # transformers >= 4.5x beam search reads config.vocab_size to split the
    # flattened (beams * vocab) candidate index; unset it crashes the oracle.
    config.vocab_size = t_model.config.vocab_size
    wrapper = Wrapper(config, backend_model=t_model)
    out = wrapper.generate(
        input_ids=torch.tensor(input_ids),
        num_latents=num_latents,
        max_new_tokens=max_new_tokens,
        min_new_tokens=max_new_tokens,
        do_sample=False,
        pad_token_id=0,
        **gen_kwargs,
    )
    return out[:, input_ids.shape[1] :].numpy()


def reference_generate_greedy(t_model, input_ids, num_latents, max_new_tokens):
    return reference_generate(t_model, input_ids, num_latents, max_new_tokens)


class TestReferenceParity:
    @pytest.mark.parametrize(
        "prompt_len,num_latents,new_tokens",
        [
            (4, 2, 4),    # stays in latent growth
            (4, 2, 20),   # crosses latent growth -> prefix growth -> slide
            (12, 8, 12),  # starts at max latents, crosses into slide
            (16, 8, 6),   # starts with a full window (immediate slide)
        ],
    )
    def test_greedy_token_exact(self, models, prompt_len, num_latents, new_tokens):
        t_model, j_model, params = models
        ids = np.random.default_rng(1).integers(1, KW["vocab_size"], (2, prompt_len))

        expected = reference_generate_greedy(t_model, ids, num_latents, new_tokens)
        got = generate(
            j_model,
            params,
            jnp.asarray(ids),
            GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents),
        )
        np.testing.assert_array_equal(np.asarray(got), expected)


def _sequence_logprob(j_model, params, prompt_row, seq_row, num_latents):
    """Teacher-forced total log-prob of ``seq_row`` after ``prompt_row``,
    along the same right-aligned static-window decode path beam search uses."""
    from perceiver_io_tpu.inference.generate import _decode_forward

    n = j_model.max_seq_len
    prompt_len = len(prompt_row)
    window = np.zeros((1, n), np.int32)
    window[0, n - prompt_len:] = prompt_row
    pad_count = np.array([n - prompt_len], np.int32)
    m = min(prompt_len, num_latents)
    total = 0.0
    for tok in seq_row:
        logits = j_model.apply(
            {"params": params}, jnp.asarray(window), jnp.asarray(pad_count),
            jnp.asarray(m, jnp.int32), method=_decode_forward,
        )
        logp = jax.nn.log_softmax(np.asarray(logits, np.float64))
        total += float(logp[0, int(tok)])
        window = np.concatenate([window[:, 1:], [[int(tok)]]], axis=1)
        pad_count = np.maximum(pad_count - 1, 0)
        m = min(m + 1, j_model.max_latents)
    return total


class TestBeamParity:
    """Beam decode vs the torch reference through HF ``generate(num_beams=k)``
    (reference ``tests/causal_language_model_pipeline_test.py:37-38``).

    Token-exact equality is asserted when it holds, but it is *environmentally
    unstable by nature*: beam search argmaxes over accumulated fp32 scores, and
    cross-framework logit noise (torch/oneDNN vs XLA, ~1e-4 per step at this
    scale) flips candidate order at genuine near-ties. Measured on the
    (4,2,14,3) case: at step 3 the two frontrunner continuations differ by
    1.3e-4 in accumulated score; the eager re-implementation of HF-4.57 beam
    semantics driven by *our* logits (tests/_eager_beam.py, pinned
    token-exactly by TestEagerBeamBookkeeping below) reproduces our scan's
    choice exactly, so the divergence is numeric, not bookkeeping. The
    fallback oracle therefore asserts both searches found near-equally-good
    sequences: length-normalized teacher-forced scores (under the same jax
    model) within 0.02 nats."""

    @pytest.mark.parametrize(
        "prompt_len,num_latents,new_tokens,num_beams",
        [
            (4, 2, 4, 3),     # latent growth only
            (4, 2, 14, 3),    # crosses prefix growth and slide
            (12, 8, 10, 2),   # starts at max latents
        ],
    )
    def test_beam_token_parity(self, models, prompt_len, num_latents, new_tokens, num_beams):
        t_model, j_model, params = models
        ids = np.random.default_rng(4).integers(1, KW["vocab_size"], (2, prompt_len))

        expected = reference_generate(
            t_model, ids, num_latents, new_tokens, num_beams=num_beams
        )
        got = np.asarray(
            generate(
                j_model,
                params,
                jnp.asarray(ids),
                GenerationConfig(
                    max_new_tokens=new_tokens,
                    num_latents=num_latents,
                    num_beams=num_beams,
                    min_new_tokens=new_tokens,
                ),
            )
        )
        if np.array_equal(got, expected):
            return
        # Near-tie fallback: both must be (near-)optimal beam outcomes.
        eff_latents = min(prompt_len, num_latents)
        for r in range(got.shape[0]):
            if np.array_equal(got[r], expected[r]):
                continue
            ours = _sequence_logprob(j_model, params, ids[r], got[r], eff_latents)
            ref_score = _sequence_logprob(j_model, params, ids[r], expected[r], eff_latents)
            gap = abs(ours - ref_score) / new_tokens
            assert gap < 0.02, (
                f"row {r}: beam outputs diverge beyond near-tie tolerance: "
                f"ours={ours:.4f} ref={ref_score:.4f} gap/token={gap:.4f}\n"
                f"ours tokens={got[r].tolist()}\nref tokens={expected[r].tolist()}"
            )

    def test_beam_eos_pads_tail(self, models):
        # Standalone EOS behavior: once a hypothesis finishes, its tail is pad.
        _, j_model, params = models
        ids = np.random.default_rng(8).integers(1, KW["vocab_size"], (2, 4))
        out = np.asarray(
            generate(
                j_model,
                params,
                jnp.asarray(ids),
                GenerationConfig(
                    max_new_tokens=10,
                    num_latents=2,
                    num_beams=3,
                    eos_token_id=5,
                    pad_token_id=0,
                ),
            )
        )
        assert out.shape == (2, 10)
        for row in out:
            hits = np.where(row == 5)[0]
            if hits.size:
                assert (row[hits[0] + 1 :] == 0).all()


class TestEagerBeamBookkeeping:
    """The near-tie fallback in TestBeamParity is sound only while "our
    logits through exact HF beam bookkeeping = our scan" holds (VERDICT r3
    ask #6). This pins it: an independent imperative HF-4.57-style beam
    search (tests/_eager_beam.py), fed the SAME jax logits, must match the
    scan token-for-token with ZERO tolerance — both searches see
    bit-identical fp32 scores, so near-ties cannot excuse a mismatch. A
    bookkeeping regression in inference/beam.py that stays inside the
    0.02-nat parity tolerance fails here."""

    @pytest.mark.parametrize(
        "prompt_len,num_latents,new_tokens,num_beams",
        [
            (4, 2, 4, 3),     # latent growth only
            (4, 2, 14, 3),    # crosses prefix growth and slide
            (12, 8, 10, 2),   # starts at max latents
        ],
    )
    def test_scan_matches_eager_bookkeeping(
        self, models, prompt_len, num_latents, new_tokens, num_beams
    ):
        from tests._eager_beam import eager_beam_search

        _, j_model, params = models
        ids = np.random.default_rng(4).integers(1, KW["vocab_size"], (2, prompt_len))
        cfg = GenerationConfig(
            max_new_tokens=new_tokens,
            num_latents=num_latents,
            num_beams=num_beams,
            min_new_tokens=new_tokens,
        )
        got = np.asarray(generate(j_model, params, jnp.asarray(ids), cfg))
        want = eager_beam_search(j_model, params, ids, cfg)
        np.testing.assert_array_equal(got, want)

    def test_scan_matches_eager_bookkeeping_with_eos(self, models):
        """EOS path: hypothesis-pool insertion, worst-eviction, and
        finalization against live beams must also agree exactly. The EOS id
        is chosen from a beam continuation so the path genuinely fires."""
        from tests._eager_beam import eager_beam_search

        _, j_model, params = models
        ids = np.random.default_rng(8).integers(1, KW["vocab_size"], (2, 4))
        base = GenerationConfig(max_new_tokens=10, num_latents=2, num_beams=3)
        probe = np.asarray(generate(j_model, params, jnp.asarray(ids), base))
        fired = False
        for eos in {int(probe[0, 2]), int(probe[1, 5]), 5}:
            # pad_token_id deliberately nonzero: post-EOS slots must carry
            # the configured pad, not the buffer's fill value (a real scan
            # bug this checker caught on first use).
            cfg = GenerationConfig(
                max_new_tokens=10, num_latents=2, num_beams=3,
                eos_token_id=eos, pad_token_id=7,
            )
            got = np.asarray(generate(j_model, params, jnp.asarray(ids), cfg))
            want = eager_beam_search(j_model, params, ids, cfg)
            np.testing.assert_array_equal(got, want)
            fired = fired or (got == eos).any()
        assert fired, "no EOS ever fired — the hypothesis-pool path went untested"


class TestValidation:
    def test_empty_prompt_rejected(self, models):
        _, j_model, params = models
        with pytest.raises(ValueError, match="out of valid range"):
            generate(j_model, params, jnp.zeros((1, 0), jnp.int32), GenerationConfig())

    def test_overlong_prompt_rejected(self, models):
        _, j_model, params = models
        with pytest.raises(ValueError, match="out of valid range"):
            generate(j_model, params, jnp.zeros((1, 17), jnp.int32), GenerationConfig())

    def test_invalid_num_latents_rejected(self, models):
        _, j_model, params = models
        for bad in (0, 9):
            with pytest.raises(ValueError, match="num_latents"):
                generate(
                    j_model,
                    params,
                    jnp.zeros((1, 4), jnp.int32),
                    GenerationConfig(num_latents=bad),
                )

    def test_prefix_overflow_rejected(self, models):
        _, j_model, params = models
        # prompt 16, num_latents 4 -> prefix 12 > max_prefix_len 8
        with pytest.raises(ValueError, match="num_latents must be >="):
            generate(
                j_model,
                params,
                jnp.zeros((1, 16), jnp.int32),
                GenerationConfig(num_latents=4),
            )

    def test_sampling_shapes_and_eos(self, models):
        _, j_model, params = models
        ids = np.random.default_rng(2).integers(1, 32, (3, 6))
        out = generate(
            j_model,
            params,
            jnp.asarray(ids),
            GenerationConfig(
                max_new_tokens=10,
                num_latents=4,
                eos_token_id=5,
                pad_token_id=0,
                sampling=SamplingConfig(do_sample=True, temperature=0.8, top_k=10),
            ),
            rng=jax.random.PRNGKey(0),
        )
        out = np.asarray(out)
        assert out.shape == (3, 10)
        for row in out:
            hits = np.where(row == 5)[0]
            if hits.size:  # everything after EOS is pad
                assert (row[hits[0] + 1 :] == 0).all()


class TestSamplers:
    def test_top_k(self):
        logits = jnp.asarray([[1.0, 3.0, 2.0, 0.0]])
        out = np.asarray(apply_top_k(logits, 2))
        assert out[0, 1] == 3.0 and out[0, 2] == 2.0
        assert out[0, 0] == NEG_INF and out[0, 3] == NEG_INF

    def test_top_p_keeps_most_probable(self):
        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
        out = np.asarray(apply_top_p(logits, 0.7))
        # 0.5 kept; 0.3 kept (cum before it 0.5 < 0.7); 0.15 dropped (0.8 >= 0.7)
        assert np.isfinite(out[0, :2]).all()
        assert out[0, 2] == NEG_INF and out[0, 3] == NEG_INF

    def test_top_p_always_keeps_argmax(self):
        logits = jnp.log(jnp.asarray([[0.9, 0.1]]))
        out = np.asarray(apply_top_p(logits, 0.5))
        assert np.isfinite(out[0, 0])


class TestKVCacheEquivalence:
    """The cached latent-growth fast path must match windowed recompute
    exactly (same weights, same rng stream)."""

    @pytest.mark.parametrize(
        "prompt_len,num_latents,new_tokens",
        [
            (4, 2, 4),    # stays in latent growth
            (4, 2, 8),    # crosses latent growth -> prefix growth
            (4, 2, 20),   # crosses all three phases (growth -> prefix -> slide)
            (12, 8, 12),  # starts in prefix growth (m == max_latents), crosses slide
            (16, 8, 6),   # full window from the start (slide only)
            (5, 5, 14),   # no initial prefix, all-latent prompt
        ],
    )
    def test_cache_matches_recompute(self, models, prompt_len, num_latents, new_tokens):
        _, j_model, params = models
        ids = jnp.asarray(
            np.random.default_rng(3).integers(1, KW["vocab_size"], (2, prompt_len))
        )
        cfg = GenerationConfig(max_new_tokens=new_tokens, num_latents=num_latents)
        cached = generate(j_model, params, ids, cfg, use_cache=True)
        recomputed = generate(j_model, params, ids, cfg, use_cache=False)
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(recomputed))

    def test_cache_with_ragged_prompts_and_sampling(self, models):
        _, j_model, params = models
        ids = jnp.asarray([[0, 0, 5, 6, 7], [2, 3, 4, 5, 6]], jnp.int32)
        pad = jnp.asarray([2, 0], jnp.int32)
        cfg = GenerationConfig(
            max_new_tokens=6, num_latents=2,
            sampling=SamplingConfig(temperature=0.8, top_k=8),
        )
        rng = jax.random.PRNGKey(7)
        cached = generate(j_model, params, ids, cfg, rng=rng, prompt_pad_count=pad)
        recomputed = generate(
            j_model, params, ids, cfg, rng=rng, prompt_pad_count=pad, use_cache=False
        )
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(recomputed))

    def test_cache_ragged_crossing_prefix_growth(self, models):
        # pads (2) fit within the nominal prefix (8 - 3 = 5), so the
        # boundary-phase cache stays eligible; run crosses all three phases.
        _, j_model, params = models
        ids = jnp.asarray(
            np.random.default_rng(5).integers(1, KW["vocab_size"], (2, 8)), jnp.int32
        )
        pad = jnp.asarray([2, 0], jnp.int32)
        cfg = GenerationConfig(max_new_tokens=14, num_latents=3)
        cached = generate(j_model, params, ids, cfg, prompt_pad_count=pad)
        recomputed = generate(
            j_model, params, ids, cfg, prompt_pad_count=pad, use_cache=False
        )
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(recomputed))

    def test_cache_falls_back_when_pads_exceed_prefix(self, models):
        # A row with more pads than the nominal prefix would put pad tokens in
        # latent slots during prefix growth; the cache must detect this and
        # fall back to exact windowed recompute for those steps.
        _, j_model, params = models
        ids = jnp.asarray(
            np.random.default_rng(6).integers(1, KW["vocab_size"], (2, 8)), jnp.int32
        )
        pad = jnp.asarray([4, 0], jnp.int32)  # 4 > prefix_len 8 - 6 = 2
        cfg = GenerationConfig(max_new_tokens=12, num_latents=6)
        cached = generate(j_model, params, ids, cfg, prompt_pad_count=pad)
        recomputed = generate(
            j_model, params, ids, cfg, prompt_pad_count=pad, use_cache=False
        )
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(recomputed))


class TestRepetitionPenalty:
    def test_matches_hf_processor(self):
        """apply_repetition_penalty == transformers'
        RepetitionPenaltyLogitsProcessor on shared inputs."""
        from transformers import RepetitionPenaltyLogitsProcessor

        from perceiver_io_tpu.inference.samplers import apply_repetition_penalty

        rng = np.random.default_rng(0)
        logits = rng.standard_normal((3, 32)).astype(np.float32)
        ids = rng.integers(0, 32, (3, 10))
        expected = (
            RepetitionPenaltyLogitsProcessor(1.7)(
                torch.tensor(ids), torch.tensor(logits)
            ).numpy()
        )
        got = np.asarray(
            apply_repetition_penalty(jnp.asarray(logits), jnp.asarray(ids), 1.7)
        )
        np.testing.assert_allclose(got, expected, rtol=1e-6)

    def test_mask_excludes_padding(self):
        from perceiver_io_tpu.inference.samplers import apply_repetition_penalty

        logits = jnp.asarray([[1.0, 2.0, 3.0, 4.0]])
        ids = jnp.asarray([[0, 1]])
        mask = jnp.asarray([[True, False]])  # position 0 is padding
        out = np.asarray(apply_repetition_penalty(logits, ids, 2.0, mask))
        assert out[0, 0] == 1.0   # pad slot's id NOT penalized
        assert out[0, 1] == 1.0   # 2.0 / 2.0
        assert out[0, 2] == 3.0 and out[0, 3] == 4.0

    def test_generate_with_penalty_cache_equivalence(self, models):
        _, j_model, params = models
        ids = jnp.asarray(
            np.random.default_rng(9).integers(1, KW["vocab_size"], (2, 4))
        )
        cfg = GenerationConfig(
            max_new_tokens=12, num_latents=2,
            sampling=SamplingConfig(repetition_penalty=1.5),
        )
        cached = generate(j_model, params, ids, cfg, use_cache=True)
        recomputed = generate(j_model, params, ids, cfg, use_cache=False)
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(recomputed))
        # penalty changes the greedy trajectory vs no penalty
        plain = generate(
            j_model, params, ids,
            GenerationConfig(max_new_tokens=12, num_latents=2),
        )
        assert not np.array_equal(np.asarray(cached), np.asarray(plain))

    def test_beam_honors_repetition_penalty(self, models):
        # the penalty must change beam output (HF applies processors under
        # beam search too), and a penalty of 1.0 must be a no-op
        _, j_model, params = models
        ids = jnp.asarray(
            np.random.default_rng(11).integers(1, KW["vocab_size"], (2, 4))
        )
        base = GenerationConfig(max_new_tokens=10, num_latents=2, num_beams=3)
        with_p = GenerationConfig(
            max_new_tokens=10, num_latents=2, num_beams=3,
            sampling=SamplingConfig(repetition_penalty=2.0),
        )
        out_base = np.asarray(generate(j_model, params, ids, base))
        out_p = np.asarray(generate(j_model, params, ids, with_p))
        assert not np.array_equal(out_base, out_p)


class TestMinNewTokens:
    def test_eos_blocked_until_min(self, models):
        """HF MinNewTokensLengthLogitsProcessor parity in greedy decode:
        force an EOS-favoring model; no EOS may appear before min_new."""
        _, j_model, params = models
        ids = np.random.default_rng(12).integers(1, KW["vocab_size"], (2, 4))
        # find the greedy first token so we can declare it "EOS"
        probe = np.asarray(
            generate(
                j_model, params, jnp.asarray(ids),
                GenerationConfig(max_new_tokens=1, num_latents=2),
            )
        )
        eos = int(probe[0, 0])
        out = np.asarray(
            generate(
                j_model, params, jnp.asarray(ids),
                GenerationConfig(
                    max_new_tokens=10, num_latents=2,
                    eos_token_id=eos, pad_token_id=0, min_new_tokens=6,
                ),
            )
        )
        # row 0 would emit eos at step 0 without the mask
        assert (out[0, :6] != eos).all(), out[0]

    def test_cache_equivalence_with_min_new(self, models):
        _, j_model, params = models
        ids = jnp.asarray(
            np.random.default_rng(13).integers(1, KW["vocab_size"], (2, 5))
        )
        cfg = GenerationConfig(
            max_new_tokens=14, num_latents=2, eos_token_id=5,
            pad_token_id=0, min_new_tokens=8,
        )
        cached = generate(j_model, params, ids, cfg, use_cache=True)
        recomputed = generate(j_model, params, ids, cfg, use_cache=False)
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(recomputed))


class TestSamplerHFParity:
    """Filter parity against transformers' own warpers on shared logits
    (the unit tests above pin our semantics; these pin HF equivalence)."""

    def test_top_k_matches_hf(self):
        from transformers import TopKLogitsWarper

        rng = np.random.default_rng(0)
        logits = rng.standard_normal((4, 64)).astype(np.float32)
        expected = TopKLogitsWarper(top_k=7)(None, torch.tensor(logits)).numpy()
        got = np.asarray(apply_top_k(jnp.asarray(logits), 7))
        # HF masks with -inf, ours with float32 min — compare the survivors
        np.testing.assert_array_equal(np.isfinite(got) & (got > NEG_INF),
                                      np.isfinite(expected))
        keep = np.isfinite(expected)
        np.testing.assert_allclose(got[keep], expected[keep], rtol=1e-6)

    def test_top_p_matches_hf(self):
        from transformers import TopPLogitsWarper

        rng = np.random.default_rng(1)
        logits = rng.standard_normal((4, 64)).astype(np.float32)
        expected = TopPLogitsWarper(top_p=0.8)(None, torch.tensor(logits)).numpy()
        got = np.asarray(apply_top_p(jnp.asarray(logits), 0.8))
        np.testing.assert_array_equal(got > NEG_INF, np.isfinite(expected))
        keep = np.isfinite(expected)
        np.testing.assert_allclose(got[keep], expected[keep], rtol=1e-6)
