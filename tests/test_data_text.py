"""Text data layer tests — the offline analogue of the reference's
``tests/text_data_module_test.py`` (SURVEY.md §4.2): masking-rate statistics,
CLM shift-by-one, padding behavior, random truncation, sharded loading."""
import numpy as np
import pytest

from perceiver_io_tpu.data import DataLoader
from perceiver_io_tpu.data.text import (
    ByteTokenizer,
    ListDataModule,
    StreamingTextPipeline,
    Task,
    TextPreprocessor,
    WordMaskingCollator,
    load_tokenizer,
    shard_iterable,
    window_shuffle,
)
from perceiver_io_tpu.data.text.collators import IGNORE_INDEX

TEXTS = [
    "The quick brown fox jumps over the lazy dog. " * 8,
    "Perceiver IO scales linearly with input size, not quadratically. " * 8,
    "TPU meshes shard computation across data and model axes. " * 8,
    "Latent bottlenecks keep attention cost independent of input length. " * 8,
] * 8


def make_dm(tmp_path, task, **kwargs):
    dm = ListDataModule(
        train_texts=TEXTS,
        valid_texts=TEXTS[:8],
        dataset_dir=str(tmp_path / "ds"),
        tokenizer="byte",
        max_seq_len=64,
        task=task,
        batch_size=4,
        **kwargs,
    )
    dm.prepare_data()
    dm.setup()
    return dm


class TestByteTokenizer:
    def test_roundtrip(self):
        t = ByteTokenizer()
        text = "héllo wörld\n"
        assert t.decode(t.encode(text)) == text

    def test_matches_transformers_perceiver_tokenizer(self):
        # Oracle: transformers' PerceiverTokenizer uses the same byte+6 layout.
        from transformers import PerceiverTokenizer

        ref = PerceiverTokenizer()
        ours = ByteTokenizer()
        text = "Byte-level parity test: åß∂ 123"
        assert ours.encode(text) == ref(text, add_special_tokens=False)["input_ids"]

    def test_batch_padding_sides(self):
        for side in ("left", "right"):
            t = ByteTokenizer(padding_side=side)
            ids, mask = t.encode_batch(["abc", "a"], max_length=5)
            assert ids.shape == (2, 5)
            n_pad = (ids[1] == t.pad_token_id).sum()
            assert n_pad == 4
            if side == "left":
                assert mask[1, :4].all() and not mask[1, 4]
            else:
                assert not mask[1, 0] and mask[1, 1:].all()

    def test_word_ids_whitespace_boundaries(self):
        t = ByteTokenizer()
        ids = t.encode("ab cd  ef")
        wids = t.word_ids(ids)
        # distinct words -> distinct ids; whitespace joins the following word
        assert wids[0] == wids[1]  # 'a','b'
        assert wids[2] == wids[3] == wids[4]  # ' ','c','d'
        assert wids[1] != wids[2] and wids[4] != wids[5]


class TestClmPipeline:
    def test_shift_by_one(self, tmp_path):
        dm = make_dm(tmp_path, Task.clm)
        batch = next(iter(dm.train_dataloader()))
        assert batch["input_ids"].shape == (4, 64)
        np.testing.assert_array_equal(batch["input_ids"][:, 1:], batch["labels"][:, :-1])
        assert not batch["pad_mask"].any()  # full chunks, no padding

    def test_cache_reused(self, tmp_path):
        dm = make_dm(tmp_path, Task.clm)
        fingerprint = dm.ds_train.dataset.input_ids[:2].copy()
        dm2 = make_dm(tmp_path, Task.clm)
        np.testing.assert_array_equal(dm2.ds_train.dataset.input_ids[:2], fingerprint)

    def test_random_shift_concatenates_neighbors(self, tmp_path):
        dm = make_dm(tmp_path, Task.clm, random_train_shift=True)
        ex = dm.ds_train[0]
        assert len(ex["input_ids"]) == 64  # still chunk_size - 1 after clm view


class TestMlmPipeline:
    def test_dynamic_word_masking_statistics(self, tmp_path):
        dm = make_dm(tmp_path, Task.mlm, mask_prob=0.15)
        mask_id = dm.tokenizer.mask_token_id
        masked = total = mask_tok = 0
        for batch in dm.train_dataloader():
            sel = batch["labels"] != IGNORE_INDEX
            masked += sel.sum()
            total += sel.size
            mask_tok += (batch["input_ids"][sel] == mask_id).sum()
        # ≈ mask_prob of tokens selected; ≈80% of selected become [MASK]
        assert 0.08 < masked / total < 0.25
        assert 0.65 < mask_tok / masked < 0.92

    def test_static_masking(self, tmp_path):
        dm = make_dm(tmp_path, Task.mlm, static_masking=True)
        batch = next(iter(dm.train_dataloader()))
        sel = batch["labels"] != IGNORE_INDEX
        assert sel.any()
        # statically masked: unmasked positions untouched
        assert (batch["input_ids"][~sel] != dm.tokenizer.mask_token_id).all()

    def test_labels_match_originals_at_masked_positions(self, tmp_path):
        dm = make_dm(tmp_path, Task.mlm)
        raw = dm.ds_train[0]
        wmc = WordMaskingCollator(dm.tokenizer, 0.5, seed=0)
        ids, labels = wmc.mask_example(raw["input_ids"], raw["word_ids"])
        sel = labels != IGNORE_INDEX
        np.testing.assert_array_equal(labels[sel], np.asarray(raw["input_ids"])[sel])
        unchanged = ids[~sel] == np.asarray(raw["input_ids"])[~sel]
        assert unchanged.all()


class TestClfPipeline:
    def test_labels_and_padding(self, tmp_path):
        dm = ListDataModule(
            train_texts=["good " * 3, "bad " * 40],
            valid_texts=["meh"],
            train_labels=[1, 0],
            valid_labels=[0],
            num_classes=2,
            dataset_dir=str(tmp_path / "clf"),
            tokenizer="byte",
            max_seq_len=32,
            task=Task.clf,
            batch_size=2,
        )
        dm.prepare_data()
        dm.setup()
        batch = next(iter(dm.train_dataloader()))
        assert batch["labels"].shape == (2,)
        assert set(batch["labels"].tolist()) == {0, 1}
        assert batch["input_ids"].shape == (2, 32)
        # short example padded, long example truncated to max_seq_len
        assert batch["pad_mask"].sum(axis=1).min() == 0
        assert batch["pad_mask"].sum(axis=1).max() == 32 - len("good " * 3)


class TestRandomTruncation:
    def test_static_shape_with_masked_tail(self, tmp_path):
        dm = make_dm(tmp_path, Task.clm, random_train_truncation=True, random_min_seq_len=16)
        shapes, tails = set(), []
        loader = dm.train_dataloader()
        for batch in loader:
            shapes.add(batch["input_ids"].shape)
            tails.append(batch["pad_mask"][:, -1].all())
        assert shapes == {(4, 64)}  # static width always
        assert any(tails)  # some batches actually truncated
        for batch in loader:
            assert (batch["labels"][batch["pad_mask"]] == IGNORE_INDEX).all()
            break


class TestLoader:
    def test_sharding_partitions_indices(self):
        ds = [{"x": np.asarray([i])} for i in range(100)]
        seen = []
        for shard in range(4):
            loader = DataLoader(
                ds, batch_size=5, shuffle=True, seed=3, shard_index=shard, shard_count=4
            )
            for batch in loader:
                seen.extend(batch["x"][:, 0].tolist())
        assert sorted(seen) == list(range(100))

    def test_epoch_reshuffle(self):
        ds = [{"x": np.asarray([i])} for i in range(64)]
        loader = DataLoader(ds, batch_size=64, shuffle=True, seed=0, shard_index=0, shard_count=1)
        first = next(iter(loader))["x"][:, 0].tolist()
        second = next(iter(loader))["x"][:, 0].tolist()
        assert first != second and sorted(first) == sorted(second)


class TestStreaming:
    def test_stream_chunks_and_shift(self):
        pipe = StreamingTextPipeline(
            lambda: iter(TEXTS),
            "byte",
            max_seq_len=32,
            batch_size=2,
            shard_index=0,
            shard_count=1,
        )
        batch = next(iter(pipe))
        assert batch["input_ids"].shape == (2, 32)
        np.testing.assert_array_equal(batch["input_ids"][:, 1:], batch["labels"][:, :-1])

    def test_sharded_streams_are_disjoint(self):
        def collect(shard):
            pipe = StreamingTextPipeline(
                lambda: (f"doc {i} content here" for i in range(50)),
                "byte",
                max_seq_len=16,
                batch_size=1,
                shard_index=shard,
                shard_count=2,
            )
            return np.concatenate([b["input_ids"].ravel() for b in pipe])

        a, b = collect(0), collect(1)
        assert not np.array_equal(a[:64], b[:64])

    def test_min_seq_len_masks_tail(self):
        pipe = StreamingTextPipeline(
            lambda: iter(TEXTS),
            "byte",
            max_seq_len=32,
            min_seq_len=8,
            batch_size=4,
            shard_index=0,
            shard_count=1,
        )
        batch = next(iter(pipe))
        assert batch["input_ids"].shape == (4, 32)
        assert batch["pad_mask"].any()
        assert (batch["labels"][batch["pad_mask"]] == IGNORE_INDEX).all()

    def test_window_shuffle_is_permutation(self):
        out = list(window_shuffle(range(100), window_size=10, seed=0))
        assert sorted(out) == list(range(100)) and out != list(range(100))

    def test_shard_iterable(self):
        assert list(shard_iterable(range(10), 1, 3)) == [1, 4, 7]


class TestPreprocessor:
    def test_inference_preprocess(self):
        p = TextPreprocessor("byte", max_seq_len=16)
        ids, mask = p.preprocess_batch(["hello", "a much longer sentence than sixteen bytes"])
        assert ids.shape[1] <= 16
        assert not mask[1].any()  # truncated, no padding

    def test_hf_tokenizer_protocol(self):
        t = load_tokenizer("byte")
        assert t.vocab_size == 262


class TestTestSplit:
    """Test-split materialization (VERDICT r3 ask #7): sources that provide a
    test split get a deterministic test loader; sources without one fail
    loudly."""

    def test_materialized_and_deterministic(self, tmp_path):
        dm = make_dm(tmp_path, Task.clm, test_texts=TEXTS[8:16])
        a = next(iter(dm.test_dataloader()))
        b = next(iter(dm.test_dataloader()))
        np.testing.assert_array_equal(a["input_ids"], b["input_ids"])
        assert a["input_ids"].shape[1] == 64

    def test_missing_split_raises(self, tmp_path):
        dm = make_dm(tmp_path, Task.clm)
        with pytest.raises(ValueError, match="no test split"):
            dm.test_dataloader()

    def test_synthetic_has_default_test_split(self, tmp_path):
        from perceiver_io_tpu.data.text.sources import SyntheticTextDataModule

        dm = SyntheticTextDataModule(
            dataset_dir=str(tmp_path / "syn"), num_train_docs=4, num_valid_docs=2,
            doc_chars=512, max_seq_len=64, task=Task.clm, batch_size=2,
        )
        dm.prepare_data()
        dm.setup()
        batch = next(iter(dm.test_dataloader()))
        assert batch["input_ids"].shape == (2, 64)

    def test_enabling_test_split_leaves_train_and_valid_unchanged(self, tmp_path):
        """The _CarvedTestSplit layout contract: the test slice comes out of
        the train tail, valid stays byte-identical."""
        from perceiver_io_tpu.data.text.sources import _CarvedTestSplit

        class Carver(_CarvedTestSplit):
            def __init__(self, test_size):
                self.source_valid_size = 0.25
                self.source_test_size = test_size

            def preproc_dir_hash_input(self):  # pragma: no cover - not used
                return ""

        texts = [f"doc{i}" for i in range(100)]
        without = Carver(0.0)._carved_splits(texts, 25)
        with_test = Carver(0.1)._carved_splits(texts, 25)
        assert with_test["valid"] == without["valid"]
        assert with_test["train"] == without["train"][: len(with_test["train"])]
        assert len(with_test["test"]) == 10
        assert not (set(with_test["test"]) & set(with_test["train"]))
        assert not (set(with_test["test"]) & set(with_test["valid"]))

    def test_carve_rejects_splits_that_consume_training_data(self):
        from perceiver_io_tpu.data.text.sources import _CarvedTestSplit

        class Carver(_CarvedTestSplit):
            source_valid_size = 0.5
            source_test_size = 0.6

        with pytest.raises(ValueError, match="no training data"):
            Carver()._carved_splits([f"d{i}" for i in range(100)], 50)
