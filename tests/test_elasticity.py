"""SLO-driven fleet elasticity tests (docs/serving.md "Elasticity"):
burn-rate autoscaler ladder, zero-downtime scale transitions, and the
flash-crowd acceptance drill.

The load-bearing drills: a deterministic FakeClock flash crowd at ~3x one
replica's capacity breaches the SLO monitor, the autoscaler walks the
degradation ladder (tighten -> scale-up -> recover -> cooldown-gated
scale-down), per-request goodput-under-SLO recovers above the static-fleet
baseline, and the scale-down drains its victim with ZERO dropped in-flight
requests — survivors replay its work token-identically (greedy
determinism) and every KV pool page returns tagged ``scale_down`` with
zero-leak accounting. Spawn failures (``fleet.scale_up``) and mid-drain
crashes (``fleet.scale_down``) are chaos-scripted, so every transition
replays bit-identically on CPU.
"""
import http.client

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.inference.generate import GenerationConfig
from perceiver_io_tpu.inference.samplers import SamplingConfig
from perceiver_io_tpu.models.text.clm import CausalLanguageModel, CausalLanguageModelConfig
from perceiver_io_tpu.observability import (
    LoadGenerator,
    MetricsRegistry,
    Tracer,
    TTFTProbe,
    WorkloadSpec,
)
from perceiver_io_tpu.observability.slo import SLOMonitor, SLOPolicy
from perceiver_io_tpu.reliability import ChaosRegistry, FakeClock
from perceiver_io_tpu.serving import (
    BucketTable,
    FleetAutoscaler,
    FleetRouter,
    LADDER,
    SlotServingEngine,
)

pytestmark = [pytest.mark.elasticity, pytest.mark.timeout(300)]

KEY = jax.random.PRNGKey(0)

# deliberately NOT a shape another test module uses (executor cache keys
# include the model fingerprint; see tests/test_fleet.py)
TINY = dict(
    vocab_size=97, max_seq_len=32, max_latents=16, num_channels=16,
    num_heads=2, num_self_attention_layers=1, cross_attention_dropout=0.0,
)
GREEDY = SamplingConfig(temperature=0.0)
GEN = GenerationConfig(max_new_tokens=6, num_latents=4, sampling=GREEDY)
TABLE = BucketTable(prompt_lens=(16,), batch_sizes=(1,))
STEP_COST = 0.01


@pytest.fixture(scope="module")
def tiny_model():
    cfg = CausalLanguageModelConfig(**TINY)
    model = CausalLanguageModel(cfg)
    params = model.init(KEY, jnp.zeros((1, 32), jnp.int32), 16)["params"]
    return model, params


def _prompts(n=6, lengths=(5, 7, 8, 6, 5, 7, 9, 6)):
    rng = np.random.default_rng(0)
    return [
        rng.integers(1, TINY["vocab_size"], size=int(L)).astype(np.int32)
        for L in lengths[:n]
    ]


def _factory(tiny_model, clock, *, slots=2):
    model, params = tiny_model

    def factory():
        return SlotServingEngine(
            model, params, GEN, TABLE, slots=slots, clock=clock,
            kv_layout="paged", rng=jax.random.PRNGKey(1),
        )

    return factory


def _make_fleet(tiny_model, *, n=1, clock=None, chaos=None, slots=2, **kw):
    clock = clock or FakeClock()
    fleet = FleetRouter(
        [_factory(tiny_model, clock, slots=slots)] * n, clock=clock,
        chaos=chaos, tracer=Tracer(clock=clock), **kw,
    )
    return fleet, clock


# -- satellite: spike arrival process ---------------------------------------
def test_spike_arrival_schedule_deterministic_and_stepped():
    """The spike schedule is a pure function of the rng, its window really
    runs at ~spike_factor x the baseline rate, and the crowd arrives even
    when a baseline gap would have leapt the whole window."""

    class _Null:
        def submit(self, *a, **k):  # pragma: no cover - never driven
            raise AssertionError

        def step(self):
            return 0

        def pending(self):
            return False

    def gaps(seed):
        gen = LoadGenerator(
            _Null(), arrival="spike", rate_rps=10.0, spike_factor=5.0,
            spike_start_s=2.0, spike_duration_s=3.0, max_requests=64,
            rng=seed, clock=FakeClock(),
        )
        return gen._gaps()

    assert gaps(7) == gaps(7)  # bit-identical replay
    assert gaps(7) != gaps(8)
    schedule = gaps(7)
    arrivals = np.cumsum(schedule)
    in_window = [t for t in arrivals if 2.0 <= t < 5.0]
    out_window = [t for t in arrivals if t < 2.0 or t >= 5.0]
    # ~5x rate inside the window: mean gap inside << outside
    assert len(in_window) >= 2 * max(1, len(out_window))
    # the first in-window arrival lands AT the window start (gap clipping)
    assert any(abs(t - 2.0) < 1e-6 for t in arrivals)
    with pytest.raises(ValueError, match="spike_duration_s"):
        LoadGenerator(_Null(), arrival="spike", rate_rps=1.0, rng=0)
    with pytest.raises(ValueError, match="spike_factor"):
        LoadGenerator(
            _Null(), arrival="spike", rate_rps=1.0, spike_factor=0.0,
            spike_duration_s=1.0, rng=0,
        )
    with pytest.raises(ValueError, match="spike_start_s"):
        LoadGenerator(
            _Null(), arrival="spike", rate_rps=1.0, spike_start_s=-1.0,
            spike_duration_s=1.0, rng=0,
        )


# -- autoscaler units --------------------------------------------------------
def test_autoscaler_validation(tiny_model):
    fleet, _ = _make_fleet(tiny_model)
    with pytest.raises(ValueError, match="max_replicas"):
        FleetAutoscaler(fleet, max_replicas=0)
    with pytest.raises(ValueError, match="min_replicas"):
        FleetAutoscaler(fleet, max_replicas=2, min_replicas=0)
    with pytest.raises(ValueError, match="evidence"):
        FleetAutoscaler(fleet, max_replicas=2, up_evidence=0)
    with pytest.raises(ValueError, match="queue_low"):
        FleetAutoscaler(fleet, max_replicas=2, queue_low=2.0, queue_high=1.0)
    with pytest.raises(ValueError, match="scale_up_slots"):
        FleetAutoscaler(fleet, max_replicas=2, scale_up_slots=0)
    scaler = FleetAutoscaler(fleet, max_replicas=3)
    assert fleet.autoscaler is scaler  # ctor installs itself
    assert scaler.rung == "steady" and LADDER.index(scaler.rung) == 0


def test_scale_bookkeeping_keyed_by_replica_id(tiny_model):
    """Replicas appear and disappear mid-run without KeyError: ids are
    monotonic and never reused, per-replica attribution survives removal,
    dispatch reaches a replica spawned mid-flight, and the gauges track."""
    fleet, clock = _make_fleet(tiny_model, n=2)
    reqs = [fleet.submit(p) for p in _prompts(6)]
    fleet.step()
    added = fleet.add_replica()
    assert added.replica_id == 2  # monotonic, never reused
    assert fleet.registry.gauge("fleet_replicas") == 3
    fleet.step()
    removed = fleet.remove_replica(0)
    assert removed.replica_id == 0
    assert [r.replica_id for r in fleet.replicas] == [1, 2]
    again = fleet.add_replica()
    assert again.replica_id == 3  # 0 is never handed out again
    fleet.run_until_idle()
    assert all(r.status == "ok" for r in reqs)
    s = fleet.stats()
    assert s["scale_ups"] == 2 and s["scale_downs"] == 1
    # attribution: every completion charged to a live-or-retired id, none lost
    assert sum(int(v) for v in s["completed_by_replica"].values()) == len(reqs)
    assert fleet.health()["replicas"] == 3
    # removing the last healthy replica is refused — healthz stays ready
    fleet.remove_replica(3)
    fleet.remove_replica(2)
    with pytest.raises(ValueError, match="no healthy replica"):
        fleet.remove_replica(1)
    assert fleet.health()["ready"]


# -- THE scale-down drill ----------------------------------------------------
def test_scale_down_mid_flight_zero_loss_token_identical_tagged(tiny_model):
    """Scale-down with work in flight: the victim's dispatched requests
    fail over and replay token-identically on survivors, its pool pages
    return tagged ``scale_down`` with zero leak, and no accepted request
    is dropped — the acceptance drill's scale-down half."""
    prompts = _prompts(6)
    # fault-free single-replica reference
    ref_fleet, _ = _make_fleet(tiny_model, n=1)
    ref = [ref_fleet.submit(p) for p in prompts]
    ref_fleet.run_until_idle()
    assert all(r.status == "ok" for r in ref)

    fleet, clock = _make_fleet(tiny_model, n=2)
    reqs = [fleet.submit(p) for p in prompts]
    for _ in range(2):
        fleet.step()  # both replicas hold resident work
    victim = fleet.replicas[0]
    in_flight = len(victim.handles)
    assert in_flight > 0
    removed = fleet.remove_replica(victim.replica_id)
    pool = removed.engine._pool
    # every page returned at the removal instant, tagged scale_down
    assert pool.in_use == 0 and pool.reserved == 0 and pool.leaked() == 0
    assert pool.stats()["frees_by_cause"].get("scale_down", 0) > 0
    assert fleet.health()["ready"]  # never below min-healthy mid-transition
    fleet.run_until_idle()
    assert all(r.status == "ok" for r in reqs)  # zero dropped
    for got, want in zip(reqs, ref):
        assert np.array_equal(got.result, want.result)  # token-identical
    s = fleet.stats()
    assert s["scale_downs"] == 1
    assert s["failovers"] == 1 and s["redispatches"] == in_flight
    assert s["completed"] == len(prompts) and s["failed"] == 0
    # survivors' pools drained clean too
    for r in fleet.replicas:
        assert r.engine._pool.leaked() == 0 and r.engine._pool.in_use == 0


def test_scale_down_victim_excludes_open_breaker_with_requeued_work(tiny_model):
    """A breaker-open replica counts as UNHEALTHY capacity (the autoscaler
    may scale up over it) but is never picked as the drain victim while it
    still holds engine handles from its failed-over work."""
    fleet, clock = _make_fleet(tiny_model, n=3)
    scaler = FleetAutoscaler(
        fleet, min_replicas=3, max_replicas=4, up_cooldown_s=0.0,
        up_evidence=1,
    )
    open_replica = fleet.replicas[0]
    open_replica.breaker.state = "open"
    open_replica.breaker.opened_at = clock()
    open_replica.handles[999] = object()  # stale re-queued work
    fleet._update_gauges()
    # unhealthy capacity: only the two closed replicas count
    assert scaler._capacity() == 2 * 2
    victim = fleet.scale_down_victim()
    assert victim is not None
    assert victim.replica_id != open_replica.replica_id
    # healthy (2) < min_replicas (3) triggers a scale-up on one poll
    assert scaler.poll() == "scale_up"
    assert len(fleet.replicas) == 4
    assert fleet.stats()["replicas_healthy"] == 3


def test_scale_chaos_sites_drillable(tiny_model):
    """``fleet.scale_up`` spawn failure holds the autoscaler's cooldown
    (then succeeds after it); ``fleet.scale_down`` crash mid-drain still
    completes the removal with zero request loss."""
    chaos = ChaosRegistry()
    chaos.fail_scale_up(1)
    fleet, clock = _make_fleet(tiny_model, n=1, chaos=chaos)
    scaler = FleetAutoscaler(
        fleet, max_replicas=2, up_cooldown_s=1.0, up_evidence=1,
        queue_high=0.0, queue_low=0.0,  # any queued work is pressure — force the rung
    )
    reqs = [fleet.submit(p) for p in _prompts(4)]
    assert scaler.poll() == "spawn_failed"
    assert len(fleet.replicas) == 1
    assert fleet.registry.counter("fleet_scale_up_failed_total") == 1
    assert scaler.spawn_failures == 1
    assert scaler.poll() is None  # cooldown holds — no spawn-failure spin
    clock.advance(1.1)
    assert scaler.poll() == "scale_up"  # retry after cooldown succeeds
    assert len(fleet.replicas) == 2
    # crash mid-drain: in-flight work is already failed over, removal lands
    chaos.crash_scale_down(1)
    fleet.step()
    victim = next(r for r in fleet.replicas if r.handles)
    removed = fleet.remove_replica(victim.replica_id)
    assert removed.replica_id not in {r.replica_id for r in fleet.replicas}
    fleet.run_until_idle()
    assert all(r.status == "ok" for r in reqs)  # zero dropped despite crash
    assert chaos.fired_count("fleet.scale_up") == 1
    assert chaos.fired_count("fleet.scale_down") == 1


# -- THE acceptance drill ----------------------------------------------------
def test_flash_crowd_breach_scale_up_recover_scale_down(tiny_model):
    """The deterministic FakeClock flash crowd at ~3x one replica's
    capacity: sustained breach -> ladder walks tighten/scale-up ->
    per-request goodput-under-SLO recovers ABOVE the static baseline ->
    load drops -> cooldown-gated scale-down back to min with zero dropped
    requests and zero pool leak, every transition evented."""
    model, params = tiny_model
    gen_cfg = GenerationConfig(max_new_tokens=8, num_latents=4, sampling=GREEDY)
    workload = WorkloadSpec(
        prompt_len=(5, 12), max_new_tokens=(6, 8), vocab=(1, TINY["vocab_size"])
    )

    def build(clock, autoscale, registry, tracer, monitor):
        def factory():
            return SlotServingEngine(
                model, params, gen_cfg, TABLE, slots=1, clock=clock,
                kv_layout="paged", rng=jax.random.PRNGKey(1),
            )

        fleet = FleetRouter(
            [factory], clock=clock, registry=registry, tracer=tracer,
            slo_monitor=monitor,
        )
        scaler = FleetAutoscaler(
            fleet, min_replicas=1, max_replicas=3,
            up_cooldown_s=0.3, down_cooldown_s=2.0,
            up_evidence=2, down_evidence=25,
            queue_high=1.0, queue_low=0.5,
        ) if autoscale else None
        return fleet, scaler

    # calibration: healthy closed-loop capacity + target with a step floor
    cal_clock = FakeClock()
    cal_fleet, _ = build(
        cal_clock, False, MetricsRegistry(clock=cal_clock), None, None
    )
    cal = LoadGenerator(
        cal_fleet, workload=workload, mode="closed", users=1, max_requests=6,
        rng=0, clock=cal_clock, step_cost_s=STEP_COST,
    ).run()
    base_rps = max(cal["completed_rps"], 0.1)
    target_ms = 3.0 * max(
        cal_fleet.registry.percentile("serving_ttft_ms", 95.0) or 0.0,
        STEP_COST * 1e3,
    )

    def run(autoscale):
        clock = FakeClock()
        registry = MetricsRegistry(clock=clock)
        tracer = Tracer(clock=clock)
        monitor = SLOMonitor(
            SLOPolicy(ttft_p95_ms=target_ms), clock=clock, registry=registry,
            tracer=tracer, fast_window_s=1.0, slow_window_s=4.0,
            breach_burn_rate=1.5, min_samples=4,
        )
        fleet, scaler = build(clock, autoscale, registry, tracer, monitor)
        probe = TTFTProbe(fleet, clock)
        gen = LoadGenerator(
            probe, workload=workload, mode="open", arrival="spike",
            rate_rps=0.8 * base_rps, spike_factor=3.0, spike_start_s=1.0,
            spike_duration_s=4.0, max_requests=24, config=gen_cfg, rng=1,
            clock=clock, step_cost_s=STEP_COST,
        )
        gen.run()
        # settle: keep the control loop polling so recovery evidence and
        # the down-cooldown elapse (bounded)
        for _ in range(600):
            if scaler is None or len(fleet.replicas) <= scaler.min_replicas:
                break
            fleet.step()
            clock.advance(STEP_COST)
        return fleet, scaler, probe, registry, tracer

    f_static, _, p_static, reg_static, _ = run(False)
    f_auto, scaler, p_auto, reg_auto, tr_auto = run(True)

    # the breach fired and the ladder walked up and back down
    assert reg_auto.counter("slo_breach_total") >= 1
    assert scaler.scale_ups >= 1 and scaler.scale_downs >= 1
    assert len(f_auto.replicas) == 1 and scaler.rung in ("steady", "recover")
    event_names = {sp.name for sp in tr_auto.spans()}
    assert {"autoscaler.scale_up", "autoscaler.scale_down",
            "autoscaler.rung"} <= event_names
    rungs = [
        sp.attrs["rung"] for sp in tr_auto.spans("autoscaler.rung")
    ]
    assert "tighten_admission" in rungs or "scale_up" in rungs
    assert set(rungs) <= set(LADDER)

    # goodput-under-SLO recovers ABOVE the static baseline, per-request
    static_good = p_static.good_under(target_ms)
    auto_good = p_auto.good_under(target_ms)
    assert auto_good > static_good
    assert reg_auto.percentile("serving_ttft_ms", 95.0) \
        < reg_static.percentile("serving_ttft_ms", 95.0)

    # zero dropped + token identity + zero-leak accounting, both runs
    for probe in (p_static, p_auto):
        assert all(r["handle"].status == "ok" for r in probe.records)
        assert len(probe.records) == 24
    for a, s in zip(p_auto.records, p_static.records):
        assert np.array_equal(a["handle"].result, s["handle"].result)
    for r in f_auto.replicas:
        assert r.engine._pool.leaked() == 0 and r.engine._pool.in_use == 0
    for retired in scaler.retired:
        assert retired["pool"]["leaked"] == 0
        assert retired["pool"]["in_use"] == 0
    s = f_auto.stats()
    assert s["failed"] == 0 and s["queued"] == 0 and s["dispatched"] == 0


# -- satellite: healthz across transitions ----------------------------------
def test_healthz_stays_ready_across_restart_and_autoscale(tiny_model):
    """``health()["ready"]`` is pinned true through every step of a rolling
    restart AND an autoscale transition, and the HTTP ``/healthz`` payload
    answers 200 with the fleet's replicas/replicas_healthy/draining counts."""
    from perceiver_io_tpu.serving import StreamingGateway

    fleet, clock = _make_fleet(tiny_model, n=2)
    reqs = [fleet.submit(p) for p in _prompts(4)]
    fleet.step()
    readiness = []
    orig_step = fleet.step

    def probed_step():
        n = orig_step()
        readiness.append(fleet.health()["ready"])
        return n

    fleet.step = probed_step
    fleet.rolling_restart()  # drives step() internally
    fleet.add_replica()
    readiness.append(fleet.health()["ready"])
    fleet.remove_replica(fleet.scale_down_victim().replica_id)
    readiness.append(fleet.health()["ready"])
    fleet.run_until_idle()
    fleet.step = orig_step
    assert readiness and all(readiness)
    assert all(r.status == "ok" for r in reqs)

    gateway = StreamingGateway(fleet, registry=fleet.registry).run_in_thread()
    try:
        conn = http.client.HTTPConnection(gateway.host, gateway.port, timeout=10)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        payload = resp.read().decode()
        import json

        health = json.loads(payload)
        conn.close()
        assert resp.status == 200
        assert health["replicas"] == 2
        assert health["replicas_healthy"] == 2
        assert health["draining"] == 0
        assert len(health["replica_detail"]) == 2
    finally:
        gateway.close()


# -- satellite: slot-count elasticity ----------------------------------------
def test_resize_slots_warm_rebuild(tiny_model):
    """resize_slots grows/shrinks an idle engine through the
    rebuild-from-warm-cache path: greedy outputs are unchanged, a
    previously-compiled slot count costs zero fresh executor builds, and
    resizing under residents is refused."""
    from perceiver_io_tpu.inference.generate import executor_cache_stats

    model, params = tiny_model
    clock = FakeClock()
    engine = SlotServingEngine(
        model, params, GEN, TABLE, slots=2, clock=clock,
        kv_layout="paged", rng=jax.random.PRNGKey(1),
    )
    prompts = _prompts(4)
    baseline = engine.serve(prompts)
    assert engine.resize_slots(4) == 2
    assert engine.slots == 4 and len(engine._slots) == 4
    assert engine._pool.slots == 4  # pool re-scaled with the slot count
    grown = engine.serve(prompts)
    for a, b in zip(baseline, grown):
        assert np.array_equal(a, b)
    # shrinking back to a seen count: zero fresh compiles (warm caches)
    misses_before = executor_cache_stats()["misses"]
    assert engine.resize_slots(2) == 4
    shrunk = engine.serve(prompts)
    assert executor_cache_stats()["misses"] == misses_before
    for a, b in zip(baseline, shrunk):
        assert np.array_equal(a, b)
    # refuse under residents
    engine2 = SlotServingEngine(
        model, params, GEN, TABLE, slots=2, clock=clock,
        kv_layout="paged", rng=jax.random.PRNGKey(1),
    )
    engine2.submit(prompts[0])
    engine2.step()
    with pytest.raises(RuntimeError, match="resize_slots"):
        engine2.resize_slots(4)
    with pytest.raises(ValueError, match="slots"):
        engine2.resize_slots(0)
    engine2.drain()


def test_evacuate_returns_pages_tagged(tiny_model):
    """Engine-level evacuation (the scale-down path in isolation): queued,
    admitting, and resident requests all finish ``cancelled`` at once, the
    pool returns every page tagged with the evacuation cause."""
    model, params = tiny_model
    engine = SlotServingEngine(
        model, params, GEN, TABLE, slots=2, clock=FakeClock(),
        kv_layout="paged", rng=jax.random.PRNGKey(1),
    )
    reqs = [engine.submit(p) for p in _prompts(5)]
    engine.step()  # residents + queued backlog
    assert engine._pool.in_use > 0
    n = engine.evacuate(cause="scale_down")
    assert n == len(reqs) - sum(1 for r in reqs if r.status == "ok")
    assert all(r.done for r in reqs)
    assert engine._pool.in_use == 0 and engine._pool.leaked() == 0
    assert engine._pool.stats()["frees_by_cause"].get("scale_down", 0) > 0
    assert not engine.pending()
    assert int(engine.registry.counter("serving_requests_cancelled_total")) == n


# -- satellite: HELP coverage ------------------------------------------------
def test_help_coverage_for_scale_and_autoscaler_families(tiny_model):
    """Every ``fleet_scale_*`` / ``autoscaler_*`` family a scaled fleet
    publishes has a direct HELP entry rendered as a ``# HELP`` line (the
    PR 9 convention, pinned by the existing coverage test style)."""
    from perceiver_io_tpu.observability.exporters import HELP_TEXT, to_prometheus_text

    chaos = ChaosRegistry()
    chaos.fail_scale_up(1)
    fleet, clock = _make_fleet(tiny_model, n=1, chaos=chaos)
    scaler = FleetAutoscaler(
        fleet, max_replicas=2, up_cooldown_s=0.0, up_evidence=1,
        queue_high=0.0, queue_low=0.0,
    )
    fleet.submit(_prompts(1)[0])
    scaler.poll()  # spawn failure
    scaler.poll()  # scale up
    fleet.run_until_idle()
    fleet.remove_replica(fleet.scale_down_victim().replica_id)
    snap = fleet.registry.snapshot()
    published = sorted(
        n for n in (*snap["counters"], *snap["gauges"], *snap["histograms"])
        if n.startswith(("fleet_scale_", "autoscaler_"))
    )
    assert "fleet_scale_up_total" in published
    assert "fleet_scale_down_total" in published
    assert "fleet_scale_up_failed_total" in published
    assert "autoscaler_evaluations_total" in published
    assert "autoscaler_ladder_rung" in published
    missing = sorted(n for n in published if n not in HELP_TEXT)
    assert not missing, f"families without a direct HELP entry: {missing}"
    text = to_prometheus_text(fleet.registry)
    for name in published:
        assert f"# HELP {name} " in text, name


# -- satellite: obs report elasticity section --------------------------------
def test_obs_report_elasticity_section(tiny_model):
    """``obs report`` renders the scale-event timeline from a live run's
    ``autoscaler.*`` events + counters, and the checked-in fixtures stay
    pinned; elasticity-less artifacts omit the section."""
    from perceiver_io_tpu.observability import report as obs_report

    chaos = ChaosRegistry()
    chaos.fail_scale_up(1)
    fleet, clock = _make_fleet(tiny_model, n=1, chaos=chaos)
    scaler = FleetAutoscaler(
        fleet, max_replicas=2, up_cooldown_s=0.0, up_evidence=1,
        queue_high=0.0, queue_low=0.0,
    )
    reqs = [fleet.submit(p) for p in _prompts(3)]
    scaler.poll()
    scaler.poll()
    fleet.run_until_idle()
    assert all(r.status == "ok" for r in reqs)
    analysis = obs_report.analyze(
        [sp.to_row() for sp in fleet.tracer.spans()], fleet.registry.snapshot()
    )
    section = analysis["elasticity"]
    assert section is not None
    assert section["scale_ups"] == 1 and section["spawn_failures"] == 1
    assert any(
        row["event"] == "autoscaler.scale_up" for row in section["timeline"]
    )
    assert any(
        row["event"] == "autoscaler.spawn_failed" for row in section["timeline"]
    )
    rendered = obs_report.format_report(analysis)
    assert "== elasticity ==" in rendered
    assert "scale-event timeline:" in rendered
    # the checked-in fixtures carry the extended section
    fixture_json = obs_report.run(
        "tests/fixtures/events.jsonl", "tests/fixtures/metrics_snapshot.json",
        as_json=True,
    )
    import json

    fixture = json.loads(fixture_json)["elasticity"]
    assert fixture["scale_ups"] == 1 and fixture["scale_downs"] == 1
    assert fixture["events_by_kind"]["autoscaler.scale_up"] == 1
    rendered_fixture = obs_report.run(
        "tests/fixtures/events.jsonl", "tests/fixtures/metrics_snapshot.json"
    )
    assert "== elasticity ==" in rendered_fixture
    assert "autoscaler.scale_down" in rendered_fixture
    # pre-elasticity artifacts: no section
    assert obs_report.analyze([], {})["elasticity"] is None
    assert "== elasticity ==" not in obs_report.format_report(
        obs_report.analyze([], {})
    )


# -- serve CLI ---------------------------------------------------------------
def test_cli_autoscale_flag_group(tmp_path, tiny_model):
    """``--serve.autoscale.*`` parses into the nested dataclass and the
    inapplicable-flag convention holds: tuning knobs without
    ``autoscale.max`` hard-error, as does ``scale_up_slots`` on the bucket
    engine."""
    from perceiver_io_tpu.scripts.cli import AutoscaleArgs, ServeArgs, build_dataclass
    from perceiver_io_tpu.scripts.text import clm as clm_script
    from perceiver_io_tpu.training.checkpoint import save_pretrained

    args = build_dataclass(
        ServeArgs,
        {
            "serve.autoscale.max": 4, "serve.autoscale.min": 2,
            "serve.autoscale.down_cooldown_s": 30.0,
            "serve.autoscale.scale_up_slots": 8,
        },
        "serve",
    )
    assert isinstance(args.autoscale, AutoscaleArgs)
    assert args.autoscale.max == 4 and args.autoscale.min == 2
    assert args.autoscale.down_cooldown_s == 30.0
    assert args.autoscale.scale_up_slots == 8

    cfg = CausalLanguageModelConfig(
        vocab_size=262, max_seq_len=32, max_latents=16, num_channels=16,
        num_heads=2, num_self_attention_layers=1, cross_attention_dropout=0.0,
    )
    model = CausalLanguageModel(cfg)
    params = model.init(KEY, jnp.zeros((1, 32), jnp.int32), 16)["params"]
    save_pretrained(str(tmp_path / "ckpt"), params, cfg)
    (tmp_path / "prompts.txt").write_text("hi\n")
    base = [
        "serve", "--ckpt", str(tmp_path / "ckpt"),
        f"--serve.prompts={tmp_path}/prompts.txt",
        "--serve.max_new_tokens=3", "--serve.num_latents=2",
        "--serve.prompt_buckets=8", "--serve.batch_buckets=2",
        "--serve.warmup=false",
    ]
    with pytest.raises(SystemExit, match="autoscale.max"):
        clm_script.main(base + ["--serve.autoscale.min=2"])
    with pytest.raises(SystemExit, match="scale_up_slots"):
        clm_script.main(base + [
            "--serve.autoscale.max=2", "--serve.autoscale.scale_up_slots=4",
        ])


# -- bench probe -------------------------------------------------------------
@pytest.mark.slow  # 2026-08 audit: ~6s; real lane is `make elasticity` —
# test_bench_probe.py keeps bench.py bitrot in tier-1
def test_bench_elasticity_probe_tiny(tiny_model):
    """The bench.py elasticity probe at a reduced shape: the A/B runs end
    to end with the acceptance pins (zero dropped, token-identical,
    zero-leak) intact; the goodput comparison itself is asserted at the
    full probe shape, not this smoke size."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_ela_probe", "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    model, params = tiny_model
    out = bench._bench_elasticity(
        model, params, CausalLanguageModelConfig(**TINY),
        n_requests=10, new_tokens=6, slots=1, max_replicas=2,
    )
    assert out["requests"] == 10
    assert out["zero_dropped"] is True
    assert out["token_identical"] is True
    assert out["pool_zero_leak"] is True
    assert out["autoscaled"]["replicas_final"] >= 1
    assert 0.0 <= out["static"]["goodput_under_slo"] <= 1.0
    assert 0.0 <= out["autoscaled"]["goodput_under_slo"] <= 1.0
