"""HTTP/SSE streaming gateway tests (docs/serving.md "Streaming"): the
per-request incremental token sink on both engines, the cancellation-safe
slot-retirement route (slot + pool pages freed mid-generation, exactly one
terminal ``cancelled`` span), the asyncio gateway over real sockets
(greedy outputs token-identical to in-process ``generate()``, including
fleet-routed and paged-KV configurations), client-disconnect propagation
with the zero-leak invariant, the scripted mass-abandonment chaos drill,
socket-anchored TTFT, the loadgen HTTP client mode, the ``obs report``
gateway section, and the bench streaming probe.

All CPU, tiny shapes, tier-1 under tight per-test budgets; socket tests
bind ephemeral localhost ports and run the gateway's event loop in a
daemon thread (the engine's single driver).
"""
import dataclasses
import http.client
import json
import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.inference.generate import GenerationConfig, generate
from perceiver_io_tpu.inference.samplers import SamplingConfig
from perceiver_io_tpu.models.text.clm import (
    CausalLanguageModel,
    CausalLanguageModelConfig,
)
from perceiver_io_tpu.observability import (
    GatewayHttpClient,
    LoadGenerator,
    MetricsRegistry,
    Tracer,
    WorkloadSpec,
    to_prometheus_text,
)
from perceiver_io_tpu.observability import report as report_mod
from perceiver_io_tpu.observability.exporters import HELP_TEXT
from perceiver_io_tpu.reliability import ChaosRegistry, FakeClock, QueueFull
from perceiver_io_tpu.serving import (
    BucketTable,
    FleetRouter,
    ServingEngine,
    SlotServingEngine,
    StreamingGateway,
)
from perceiver_io_tpu.serving.gateway import GATEWAY_COUNTERS

pytestmark = [pytest.mark.gateway, pytest.mark.timeout(300)]

KEY = jax.random.PRNGKey(0)

# Deliberately NOT a shape another test module uses: executor cache keys
# include the module fingerprint, and an identically-configured model in
# another file would pre-populate the caches this file relies on warming.
TINY = dict(
    vocab_size=89, max_seq_len=32, max_latents=8, num_channels=16,
    num_heads=2, num_self_attention_layers=1, cross_attention_dropout=0.0,
)
GREEDY = SamplingConfig(temperature=0.0)
TABLE = BucketTable(prompt_lens=(8,), batch_sizes=(1,))


@pytest.fixture(scope="module")
def tiny_model():
    cfg = CausalLanguageModelConfig(**TINY)
    model = CausalLanguageModel(cfg)
    params = model.init(KEY, jnp.zeros((1, 32), jnp.int32), 8)["params"]
    return model, params


def _gcfg(max_new=4, num_latents=2, **kw):
    return GenerationConfig(
        max_new_tokens=max_new, num_latents=num_latents, sampling=GREEDY, **kw
    )


def _ref(model, params, prompt, cfg):
    """Unbucketed per-request generate(): the parity oracle."""
    return np.asarray(
        generate(model, params, jnp.asarray(np.asarray(prompt, np.int32)[None]), cfg)
    )[0]


def _prompts(seed, lengths):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 80, size=int(n)).astype(np.int32) for n in lengths]


# -- http helpers -----------------------------------------------------------
def _post_generate(host, port, payload, timeout=60):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request(
        "POST", "/v1/generate", body=json.dumps(payload),
        headers={"Content-Type": "application/json"},
    )
    return conn, conn.getresponse()


def _read_stream(resp):
    """(tokens, terminal_record) off an SSE or JSON-lines response."""
    toks, term = [], None
    while True:
        line = resp.readline()
        if not line:
            break
        line = line.strip()
        if not line:
            continue
        if line.startswith(b"data:"):
            line = line[5:].strip()
        rec = json.loads(line)
        if rec.get("done"):
            term = rec
            break
        toks.append(int(rec["token"]))
    return toks, term


def _get(host, port, path, timeout=30):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, body


def _wait_for(predicate, timeout_s=20.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# -- the incremental token sink --------------------------------------------
@pytest.mark.timeout(120)
def test_slot_engine_on_token_streams_incrementally(tiny_model):
    """The engine-surface half of the tentpole: the slot engine delivers
    each token to the per-request sink the same step() that produced it —
    never all at retirement — and the streamed (index, token) sequence is
    exactly the final result's real tokens."""
    model, params = tiny_model
    cfg = _gcfg(max_new=5)
    engine = SlotServingEngine(
        model, params, cfg, TABLE, slots=2, rng=jax.random.PRNGKey(1)
    )
    engine.warmup()
    prompts = _prompts(0, [4, 7])
    sinks = [[], []]
    reqs = [
        engine.submit(p, on_token=lambda i, t, s=sinks[j]: s.append((i, t)))
        for j, p in enumerate(prompts)
    ]
    growth = []
    while engine.pending():
        before = sum(len(s) for s in sinks)
        engine.step()
        growth.append(sum(len(s) for s in sinks) - before)
    # tokens arrived incrementally: at most one per resident per step,
    # across more than one step
    assert max(growth) <= 2 and sum(1 for g in growth if g > 0) >= 5
    for req, sink, p in zip(reqs, sinks, prompts):
        assert req.status == "ok"
        expect = _ref(model, params, p, cfg)
        np.testing.assert_array_equal(req.result, expect)
        assert sink == [(i, int(t)) for i, t in enumerate(expect)]


@pytest.mark.timeout(120)
def test_bucket_engine_on_token_batch_granular(tiny_model):
    """The bucket engine powers the same sink at batch granularity: no
    tokens until its micro-batch fence, then every real token in order
    (trimmed at EOS — pad filler after EOS never reaches the sink)."""
    model, params = tiny_model
    cfg = _gcfg(max_new=4)
    probe = _prompts(1, [5])[0]
    eos = int(_ref(model, params, probe, cfg)[1])  # greedy token at step 1
    cfg_eos = dataclasses.replace(cfg, eos_token_id=eos)
    engine = ServingEngine(model, params, cfg_eos, TABLE, rng=jax.random.PRNGKey(1))
    sink = []
    req = engine.submit(probe, on_token=lambda i, t: sink.append((i, t)))
    assert sink == []  # nothing streams before the batch runs
    engine.step()
    assert req.status == "ok"
    toks = req.result.tolist()
    expect = toks[: toks.index(eos) + 1]
    assert sink == [(i, int(t)) for i, t in enumerate(expect)]
    assert sink[-1][1] == eos and len(sink) < cfg.max_new_tokens


@pytest.mark.timeout(120)
def test_raising_sink_is_isolated(tiny_model):
    """A torn-down stream consumer (raising sink) must not fail the
    request it observes — counted, isolated, request completes ok."""
    model, params = tiny_model
    cfg = _gcfg(max_new=3)
    engine = SlotServingEngine(
        model, params, cfg, TABLE, slots=2, rng=jax.random.PRNGKey(1)
    )

    def bad_sink(i, t):
        raise RuntimeError("consumer gone")

    req = engine.submit(_prompts(2, [4])[0], on_token=bad_sink)
    engine.run_until_idle()
    assert req.status == "ok"
    assert engine.registry.counter("serving_token_sink_errors_total") == 3


# -- cancel(): the new retirement route -------------------------------------
@pytest.mark.timeout(180)
def test_cancel_resident_frees_slot_and_pool_immediately(tiny_model):
    """The acceptance drill, engine-level: cancelling a resident request
    mid-generation frees its slot and returns ALL pool pages at the cancel
    instant (zero-leak via kv_pool_blocks_in_use), ends exactly one
    terminal ``cancelled`` span + one ``serving.cancelled`` event, never
    perturbs the surviving resident's tokens, and the freed slot admits
    the next queued request."""
    model, params = tiny_model
    cfg = _gcfg(max_new=8)
    tracer = Tracer()
    engine = SlotServingEngine(
        model, params, cfg, TABLE, slots=2, kv_layout="paged",
        tracer=tracer, rng=jax.random.PRNGKey(1),
    )
    engine.warmup()
    prompts = _prompts(3, [5, 8, 6])
    reqs = [engine.submit(p) for p in prompts]
    for _ in range(2):
        engine.step()  # both residents admitted, 2 tokens each
    victim, survivor, queued = reqs
    in_use_before = engine._pool.in_use
    assert in_use_before > 0 and engine._pool.mapped_blocks(0) > 0
    assert engine.cancel(victim.request_id) is True
    # pages back the same instant — BEFORE the next step() runs
    assert engine._pool.mapped_blocks(0) == 0
    assert engine._pool.in_use < in_use_before
    assert engine._pool.frees_by_cause.get("cancelled", 0) > 0
    assert victim.status == "cancelled" and victim.result is None
    engine.run_until_idle()
    # survivors token-identical to the oracle, queued request admitted
    # into the freed slot and also identical
    np.testing.assert_array_equal(
        survivor.result, _ref(model, params, prompts[1], cfg)
    )
    np.testing.assert_array_equal(
        queued.result, _ref(model, params, prompts[2], cfg)
    )
    assert engine._pool.in_use == 0 and engine._pool.reserved == 0
    assert engine._pool.leaked() == 0
    terminal = [
        sp for sp in tracer.spans("serving.request") if sp.status == "cancelled"
    ]
    assert len(terminal) == 1 and terminal[0].trace_id == victim.trace_id
    events = tracer.spans("serving.cancelled")
    assert len(events) == 1 and events[0].attrs["stage"] == "resident"
    assert engine.health()["cancelled"] == 1
    stats = engine.stats()
    assert stats["cancelled"] == 1 and stats["completed"] == 2
    # cancelling an already-terminal request is a no-op
    assert engine.cancel(victim.request_id) is False


@pytest.mark.timeout(120)
def test_cancel_queued_and_mid_chunked_admission(tiny_model):
    """The other two lifecycle stages: a queued request leaves the queue
    (base-class route), and an in-flight chunked admission is dropped with
    its reserved pages returned before the row ever enters the state."""
    model, params = tiny_model
    cfg = _gcfg(max_new=3)
    tracer = Tracer()
    engine = SlotServingEngine(
        model, params, cfg, TABLE, slots=1, kv_layout="paged",
        prefill_chunk=2, tracer=tracer, rng=jax.random.PRNGKey(1),
    )
    prompts = _prompts(4, [8, 4])
    admitting, queued = [engine.submit(p) for p in prompts]
    engine.step()  # starts the chunked admission for the 8-token prompt
    assert engine._admitting is not None
    assert engine.cancel(queued.request_id) is True  # still queued
    assert queued.status == "cancelled"
    assert engine.cancel(admitting.request_id) is True  # mid-admission
    assert admitting.status == "cancelled"
    assert engine._admitting is None
    assert engine._pool.in_use == 0 and engine._pool.reserved == 0
    stages = sorted(sp.attrs["stage"] for sp in tracer.spans("serving.cancelled"))
    assert stages == ["admitting", "queued"]
    assert not engine.pending()


@pytest.mark.timeout(180)
def test_fleet_cancel_and_ttft_anchor(tiny_model):
    """Fleet-level cancel reaches the dispatched copy's replica (slot +
    pages freed there) and finalizes exactly once; ttft_anchor_s passes
    through dispatch so a socket-accept anchor backdates the SLO-judged
    TTFT by exactly the anchor offset under FakeClock."""
    model, params = tiny_model
    cfg = _gcfg(max_new=4)
    clock = FakeClock(100.0)

    def factory():
        return SlotServingEngine(
            model, params, cfg, TABLE, slots=2, clock=clock,
            rng=jax.random.PRNGKey(1),
        )

    fleet = FleetRouter([factory, factory], clock=clock)
    prompts = _prompts(5, [5, 6])
    # anchored 3s before the fleet submit: the recorded TTFT must be
    # exactly 3000ms more than an unanchored request's (all other time is
    # frozen under FakeClock)
    anchored = fleet.submit(prompts[0], ttft_anchor_s=clock() - 3.0)
    plain = fleet.submit(prompts[1])
    fleet.run_until_idle()
    assert anchored.status == "ok" and plain.status == "ok"
    p_hi = fleet.registry.percentile("serving_ttft_ms", 100.0)
    p_lo = fleet.registry.percentile("serving_ttft_ms", 0.0)
    assert p_hi == pytest.approx(p_lo + 3000.0)
    # cancel a dispatched request mid-generation
    sink = []
    victim = fleet.submit(prompts[0], on_token=lambda i, t: sink.append(t))
    survivor = fleet.submit(prompts[1])
    fleet.step()
    fleet.step()
    assert victim.status == "dispatched" and len(sink) >= 1
    assert fleet.cancel(victim.request_id) is True
    assert victim.status == "cancelled"
    assert fleet.registry.counter("fleet_requests_cancelled_total") == 1
    replica_cancels = sum(
        r.engine.registry.counter("serving_requests_cancelled_total")
        for r in fleet.replicas
    )
    assert replica_cancels == 1
    fleet.run_until_idle()
    np.testing.assert_array_equal(
        survivor.result, _ref(model, params, prompts[1], cfg)
    )
    assert fleet.cancel(victim.request_id) is False
    assert fleet.stats()["cancelled"] == 1
    assert fleet.health()["cancelled"] == 1


# -- the gateway over real sockets ------------------------------------------
@pytest.mark.timeout(300)
def test_gateway_http_token_identity_paged(tiny_model):
    """THE acceptance pin: greedy outputs streamed over HTTP are
    token-identical to in-process generate() — through the paged-KV slot
    engine, with concurrent connections, both wire framings, and a
    per-request max_new_tokens override."""
    model, params = tiny_model
    cfg = _gcfg(max_new=5)
    tracer = Tracer()
    engine = SlotServingEngine(
        model, params, cfg, TABLE, slots=2, kv_layout="paged",
        tracer=tracer, rng=jax.random.PRNGKey(1),
    )
    engine.warmup()
    gw = StreamingGateway(engine, tracer=tracer).run_in_thread()
    try:
        prompts = _prompts(6, [4, 7, 6])
        payloads = [
            {"prompt_ids": prompts[0].tolist()},  # default sse
            {"prompt_ids": prompts[1].tolist(), "stream": "jsonl"},
            {"prompt_ids": prompts[2].tolist(), "max_new_tokens": 3},
        ]
        results = [None] * 3

        def run_one(i):
            conn, resp = _post_generate(gw.host, gw.port, payloads[i])
            try:
                assert resp.status == 200
                results[i] = _read_stream(resp)
            finally:
                conn.close()

        threads = [
            threading.Thread(target=run_one, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        cfgs = [cfg, cfg, dataclasses.replace(cfg, max_new_tokens=3)]
        for (toks, term), p, c in zip(results, prompts, cfgs):
            assert term is not None and term["status"] == "ok"
            assert term["trace_id"] is not None
            np.testing.assert_array_equal(
                np.asarray(toks, np.int32), _ref(model, params, p, c)
            )
        assert engine._pool.in_use == 0 and engine._pool.leaked() == 0
        stats = gw.stats()
        assert stats["streams"] == 3 and stats["streams_completed"] == 3
        assert stats["streams_cancelled"] == 0 and stats["bytes_sent"] > 0
        # the stream's gateway.request event joins the engine trace
        gw_events = tracer.spans("gateway.request")
        assert len(gw_events) == 3
        assert {e.trace_id for e in gw_events} == {
            sp.trace_id for sp in tracer.spans("serving.request")
        }
        # socket TTFT (accept -> first byte out) is never below the
        # engine-side TTFT anchored at the same accept instant
        sock_p50 = engine.registry.percentile("gateway_socket_ttft_ms", 50.0)
        eng_p50 = engine.registry.percentile("serving_ttft_ms", 50.0)
        assert sock_p50 is not None and sock_p50 >= eng_p50 > 0.0
    finally:
        gw.close()


@pytest.mark.timeout(300)
def test_gateway_http_token_identity_fleet_and_bucket(tiny_model):
    """The same identity bar through a 2-replica fleet (the gateway's
    submit rides the router's dispatch + anchor plumbing) and through the
    bucket engine (batch-granular streaming)."""
    model, params = tiny_model
    cfg = _gcfg(max_new=4)

    def factory():
        return SlotServingEngine(
            model, params, cfg, TABLE, slots=2, rng=jax.random.PRNGKey(1)
        )

    fleet = FleetRouter([factory, factory], registry=MetricsRegistry())
    fleet.warmup()
    gw = StreamingGateway(fleet).run_in_thread()
    prompts = _prompts(7, [5, 7])
    try:
        for p in prompts:
            conn, resp = _post_generate(
                gw.host, gw.port, {"prompt_ids": p.tolist(), "stream": "jsonl"}
            )
            toks, term = _read_stream(resp)
            conn.close()
            assert term["status"] == "ok"
            np.testing.assert_array_equal(
                np.asarray(toks, np.int32), _ref(model, params, p, cfg)
            )
    finally:
        gw.close()
    # bucket engine: same wire protocol, tokens land in one burst
    engine = ServingEngine(model, params, cfg, TABLE, rng=jax.random.PRNGKey(1))
    gw2 = StreamingGateway(engine).run_in_thread()
    try:
        p = prompts[0]
        conn, resp = _post_generate(gw2.host, gw2.port, {"prompt_ids": p.tolist()})
        toks, term = _read_stream(resp)
        conn.close()
        assert term["status"] == "ok"
        np.testing.assert_array_equal(
            np.asarray(toks, np.int32), _ref(model, params, p, cfg)
        )
    finally:
        gw2.close()


# -- speculative burst flush ------------------------------------------------
def _read_stream_indexed(resp):
    """((index, token) pairs, terminal_record) — keeps the wire indices
    the per-stream ``sent`` cursor orders (``_read_stream`` drops them)."""
    pairs, term = [], None
    while True:
        line = resp.readline()
        if not line:
            break
        line = line.strip()
        if not line:
            continue
        if line.startswith(b"data:"):
            line = line[5:].strip()
        rec = json.loads(line)
        if rec.get("done"):
            term = rec
            break
        pairs.append((int(rec["index"]), int(rec["token"])))
    return pairs, term


@pytest.mark.speculative
@pytest.mark.timeout(300)
@pytest.mark.slow  # 2026-08 audit: ~9s; burst-frame ordering is re-proved at
# the engine layer (test_speculative burst/ITL drill) — the SSE composition
# re-proof moves to `slow` depth
def test_gateway_speculative_burst_flushes_frames_in_index_order(tiny_model):
    """A speculative round that accepts a burst flushes one SSE frame PER
    token, in index order — never a coalesced multi-token frame, never out
    of order. On this 1-layer model a d=1 draft IS the full stack, so every
    proposal verifies and every non-tail round lands k+1 tokens at once."""
    model, params = tiny_model
    cfg = _gcfg(max_new=6)
    engine = SlotServingEngine(
        model, params, cfg, TABLE, slots=2, kv_layout="paged",
        speculation="k4d1", rng=jax.random.PRNGKey(1),
    )
    engine.warmup()
    gw = StreamingGateway(engine).run_in_thread()
    try:
        for p in _prompts(11, [4, 7]):
            conn, resp = _post_generate(
                gw.host, gw.port, {"prompt_ids": p.tolist(), "stream": "jsonl"}
            )
            pairs, term = _read_stream_indexed(resp)
            conn.close()
            assert term is not None and term["status"] == "ok"
            # exact-once per index: the burst arrived as len(pairs) separate
            # frames numbered 0..n-1 in order
            assert [i for i, _ in pairs] == list(range(cfg.max_new_tokens))
            np.testing.assert_array_equal(
                np.asarray([t for _, t in pairs], np.int32),
                _ref(model, params, p, cfg),
            )
    finally:
        gw.close()
    spec = engine.stats()["speculation"]
    assert spec["mode"] == "k4d1" and spec["acceptance_rate"] == 1.0
    # far fewer verify rounds ran than frames hit the wire: the per-token
    # frames above really were flushed from multi-token engine steps
    assert spec["emitted"] == 2 * cfg.max_new_tokens
    assert spec["rounds"] < spec["emitted"] and spec["tokens_per_round"] > 1.0
    assert engine._pool.in_use == 0 and engine._pool.leaked() == 0


@pytest.mark.speculative
@pytest.mark.timeout(300)
@pytest.mark.slow  # 2026-08 audit: ~9s; replay dedup stays tier-1 in
# test_fleet.py (hung-replica failover drill) — the speculative-burst
# variant of the same cursor invariant moves to `slow` depth
def test_gateway_speculative_failover_replay_no_duplicate_indices(tiny_model):
    """Crash a replica mid-burst: the fleet re-runs the stream's request on
    the survivor, whose replay re-emits indices from 0 — the gateway's
    per-stream ``sent`` cursor drops the already-written prefix, so the wire
    sees every index exactly once and tokens stay identical to generate()."""
    model, params = tiny_model
    cfg = _gcfg(max_new=12)
    reg = MetricsRegistry()  # shared: outlives the crashed replica's restart

    def factory():
        return SlotServingEngine(
            model, params, cfg, TABLE, slots=2, speculation="k4d1",
            registry=reg, rng=jax.random.PRNGKey(1),
        )

    chaos = ChaosRegistry()
    chaos.crash_replica(0, 3)  # 3rd supervised step: >=1 burst already out
    fleet = FleetRouter([factory, factory], chaos=chaos)
    fleet.warmup()
    gw = StreamingGateway(fleet).run_in_thread()
    prompts = _prompts(12, [5, 7])
    results = [None, None]

    def run_one(i):
        conn, resp = _post_generate(
            gw.host, gw.port,
            {"prompt_ids": prompts[i].tolist(), "stream": "jsonl"},
        )
        try:
            results[i] = _read_stream_indexed(resp)
        finally:
            conn.close()

    try:
        threads = [
            threading.Thread(target=run_one, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
    finally:
        gw.close()

    assert chaos.fired_count("fleet.replica_step.0") == 1
    assert fleet.stats()["failovers"] >= 1
    for (pairs, term), p in zip(results, prompts):
        assert term is not None and term["status"] == "ok"
        # no duplicate indices across the replay: exactly 0..n-1, in order
        assert [i for i, _ in pairs] == list(range(cfg.max_new_tokens))
        np.testing.assert_array_equal(
            np.asarray([t for _, t in pairs], np.int32),
            _ref(model, params, p, cfg),
        )
    # the replay DID re-offer indices the wire already had: the engines
    # emitted strictly more on_token calls than frames were written
    emitted = reg.snapshot()["counters"]["spec_tokens_emitted_total"]
    assert emitted > sum(len(pairs) for pairs, _ in results)


@pytest.mark.timeout(300)
def test_gateway_client_disconnect_cancels_and_frees(tiny_model):
    """A real client disconnect mid-generation: the gateway notices the
    socket EOF, cancels the request (slot + every pool page freed, one
    terminal cancelled span), and the concurrent surviving stream's
    tokens are unchanged."""
    model, params = tiny_model
    cfg = _gcfg(max_new=16)
    tracer = Tracer()
    engine = SlotServingEngine(
        model, params, cfg, TABLE, slots=2, kv_layout="paged",
        tracer=tracer, rng=jax.random.PRNGKey(1),
    )
    engine.warmup()
    orig_step = engine.step
    engine.step = lambda: (time.sleep(0.03), orig_step())[1]  # widen the window
    gw = StreamingGateway(engine, tracer=tracer).run_in_thread()
    prompts = _prompts(8, [5, 7])
    survivor_out = {}

    def survive():
        conn, resp = _post_generate(
            gw.host, gw.port, {"prompt_ids": prompts[1].tolist(), "stream": "jsonl"}
        )
        try:
            survivor_out["result"] = _read_stream(resp)
        finally:
            conn.close()

    t = threading.Thread(target=survive)
    try:
        # the victim: raw socket, read the response head + first token,
        # then vanish
        s = socket.create_connection((gw.host, gw.port), timeout=30)
        body = json.dumps(
            {"prompt_ids": prompts[0].tolist(), "stream": "jsonl"}
        ).encode()
        s.sendall(
            b"POST /v1/generate HTTP/1.1\r\nHost: t\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body
        )
        t.start()
        buf = b""
        while b'"token"' not in buf:
            chunk = s.recv(4096)
            assert chunk, "gateway closed the victim stream prematurely"
            buf += chunk
        s.close()  # the client vanishes mid-generation
        _wait_for(
            lambda: engine.registry.counter("serving_requests_cancelled_total") >= 1,
            what="disconnect-propagated cancellation",
        )
        t.join(60)
    finally:
        gw.close()
    toks, term = survivor_out["result"]
    assert term["status"] == "ok"
    np.testing.assert_array_equal(
        np.asarray(toks, np.int32), _ref(model, params, prompts[1], cfg)
    )
    assert engine._pool.in_use == 0 and engine._pool.reserved == 0
    assert engine._pool.leaked() == 0
    assert engine._pool.frees_by_cause.get("cancelled", 0) > 0
    terminal = [
        sp for sp in tracer.spans("serving.request") if sp.status == "cancelled"
    ]
    assert len(terminal) == 1
    stats = gw.stats()
    assert stats["streams_cancelled"] == 1 and stats["streams_completed"] == 1
    assert stats["streams"] == 2


@pytest.mark.timeout(300)
def test_gateway_chaos_mass_abandonment(tiny_model):
    """The chaos drill (acceptance): scripted ``gateway.disconnect`` faults
    abandon 50% of in-flight streams mid-generation; every survivor
    completes token_identical, zero slot/page leak, and disposition
    accounting reconciles (completed + cancelled == accepted streams)."""
    model, params = tiny_model
    cfg = _gcfg(max_new=10)
    chaos = ChaosRegistry()
    # streams are numbered in accept order: cut 1 and 3 before their 2nd token
    chaos.disconnect_stream(1, after_tokens=2)
    chaos.disconnect_stream(3, after_tokens=2)
    tracer = Tracer()
    engine = SlotServingEngine(
        model, params, cfg, TABLE, slots=2, kv_layout="paged",
        tracer=tracer, rng=jax.random.PRNGKey(1),
    )
    engine.warmup()
    gw = StreamingGateway(engine, tracer=tracer, chaos=chaos).run_in_thread()
    prompts = _prompts(9, [5, 6, 7, 8])
    results = []
    try:
        conns = []
        # sequential connects pin the accept-order stream ids 1..4
        for p in prompts:
            conns.append(_post_generate(
                gw.host, gw.port, {"prompt_ids": p.tolist(), "stream": "jsonl"}
            ))
        for conn, resp in conns:
            results.append(_read_stream(resp))
            conn.close()
        _wait_for(
            lambda: gw.stats()["streams_completed"]
            + gw.stats()["streams_cancelled"] >= 4,
            what="all streams terminal",
        )
    finally:
        gw.close()
    victims = [results[0], results[2]]
    survivors = [(results[1], prompts[1]), (results[3], prompts[3])]
    for toks, term in victims:
        assert term is None  # cut before the terminal record
        assert len(toks) == 1  # exactly after_tokens - 1 made the wire
    for (toks, term), p in survivors:
        assert term is not None and term["status"] == "ok"
        np.testing.assert_array_equal(
            np.asarray(toks, np.int32), _ref(model, params, p, cfg)
        )
    # zero-leak + closed accounting
    assert engine._pool.in_use == 0 and engine._pool.reserved == 0
    assert engine._pool.leaked() == 0
    stats = gw.stats()
    assert stats["streams"] == 4
    assert stats["streams_cancelled"] == 2 and stats["streams_completed"] == 2
    counts = engine.registry.counters()
    assert counts["serving_requests_cancelled_total"] == 2
    assert counts["serving_requests_completed_total"] == 2
    assert counts["serving_requests_submitted_total"] == 4
    assert chaos.fired_count() == 2
    cancelled_events = [
        sp for sp in tracer.spans("gateway.request")
        if sp.status == "cancelled"
    ]
    assert len(cancelled_events) == 2


@pytest.mark.timeout(180)
def test_gateway_endpoints_and_rejections(tiny_model):
    """The non-streaming surface: /healthz LB semantics, /metrics with
    HELP lines, 404/405, 400 on bad JSON and infeasible prompts (engine
    rejection counters move), 503 + Retry-After on backpressure."""
    model, params = tiny_model
    cfg = _gcfg(max_new=3)
    engine = SlotServingEngine(
        model, params, cfg, TABLE, slots=2, rng=jax.random.PRNGKey(1)
    )
    gw = StreamingGateway(engine).run_in_thread()
    try:
        status, body = _get(gw.host, gw.port, "/healthz")
        health = json.loads(body)
        assert status == 200 and health["ready"] is True
        assert "cancelled" in health  # the extended shared schema
        status, body = _get(gw.host, gw.port, "/metrics")
        assert status == 200
        text = body.decode()
        for family in GATEWAY_COUNTERS:
            assert f"# HELP {family} " in text, family
        status, _ = _get(gw.host, gw.port, "/nope")
        assert status == 404
        conn = http.client.HTTPConnection(gw.host, gw.port, timeout=30)
        conn.request("GET", "/v1/generate")
        assert conn.getresponse().status == 405
        conn.close()
        conn, resp = _post_generate(gw.host, gw.port, None)  # "null" body
        assert resp.status == 400
        conn.close()
        # infeasible: longer than the largest bucket -> 400 with the
        # engine's own error + trace id, rejected counters on both layers
        conn, resp = _post_generate(
            gw.host, gw.port, {"prompt_ids": list(range(1, 20))}
        )
        assert resp.status == 400
        detail = json.loads(resp.read())
        assert "exceeds the largest bucket" in detail["error"]
        conn.close()
        assert engine.registry.counter("serving_requests_rejected_total") == 1
        assert engine.registry.counter("gateway_streams_rejected_total") == 2
        # malformed FIELDS are clean 400s too, never a bare connection
        # reset out of a dead handler (review hardening)
        for bad in ({"prompt_ids": [1, 2], "deadline_s": "5"},
                    {"prompt_ids": [1, 2], "max_new_tokens": [4]},
                    {"prompt_ids": "not-ids"},
                    # remote buffer-sizing is bounded: absurd or
                    # non-positive max_new overrides are 400s, never an
                    # allocation (review hardening)
                    {"prompt_ids": [1, 2], "max_new_tokens": 10**9},
                    {"prompt_ids": [1, 2], "max_new_tokens": 0}):
            conn, resp = _post_generate(gw.host, gw.port, bad)
            assert resp.status == 400, bad
            assert "error" in json.loads(resp.read())
            conn.close()
        assert engine.registry.counter("gateway_streams_rejected_total") == 7
        # an attacker-sized Content-Length is answered 413 and never
        # buffered
        s = socket.create_connection((gw.host, gw.port), timeout=30)
        s.sendall(b"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Length: 9999999999\r\n\r\n")
        head = s.recv(4096)
        assert b"413" in head.split(b"\r\n", 1)[0]
        s.close()
    finally:
        gw.close()

    # backpressure -> 503 (stubbed engine: deterministic without racing a
    # real queue)
    class SheddingStub:
        registry = MetricsRegistry()
        tracer = None

        def submit(self, *a, **k):
            raise QueueFull("stub at capacity")

        def pending(self):
            return False

        def step(self):
            return 0

        def health(self):
            return {"ready": False}

        def cancel(self, request_id):
            return False

    gw2 = StreamingGateway(SheddingStub()).run_in_thread()
    try:
        conn, resp = _post_generate(gw2.host, gw2.port, {"prompt_ids": [1, 2, 3]})
        assert resp.status == 503
        assert resp.getheader("Retry-After") == "1"
        assert "stub at capacity" in json.loads(resp.read())["error"]
        conn.close()
        status, _ = _get(gw2.host, gw2.port, "/healthz")
        assert status == 503  # not ready -> LB pulls the backend
    finally:
        gw2.close()
    with pytest.raises(ValueError, match="stream must be one of"):
        StreamingGateway(SheddingStub(), stream="bogus")


# -- loadgen http client mode -----------------------------------------------
@pytest.mark.timeout(300)
def test_loadgen_http_mode_over_gateway(tiny_model):
    """The loadgen satellite: the same LoadGenerator drives the full
    network path through GatewayHttpClient — goodput accounting via the
    shared slo.py helpers, bytes-on-wire reported beside offered/completed."""
    model, params = tiny_model
    cfg = _gcfg(max_new=3)
    engine = SlotServingEngine(
        model, params, cfg, TABLE, slots=2, tracer=Tracer(),
        rng=jax.random.PRNGKey(1),
    )
    engine.warmup()
    gw = StreamingGateway(engine).run_in_thread()
    try:
        client = GatewayHttpClient(gw.host, gw.port)
        gen = LoadGenerator(
            client,
            workload=WorkloadSpec(prompt_len=(4, 8), max_new_tokens=(2, 3),
                                  vocab=(1, 80)),
            mode="open", arrival="uniform", rate_rps=50.0, max_requests=5,
            config=cfg, rng=3,
        )
        report = gen.run()
    finally:
        gw.close()
    assert report["offered"] == 5 and report["completed"] == 5
    assert report["goodput_ratio"] == 1.0
    assert report["bytes_on_wire"] > 0
    assert all(h.status == "ok" for h in gen.handles)
    # streamed tokens round-trip: each handle's result matches the oracle
    for h in gen.handles:
        assert h.result is not None and h.result.size >= 2
        assert h.trace_id is not None
    # shed maps back to QueueFull at submit (503), reject to ValueError
    # (400) — over a stub gateway so the mapping is deterministic
    class SheddingStub:
        registry = MetricsRegistry()
        tracer = None

        def submit(self, *a, **k):
            raise QueueFull("stub at capacity")

        def pending(self):
            return False

        def step(self):
            return 0

        def health(self):
            return {"ready": False}

        def cancel(self, request_id):
            return False

    gw2 = StreamingGateway(SheddingStub()).run_in_thread()
    try:
        client2 = GatewayHttpClient(gw2.host, gw2.port)
        with pytest.raises(QueueFull, match="503"):
            client2.submit(np.asarray([1, 2, 3], np.int32))
    finally:
        gw2.close()
    # a transport-level failure is ONE failed request, not a crashed run:
    # the client returns a terminal handle the generator's accounting
    # absorbs (review hardening)
    dead = GatewayHttpClient("127.0.0.1", 9, timeout_s=0.5)  # discard port
    handle = dead.submit(np.asarray([1, 2], np.int32))
    assert handle.status == "failed" and handle.error
    assert not dead.pending()


# -- obs report + HELP satellites -------------------------------------------
@pytest.mark.timeout(60)
def test_report_gateway_section_pinned_over_fixtures():
    """The fixture satellite: the checked-in artifacts render the gateway
    section with pinned values — connection/stream table, cancellation
    counts, socket-vs-engine TTFT deltas."""
    analysis = json.loads(report_mod.run(
        "tests/fixtures/events.jsonl",
        "tests/fixtures/metrics_snapshot.json", as_json=True,
    ))
    gw = analysis["gateway"]
    assert gw["connections"] == {"total": 5, "active": 0}
    assert gw["streams"]["total"] == 5
    assert gw["streams"]["completed"] == 4
    assert gw["streams"]["cancelled"] == 1
    assert gw["streams"]["by_status"] == {"cancelled": 1, "ok": 4}
    assert gw["streams"]["tokens_streamed"] == 12
    assert gw["cancellations"]["events"] == 1
    assert gw["cancellations"]["requests_cancelled"] == 1
    assert gw["socket_ttft"]["p50_ms"] == 42.0
    assert gw["socket_vs_engine_ttft_delta_ms"] == {
        "p50_ms": 2.0, "p95_ms": 3.0,
    }
    # the cancelled request reached the terminal-span table too
    assert analysis["requests"]["by_status"]["cancelled"] == 1
    text = report_mod.run(
        "tests/fixtures/events.jsonl", "tests/fixtures/metrics_snapshot.json"
    )
    assert "== gateway ==" in text
    assert "streams: 5 accepted  completed=4  cancelled=1  rejected=0" in text
    assert "socket-vs-engine ttft delta ms: p50=2.0 p95=3.0" in text
    # artifacts without a gateway render no section (old runs unchanged)
    assert report_mod.analyze([], {})["gateway"] is None
    # events-only fallback (no snapshot): stream counts derive from the
    # gateway.request events' terminal statuses, no literal None rendering
    rows = [
        {"span": "gateway.request", "trace_id": "t1", "start_s": 0.0,
         "duration_ms": 0.0, "status": "ok", "attrs": {"tokens": 3, "bytes": 10}},
        {"span": "gateway.request", "trace_id": "t2", "start_s": 0.0,
         "duration_ms": 0.0, "status": "cancelled",
         "attrs": {"tokens": 1, "bytes": 4}},
    ]
    fallback = report_mod.analyze(rows, None)["gateway"]
    assert fallback["source"] == "events"
    assert fallback["streams"]["total"] == 2
    assert fallback["streams"]["completed"] == 1
    assert fallback["streams"]["cancelled"] == 1
    rendered = report_mod.format_report(report_mod.analyze(rows, None))
    section = rendered.split("== gateway ==")[1].split("\n==")[0]
    assert "(from events)" in section and "None" not in section


@pytest.mark.timeout(180)
def test_every_gateway_family_has_direct_help(tiny_model):
    """The HELP satellite (PR 9 convention): every family a
    traffic-bearing gateway + engine publishes — gateway_* and the new
    cancelled counters included — has a non-fallback # HELP line."""
    model, params = tiny_model
    cfg = _gcfg(max_new=3)
    engine = SlotServingEngine(
        model, params, cfg, TABLE, slots=2, kv_layout="paged",
        rng=jax.random.PRNGKey(1),
    )
    gw = StreamingGateway(engine).run_in_thread()
    try:
        conn, resp = _post_generate(
            gw.host, gw.port, {"prompt_ids": _prompts(12, [5])[0].tolist()}
        )
        _read_stream(resp)
        conn.close()
    finally:
        gw.close()
    snap = engine.registry.snapshot()
    published = (
        set(snap["counters"]) | set(snap["gauges"]) | set(snap["histograms"])
    )
    assert set(GATEWAY_COUNTERS) <= published
    assert "gateway_socket_ttft_ms" in published
    assert "serving_requests_cancelled_total" in published
    missing = sorted(n for n in published if n not in HELP_TEXT)
    assert not missing, f"families without a direct HELP entry: {missing}"
    text = to_prometheus_text(engine.registry)
    for name in published:
        assert f"# HELP {name} " in text, name


# -- bench probes -----------------------------------------------------------
@pytest.mark.timeout(300)
@pytest.mark.slow  # 2026-08 audit: ~4s; bench probes' real lane is their
# make target (`make stream-bench`) and test_bench_probe.py keeps bench.py
# import/CLI bitrot in tier-1
def test_bench_streaming_probe_tiny(tiny_model):
    """Tiny end-to-end run of the extras.streaming probe: deterministic
    FakeClock abandonment with zero leak, closed accounting, survivor
    identity, and a reclaim latency bounded by one scheduler pass."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_gw_tiny", "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    model, params = tiny_model
    cfg = CausalLanguageModelConfig(**TINY)
    out = bench._bench_streaming(
        model, params, cfg, slots=2, n_requests=4, new_tokens=4,
        cancel_after_tokens=1,
    )
    assert out["requests"] == 4 and out["abandoned"] == 2
    assert out["token_identical"] is True
    assert out["accounting_closed"] is True
    assert out["completed"] == 2 and out["cancelled"] == 2
    assert out["pool"]["leaked"] == 0
    assert out["pool"]["in_use_after_drain"] == 0
    assert out["pool"]["frees_by_cause"].get("cancelled", 0) > 0
    assert out["reclaim"]["max_ms"] <= out["reclaim"]["bound_ms"]


@pytest.mark.timeout(300)
@pytest.mark.slow  # 2026-08 audit: ~6s; goodput accounting is pinned by
# test_slo.py's unit drills — the sockets-transport probe re-proof rides
# the `make slo` lane
def test_bench_slo_goodput_http_transport_tiny(tiny_model):
    """The one-flag transport switch: the same slo_goodput probe runs its
    sweep over real sockets (GatewayHttpClient), reporting bytes-on-wire
    per point with the shared goodput accounting."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_gw_http_tiny", "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    model, params = tiny_model
    cfg = CausalLanguageModelConfig(**TINY)
    out = bench._bench_slo_goodput(
        model, params, cfg, requests_per_rate=4, new_tokens=3, slots=2,
        rate_factors=(1.0,), transport="http",
    )
    assert out["transport"] == "http"
    assert len(out["sweep"]) == 1
    point = out["sweep"][0]
    assert point["offered"] == 4
    assert point["bytes_on_wire"] > 0
    assert point["p95_ttft_ms"] is not None
    with pytest.raises(ValueError, match="transport"):
        bench._bench_slo_goodput(model, params, cfg, transport="carrier-pigeon")


# -- CLI flag surface --------------------------------------------------------
@pytest.mark.timeout(60)
def test_serve_http_flag_group():
    """--serve.http.* is a real nested flag group: specs exist, values
    build, defaults keep the gateway off."""
    from perceiver_io_tpu.scripts.cli import ServeArgs, build_dataclass, flag_specs

    specs = flag_specs(ServeArgs, "serve")
    for flag in ("serve.http.port", "serve.http.host", "serve.http.stream",
                 "serve.http.max_streams"):
        assert flag in specs, flag
    args = build_dataclass(ServeArgs, {
        "serve.http.port": "0", "serve.http.stream": "jsonl",
        "serve.http.max_streams": "3",
    }, "serve")
    assert args.http.port == 0 and args.http.stream == "jsonl"
    assert args.http.max_streams == 3
    assert build_dataclass(ServeArgs, {}, "serve").http.port is None
