"""SLO telemetry tests (docs/observability.md "SLO telemetry"):
per-request TTFT / inter-token latency accounting on both engines, the
multi-window burn-rate monitor, the deterministic synthetic-user load
generator, telemetry-driven fleet admission, and the `obs report` SLO
section.

The load-bearing drill (the PR's acceptance criterion): under FakeClock,
an injected latency fault raises the burn-rate gauges, increments
`slo_breach_total`, arms the ProfilerTrigger, and tightens FleetRouter
admission (the shed counter moves) — then everything recovers when the
fault clears. All pure-CPU, tiny shapes, zero sleeps — tier-1 under
tight per-test budgets.
"""
import contextlib
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.inference.generate import GenerationConfig
from perceiver_io_tpu.inference.samplers import SamplingConfig
from perceiver_io_tpu.models.text.clm import (
    CausalLanguageModel,
    CausalLanguageModelConfig,
)
from perceiver_io_tpu.observability import (
    LoadGenerator,
    MetricsRegistry,
    ProfilerTrigger,
    SLOMonitor,
    SLOPolicy,
    Tracer,
    WorkloadSpec,
    goodput_ratio,
    offered_load,
    to_prometheus_text,
)
from perceiver_io_tpu.observability import report as report_mod
from perceiver_io_tpu.observability.exporters import HELP_TEXT
from perceiver_io_tpu.reliability import FakeClock, QueueFull
from perceiver_io_tpu.serving import (
    BucketTable,
    FleetRouter,
    ServingEngine,
    SlotServingEngine,
)

pytestmark = [pytest.mark.slo, pytest.mark.timeout(300)]

KEY = jax.random.PRNGKey(0)

# Deliberately NOT a shape other test modules use: executor cache keys
# include the module fingerprint, and an identically-configured model in
# another file would pre-populate the cache this file relies on warming.
TINY = dict(
    vocab_size=83, max_seq_len=32, max_latents=8, num_channels=16,
    num_heads=2, num_self_attention_layers=1, cross_attention_dropout=0.0,
)
GREEDY = SamplingConfig(temperature=0.0)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = CausalLanguageModelConfig(**TINY)
    model = CausalLanguageModel(cfg)
    params = model.init(KEY, jnp.zeros((1, 32), jnp.int32), 8)["params"]
    return model, params


def _gcfg(max_new=4, num_latents=2):
    return GenerationConfig(
        max_new_tokens=max_new, num_latents=num_latents, sampling=GREEDY
    )


def _null_trigger():
    return ProfilerTrigger(
        "/tmp/slo-test", capture_fn=lambda d: contextlib.nullcontext()
    )


# -- units ------------------------------------------------------------------
@pytest.mark.timeout(60)
def test_policy_and_monitor_validation():
    with pytest.raises(ValueError, match="at least one target"):
        SLOPolicy().dimensions()
    with pytest.raises(ValueError, match="error_rate"):
        SLOPolicy(error_rate=1.5).dimensions()
    assert [d for d, _ in SLOPolicy(
        ttft_p95_ms=1.0, inter_token_p95_ms=1.0, error_rate=0.1
    ).dimensions()] == ["ttft", "inter_token", "error"]
    policy = SLOPolicy(ttft_p95_ms=1.0)
    with pytest.raises(ValueError, match="fast_window_s"):
        SLOMonitor(policy, fast_window_s=10.0, slow_window_s=5.0)
    with pytest.raises(ValueError, match="breach_burn_rate"):
        SLOMonitor(policy, breach_burn_rate=0.0)
    with pytest.raises(ValueError, match="windows"):
        SLOMonitor(policy, fast_window_s=0.0)


@pytest.mark.timeout(60)
def test_offered_goodput_shared_definition():
    """The ONE goodput denominator (observability/slo.py): offered =
    accepted + shed + rejected, for both counter prefixes — the helper
    bench's fleet_chaos / observability / slo_goodput probes share."""
    counts = {
        "serving_requests_submitted_total": 8.0,
        "serving_requests_shed_total": 2.0,
        "serving_requests_rejected_total": 2.0,
        "serving_requests_completed_total": 6.0,
    }
    assert offered_load(counts) == 12
    assert goodput_ratio(counts) == 0.5
    fleet = {
        "fleet_requests_submitted_total": 4.0,
        "fleet_requests_completed_total": 4.0,
    }
    assert offered_load(fleet, "fleet") == 4
    assert goodput_ratio(fleet, "fleet") == 1.0
    assert goodput_ratio({}, "fleet") == 0.0  # empty counters: no div-zero


@pytest.mark.timeout(60)
def test_burn_rate_monitor_breach_and_recovery():
    """The monitor-level drill: healthy samples → zero burn; a latency
    fault → both windows burn, gauges rise, `slo_breach_total` and the
    breach event fire, the trigger arms; fresh healthy samples → the fast
    window clears, the dimension recovers."""
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    tracer = Tracer(clock=clock)
    trigger = _null_trigger()
    mon = SLOMonitor(
        SLOPolicy(ttft_p95_ms=100.0), clock=clock, registry=reg,
        tracer=tracer, profiler_trigger=trigger,
        fast_window_s=10.0, slow_window_s=50.0, min_samples=3,
    )
    for _ in range(10):
        mon.observe_ttft(50.0)
        clock.advance(1.0)
    assert mon.poll()["ttft"] == {
        "burn_fast": 0.0, "burn_slow": 0.0, "breached": False,
        "samples_fast": 10,
    }
    assert not mon.breached and not trigger.armed
    # the fault: every sample misses the target
    for _ in range(10):
        mon.observe_ttft(500.0)
        clock.advance(1.0)
    verdict = mon.poll()["ttft"]
    assert verdict["breached"] and verdict["burn_fast"] == 20.0
    assert mon.breached and mon.active_breaches == ["ttft"]
    assert reg.counter("slo_breach_total") == 1
    assert reg.counter("slo_breach_ttft_total") == 1
    assert reg.gauge("slo_burn_rate_ttft_fast") == 20.0
    assert reg.gauge("slo_burn_rate") > 0.0
    assert trigger.armed
    breach = tracer.spans("slo.breach")
    assert len(breach) == 1 and breach[0].attrs["dimension"] == "ttft"
    # a second poll while still burning must NOT double-count the breach
    mon.poll()
    assert reg.counter("slo_breach_total") == 1
    # the fault clears: fresh samples push the fast window under threshold
    for _ in range(12):
        mon.observe_ttft(10.0)
        clock.advance(1.0)
    assert not mon.poll()["ttft"]["breached"]
    assert not mon.breached
    assert reg.counter("slo_recoveries_total") == 1
    assert len(tracer.spans("slo.recover")) == 1
    assert reg.gauge("slo_burn_rate_ttft_fast") == 0.0


@pytest.mark.timeout(60)
def test_monitor_blip_does_not_breach():
    """Multi-window semantics: a short burst of bad samples against a long
    healthy history burns the fast window but not the slow one — no
    breach (the slow window is the sustained-burn proof)."""
    clock = FakeClock()
    mon = SLOMonitor(
        SLOPolicy(ttft_p95_ms=100.0), clock=clock,
        fast_window_s=5.0, slow_window_s=100.0, min_samples=2,
    )
    for _ in range(96):
        mon.observe_ttft(10.0)
        clock.advance(1.0)
    for _ in range(4):
        mon.observe_ttft(500.0)
        clock.advance(1.0)
    verdict = mon.poll()["ttft"]
    assert verdict["burn_fast"] >= 2.0  # the blip IS visible...
    assert verdict["burn_slow"] < 2.0  # ...but not sustained
    assert not mon.breached  # so no breach


@pytest.mark.timeout(60)
def test_monitor_stall_is_not_recovery():
    """A total stall after a breach — no samples at all — must HOLD the
    breach: an empty fast window is absence of evidence, and loosening
    admission mid-outage would make the outage worse."""
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    mon = SLOMonitor(
        SLOPolicy(ttft_p95_ms=100.0), clock=clock, registry=reg,
        fast_window_s=5.0, slow_window_s=20.0, min_samples=3,
    )
    for _ in range(5):
        mon.observe_ttft(500.0)
        clock.advance(1.0)
    assert mon.poll()["ttft"]["breached"]
    clock.advance(30.0)  # everything ages out of BOTH windows
    verdict = mon.poll()["ttft"]
    assert verdict["burn_fast"] == 0.0 and verdict["samples_fast"] == 0
    assert verdict["breached"] and mon.breached  # held, not recovered
    assert reg.counter("slo_recoveries_total") == 0
    # fresh healthy evidence (min_samples of it) is what recovers
    for _ in range(3):
        mon.observe_ttft(10.0)
    assert not mon.poll()["ttft"]["breached"]
    assert reg.counter("slo_recoveries_total") == 1


@pytest.mark.timeout(60)
def test_monitor_error_dimension_from_counters():
    """watch_counters: the error dimension fed by diffing cumulative
    disposition counters per poll — failures past the budget breach."""
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    mon = SLOMonitor(
        SLOPolicy(error_rate=0.1), clock=clock, registry=reg,
        fast_window_s=10.0, slow_window_s=10.0, min_samples=4,
    )
    counts = {"serving_requests_completed_total": 0.0,
              "serving_requests_failed_total": 0.0}
    mon.watch_counters(lambda: dict(counts))
    counts["serving_requests_completed_total"] = 8.0
    assert mon.poll()["error"]["samples_fast"] == 8
    assert not mon.breached
    counts["serving_requests_failed_total"] = 8.0
    verdict = mon.poll()["error"]
    assert verdict["samples_fast"] == 16
    # 8 bad / 16 = 0.5 against a 0.1 budget -> burn 5x
    assert verdict["burn_fast"] == 5.0 and mon.breached


@pytest.mark.timeout(60)
def test_slo_tightened_sheds_do_not_feed_the_error_dimension():
    """No feedback loop: sheds caused by the breach's own admission
    tightening (counted in *_slo_shed_total beside the ordinary shed
    counter) are excluded from the error feed — otherwise tightening
    sheds load, the sheds burn the error budget, and the breach sustains
    itself forever. Ordinary sheds still count."""
    clock = FakeClock()
    mon = SLOMonitor(
        SLOPolicy(error_rate=0.1), clock=clock,
        fast_window_s=10.0, slow_window_s=10.0, min_samples=2,
    )
    counts = {
        "fleet_requests_completed_total": 0.0,
        "fleet_requests_shed_total": 0.0,
        "fleet_slo_shed_total": 0.0,
    }
    mon.watch_counters(lambda: dict(counts), prefix="fleet")
    # 4 tightening-induced sheds (double-counted in the shed counter):
    # zero error samples reach the window
    counts["fleet_requests_shed_total"] = 4.0
    counts["fleet_slo_shed_total"] = 4.0
    assert mon.poll()["error"]["samples_fast"] == 0
    # 2 ordinary sheds on top: exactly those 2 count as bad
    counts["fleet_requests_shed_total"] = 6.0
    verdict = mon.poll()["error"]
    assert verdict["samples_fast"] == 2 and verdict["burn_fast"] == 10.0


@pytest.mark.timeout(60)
def test_profiler_trigger_arm_respects_budget():
    trigger = _null_trigger()
    assert trigger.arm() and trigger.armed
    with trigger.capture():
        pass
    # cooldown after a capture: arm() must refuse, exactly like observe()
    assert not trigger.arm()
    trigger._cooldown_left = 0
    trigger.captures = trigger.max_captures
    assert not trigger.arm()


# -- load generator ---------------------------------------------------------
@pytest.mark.timeout(60)
def test_loadgen_validation_and_arrivals():
    class _Stub:
        def submit(self, *a, **k):
            raise AssertionError("not driven")

        def step(self):
            return 0

        def pending(self):
            return False

    stub = _Stub()
    with pytest.raises(ValueError, match="arrival"):
        LoadGenerator(stub, arrival="nope")
    with pytest.raises(ValueError, match="mode"):
        LoadGenerator(stub, mode="nope")
    with pytest.raises(ValueError, match="ramp_to_rps"):
        LoadGenerator(stub, arrival="ramp")
    with pytest.raises(ValueError, match="ramp_to_rps"):
        LoadGenerator(stub, arrival="ramp", ramp_to_rps=0.0)
    with pytest.raises(ValueError, match="step_cost_s"):
        LoadGenerator(stub, step_cost_s=0.0)
    with pytest.raises(ValueError, match="rate_rps"):
        LoadGenerator(stub, rate_rps=0.0)
    # uniform: exact spacing; bursty: zero gaps inside each burst; ramp:
    # drawn from a rate that interpolates start -> end
    uni = LoadGenerator(stub, arrival="uniform", rate_rps=4.0, max_requests=4)
    assert uni._gaps() == [0.25] * 4
    bursty = LoadGenerator(
        stub, arrival="bursty", rate_rps=8.0, burst_size=4, max_requests=8
    )
    gaps = bursty._gaps()
    assert gaps[1] == gaps[2] == gaps[3] == 0.0 and gaps[0] > 0.0
    ramp = LoadGenerator(
        stub, arrival="ramp", rate_rps=2.0, ramp_to_rps=20.0, max_requests=32
    )
    assert len(ramp._gaps()) == 32
    # same seed -> identical schedule (the determinism contract)
    a = LoadGenerator(stub, arrival="poisson", rate_rps=5.0, max_requests=16,
                      rng=7)._gaps()
    b = LoadGenerator(stub, arrival="poisson", rate_rps=5.0, max_requests=16,
                      rng=7)._gaps()
    assert a == b


def test_loadgen_open_loop_deterministic_replay(tiny_model):
    """Two identical FakeClock open-loop drills replay bit-identically:
    same report, same registry percentiles, same emitted tokens."""
    model, params = tiny_model

    def run():
        clock = FakeClock()
        engine = SlotServingEngine(
            model, params, _gcfg(), BucketTable(prompt_lens=(8,), batch_sizes=(1,)),
            slots=2, clock=clock, rng=jax.random.PRNGKey(1),
        )
        gen = LoadGenerator(
            engine,
            workload=WorkloadSpec(prompt_len=(4, 8), max_new_tokens=(2, 4),
                                  vocab=(1, TINY["vocab_size"])),
            mode="open", arrival="poisson", rate_rps=40.0, max_requests=6,
            config=_gcfg(), rng=3, clock=clock, step_cost_s=0.01,
        )
        report = gen.run()
        outs = [h.result.tolist() for h in gen.handles if h.status == "ok"]
        return report, outs, engine.stats()["ttft_ms"], engine.stats()["inter_token_ms"]

    r1, r2 = run(), run()
    assert r1 == r2
    report = r1[0]
    assert report["offered"] == 6 and report["completed"] == 6
    assert report["goodput_ratio"] == 1.0
    assert report["arrival"] == "poisson"


def test_loadgen_closed_loop_bounds_concurrency(tiny_model):
    """Closed loop: at most `users` requests are ever in flight, think
    times gate resubmission, and the drill is deterministic."""
    model, params = tiny_model
    clock = FakeClock()
    engine = SlotServingEngine(
        model, params, _gcfg(), BucketTable(prompt_lens=(8,), batch_sizes=(1,)),
        slots=4, clock=clock, rng=jax.random.PRNGKey(1),
    )
    submits = []
    original = engine.submit

    def spy(prompt, config=None, **kw):
        req = original(prompt, config, **kw)
        submits.append(clock())
        return req

    engine.submit = spy
    gen = LoadGenerator(
        engine,
        workload=WorkloadSpec(prompt_len=(4, 8), max_new_tokens=(2, 3),
                              vocab=(1, TINY["vocab_size"]),
                              think_time_s=(0.05, 0.05)),
        mode="closed", users=2, max_requests=6, config=_gcfg(),
        rng=5, clock=clock, step_cost_s=0.01,
    )
    report = gen.run()
    assert report["offered"] == 6 and report["completed"] == 6
    # never more than `users` in flight: submit k+2 comes after submit k's
    # request finished (2 users); with think time the schedule is spaced
    assert len(submits) == 6
    assert all(b >= a for a, b in zip(submits, submits[1:]))


@pytest.mark.timeout(120)
def test_loadgen_drives_bucket_engine_and_fleet(tiny_model):
    """The generator works over the WHOLE shared request surface: the
    bucket engine and the fleet router, unchanged."""
    model, params = tiny_model
    clock = FakeClock()
    table = BucketTable(prompt_lens=(8,), batch_sizes=(1, 2))
    engine = ServingEngine(
        model, params, _gcfg(), table, clock=clock, rng=jax.random.PRNGKey(1)
    )
    rep = LoadGenerator(
        engine, workload=WorkloadSpec(prompt_len=(4, 8), vocab=(1, 80)),
        mode="open", arrival="uniform", rate_rps=100.0, max_requests=4,
        rng=0, clock=clock, step_cost_s=0.01,
    ).run()
    assert rep["completed"] == 4

    def factory():
        return SlotServingEngine(
            model, params, _gcfg(), BucketTable(prompt_lens=(8,), batch_sizes=(1,)),
            slots=2, clock=clock, rng=jax.random.PRNGKey(1),
        )

    fleet = FleetRouter([factory] * 2, clock=clock)
    rep = LoadGenerator(
        fleet, workload=WorkloadSpec(prompt_len=(4, 8), vocab=(1, 80)),
        mode="open", arrival="bursty", rate_rps=100.0, burst_size=2,
        max_requests=4, rng=0, clock=clock, step_cost_s=0.01,
    ).run()
    assert rep["completed"] == 4
    # fleet-scope mirror: the router registry saw every replica's samples
    assert fleet.registry.histogram("serving_ttft_ms").count == 4


# -- per-token latency accounting ------------------------------------------
def test_slot_engine_ttft_and_inter_token_accounting(tiny_model):
    """Slot engine: one TTFT sample + one `serving.first_token` event per
    request (queue wait + prefill included via the request's submit time),
    one ITL sample per subsequent token, on the injectable clock —
    values exactly reproducible under FakeClock."""
    model, params = tiny_model
    clock = FakeClock()
    tracer = Tracer(clock=clock)
    engine = SlotServingEngine(
        model, params, _gcfg(max_new=3),
        BucketTable(prompt_lens=(8,), batch_sizes=(1,)),
        slots=2, clock=clock, tracer=tracer, rng=jax.random.PRNGKey(1),
    )
    reqs = [engine.submit(np.arange(1, 9, dtype=np.int32)) for _ in range(2)]
    while engine.pending():
        engine.step()
        clock.advance(0.01)
    assert all(r.status == "ok" for r in reqs)
    reg = engine.registry
    ttft = reg.histogram("serving_ttft_ms")
    itl = reg.histogram("serving_inter_token_ms")
    assert ttft.count == 2
    # 3 tokens per request -> 2 inter-token gaps each
    assert itl.count == 2 * (3 - 1)
    # both requests' first tokens materialized on the first decode step, at
    # t=0 on the FakeClock (prefills and the step ran before any advance)
    assert ttft.percentile(95.0) == 0.0
    # each subsequent token is exactly one 10ms step later
    assert itl.percentile(50.0) == 10.0 and itl.percentile(95.0) == 10.0
    events = tracer.spans("serving.first_token")
    assert len(events) == 2
    assert {e.trace_id for e in events} == {r.trace_id for r in reqs}
    assert all("ttft_ms" in e.attrs and "slot" in e.attrs for e in events)
    stats = engine.stats()
    assert stats["ttft_ms"]["p95"] == 0.0
    assert stats["inter_token_ms"]["p95"] == 10.0


def test_bucket_engine_ttft_batch_amortized(tiny_model):
    """Bucket engine: batch-granular accounting — TTFT is submit → batch
    completion, ITL the amortized per-token device time, ONE sample per
    request, `batch_granular` flagged on the event."""
    model, params = tiny_model
    clock = FakeClock()
    tracer = Tracer(clock=clock)
    engine = ServingEngine(
        model, params, _gcfg(max_new=4),
        BucketTable(prompt_lens=(8,), batch_sizes=(2,)),
        clock=clock, tracer=tracer, rng=jax.random.PRNGKey(1),
    )
    reqs = [engine.submit(np.arange(1, 9, dtype=np.int32)) for _ in range(2)]
    clock.advance(0.5)  # queue wait: must land inside TTFT
    engine.run_until_idle()
    reg = engine.registry
    assert reg.histogram("serving_ttft_ms").count == 2
    assert reg.histogram("serving_inter_token_ms").count == 2
    assert reg.percentile("serving_ttft_ms", 50.0) >= 500.0
    events = tracer.spans("serving.first_token")
    assert len(events) == 2
    assert all(e.attrs.get("batch_granular") for e in events)
    assert {e.trace_id for e in events} == {r.trace_id for r in reqs}


def test_fleet_ttft_anchored_at_front_door(tiny_model):
    """TTFT is user-facing: a request that waits in the FLEET queue (the
    engine hasn't seen it yet) still counts that wait in its TTFT — the
    router hands its submit time down as the anchor at dispatch."""
    model, params = tiny_model
    clock = FakeClock()

    def factory():
        return SlotServingEngine(
            model, params, _gcfg(), BucketTable(prompt_lens=(8,), batch_sizes=(1,)),
            slots=2, clock=clock, rng=jax.random.PRNGKey(1),
        )

    fleet = FleetRouter([factory], clock=clock)
    fleet.submit(np.arange(1, 9, dtype=np.int32))
    clock.advance(2.0)  # fleet queue wait before any dispatch
    while fleet.pending():
        fleet.step()
        clock.advance(0.01)
    # fleet scope (mirror) and replica scope (private registry) both carry
    # the front-door-anchored number
    assert fleet.registry.percentile("serving_ttft_ms", 50.0) >= 2000.0
    replica_reg = fleet.replicas[0].engine.registry
    assert replica_reg.percentile("serving_ttft_ms", 50.0) >= 2000.0
    assert fleet.stats()["ttft_ms"]["p50"] >= 2000.0


# -- the acceptance drill ---------------------------------------------------
@pytest.mark.timeout(120)
def test_fleet_slo_drill_breach_tightens_admission_then_recovers(tiny_model):
    """THE acceptance drill, deterministic under FakeClock: injected
    latency fault → burn-rate gauge rises → `slo_breach_total`
    increments, the ProfilerTrigger arms, fleet admission tightens (the
    shed counters move at the reduced bound) — then recovery when the
    fault clears restores the configured bound."""
    model, params = tiny_model
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    tracer = Tracer(clock=clock)
    trigger = _null_trigger()
    monitor = SLOMonitor(
        SLOPolicy(ttft_p95_ms=50.0), clock=clock, registry=reg,
        tracer=tracer, profiler_trigger=trigger,
        fast_window_s=5.0, slow_window_s=20.0, min_samples=3,
    )

    def factory():
        return SlotServingEngine(
            model, params, _gcfg(), BucketTable(prompt_lens=(8,), batch_sizes=(1,)),
            slots=2, clock=clock, rng=jax.random.PRNGKey(1),
        )

    fleet = FleetRouter(
        [factory] * 2, clock=clock, registry=reg, tracer=tracer,
        max_pending=8, slo_monitor=monitor, slo_shed_factor=0.25,
    )
    rng = np.random.default_rng(0)

    def prompt():
        return rng.integers(1, 80, size=8).astype(np.int32)

    def drain():
        while fleet.pending():
            fleet.step()
            clock.advance(0.01)
        fleet.step()  # one more poll so final dispositions are evaluated

    # phase 1 — healthy: sub-ms TTFT, no burn, full admission
    for _ in range(4):
        fleet.submit(prompt())
    drain()
    assert not monitor.breached
    assert reg.gauge("slo_burn_rate_ttft_fast") == 0.0

    # phase 2 — the latency fault: requests age 1s before the first token
    for _ in range(4):
        fleet.submit(prompt())
    clock.advance(1.0)
    drain()
    assert monitor.breached and monitor.active_breaches == ["ttft"]
    assert reg.gauge("slo_burn_rate_ttft_fast") >= 2.0  # the gauge rose
    assert reg.counter("slo_breach_total") == 1
    assert trigger.armed  # breach armed the profiler
    assert len(tracer.spans("slo.breach")) == 1
    # tightened admission: max_pending 8 -> 2
    assert fleet._effective_admission()[0] == 2
    assert not fleet.health()["ready"] or True  # ready reflects new bound
    accepted = 0
    with pytest.raises(QueueFull, match="tightened from 8 by SLO burn"):
        for _ in range(5):
            fleet.submit(prompt())
            accepted += 1
    assert accepted == 2
    assert reg.counter("fleet_slo_shed_total") == 1  # the shed counter moved
    assert reg.counter("fleet_requests_shed_total") == 1

    # phase 3 — the fault clears. Aging the bad samples out alone is NOT
    # recovery: an empty fast window is a stalled system, not a healthy
    # one, so the breach (and tightened admission) holds until fresh
    # samples prove health.
    drain()
    clock.advance(5.0)
    fleet.step()
    assert monitor.breached  # no evidence yet -> still held
    healthy = 0
    while monitor.breached:
        fleet.submit(prompt())  # 1-in-flight at a time: under the bound
        healthy += 1
        drain()
    assert healthy == 3  # exactly min_samples of good evidence recovered it
    assert reg.counter("slo_recoveries_total") == 1
    assert len(tracer.spans("slo.recover")) == 1
    assert fleet._effective_admission()[0] == 8  # configured bound restored
    for _ in range(5):
        fleet.submit(prompt())  # full bound again: no shed
    drain()
    assert reg.counter("fleet_slo_shed_total") == 1  # unchanged
    stats = fleet.stats()
    assert stats["slo"]["breached"] is False
    assert stats["slo"]["breaches"] == 1
    assert stats["slo_sheds"] == 1
    # disposition accounting closed: every accepted request completed
    assert stats["completed"] == 4 + 4 + 2 + healthy + 5


def test_overload_sheds_during_breach_stay_ordinary(tiny_model):
    """Shed attribution: during a breach, only sheds the CONFIGURED bound
    would have admitted count as SLO-tightened — genuine overload sheds
    stay ordinary (and keep feeding the error dimension), so tightening
    cannot launder real overload out of the burn signal."""
    model, params = tiny_model
    clock = FakeClock()

    class _Breached:
        breached = True

        def sink(self, name, value):
            pass

        def watch_counters(self, source, prefix="serving"):
            pass

        def poll(self):
            return {}

    def factory():
        return SlotServingEngine(
            model, params, _gcfg(), BucketTable(prompt_lens=(8,), batch_sizes=(1,)),
            slots=2, clock=clock, rng=jax.random.PRNGKey(1),
        )

    monitor = _Breached()
    monitor.breached = False
    fleet = FleetRouter(
        [factory], clock=clock, max_pending=4,
        slo_monitor=monitor, slo_shed_factor=0.5,
    )
    rng = np.random.default_rng(0)
    for _ in range(4):  # fill to the CONFIGURED bound while healthy
        fleet.submit(rng.integers(1, 80, size=8).astype(np.int32))
    monitor.breached = True  # breach with in_flight already at the bound
    with pytest.raises(QueueFull):
        fleet.submit(rng.integers(1, 80, size=8).astype(np.int32))
    # would have shed at the configured bound too -> NOT an SLO shed
    assert fleet.registry.counter("fleet_requests_shed_total") == 1
    assert fleet.registry.counter("fleet_slo_shed_total") == 0
    while fleet.pending():
        fleet.step()
        clock.advance(0.01)
    # now under the configured bound but over the tightened one (2):
    # these sheds ARE attributable to the tightening
    fleet.submit(rng.integers(1, 80, size=8).astype(np.int32))
    fleet.submit(rng.integers(1, 80, size=8).astype(np.int32))
    with pytest.raises(QueueFull, match="tightened"):
        fleet.submit(rng.integers(1, 80, size=8).astype(np.int32))
    assert fleet.registry.counter("fleet_slo_shed_total") == 1


# -- obs report SLO section -------------------------------------------------
@pytest.mark.timeout(60)
def test_report_slo_section_pinned_over_fixtures():
    """The checked-in fixture artifacts render the SLO section with
    pinned values (the satellite's contract: fixture schema drift fails
    here, not in CI's make obs-report)."""
    analysis = json.loads(report_mod.run(
        "tests/fixtures/events.jsonl",
        "tests/fixtures/metrics_snapshot.json", as_json=True,
    ))
    slo = analysis["slo"]
    assert slo["ttft"] == {
        "source": "snapshot", "count": 4, "p50_ms": 40.0, "p95_ms": 60.0,
        "p99_ms": 60.0, "max_ms": 60.0,
    }
    assert slo["inter_token"] == {
        "source": "snapshot", "count": 8, "p50_ms": 5.0, "p95_ms": 10.0,
        "p99_ms": 10.0, "max_ms": 10.0,
    }
    assert slo["first_token_events"] == 4
    assert slo["breaches"] == 1 and slo["recoveries"] == 1
    assert slo["burn_rates"]["slo_burn_rate_ttft_slow"] == 4.0
    assert [t["event"] for t in slo["timeline"]] == ["slo.breach", "slo.recover"]
    assert slo["timeline"][0]["dimension"] == "ttft"
    # offered includes the gateway fixture's cancelled request (5 accepted,
    # 4 completed) — a client-abandoned request is offered load that did
    # not complete, so it stays in the denominator
    assert slo["goodput"] == {
        "prefix": "serving", "offered": 5, "completed": 4, "ratio": 0.8,
    }
    text = report_mod.run(
        "tests/fixtures/events.jsonl", "tests/fixtures/metrics_snapshot.json"
    )
    assert "== slo ==" in text
    assert "breaches=1  recoveries=1" in text
    assert "goodput (serving): 4/5 offered = 0.8" in text
    assert "slo.breach" in text and "dim=ttft" in text


@pytest.mark.timeout(60)
def test_report_slo_events_only_fallback_and_absence():
    """Events-only input recomputes TTFT through the registry's own
    Histogram (same nearest-rank); artifacts without SLO telemetry render
    no section at all."""
    events = [
        {"span": "serving.first_token", "trace_id": f"t{i}", "start_s": 0.0,
         "duration_ms": 0.0, "status": "ok", "attrs": {"ttft_ms": v}}
        for i, v in enumerate([20.0, 30.0, 40.0, 60.0])
    ]
    slo = report_mod.analyze(events, None)["slo"]
    assert slo["ttft"]["source"] == "events"
    assert slo["ttft"]["p95_ms"] == 60.0 and slo["ttft"]["p50_ms"] == 40.0
    assert slo["inter_token"] is None
    # no SLO telemetry anywhere -> no section (old artifacts unchanged)
    assert report_mod.analyze([{"span": "serving.request", "status": "ok",
                                "duration_ms": 5.0}], {})["slo"] is None


def test_report_percentiles_match_live_registry(tiny_model):
    """The acceptance pin: `obs report`'s SLO percentiles over a real
    run's artifacts equal the live registry's nearest-rank values
    exactly (same Histogram, same window)."""
    model, params = tiny_model
    clock = FakeClock()
    tracer = Tracer(clock=clock)
    registry = MetricsRegistry(clock=clock)
    engine = SlotServingEngine(
        model, params, _gcfg(max_new=3),
        BucketTable(prompt_lens=(8,), batch_sizes=(1,)),
        slots=2, clock=clock, tracer=tracer, registry=registry,
        rng=jax.random.PRNGKey(1),
    )
    gen = LoadGenerator(
        engine, workload=WorkloadSpec(prompt_len=(4, 8), max_new_tokens=(2, 3),
                                      vocab=(1, 80)),
        mode="open", arrival="poisson", rate_rps=30.0, max_requests=8,
        config=_gcfg(max_new=3), rng=2, clock=clock, step_cost_s=0.013,
    )
    gen.run()
    snap = registry.snapshot()
    slo = report_mod.analyze(
        [sp.to_row() for sp in tracer.spans()],
        {"histograms": snap["histograms"], "counters": snap["counters"]},
    )["slo"]
    p95_ttft = registry.percentile("serving_ttft_ms", 95.0)
    p95_itl = registry.percentile("serving_inter_token_ms", 95.0)
    assert slo["ttft"]["p95_ms"] == round(p95_ttft, 6)
    assert slo["inter_token"]["p95_ms"] == round(p95_itl, 6)
    assert slo["ttft"]["source"] == "snapshot"
    assert slo["goodput"]["ratio"] == 1.0


# -- HELP satellite ---------------------------------------------------------
def test_every_paged_slot_engine_family_has_direct_help(tiny_model):
    """The satellite: every metric family a warmed, traffic-bearing PAGED
    slot engine publishes has a non-fallback `# HELP` line — the
    kv_pool_* / kv_cache_* families included (they used to fall back to
    generic prefix help or none at all)."""
    model, params = tiny_model
    clock = FakeClock()
    engine = SlotServingEngine(
        model, params, _gcfg(), BucketTable(prompt_lens=(8,), batch_sizes=(1,)),
        slots=2, clock=clock, kv_layout="paged", rng=jax.random.PRNGKey(1),
    )
    engine.warmup()
    for _ in range(2):
        engine.submit(np.arange(1, 9, dtype=np.int32))
    engine.drain()
    snap = engine.registry.snapshot()
    published = (
        set(snap["counters"]) | set(snap["gauges"]) | set(snap["histograms"])
    )
    assert any(n.startswith("kv_pool_") for n in published)
    assert "kv_cache_capacity_bytes" in published
    assert "serving_ttft_ms" in published
    missing = sorted(n for n in published if n not in HELP_TEXT)
    assert not missing, f"families without a direct HELP entry: {missing}"
    text = to_prometheus_text(engine.registry)
    for name in published:
        assert f"# HELP {name} " in text, name


# -- bench probe ------------------------------------------------------------
@pytest.mark.timeout(300)
@pytest.mark.slow  # 2026-08 audit: ~6s; real lane is `make slo` —
# test_bench_probe.py keeps bench.py bitrot in tier-1
def test_bench_slo_goodput_probe_tiny(tiny_model):
    """Tiny end-to-end sweep through the real bench probe: the record
    carries the goodput-under-SLO curve (p95 TTFT / p95 ITL per offered
    rate), a knee, calibration-derived targets, and the obs-report
    percentile cross-check."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_slo_tiny", "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    model, params = tiny_model
    cfg = CausalLanguageModelConfig(**TINY)
    out = bench._bench_slo_goodput(
        model, params, cfg, requests_per_rate=5, new_tokens=3, slots=2,
        rate_factors=(0.5, 2.0),
    )
    assert len(out["sweep"]) == 2
    for point in out["sweep"]:
        assert point["p95_ttft_ms"] is not None
        assert point["p95_inter_token_ms"] is not None
        assert point["offered"] == 5
        assert 0.0 <= point["goodput_ratio"] <= 1.0
    assert out["slo"]["ttft_p95_ms"] > 0
    assert out["knee"]["index"] in (0, 1)
    assert out["knee"]["goodput_rps"] == max(
        p["goodput_rps"] for p in out["sweep"]
    )
    assert out["report_percentiles_match_registry"] is True


# -- CLI flag group ---------------------------------------------------------
@pytest.mark.timeout(60)
def test_obs_slo_flag_group_parses_and_fit_rejects():
    """`--obs.slo.*` exists as a nested flag group; fit rejects it under
    the inapplicable-flag convention (SLO targets are serving-only)."""
    from perceiver_io_tpu.observability import ObservabilityArgs
    from perceiver_io_tpu.scripts.cli import build_dataclass, flag_specs
    from perceiver_io_tpu.scripts.text import clm as clm_script

    specs = flag_specs(ObservabilityArgs, "obs")
    for flag in ("obs.slo.ttft_p95_ms", "obs.slo.inter_token_p95_ms",
                 "obs.slo.error_rate", "obs.slo.fast_window_s",
                 "obs.slo.slow_window_s", "obs.slo.burn_rate",
                 "obs.slo.shed_factor"):
        assert flag in specs, flag
    obs = build_dataclass(
        ObservabilityArgs,
        {"obs.slo.ttft_p95_ms": 250.0, "obs.slo.burn_rate": 3.0}, "obs",
    )
    assert obs.slo.enabled and obs.slo.ttft_p95_ms == 250.0
    assert obs.slo.burn_rate == 3.0 and obs.slo.shed_factor == 0.5
    assert obs.slo.policy().ttft_p95_ms == 250.0
    assert not ObservabilityArgs().slo.enabled
    with pytest.raises(SystemExit, match="applies to the serve subcommand"):
        clm_script.main([
            "fit", "--data=synthetic", "--obs.slo.ttft_p95_ms=100",
        ])


@pytest.mark.timeout(60)
def test_obs_kit_builds_monitor_only_when_targets_set(tmp_path):
    from perceiver_io_tpu.observability import ObservabilityArgs, SLOArgs
    from perceiver_io_tpu.scripts.cli import _obs_kit

    kit = _obs_kit(ObservabilityArgs(), str(tmp_path))
    assert kit["slo_monitor"] is None
    kit = _obs_kit(
        ObservabilityArgs(
            slo=SLOArgs(ttft_p95_ms=100.0, burn_rate=4.0, fast_window_s=5.0),
            profile_on_regress_factor=2.0,
        ),
        str(tmp_path),
    )
    mon = kit["slo_monitor"]
    assert mon is not None
    assert mon.breach_burn_rate == 4.0 and mon.fast_window_s == 5.0
    # the kit chains breach -> profiler-trigger arming
    assert mon.profiler_trigger is kit["trigger"] is not None
    # non-main processes build no monitor (rank-0 convention)
    kit = _obs_kit(
        ObservabilityArgs(slo=SLOArgs(ttft_p95_ms=100.0)), str(tmp_path),
        is_main=False,
    )
    assert kit["slo_monitor"] is None


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_serve_cli_slo_end_to_end(tmp_path, capsys):
    """Full CLI loop: a serve run with `--obs.slo.*` leaves serve_stats
    with an slo block, TTFT/ITL histograms in the snapshot, burn gauges,
    and `serving.first_token` events in events.jsonl — all of which
    `obs report` renders as the SLO section."""
    from perceiver_io_tpu.inference.generate import reset_executor_caches
    from perceiver_io_tpu.observability import default_ledger
    from perceiver_io_tpu.scripts.text import clm as clm_script
    from perceiver_io_tpu.training.checkpoint import save_pretrained

    reset_executor_caches()
    default_ledger().reset()
    cfg = CausalLanguageModelConfig(
        vocab_size=262, max_seq_len=32, max_latents=16, num_channels=16,
        num_heads=2, num_self_attention_layers=1, cross_attention_dropout=0.0,
    )
    model = CausalLanguageModel(cfg)
    params = model.init(KEY, jnp.zeros((1, 32), jnp.int32), 16)["params"]
    save_pretrained(str(tmp_path / "ckpt"), params, cfg)
    (tmp_path / "prompts.txt").write_text("hello\nhi\n")
    events_path = str(tmp_path / "events.jsonl")
    snap_path = str(tmp_path / "snapshot.json")
    clm_script.main([
        "serve", "--ckpt", str(tmp_path / "ckpt"),
        f"--serve.prompts={tmp_path}/prompts.txt",
        "--serve.max_new_tokens=3", "--serve.num_latents=2",
        "--serve.engine=slots", "--serve.slots=2",
        "--serve.prompt_buckets=8", "--serve.decode_strategy=cached",
        "--obs.slo.ttft_p95_ms=60000", "--obs.slo.error_rate=0.5",
        f"--obs.events_path={events_path}",
        f"--obs.snapshot_path={snap_path}",
    ])
    stats_lines = [
        json.loads(line) for line in capsys.readouterr().out.splitlines()
        if line.startswith('{"serve_stats"')
    ]
    assert len(stats_lines) == 1
    stats = stats_lines[0]["serve_stats"]
    assert stats["slo"]["policy"]["ttft_p95_ms"] == 60000.0
    assert stats["slo"]["breached"] is False  # generous target: no breach
    assert stats["ttft_ms"]["p95"] is not None
    from perceiver_io_tpu.observability import read_events_jsonl

    events = read_events_jsonl(events_path)
    assert sum(1 for e in events if e["span"] == "serving.first_token") == 2
    snap = json.load(open(snap_path))
    assert "serving_ttft_ms" in snap["histograms"]
    assert "slo_burn_rate" in snap["gauges"]
    text = report_mod.run(events_path, snap_path)
    assert "== slo ==" in text and "snapshot" in text
    reset_executor_caches()
    default_ledger().reset()
