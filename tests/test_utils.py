"""FLOPs/params estimators and profiling utilities."""
import os

import jax
import jax.numpy as jnp
import numpy as np

from perceiver_io_tpu.utils import (
    ComputeEstimator,
    StepTimer,
    count_params,
    num_training_steps,
    num_training_tokens,
    trace,
    training_flops,
)
from perceiver_io_tpu.utils.flops import flops_approx, training_flops_per_step


def test_estimator_matches_reference_formulas():
    est = ComputeEstimator(vocab_size=262, max_seq_len=4096, num_latents=512)
    c = 512
    # reference per-component formulas (flops.py:62-87)
    assert est._input_embed(c) == 4 * c
    assert est._mlp_layer(c) == 16 * c * c
    assert est._self_attn_layer(c) == 6 * c * c + 2 * c * 512 + 2 * c * c
    assert est._cross_attn_layer(c) == 4 * c * c + 2 * c * 512
    assert est._final_logits(c) == 2 * c * 262
    # fwd+bwd = 3x forward
    assert est.self_attn(c, 9) % 3 == 0
    # halving prefix dropout raises cross-attention compute
    assert est.cross_attn(c, 0.0) > est.cross_attn(c, 0.5)


def test_token_helpers_inverse():
    tokens = num_training_tokens(num_steps=100, num_latents=512, batch_size=8)
    assert tokens == 100 * 512 * 8
    assert num_training_steps(tokens, 512, 8) == 100


def test_training_flops_scales_linearly():
    est = ComputeEstimator(262, 2048, 512)
    f1, t1 = training_flops(est, 512, 9, num_steps=10, batch_size=4)
    f2, t2 = training_flops(est, 512, 9, num_steps=20, batch_size=4)
    assert f2 == 2 * f1 and t2 == 2 * t1
    assert training_flops_per_step(est, 512, 9, batch_size=4) * 10 > f1  # dropout 0 > 0.5


def test_count_params_no_allocation():
    from perceiver_io_tpu.models.text.clm import CausalLanguageModel, CausalLanguageModelConfig

    cfg = CausalLanguageModelConfig(
        vocab_size=262, max_seq_len=64, max_latents=32, num_channels=32,
        num_heads=2, num_self_attention_layers=2,
    )
    model = CausalLanguageModel(cfg)
    n = count_params(model, jnp.zeros((1, 64), jnp.int32), 32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64), jnp.int32), 32)["params"]
    actual = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    assert n == actual
    # C = 6N approximation is positive and param-proportional
    assert flops_approx(n) == 6 * n


def test_step_timer():
    f = jax.jit(lambda x: x @ x)
    x = jnp.ones((128, 128))
    result = StepTimer(warmup=1).measure(lambda: f(x), iters=3, flops_per_step=2 * 128**3,
                                         peak_flops=1e12)
    assert result["step_time_s"] > 0
    assert result["flops_per_sec"] > 0
    assert 0 < result["mfu"] < 1e6


def test_trace_writes_capture(tmp_path):
    log_dir = str(tmp_path / "profile")
    with trace(log_dir):
        jax.block_until_ready(jnp.ones((8, 8)) * 2)
    # a plugins/profile capture directory must exist and be non-empty
    found = []
    for root, _, files in os.walk(log_dir):
        found.extend(files)
    assert found, "profiler trace produced no files"
