"""Inference pipelines over tiny models — the reference's six HF pipeline
surfaces (SURVEY.md §2.2) driven end to end."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.data.text.preprocessor import TextPreprocessor
from perceiver_io_tpu.data.text.tokenizers import ByteTokenizer
from perceiver_io_tpu.inference import pipeline
from perceiver_io_tpu.models.core.config import (
    ClassificationDecoderConfig,
    PerceiverIOConfig,
)

KEY = jax.random.PRNGKey(0)


@pytest.fixture(scope="module")
def tiny_clm():
    from perceiver_io_tpu.models.text.clm import CausalLanguageModel, CausalLanguageModelConfig

    cfg = CausalLanguageModelConfig(
        vocab_size=262, max_seq_len=32, max_latents=16, num_channels=32,
        num_heads=2, num_self_attention_layers=1, cross_attention_dropout=0.0,
    )
    model = CausalLanguageModel(cfg)
    params = model.init(KEY, jnp.zeros((1, 32), jnp.int32), 16)["params"]
    return model, params


def test_text_generation_pipeline(tiny_clm):
    model, params = tiny_clm
    pipe = pipeline("text-generation", model, params, ByteTokenizer(padding_side="left"))
    outs = pipe(["hello", "hi"], max_new_tokens=4, num_latents=4, temperature=0.0)
    assert len(outs) == 2
    assert outs[0].startswith("hello")
    new_only = pipe("hello", max_new_tokens=4, num_latents=4, temperature=0.0,
                    return_full_text=False)
    assert len(new_only) == 1 and not new_only[0].startswith("hello")


def test_fill_mask_pipeline():
    from perceiver_io_tpu.models.text.common import TextEncoderConfig
    from perceiver_io_tpu.models.text.mlm import (
        MaskedLanguageModel,
        TextDecoderConfig,
    )

    tokenizer = ByteTokenizer()
    cfg = PerceiverIOConfig(
        encoder=TextEncoderConfig(
            vocab_size=tokenizer.vocab_size, max_seq_len=32, num_input_channels=16,
            num_cross_attention_heads=1, num_self_attention_heads=2,
            num_self_attention_layers_per_block=1,
        ),
        decoder=TextDecoderConfig(vocab_size=tokenizer.vocab_size, max_seq_len=32),
        num_latents=4,
        num_latent_channels=16,
    )
    model = MaskedLanguageModel(cfg)
    params = model.init(KEY, jnp.zeros((1, 8), jnp.int32))["params"]

    prep = TextPreprocessor(tokenizer, max_seq_len=32)
    pipe = pipeline("fill-mask", model, params, prep)
    filled = pipe("a<mask>c", top_k=3)
    assert len(filled) == 1 and len(filled[0]) == 3
    # every filling restores the unmasked characters
    assert all(f.startswith("a") and f.endswith("c") and len(f) == 3 for f in filled[0])


def test_text_classification_pipeline():
    from perceiver_io_tpu.models.text.classifier import TextClassifier
    from perceiver_io_tpu.models.text.common import TextEncoderConfig

    tokenizer = ByteTokenizer()
    cfg = PerceiverIOConfig(
        encoder=TextEncoderConfig(
            vocab_size=tokenizer.vocab_size, max_seq_len=32, num_input_channels=16,
            num_cross_attention_heads=1, num_self_attention_heads=2,
            num_self_attention_layers_per_block=1,
        ),
        decoder=ClassificationDecoderConfig(
            num_classes=2, num_output_query_channels=16, num_cross_attention_heads=1
        ),
        num_latents=4,
        num_latent_channels=16,
    )
    model = TextClassifier(cfg)
    params = model.init(KEY, jnp.zeros((1, 8), jnp.int32))["params"]

    pipe = pipeline(
        "sentiment-analysis", model, params, TextPreprocessor(tokenizer, max_seq_len=32)
    )
    out = pipe(["great movie", "terrible movie"])
    assert len(out) == 2
    assert all(o["label"] in ("NEGATIVE", "POSITIVE") and 0 <= o["score"] <= 1 for o in out)


def test_image_classification_pipeline():
    from perceiver_io_tpu.models.vision.image_classifier import (
        ImageClassifier,
        ImageEncoderConfig,
    )

    cfg = PerceiverIOConfig(
        encoder=ImageEncoderConfig(
            image_shape=(8, 8, 1), num_frequency_bands=4,
            num_cross_attention_heads=1, num_self_attention_heads=2,
            num_self_attention_layers_per_block=1,
        ),
        decoder=ClassificationDecoderConfig(
            num_classes=10, num_output_query_channels=16, num_cross_attention_heads=2
        ),
        num_latents=4,
        num_latent_channels=16,
    )
    model = ImageClassifier(cfg)
    params = model.init(KEY, jnp.zeros((1, 8, 8, 1)))["params"]

    pipe = pipeline("image-classification", model, params)
    imgs = np.random.default_rng(0).integers(0, 256, (3, 8, 8), dtype=np.uint8)
    out = pipe(imgs, top_k=2)
    assert len(out) == 3 and len(out[0]) == 2
    assert out[0][0]["score"] >= out[0][1]["score"]


def test_optical_flow_pipeline():
    from perceiver_io_tpu.models.vision.optical_flow import (
        OpticalFlow,
        OpticalFlowDecoderConfig,
        OpticalFlowEncoderConfig,
    )

    cfg = PerceiverIOConfig(
        encoder=OpticalFlowEncoderConfig(
            image_shape=(8, 8), num_frequency_bands=4,
            num_cross_attention_heads=1, num_self_attention_heads=2,
            num_self_attention_layers_per_block=1,
        ),
        decoder=OpticalFlowDecoderConfig(image_shape=(8, 8), num_cross_attention_heads=1),
        num_latents=4,
        num_latent_channels=16,
    )
    model = OpticalFlow(cfg)
    params = model.init(KEY, jnp.zeros((1, 2, 27, 8, 8)))["params"]

    pipe = pipeline("optical-flow", model, params, patch_size=(8, 8), patch_min_overlap=2, batch_size=2)
    rng = np.random.default_rng(0)
    pair = (
        rng.integers(0, 256, (10, 12, 3), dtype=np.uint8),
        rng.integers(0, 256, (10, 12, 3), dtype=np.uint8),
    )
    flow = pipe(pair)
    assert flow.shape == (10, 12, 2)
    rendered = pipeline(
        "optical-flow", model, params, patch_size=(8, 8), patch_min_overlap=2, batch_size=2, render=True
    )(pair)
    assert rendered.shape == (10, 12, 3) and rendered.dtype == np.uint8


def test_symbolic_audio_pipeline():
    from perceiver_io_tpu.models.audio.symbolic import (
        SymbolicAudioModel,
        SymbolicAudioModelConfig,
    )

    cfg = SymbolicAudioModelConfig(
        max_seq_len=32, max_latents=16, num_channels=32,
        num_heads=2, num_self_attention_layers=1, cross_attention_dropout=0.0,
    )
    model = SymbolicAudioModel(cfg)
    params = model.init(KEY, jnp.zeros((1, 32), jnp.int32), 16)["params"]

    pipe = pipeline("symbolic-audio-generation", model, params)
    prompt = np.array([60, 256 + 49, 128 + 60], np.int32)  # on, shift, off
    outs = pipe([prompt, prompt[:2]], max_new_tokens=5, num_latents=4, temperature=0.0)
    assert len(outs) == 2
    assert len(outs[0]) == len(prompt) + 5
    np.testing.assert_array_equal(outs[0][:3], prompt)
    assert (np.asarray(outs[0]) < cfg.vocab_size).all()


def test_symbolic_audio_pipeline_beam():
    # reference tests/symbolic_audio_model_pipeline_test.py:95-96 drives
    # num_beams=3 through the audio pipeline surface.
    from perceiver_io_tpu.models.audio.symbolic import (
        SymbolicAudioModel,
        SymbolicAudioModelConfig,
    )

    cfg = SymbolicAudioModelConfig(
        max_seq_len=32, max_latents=16, num_channels=32,
        num_heads=2, num_self_attention_layers=1, cross_attention_dropout=0.0,
    )
    model = SymbolicAudioModel(cfg)
    params = model.init(KEY, jnp.zeros((1, 32), jnp.int32), 16)["params"]

    pipe = pipeline("symbolic-audio-generation", model, params)
    prompt = np.array([60, 256 + 49, 128 + 60], np.int32)
    outs = pipe([prompt], max_new_tokens=5, num_latents=4, num_beams=3)
    assert len(outs) == 1 and len(outs[0]) == len(prompt) + 5
    assert (np.asarray(outs[0]) < cfg.vocab_size).all()


def test_text_generation_pipeline_beam(tiny_clm):
    # reference tests/causal_language_model_pipeline_test.py:37-38.
    model, params = tiny_clm
    pipe = pipeline("text-generation", model, params, ByteTokenizer(padding_side="left"))
    outs = pipe(["hello", "hi"], max_new_tokens=4, num_latents=4, num_beams=3)
    assert len(outs) == 2 and all(isinstance(o, str) for o in outs)


def test_unknown_task_rejected(tiny_clm):
    model, params = tiny_clm
    with pytest.raises(ValueError, match="unknown task"):
        pipeline("not-a-task", model, params)


def test_pipeline_from_pretrained_round_trip(tiny_clm, tmp_path):
    from perceiver_io_tpu.inference import pipeline_from_pretrained
    from perceiver_io_tpu.training.checkpoint import save_pretrained

    model, params = tiny_clm
    save_pretrained(str(tmp_path / "m"), params, model.config)

    pipe = pipeline_from_pretrained(
        "text-generation", str(tmp_path / "m"), ByteTokenizer(padding_side="left")
    )
    direct = pipeline("text-generation", model, params, ByteTokenizer(padding_side="left"))
    a = pipe("hello", max_new_tokens=4, num_latents=4, temperature=0.0)
    b = direct("hello", max_new_tokens=4, num_latents=4, temperature=0.0)
    assert a == b


def test_bf16_param_storage(tiny_clm, tmp_path):
    """cast_float_params: float leaves become bf16 (int leaves untouched),
    the model still runs, and logits stay close to the fp32-weight path —
    the decode-loop weight-traffic optimization (docs/parallelism.md)."""
    from perceiver_io_tpu.inference import cast_float_params, pipeline_from_pretrained
    from perceiver_io_tpu.training.checkpoint import save_pretrained

    model, params = tiny_clm
    cast = cast_float_params(params, jnp.bfloat16)
    leaves = jax.tree_util.tree_leaves(cast)
    assert all(
        l.dtype == jnp.bfloat16 for l in leaves
        if jnp.issubdtype(l.dtype, jnp.floating)
    )

    ids = jnp.asarray(np.random.default_rng(0).integers(1, 262, (2, 32)), jnp.int32)
    logits32 = model.apply({"params": params}, ids, 16)
    logits16 = model.apply({"params": cast}, ids, 16).astype(jnp.float32)
    np.testing.assert_allclose(
        np.asarray(logits16), np.asarray(logits32), atol=5e-2, rtol=5e-2
    )

    # end-to-end through the pretrained loader
    save_pretrained(str(tmp_path / "m16"), params, model.config)
    pipe = pipeline_from_pretrained(
        "text-generation", str(tmp_path / "m16"), ByteTokenizer(padding_side="left"),
        params_dtype=jnp.bfloat16,
    )
    out = pipe("hello", max_new_tokens=4, num_latents=4, temperature=0.0)
    assert len(out) == 1 and out[0].startswith("hello")
