"""Optimistic KV admission with preemption (docs/serving.md "Preemption &
priorities"; ``serving/kv_pool.py`` ``reserve_lazy``, ``serving/slots.py``
preemption section).

The load-bearing assertions:

- **token identity through preempt/resume**: a preempted request is
  requeued and replayed from its original prompt, and the greedy token
  stream it finally delivers is identical to an unpressured engine's —
  across paged, paged_int8, prefix-shared, and chunked-prefill
  geometries, and identical to the DENSE layout / per-request
  ``generate()`` where the layout is exact;
- **lazy allocation as a unit**: ``reserve_lazy`` hard-commits only
  prompt pages + headroom, records the worst case as a soft watermark,
  and ``ensure`` on a lazy slot allocates decode pages at boundary
  crossings from the free heap — raising ``PoolExhausted`` (never
  partially mapping) when every free block is spoken for;
- **victim policy**: lowest priority tier first (never a higher tier),
  then most-tenant-pages / most-pages-held / fewest-tokens-generated;
  admission-time preemption crosses tiers only; the LAST resident is
  never preempted (forward progress);
- **zero leak under scripted exhaustion**: the ``kv.exhaust`` chaos site
  forces the PoolExhausted path deterministically — a preemption storm
  drains leak-free with every request still completing token-identical;
- **frees_by_cause completeness**: eos/max_new/deadline retire as
  ``retire``, plus ``cancelled`` / ``failover`` / ``scale_down`` /
  ``preempted`` — every retirement route lands in exactly one bucket and
  the pool balances to zero.

All pure-CPU, tiny shapes, fast — tier-1 (marker ``preemption``).
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from perceiver_io_tpu.inference.generate import GenerationConfig, generate
from perceiver_io_tpu.inference.samplers import SamplingConfig
from perceiver_io_tpu.models.text.clm import (
    CausalLanguageModel,
    CausalLanguageModelConfig,
)
from perceiver_io_tpu.reliability import ChaosRegistry, FakeClock
from perceiver_io_tpu.serving import BucketTable, KVPagePool, SlotServingEngine
from perceiver_io_tpu.serving.kv_pool import PoolExhausted
from perceiver_io_tpu.serving.slots import PREEMPTION_MODES

pytestmark = [pytest.mark.preemption, pytest.mark.timeout(300)]

KEY = jax.random.PRNGKey(0)

# Deliberately NOT a shape other test modules use (executor cache keys
# include the module fingerprint; an identically-configured model in
# another file would pre-populate the cache this file counts).
TINY = dict(
    vocab_size=71, max_seq_len=32, max_latents=8, num_channels=16,
    num_heads=2, num_self_attention_layers=1, cross_attention_dropout=0.0,
)

GREEDY = SamplingConfig(temperature=0.0)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = CausalLanguageModelConfig(**TINY)
    model = CausalLanguageModel(cfg)
    params = model.init(KEY, jnp.zeros((1, 32), jnp.int32), 8)["params"]
    return model, params


def _prompts(rng, lengths, vocab=71):
    return [rng.integers(1, vocab, size=int(n)).astype(np.int32)
            for n in lengths]


def _ref(model, params, prompt, cfg):
    return np.asarray(
        generate(model, params, jnp.asarray(prompt[None, :]), cfg)
    )[0]


# -- the lazy allocator as a unit -------------------------------------------
def test_reserve_lazy_commits_prompt_plus_headroom():
    """Hard commitment = min(prompt - shared + headroom, worst case);
    the worst case becomes a soft watermark, not a reservation."""
    pool = KVPagePool(num_blocks=12, block_size=4, slots=3, max_len=32)
    committed = pool.reserve_lazy(0, 5, 24, headroom=1)  # 2 prompt + 1
    assert committed == 3
    assert pool.reserved == 3
    assert pool.is_lazy(0) and not pool.is_lazy(1)
    # headroom can never over-reserve past the worst case
    assert pool.reserve_lazy(1, 4, 6, headroom=5) == 2  # clamped to total
    # strict path untouched, and the two ledgers co-exist
    pool.reserve(2, 8)
    assert not pool.is_lazy(2)
    assert pool.reserved == 3 + 2 + 2
    assert pool.headroom_blocks == 12 - 7
    pool.release(0)
    pool.release(1)
    pool.release(2)
    assert pool.leaked() == 0 and not pool.is_lazy(0)


def test_reserve_lazy_raise_semantics():
    """Admit-time raises mirror reserve(): ValueError for structural
    bugs (double booking, bad ranges), PoolExhausted for doesn't-fit-now."""
    pool = KVPagePool(num_blocks=6, block_size=4, slots=2, max_len=32)
    pool.reserve_lazy(0, 4, 8)
    with pytest.raises(ValueError):
        pool.reserve_lazy(0, 4, 8)  # double booking
    with pytest.raises(ValueError):
        pool.reserve_lazy(1, 12, 8)  # prompt past total
    with pytest.raises(ValueError):
        pool.reserve_lazy(1, 4, 99)  # past one slot's page budget
    with pytest.raises(ValueError):
        pool.reserve_lazy(1, 4, 8, headroom=-1)
    # slot 0 hard-committed 1 block; 6 prompt blocks no longer fit
    with pytest.raises(PoolExhausted):
        pool.reserve_lazy(1, 24, 24)
    pool.release(0)
    assert pool.leaked() == 0 and pool.reserved == 0


def test_lazy_ensure_boundary_crossing_and_exhaustion():
    """Decode pages past the commitment come from the free heap — but
    never from blocks other slots' hard reservations have spoken for;
    a dry crossing raises with the table unchanged (no partial map)."""
    pool = KVPagePool(num_blocks=6, block_size=4, slots=3, max_len=32)
    pool.reserve_lazy(0, 4, 24)  # commit 1, soft watermark 6
    assert pool.ensure(0, 4)  # within the commitment
    assert pool.ensure(0, 12)  # 2 decode pages from the free heap
    # outstanding reservation fully consumed: reserved == mapped blocks
    assert pool.mapped_blocks(0) == 3 and pool.reserved == pool.in_use == 3
    pool.reserve(1, 9)  # 3 blocks hard: exactly the 3 free blocks left
    before = list(pool.table_row(0))
    with pytest.raises(PoolExhausted):
        pool.ensure(0, 16)  # the next crossing would eat a reservation
    assert list(pool.table_row(0)) == before  # unchanged on raise
    # a strict slot's ensure past ITS reservation stays a loud bug
    pool.ensure(1, 9)
    with pytest.raises(ValueError):
        pool.ensure(1, 13)
    # past the soft watermark = admission accounting bug, not pressure
    pool.release(1)
    with pytest.raises(ValueError):
        pool.ensure(0, 25)
    pool.release(0)
    assert pool.leaked() == 0
    assert pool.stats()["lazy_slots"] == 0


def test_ensure_many_multi_page_burst_and_determinism():
    """A speculative round's accepted burst maps every page it needs in
    ONE call, and the block-id sequence is identical to n single ensure()
    calls (same min-heap order) — the paged schedule stays deterministic
    whether tokens arrive one per step or k+1 per round."""
    pool = KVPagePool(num_blocks=12, block_size=4, slots=2, max_len=48)
    pool.reserve(0, 20)  # 5 blocks
    assert pool.ensure_many(0, 4) is True  # first page
    assert pool.ensure_many(0, 4) is False  # already mapped: no-op
    assert pool.ensure_many(0, 17) is True  # +9 tokens in one burst
    assert pool.mapped_blocks(0) == 5
    burst_row = list(pool.table_row(0))
    pool.release(0)
    pool.reserve(1, 20)
    for tokens in (4, 8, 12, 16, 17):
        pool.ensure(1, tokens)
    assert list(pool.table_row(1)) == burst_row
    pool.release(1)
    assert pool.leaked() == 0


def test_ensure_many_lazy_guard_mid_burst():
    """A lazy slot's burst spends headroom only for pages past its hard
    commitment: reservation-consuming pages never trip the guard, and a
    burst needing more unreserved blocks than remain raises PoolExhausted
    BEFORE mapping anything."""
    pool = KVPagePool(num_blocks=6, block_size=4, slots=3, max_len=32)
    pool.reserve_lazy(0, 4, 24)  # commit 1, soft watermark 6
    assert pool.ensure_many(0, 4)  # consumes the commitment
    pool.reserve(1, 12)  # 3 blocks hard -> headroom = 2
    assert pool.headroom_blocks == 2
    assert pool.ensure_many(0, 12)  # 2 lazy pages: exactly the headroom
    before = list(pool.table_row(0))
    with pytest.raises(PoolExhausted):
        pool.ensure_many(0, 16)  # one more lazy page than remains
    assert list(pool.table_row(0)) == before  # untouched on raise
    assert pool.mapped_blocks(0) == 3
    # past the soft watermark stays a loud structural bug, not pressure
    with pytest.raises(ValueError):
        pool.ensure_many(0, 25)
    pool.release(0)
    pool.release(1)
    assert pool.leaked() == 0 and pool.allocs_total == pool.frees_total


def test_ensure_many_exhaustion_leaves_table_untouched():
    """The atomicity bar ensure() can't give a burst: exhaustion MID-SPAN
    must not leave leading pages mapped. ensure_many pre-checks the whole
    span, so the retry-after-preempt loop never double-counts pages."""
    pool = KVPagePool(num_blocks=4, block_size=4, slots=2, max_len=32)
    pool.reserve_lazy(0, 4, 20, headroom=0)  # commit 1 of worst-case 5
    pool.ensure_many(0, 4)
    pool.reserve(1, 8)  # 2 blocks hard -> headroom = 1
    before = list(pool.table_row(0))
    in_use = pool.in_use
    with pytest.raises(PoolExhausted):
        pool.ensure_many(0, 16)  # needs 3 lazy pages, 1 unreserved free
    assert list(pool.table_row(0)) == before
    assert pool.in_use == in_use  # nothing mapped, nothing leaked
    # after the victim frees (release), the same burst succeeds
    pool.release(1)
    assert pool.ensure_many(0, 16)
    assert pool.mapped_blocks(0) == 4
    pool.release(0)
    assert pool.leaked() == 0


# -- ctor validation ---------------------------------------------------------
def test_preemption_requires_paged_layout(tiny_model):
    model, params = tiny_model
    cfg = GenerationConfig(max_new_tokens=4, num_latents=2, sampling=GREEDY)
    table = BucketTable(prompt_lens=(8,), batch_sizes=(1,))
    with pytest.raises(ValueError, match="preemption"):
        SlotServingEngine(model, params, cfg, table, slots=2,
                          preemption="bogus")
    with pytest.raises(ValueError, match="paged"):
        SlotServingEngine(model, params, cfg, table, slots=2,
                          kv_layout="dense", preemption="recompute")
    with pytest.raises(ValueError, match="admit_headroom_blocks"):
        SlotServingEngine(model, params, cfg, table, slots=2,
                          kv_layout="paged", preemption="recompute",
                          admit_headroom_blocks=-1)
    assert PREEMPTION_MODES == ("off", "recompute", "swap", "auto")


# -- token identity through preempt -> requeue -> readmit -> complete -------
def _pressured_engine(model, params, cfg, *, kv_layout="paged", slots=4,
                      kv_blocks=10, **kw):
    table = BucketTable(prompt_lens=(8,), batch_sizes=(1,))
    return SlotServingEngine(
        model, params, cfg, table, slots=slots, kv_layout=kv_layout,
        kv_block_size=4, kv_blocks=kv_blocks, preemption="recompute",
        clock=FakeClock(), **kw
    )


def _longtail(rng, n=6):
    """Mixed declared max_new: shorts + near-context longs — the strict
    arm's worst case would head-of-line block; lazy admission overcommits
    and preempts under pressure."""
    base = GenerationConfig(max_new_tokens=3, num_latents=2, sampling=GREEDY)
    long_cfg = dataclasses.replace(base, max_new_tokens=14)
    prompts = _prompts(rng, [5, 7, 6, 4, 7, 5][:n])
    cfgs = [long_cfg if i % 2 else base for i in range(n)]
    return prompts, cfgs


@pytest.mark.slow  # 2026-08 audit: ~18s; plain-paged preemption identity +
# zero-leak stay tier-1 via the kv.exhaust storm drill here and the
# speculative storm drill (tests/test_speculative.py)
def test_paged_preemption_token_identity_and_zero_leak(tiny_model):
    """Genuine exhaustion (no chaos): lazy admission packs more residents
    than the pool can grow, boundary crossings preempt victims, preempted
    requests requeue + readmit — every final output token-identical to
    per-request generate(), pool drained to zero."""
    model, params = tiny_model
    prompts, cfgs = _longtail(np.random.default_rng(3))
    engine = _pressured_engine(
        model, params, cfgs[0], kv_blocks=8, admit_headroom_blocks=0
    )
    handles = [engine.submit(p, config=c) for p, c in zip(prompts, cfgs)]
    engine.run_until_idle()
    pre = engine.stats()["preemption"]
    assert pre["mode"] == "recompute"
    assert pre["preemptions"] > 0
    assert pre["readmissions"] > 0
    assert pre["by_tier"].get(0, 0) == pre["preemptions"]
    for h, p, c in zip(handles, prompts, cfgs):
        assert h.status == "ok"
        np.testing.assert_array_equal(h.result, _ref(model, params, p, c))
    pool = engine._pool
    assert pool.in_use == 0 and pool.leaked() == 0
    assert pool.allocs_total == pool.frees_total > 0
    assert pool.frees_by_cause.get("preempted", 0) > 0
    assert engine.registry.counter("kv_preemptions_total") == \
        pre["preemptions"]
    assert engine.registry.counter("kv_preemptions_tier_0_total") == \
        pre["preemptions"]
    assert engine.health()["preemption"] == "recompute"


@pytest.mark.parametrize("geometry", ["chunked", "prefix", "int8"])
def test_preemption_token_identity_geometries(tiny_model, geometry):
    """Preempt/replay is invisible across the hard geometries: a
    chunked-prefill victim (preempted mid-admission restarts its chunks),
    a prefix-shared victim (derefs published blocks, never frees them out
    from under sharers), and the int8 pool (quantized decode replays
    bit-identically vs an UNPRESSURED int8 engine — the approximate
    layout is compared against itself, not the exact reference)."""
    model, params = tiny_model
    rng = np.random.default_rng(11)
    prompts, cfgs = _longtail(rng)
    kw = {}
    layout = "paged"
    if geometry == "chunked":
        kw["prefill_chunk"] = 4
    elif geometry == "prefix":
        kw["prefix_cache"] = "on"
        shared = prompts[0][:4]
        prompts = [np.concatenate([shared, p]).astype(np.int32)[:8]
                   for p in prompts]
    else:
        layout = "paged_int8"

    def run(kv_blocks, preemption):
        table = BucketTable(prompt_lens=(8, 16), batch_sizes=(1,))
        engine = SlotServingEngine(
            model, params, cfgs[0], table, slots=4, kv_layout=layout,
            kv_block_size=4, kv_blocks=kv_blocks, preemption=preemption,
            clock=FakeClock(), **kw
        )
        handles = [engine.submit(p, config=c) for p, c in zip(prompts, cfgs)]
        engine.run_until_idle()
        return engine, handles

    pressured, tight = run(8, "recompute")
    relaxed, ample = run(32, None)
    assert pressured.stats()["preemption"]["preemptions"] > 0
    for h_tight, h_ample in zip(tight, ample):
        assert h_tight.status == "ok" and h_ample.status == "ok"
        np.testing.assert_array_equal(h_tight.result, h_ample.result)
    assert pressured._pool.leaked() == 0
    if geometry != "prefix":
        # prefix geometry legitimately retains published cache blocks at
        # idle (referenced by the index, not leaked — test_prefix_cache's
        # retention convention); the others must drain to empty
        assert pressured._pool.in_use == 0
    assert pressured._pool.frees_by_cause.get("preempted", 0) > 0


# -- victim policy -----------------------------------------------------------
def test_priority_tiers_never_preempt_higher(tiny_model):
    """Batch-tier (priority 0) residents yield to an interactive
    (priority 1) submission; the interactive request is NEVER the victim,
    and per-tenant fairness picks the most-pages tenant first."""
    model, params = tiny_model
    base = GenerationConfig(max_new_tokens=12, num_latents=2, sampling=GREEDY)
    engine = _pressured_engine(model, params, base, kv_blocks=8)
    prompts = _prompts(np.random.default_rng(5), [6, 6, 6, 6])
    batch = [
        engine.submit(prompts[0], priority=0, tenant="batch-a"),
        engine.submit(prompts[1], priority=0, tenant="batch-a"),
        engine.submit(prompts[2], priority=0, tenant="batch-b"),
    ]
    interactive = engine.submit(prompts[3], priority=1, tenant="live")
    engine.run_until_idle()
    assert interactive.status == "ok" and interactive.preemptions == 0
    assert engine.stats()["preemption"]["preemptions"] > 0
    assert sum(r.preemptions for r in batch) == \
        engine.stats()["preemption"]["preemptions"]
    for h, p in zip(batch + [interactive], prompts):
        np.testing.assert_array_equal(
            h.result, _ref(model, params, p, base)
        )
    assert engine._pool.leaked() == 0
    by_tier = engine.stats()["preemption"]["by_tier"]
    assert set(by_tier) == {0}


def test_priority_orders_queue_admission(tiny_model):
    """The queue admits by tier (FIFO within a tier): a later high-tier
    submission starts before earlier low-tier ones."""
    model, params = tiny_model
    cfg = GenerationConfig(max_new_tokens=2, num_latents=2, sampling=GREEDY)
    table = BucketTable(prompt_lens=(8,), batch_sizes=(1,))
    engine = SlotServingEngine(
        model, params, cfg, table, slots=1, kv_layout="paged",
        kv_block_size=4, preemption="recompute", clock=FakeClock(),
    )
    prompts = _prompts(np.random.default_rng(9), [5, 5, 5])
    low1 = engine.submit(prompts[0], priority=0)
    low2 = engine.submit(prompts[1], priority=0)
    high = engine.submit(prompts[2], priority=5)
    order = []
    while engine.pending():
        engine.step()
        for h in (low1, low2, high):
            if h.done and h.request_id not in order:
                order.append(h.request_id)
    # the queue sorts by tier before the first admission, FIFO within it
    assert order == [high.request_id, low1.request_id, low2.request_id]


def test_last_resident_never_preempted(tiny_model):
    """Forward progress: with a single live request there is no victim,
    no self-yield, and the reclaim path reports the (structurally
    unreachable) stuck outcome instead of preempting the sole resident."""
    model, params = tiny_model
    cfg = GenerationConfig(max_new_tokens=4, num_latents=2, sampling=GREEDY)
    engine = _pressured_engine(model, params, cfg, kv_blocks=10)
    h = engine.submit(_prompts(np.random.default_rng(2), [6])[0])
    engine.step()  # resident now
    entry = next(s for s in engine._slots if s is not None)
    assert engine._pick_victim(
        entry.req.priority, strict=False, exclude_slot=entry.slot
    ) is None
    assert engine._reclaim_decode_page(entry) == "stuck"
    assert engine._slots[entry.slot] is entry  # untouched
    engine.run_until_idle()
    assert h.status == "ok" and h.preemptions == 0
    assert engine.stats()["preemption"]["preemptions"] == 0


# -- scripted exhaustion (chaos kv.exhaust) ----------------------------------
def test_kv_exhaust_chaos_storm_zero_leak(tiny_model):
    """A scripted preemption storm (kv.exhaust on consecutive decode
    steps) forces the PoolExhausted path without real pressure: every
    request still completes token-identically and the pool drains to
    zero — the new chaos site's zero-leak bar."""
    model, params = tiny_model
    cfg = GenerationConfig(max_new_tokens=6, num_latents=2, sampling=GREEDY)
    chaos = ChaosRegistry()
    chaos.exhaust_kv(2, count=4)  # steps 2-5 each force one exhaustion
    engine = _pressured_engine(
        model, params, cfg, kv_blocks=24, chaos=chaos
    )
    prompts = _prompts(np.random.default_rng(13), [5, 7, 6, 4])
    handles = [engine.submit(p) for p in prompts]
    engine.run_until_idle()
    pre = engine.stats()["preemption"]
    assert pre["preemptions"] >= 4
    assert pre["readmissions"] >= 1
    for h, p in zip(handles, prompts):
        assert h.status == "ok"
        np.testing.assert_array_equal(h.result, _ref(model, params, p, cfg))
    pool = engine._pool
    assert pool.in_use == 0 and pool.leaked() == 0
    assert pool.allocs_total == pool.frees_total
    assert pool.frees_by_cause.get("preempted", 0) >= 4
    assert chaos.fired_count("kv.exhaust") == 4


def test_kv_exhaust_off_engine_unaffected(tiny_model):
    """The chaos site is only consulted when preemption is enabled — a
    strict-reservation engine with the same schedule never trips it."""
    model, params = tiny_model
    cfg = GenerationConfig(max_new_tokens=4, num_latents=2, sampling=GREEDY)
    chaos = ChaosRegistry()
    chaos.exhaust_kv(1, count=3)
    table = BucketTable(prompt_lens=(8,), batch_sizes=(1,))
    engine = SlotServingEngine(
        model, params, cfg, table, slots=2, kv_layout="paged",
        kv_block_size=4, chaos=chaos, clock=FakeClock(),
    )
    h = engine.submit(_prompts(np.random.default_rng(1), [6])[0])
    engine.run_until_idle()
    assert h.status == "ok"
    assert chaos.log == []


# -- frees_by_cause completeness ---------------------------------------------
def test_frees_by_cause_every_retirement_route(tiny_model):
    """Each retirement route frees its pages into exactly one bucket:
    eos, max_new and deadline land in ``retire``; cancel, executor
    failure, scale-down evacuation and preemption each tag their own
    cause — and the pool balances to zero after all of them."""
    model, params = tiny_model
    base = GenerationConfig(max_new_tokens=4, num_latents=2, sampling=GREEDY)
    table = BucketTable(prompt_lens=(8,), batch_sizes=(1,))
    clock = FakeClock()
    chaos = ChaosRegistry()
    engine = SlotServingEngine(
        model, params, base, table, slots=2, kv_layout="paged",
        kv_block_size=4, preemption="recompute", clock=clock, chaos=chaos,
    )
    pool = engine._pool
    rng = np.random.default_rng(17)
    prompt = _prompts(rng, [6])[0]

    def delta(action):
        before = dict(pool.frees_by_cause)
        action()
        while engine.pending():
            engine.step()
        after = pool.frees_by_cause
        return {k: after.get(k, 0) - before.get(k, 0)
                for k in set(after) | set(before)
                if after.get(k, 0) != before.get(k, 0)}

    # max_new: ordinary completion
    d = delta(lambda: engine.submit(prompt))
    assert set(d) == {"retire"}
    # eos: the first greedily-emitted token doubles as the stop token.
    # The slot engine pins one sampling/eos plan per engine (only
    # max_new_tokens varies per request), so the eos route gets its own
    # engine built around that stop token.
    first = int(_ref(model, params, prompt, base)[0])
    eos_engine = SlotServingEngine(
        model, params, dataclasses.replace(base, eos_token_id=first),
        table, slots=2, kv_layout="paged", kv_block_size=4,
        preemption="recompute", clock=FakeClock(),
    )
    h = eos_engine.submit(prompt)
    while eos_engine.pending():
        eos_engine.step()
    # fixed-length result row: the stop token lands, the tail stays pad —
    # the request retired on eos, not max_new
    assert h.status == "ok" and int(h.result[0]) == first
    assert np.all(h.result[1:] == base.pad_token_id)
    assert set(eos_engine._pool.frees_by_cause) == {"retire"}
    assert eos_engine._pool.in_use == 0 and eos_engine._pool.leaked() == 0
    # deadline: resident expires mid-generation
    def deadline():
        engine.submit(prompt, deadline_s=1.0)
        engine.step()
        clock.advance(5.0)
    d = delta(deadline)
    assert set(d) == {"retire"}
    # cancelled: client disconnect on a resident
    def cancel():
        h = engine.submit(prompt)
        engine.step()
        engine.cancel(h.request_id)
    d = delta(cancel)
    assert set(d) == {"cancelled"}
    # failover: executor fault fails the resident (the next consulted
    # serving.batch dispatch — the site counter is engine-lifetime 1-based)
    def fail():
        chaos.fail_batch(chaos._counters.get("serving.batch", 0) + 1)
        engine.submit(prompt)
    d = delta(fail)
    assert set(d) == {"failover"}
    # scale_down: fleet evacuation
    def scale_down():
        engine.submit(prompt)
        engine.step()
        engine.evacuate("scale_down")
    d = delta(scale_down)
    assert set(d) == {"scale_down"}
    # preempted: a storm step forces a victim out (kv.exhaust keeps its
    # own 1-based consult counter)
    def preempt():
        chaos.exhaust_kv(chaos._counters.get("kv.exhaust", 0) + 1)
        for p in _prompts(rng, [5, 6]):
            engine.submit(p)
    d = delta(preempt)
    assert d.get("preempted", 0) > 0 and set(d) <= {"retire", "preempted"}
    assert pool.in_use == 0 and pool.leaked() == 0
    assert pool.allocs_total == pool.frees_total
    assert set(pool.frees_by_cause) == {
        "retire", "cancelled", "failover", "scale_down", "preempted"
    }


# -- observability surfaces --------------------------------------------------
def test_preemption_stats_gauges_and_report(tiny_model):
    """The stats()/gauge/report surfaces agree: headroom gauge tracks the
    pool, the report's kv section gains the preemption rollup, and
    HELP_TEXT documents the new families."""
    model, params = tiny_model
    prompts, cfgs = _longtail(np.random.default_rng(23))
    engine = _pressured_engine(model, params, cfgs[0], kv_blocks=8)
    for p, c in zip(prompts, cfgs):
        engine.submit(p, config=c)
    engine.run_until_idle()
    snap = engine.registry.snapshot()
    assert snap["gauges"]["kv_pool_headroom_blocks"] == \
        engine._pool.headroom_blocks
    pre = engine.stats()["preemption"]
    assert pre["headroom_blocks"] == engine._pool.headroom_blocks
    assert pre["admit_headroom_blocks"] == 0

    from perceiver_io_tpu.observability.exporters import HELP_TEXT
    from perceiver_io_tpu.observability.report import _kv_pool_section
    for name in ("kv_preemptions_total", "kv_readmissions_total",
                 "kv_pool_headroom_blocks"):
        assert name in HELP_TEXT
    section = _kv_pool_section(snap)
    assert section["preemption"]["preemptions"] == pre["preemptions"]
    assert section["preemption"]["readmissions"] == pre["readmissions"]


# -- the bench probe ---------------------------------------------------------
@pytest.mark.slow  # 2026-08 audit: ~6s; real lane is `make preemption` —
# test_bench_probe.py keeps bench.py bitrot in tier-1
def test_bench_preemption_probe_tiny(tiny_model):
    """The extras.preemption A/B at a pure-CPU tiny shape: optimistic
    admission packs more residents per HBM byte than strict worst-case
    reservation at the same budget, beats it on goodput-under-SLO,
    actually exercises preempt/readmit cycles, and stays token-identical
    (the acceptance invariants; the bench-shape record carries the real
    numbers)."""
    import importlib.util
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(root, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    model, params = tiny_model
    out = bench._bench_preemption(
        model, params, model.config, budget_slots=2, engine_slots=8,
        n_requests=12,
    )
    assert out["token_identical"] is True
    assert out["optimistic"]["max_residents"] > out["strict"]["max_residents"]
    assert out["max_residents_ratio"] > 1.0
    assert out["optimistic"]["residents_per_hbm_byte"] > \
        out["strict"]["residents_per_hbm_byte"]
    assert out["optimistic"]["goodput_under_slo"] >= \
        out["strict"]["goodput_under_slo"]
    assert out["optimistic"]["preemptions"] > 0
    assert out["optimistic"]["readmissions"] > 0
    assert out["strict"]["preemptions"] == 0
    assert out["strict"]["tokens_per_sec"] > 0
    assert out["optimistic"]["tokens_per_sec"] > 0
