"""Audio data layer: MIDI event codec round-trips and the symbolic
datamodule's sampling/collation semantics (reference behavior per
``perceiver/data/audio/midi_processor.py`` and ``symbolic.py``)."""
import numpy as np
import pytest

from perceiver_io_tpu.data.audio import (
    PAD_TOKEN,
    SEPARATOR,
    VOCAB_SIZE,
    ControlChange,
    Note,
    SymbolicAudioCollator,
    SymbolicAudioDataModule,
    SymbolicAudioDataset,
    events_from_notes,
    notes_from_events,
)
from perceiver_io_tpu.data.audio.midi import (
    NOTE_OFF_OFFSET,
    TIME_SHIFT_OFFSET,
    VELOCITY_OFFSET,
)
from perceiver_io_tpu.data.text.collators import IGNORE_INDEX


# -- codec ----------------------------------------------------------------
def test_vocab_constants():
    assert VOCAB_SIZE == 389 and PAD_TOKEN == 388 and SEPARATOR == -1
    assert NOTE_OFF_OFFSET == 128 and TIME_SHIFT_OFFSET == 256 and VELOCITY_OFFSET == 356


def test_simple_encode():
    notes = [Note(pitch=60, velocity=80, start=0.0, end=0.5)]
    events = events_from_notes(notes)
    # velocity bucket 20, note_on 60, time shift 0.5s (value 49), note_off 60
    assert events == [VELOCITY_OFFSET + 20, 60, TIME_SHIFT_OFFSET + 49, NOTE_OFF_OFFSET + 60]


def test_round_trip_notes():
    rng = np.random.default_rng(0)
    notes = []
    t = 0.0
    for i in range(50):
        t += float(rng.uniform(0.01, 0.3))
        notes.append(
            Note(
                # unique pitches: overlapping same-pitch notes are inherently
                # ambiguous in the event encoding (last-on wins on decode,
                # same as the reference's note_on_dict)
                pitch=21 + i,
                velocity=int(rng.integers(1, 128)) // 4 * 4,  # bucket-aligned
                start=round(t, 2),
                end=round(t + float(rng.uniform(0.05, 2.0)), 2),
            )
        )
    decoded = notes_from_events(events_from_notes(notes))
    assert len(decoded) == len(notes)
    for orig, dec in zip(sorted(notes, key=lambda n: (n.start, n.pitch)), decoded):
        assert dec.pitch == orig.pitch
        assert abs(dec.start - orig.start) < 0.011
        assert abs(dec.end - orig.end) < 0.011
        assert dec.velocity == orig.velocity


def test_long_gap_emits_repeated_shifts():
    notes = [Note(60, 80, 0.0, 2.5)]
    events = events_from_notes(notes)
    # 2.5s gap between on and off: two max shifts (1s) + one 0.5s shift
    shifts = [e for e in events if TIME_SHIFT_OFFSET <= e < VELOCITY_OFFSET]
    assert shifts == [TIME_SHIFT_OFFSET + 99, TIME_SHIFT_OFFSET + 99, TIME_SHIFT_OFFSET + 49]


def test_velocity_change_only_when_bucket_changes():
    notes = [
        Note(60, 80, 0.0, 0.1),
        Note(62, 81, 0.2, 0.3),  # same bucket (20) -> no velocity event
        Note(64, 100, 0.4, 0.5),  # bucket 25 -> velocity event
    ]
    events = events_from_notes(notes)
    vel_events = [e for e in events if e >= VELOCITY_OFFSET]
    assert vel_events == [VELOCITY_OFFSET + 20, VELOCITY_OFFSET + 25]


def test_sustain_extends_notes():
    # pedal down before note ends: note-off deferred to pedal release
    notes = [Note(60, 80, 0.1, 0.3)]
    controls = [ControlChange(64, 100, 0.0), ControlChange(64, 0, 1.0)]
    decoded = notes_from_events(events_from_notes(notes, controls))
    assert len(decoded) == 1
    assert abs(decoded[0].end - 1.0) < 0.011
    # next same-pitch note cuts the sustained one
    notes = [Note(60, 80, 0.1, 0.3), Note(60, 80, 0.6, 0.7)]
    decoded = notes_from_events(events_from_notes(notes, controls))
    assert abs(decoded[0].end - 0.6) < 0.011


def test_unmatched_note_off_dropped():
    assert notes_from_events([NOTE_OFF_OFFSET + 60]) == []
    assert notes_from_events([60]) == []  # never closed -> dropped


# -- dataset / collator ---------------------------------------------------
def _stream(pieces, rng=None):
    return SymbolicAudioDataModule.flatten_pieces(
        [np.asarray(p, np.int16) for p in pieces]
    )


def test_dataset_picks_longest_span():
    # stream with separators; windows crossing a boundary keep longest span
    pieces = [np.arange(5), np.arange(100, 160), np.arange(200, 203)]
    data = _stream(pieces)
    ds = SymbolicAudioDataset(data, max_seq_len=20, seed=0)
    for _ in range(20):
        sample = ds[0]["input_ids"]
        assert SEPARATOR not in sample
        assert len(sample) <= 21


def test_dataset_min_seq_len():
    data = _stream([np.arange(300)])
    ds = SymbolicAudioDataset(data, max_seq_len=40, min_seq_len=10, seed=0)
    lengths = {len(ds[0]["input_ids"]) for _ in range(50)}
    assert all(11 <= n <= 41 for n in lengths)
    assert len(lengths) > 5  # actually random


def test_collator_left_pad_shift_by_one():
    coll = SymbolicAudioCollator(max_seq_len=8, padding_side="left")
    batch = coll([{"input_ids": np.arange(1, 6)}])  # 5 tokens, width 9
    assert batch["input_ids"].shape == (1, 8)
    np.testing.assert_array_equal(batch["input_ids"][0, -4:], [1, 2, 3, 4])
    np.testing.assert_array_equal(batch["labels"][0, -5:], [1, 2, 3, 4, 5])
    assert batch["pad_mask"][0, :4].all() and not batch["pad_mask"][0, 4:].any()
    # shift-by-one: 4 input pads but only 3 label pads
    assert (batch["labels"][0, :3] == IGNORE_INDEX).all()


def test_collator_right_pad():
    coll = SymbolicAudioCollator(max_seq_len=8, padding_side="right")
    batch = coll([{"input_ids": np.arange(1, 6)}])
    np.testing.assert_array_equal(batch["input_ids"][0, :5], [1, 2, 3, 4, 5])
    np.testing.assert_array_equal(batch["labels"][0, :4], [2, 3, 4, 5])
    assert (batch["labels"][0, 4:] == IGNORE_INDEX).all()


def test_datamodule_from_streams_batches():
    rng = np.random.default_rng(0)
    train = _stream([rng.integers(0, 388, 400) for _ in range(3)])
    valid = _stream([rng.integers(0, 388, 200)])
    dm = SymbolicAudioDataModule.from_token_streams(
        train, valid, max_seq_len=32, batch_size=4
    )
    dm.setup()
    batch = next(iter(dm.train_dataloader()))
    assert batch["input_ids"].shape == (4, 32)
    assert batch["labels"].shape == (4, 32)
    assert batch["pad_mask"].dtype == bool
    assert batch["input_ids"].max() < VOCAB_SIZE


# -- train/valid split semantics ------------------------------------------
def test_maestro_manifest_split(tmp_path):
    """Official-manifest split (reference maestro_v3.py:58-76): train ->
    train, validation -> valid, test excluded; splits disjoint."""
    import json

    from perceiver_io_tpu.data.audio.symbolic import MaestroV3DataModule

    root = tmp_path / "maestro-v3.0.0"
    names = [f"2018/piece_{i}.midi" for i in range(6)]
    splits = ["train", "validation", "test", "train", "validation", "train"]
    for name in names:
        p = root / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.touch()
    manifest = {
        "midi_filename": {str(i): n for i, n in enumerate(names)},
        "split": {str(i): s for i, s in enumerate(splits)},
    }
    (root / "maestro-v3.0.0.json").write_text(json.dumps(manifest))

    dm = MaestroV3DataModule(str(tmp_path), max_seq_len=32)
    sources = dm.load_source_dataset()
    train = {p.name for p in sources["train"]}
    valid = {p.name for p in sources["valid"]}
    assert train == {"piece_0.midi", "piece_3.midi", "piece_5.midi"}
    assert valid == {"piece_1.midi", "piece_4.midi"}
    assert not train & valid  # disjoint; test pieces in neither


def test_giantmidi_presplit_dirs(tmp_path):
    from perceiver_io_tpu.data.audio.symbolic import GiantMidiPianoDataModule

    for split in ("train", "valid"):
        d = tmp_path / "midis" / split
        d.mkdir(parents=True)
        (d / f"{split}_piece.mid").touch()
    dm = GiantMidiPianoDataModule(str(tmp_path), max_seq_len=32)
    sources = dm.load_source_dataset()
    assert sources["train"] == tmp_path / "midis" / "train"
    assert sources["valid"] == tmp_path / "midis" / "valid"


def test_giantmidi_bucket_split_disjoint_and_stable(tmp_path):
    import zlib

    from perceiver_io_tpu.data.audio.symbolic import GiantMidiPianoDataModule

    root = tmp_path / "midis"
    root.mkdir()
    names = [f"piece_{i:03d}.mid" for i in range(40)]
    for n in names:
        (root / n).touch()
    dm = GiantMidiPianoDataModule(str(tmp_path), max_seq_len=32)
    sources = dm.load_source_dataset()
    train = {p.name for p in sources["train"]}
    valid = {p.name for p in sources["valid"]}
    assert not train & valid
    assert train | valid == set(names)
    assert valid  # bucket 0 of 10 over 40 names is non-empty
    for n in valid:
        assert zlib.crc32(n.encode()) % dm.num_buckets == dm.valid_bucket


def test_prepare_data_rejects_overlapping_splits(tmp_path):
    (tmp_path / "a.mid").touch()

    class Leaky(SymbolicAudioDataModule):
        def load_source_dataset(self):
            return {"train": tmp_path, "valid": tmp_path}

    dm = Leaky(str(tmp_path / "ds"), max_seq_len=32)
    with pytest.raises(ValueError, match="overlap"):
        dm.prepare_data()


def test_stale_cache_with_different_split_signature_refused(tmp_path):
    """A preproc cache built under one bucket layout must not silently serve
    another (split membership would leak across train/test)."""
    from perceiver_io_tpu.data.audio.symbolic import GiantMidiPianoDataModule

    dm = GiantMidiPianoDataModule(dataset_dir=str(tmp_path), max_seq_len=32)
    pre = dm.preproc_dir
    pre.mkdir(parents=True)
    import json

    (pre / "split_manifest.json").write_text(json.dumps({"train": [], "valid": [], "_signature": ""}))
    dm2 = GiantMidiPianoDataModule(dataset_dir=str(tmp_path), max_seq_len=32)
    dm2.test_bucket = 3
    with pytest.raises(ValueError, match="different .*split configuration"):
        dm2.prepare_data()
    # The default layout still accepts its own (pre-existing) cache.
    dm.prepare_data()
