"""Export-direction conversion tests (VERDICT r3 ask #5): JAX params →
reference (torch) formats, closing the three-form round-trip invariant
(reference ``docs/library-design.md:17-50``).

Oracles, strongest first:

1. **Strict load into the real reference module.** Every export is loaded
   with ``load_state_dict(strict=True)`` into the actual torch reference
   implementation (``tests/_reference.py``) — key set and shapes must match
   the reference exactly, including registered buffers.
2. **Round-trip exactness.** reference state_dict → import → export →
   identical key set, bit-identical fp32 values (transposes are lossless).
3. **Train-then-export logits parity.** The verdict's flow: import → one
   optimizer step in JAX → export → the reference model's torch logits match
   our JAX logits at atol 1e-4 (mlm + clm).
4. **save_pretrained artifact.** ``save_reference_checkpoint`` writes a
   directory whose ``pytorch_model.bin`` strict-loads into the reference
   backend after stripping the wrapper prefix, and whose ``config.json``
   ``model_config`` reconstructs the backend config (our config dataclasses
   are field-identical to the reference's — asserted here).
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

torch = pytest.importorskip("torch")

from tests._reference import load_reference

import perceiver_io_tpu.convert as convert
from perceiver_io_tpu.models.core.config import (
    ClassificationDecoderConfig,
    PerceiverIOConfig,
)
from perceiver_io_tpu.models.audio.symbolic import SymbolicAudioModelConfig
from perceiver_io_tpu.models.text.classifier import TextClassifierConfig
from perceiver_io_tpu.models.text.clm import CausalLanguageModel, CausalLanguageModelConfig
from perceiver_io_tpu.models.text.common import TextEncoderConfig
from perceiver_io_tpu.models.text.mlm import MaskedLanguageModel, TextDecoderConfig
from perceiver_io_tpu.models.vision.image_classifier import ImageEncoderConfig
from perceiver_io_tpu.models.vision.optical_flow import (
    OpticalFlowDecoderConfig,
    OpticalFlowEncoderConfig,
)

ref = load_reference()
pytestmark = pytest.mark.skipif(ref is None, reason="reference tree unavailable")

CLM_KW = dict(
    vocab_size=32, max_seq_len=16, max_latents=8, num_channels=16,
    num_heads=2, num_self_attention_layers=2, cross_attention_dropout=0.5,
)
MLM_ENC_KW = dict(
    vocab_size=32, max_seq_len=24, num_input_channels=16,
    num_cross_attention_heads=1, num_self_attention_heads=2,
    num_self_attention_layers_per_block=2,
)
MLM_DEC_KW = dict(vocab_size=32, max_seq_len=24)


def _mlm_configs(tied=True):
    dec_kw = dict(MLM_DEC_KW)
    if not tied:
        dec_kw["num_output_query_channels"] = 16
    t = ref.mlm.MaskedLanguageModelConfig(
        encoder=ref.mlm.TextEncoderConfig(**MLM_ENC_KW),
        decoder=ref.mlm.TextDecoderConfig(**dec_kw),
        num_latents=4, num_latent_channels=16,
    )
    j = PerceiverIOConfig(
        encoder=TextEncoderConfig(**MLM_ENC_KW),
        decoder=TextDecoderConfig(**dec_kw),
        num_latents=4, num_latent_channels=16,
    )
    return t, j


def _cases():
    """(name, reference model, jax config, importer, exporter) per task.

    Yields nothing when the reference tree is absent: parametrize evaluates
    this at *collection* time, before the module-level skipif applies, so
    dereferencing ``ref`` here would turn a skip into a collection error.
    """
    if ref is None:
        return
    torch.manual_seed(0)
    t_mlm, j_mlm = _mlm_configs()
    yield (
        "mlm",
        ref.mlm.MaskedLanguageModel(t_mlm).eval(),
        j_mlm,
        convert.import_masked_language_model,
        convert.export_masked_language_model,
    )
    yield (
        "clm",
        ref.clm.CausalLanguageModel(ref.clm.CausalLanguageModelConfig(**CLM_KW)).eval(),
        CausalLanguageModelConfig(**CLM_KW),
        convert.import_causal_language_model,
        convert.export_causal_language_model,
    )
    sam_kw = dict(CLM_KW, vocab_size=389)
    yield (
        "sam",
        ref.sam.SymbolicAudioModel(ref.sam.SymbolicAudioModelConfig(**sam_kw)).eval(),
        SymbolicAudioModelConfig(**sam_kw),
        convert.import_symbolic_audio_model,
        convert.export_symbolic_audio_model,
    )
    clf_dec = dict(num_classes=2, num_output_query_channels=16, num_cross_attention_heads=1)
    yield (
        "txt-clf",
        ref.txt_clf.TextClassifier(
            ref.txt_clf.TextClassifierConfig(
                encoder=ref.mlm.TextEncoderConfig(**MLM_ENC_KW),
                decoder=ref.core_config.ClassificationDecoderConfig(**clf_dec),
                num_latents=4, num_latent_channels=16,
            )
        ).eval(),
        TextClassifierConfig(
            encoder=TextEncoderConfig(**MLM_ENC_KW),
            decoder=ClassificationDecoderConfig(**clf_dec),
            num_latents=4, num_latent_channels=16,
        ),
        convert.import_text_classifier,
        convert.export_text_classifier,
    )
    img_enc = dict(
        image_shape=(8, 8, 1), num_frequency_bands=4, num_cross_attention_heads=1,
        num_self_attention_heads=2, num_self_attention_layers_per_block=2,
    )
    yield (
        "img-clf",
        ref.img_clf.ImageClassifier(
            ref.img_clf.ImageClassifierConfig(
                encoder=ref.img_clf.ImageEncoderConfig(**img_enc),
                decoder=ref.core_config.ClassificationDecoderConfig(**clf_dec),
                num_latents=4, num_latent_channels=16,
            )
        ).eval(),
        PerceiverIOConfig(
            encoder=ImageEncoderConfig(**img_enc),
            decoder=ClassificationDecoderConfig(**clf_dec),
            num_latents=4, num_latent_channels=16,
        ),
        convert.import_image_classifier,
        convert.export_image_classifier,
    )
    flow_enc = dict(
        image_shape=(6, 8), num_patch_input_channels=27, num_patch_hidden_channels=16,
        num_frequency_bands=4, num_cross_attention_heads=1,
        num_self_attention_heads=2, num_self_attention_layers_per_block=2,
    )
    flow_dec = dict(image_shape=(6, 8), num_cross_attention_heads=1)
    yield (
        "flow",
        ref.flow.OpticalFlow(
            ref.flow.OpticalFlowConfig(
                encoder=ref.flow.OpticalFlowEncoderConfig(**flow_enc),
                decoder=ref.flow.OpticalFlowDecoderConfig(**flow_dec),
                num_latents=8, num_latent_channels=16,
            )
        ).eval(),
        PerceiverIOConfig(
            encoder=OpticalFlowEncoderConfig(**flow_enc),
            decoder=OpticalFlowDecoderConfig(**flow_dec),
            num_latents=8, num_latent_channels=16,
        ),
        convert.import_optical_flow,
        convert.export_optical_flow,
    )


@pytest.mark.parametrize("case", list(_cases()), ids=lambda c: c[0])
def test_roundtrip_strict_load_and_exact_values(case):
    """import → export reproduces the reference state_dict exactly and
    strict-loads into a fresh copy of the real reference module."""
    name, t_model, j_config, importer, exporter = case
    sd = t_model.state_dict()
    params = importer(sd, j_config)
    out = exporter(params, j_config)

    assert set(out) == set(sd.keys()), (
        f"key mismatch: missing={set(sd) - set(out)}, extra={set(out) - set(sd)}"
    )
    for k, v in sd.items():
        np.testing.assert_allclose(
            out[k], v.detach().numpy(), atol=1e-6, rtol=0, err_msg=k
        )
    # The real acceptance check the reference library itself would run:
    t_model.load_state_dict({k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in out.items()}, strict=True)


def test_untied_mlm_roundtrip():
    torch.manual_seed(1)
    t_cfg, j_cfg = _mlm_configs(tied=False)
    t_model = ref.mlm.MaskedLanguageModel(t_cfg).eval()
    sd = t_model.state_dict()
    out = convert.export_masked_language_model(
        convert.import_masked_language_model(sd, j_cfg), j_cfg
    )
    assert set(out) == set(sd.keys())
    t_model.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in out.items()}, strict=True
    )


def _train_one_step(model, params, loss_grad_fn):
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    grads = loss_grad_fn(params)
    updates, _ = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates)


def test_clm_train_then_export_logits_parity():
    """The verdict's flow: import → train a step in JAX → export → the
    reference's torch forward matches the JAX forward at 1e-4."""
    torch.manual_seed(2)
    t_model = ref.clm.CausalLanguageModel(ref.clm.CausalLanguageModelConfig(**CLM_KW)).eval()
    j_config = CausalLanguageModelConfig(**CLM_KW)
    j_model = CausalLanguageModel(config=j_config)
    params = convert.import_causal_language_model(t_model.state_dict(), j_config)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, 32, (2, 13))
    prefix_len = 5

    def grad_fn(p):
        def loss(p):
            logits = j_model.apply({"params": p}, jnp.asarray(ids), prefix_len)
            return -jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1).mean()
        return jax.grad(loss)(p)

    params = _train_one_step(j_model, params, grad_fn)

    out = convert.export_causal_language_model(params, j_config)
    t_model.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in out.items()}, strict=True
    )
    with torch.no_grad():
        t_logits = t_model(torch.tensor(ids), prefix_len=prefix_len)
    j_logits = j_model.apply({"params": params}, jnp.asarray(ids), prefix_len)
    np.testing.assert_allclose(
        np.asarray(j_logits, np.float32), t_logits.numpy(), atol=1e-4, rtol=1e-4
    )


def test_mlm_train_then_export_logits_parity():
    torch.manual_seed(3)
    t_cfg, j_config = _mlm_configs()
    t_model = ref.mlm.MaskedLanguageModel(t_cfg).eval()
    j_model = MaskedLanguageModel(j_config)
    params = convert.import_masked_language_model(t_model.state_dict(), j_config)

    rng = np.random.default_rng(1)
    ids = rng.integers(0, 32, (2, 24))

    def grad_fn(p):
        def loss(p):
            logits = j_model.apply({"params": p}, jnp.asarray(ids))
            return -jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1).mean()
        return jax.grad(loss)(p)

    params = _train_one_step(j_model, params, grad_fn)

    out = convert.export_masked_language_model(params, j_config)
    t_model.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in out.items()}, strict=True
    )
    with torch.no_grad():
        t_logits = t_model(torch.tensor(ids))
    j_logits = j_model.apply({"params": params}, jnp.asarray(ids))
    np.testing.assert_allclose(
        np.asarray(j_logits, np.float32), t_logits.numpy(), atol=1e-4, rtol=1e-4
    )


def test_save_reference_checkpoint_artifact(tmp_path):
    """The save_pretrained-style directory: backend_model.-prefixed torch
    bin strict-loads into the reference backend; config.json reconstructs
    the backend config (field-identity with the reference asserted)."""
    torch.manual_seed(4)
    t_model = ref.clm.CausalLanguageModel(ref.clm.CausalLanguageModelConfig(**CLM_KW)).eval()
    j_config = CausalLanguageModelConfig(**CLM_KW)
    params = convert.import_causal_language_model(t_model.state_dict(), j_config)

    save_dir = convert.save_reference_checkpoint(params, j_config, str(tmp_path / "clm"), "clm")

    import json
    import os

    with open(os.path.join(save_dir, "config.json")) as f:
        cfg = json.load(f)
    assert cfg["model_type"] == "perceiver-ar-causal-language-model"
    # Our config dataclass is field-identical to the reference's, so
    # model_config reconstructs the reference backend config losslessly.
    ref_fields = {f.name for f in dataclasses.fields(ref.clm.CausalLanguageModelConfig)}
    assert set(cfg["model_config"]) == {
        f.name for f in dataclasses.fields(CausalLanguageModelConfig)
    } == ref_fields
    rebuilt = ref.clm.CausalLanguageModelConfig.create(**cfg["model_config"])
    assert rebuilt == ref.clm.CausalLanguageModelConfig(**CLM_KW)

    sd = torch.load(os.path.join(save_dir, "pytorch_model.bin"), weights_only=True)
    stripped = {k.removeprefix("backend_model."): v for k, v in sd.items()}
    t_model.load_state_dict(stripped, strict=True)

    # The artifact's central claim: the REAL reference HF wrapper loads the
    # directory via from_pretrained and reproduces the source logits.
    import importlib

    hf_clm = importlib.import_module("perceiver.model.text.clm.huggingface")
    wrapper = hf_clm.PerceiverCausalLanguageModel.from_pretrained(save_dir)
    wrapper.eval()
    ids = np.random.default_rng(7).integers(0, 32, (2, 13))
    with torch.no_grad():
        want = t_model(torch.tensor(ids), prefix_len=5).numpy()
        got = wrapper(torch.tensor(ids), prefix_len=5).logits.numpy()
    np.testing.assert_allclose(got, want, atol=1e-6, rtol=1e-6)


def test_export_task_mismatch_rejected(tmp_path):
    """A SAM model exported as 'clm' (structurally compatible trees!) must
    fail loudly instead of writing mislabeled wrapper metadata."""
    torch.manual_seed(6)
    sam_kw = dict(CLM_KW, vocab_size=389)
    t_model = ref.sam.SymbolicAudioModel(ref.sam.SymbolicAudioModelConfig(**sam_kw)).eval()
    j_config = SymbolicAudioModelConfig(**sam_kw)
    params = convert.import_symbolic_audio_model(t_model.state_dict(), j_config)
    with pytest.raises(ValueError, match="task mismatch"):
        convert.save_reference_checkpoint(params, j_config, str(tmp_path / "x"), "clm")
    convert.save_reference_checkpoint(params, j_config, str(tmp_path / "ok"), "sam")


def test_save_reference_checkpoint_mlm_config_fields(tmp_path):
    torch.manual_seed(5)
    t_cfg, j_config = _mlm_configs()
    t_model = ref.mlm.MaskedLanguageModel(t_cfg).eval()
    params = convert.import_masked_language_model(t_model.state_dict(), j_config)
    save_dir = convert.save_reference_checkpoint(params, j_config, str(tmp_path / "mlm"), "mlm")

    import json
    import os

    with open(os.path.join(save_dir, "config.json")) as f:
        cfg = json.load(f)
    assert cfg["model_type"] == "perceiver-io-masked-language-model"
    mc = cfg["model_config"]
    # The reference wrapper rebuilds nested configs from these dicts
    # (mlm/huggingface.py:33-39); field sets must match its dataclasses.
    assert set(mc["encoder"]) == {f.name for f in dataclasses.fields(ref.mlm.TextEncoderConfig)}
    assert set(mc["decoder"]) == {f.name for f in dataclasses.fields(ref.mlm.TextDecoderConfig)}
    rebuilt = ref.mlm.MaskedLanguageModelConfig(
        encoder=ref.mlm.TextEncoderConfig(**mc["encoder"]),
        decoder=ref.mlm.TextDecoderConfig(**mc["decoder"]),
        **{k: v for k, v in mc.items() if k not in ("encoder", "decoder")},
    )
    assert rebuilt == t_cfg
    sd = torch.load(os.path.join(save_dir, "pytorch_model.bin"), weights_only=True)
    stripped = {k.removeprefix("backend_model."): v for k, v in sd.items()}
    t_model.load_state_dict(stripped, strict=True)


@pytest.mark.slow
def test_img_clf_train_then_export_logits_parity():
    """Train-then-export parity for an encoder/decoder family (VERDICT r4
    ask #7): import → one optimizer step on the Fourier-adapter image
    classifier in JAX → export → reference torch forward matches at 1e-4.
    Proves the export path under trained (not just initialized) weights for
    the Fourier position adapter + classification decoder."""
    torch.manual_seed(7)
    from perceiver_io_tpu.models.vision.image_classifier import ImageClassifier

    enc_kw = dict(
        image_shape=(8, 8, 1), num_frequency_bands=4, num_cross_attention_heads=1,
        num_self_attention_heads=2, num_self_attention_layers_per_block=2,
    )
    clf_dec = dict(num_classes=2, num_output_query_channels=16, num_cross_attention_heads=1)
    t_model = ref.img_clf.ImageClassifier(
        ref.img_clf.ImageClassifierConfig(
            encoder=ref.img_clf.ImageEncoderConfig(**enc_kw),
            decoder=ref.core_config.ClassificationDecoderConfig(**clf_dec),
            num_latents=4, num_latent_channels=16,
        )
    ).eval()
    j_config = PerceiverIOConfig(
        encoder=ImageEncoderConfig(**enc_kw),
        decoder=ClassificationDecoderConfig(**clf_dec),
        num_latents=4, num_latent_channels=16,
    )
    j_model = ImageClassifier(config=j_config)
    params = convert.import_image_classifier(t_model.state_dict(), j_config)

    rng = np.random.default_rng(2)
    imgs = rng.normal(size=(2, 8, 8, 1)).astype(np.float32)
    labels = jnp.asarray(rng.integers(0, 2, (2,)))

    def grad_fn(p):
        def loss(p):
            logits = j_model.apply({"params": p}, jnp.asarray(imgs))
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            return -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
        return jax.grad(loss)(p)

    params = _train_one_step(j_model, params, grad_fn)

    out = convert.export_image_classifier(params, j_config)
    t_model.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in out.items()}, strict=True
    )
    with torch.no_grad():
        t_logits = t_model(torch.tensor(imgs))
    j_logits = j_model.apply({"params": params}, jnp.asarray(imgs))
    np.testing.assert_allclose(
        np.asarray(j_logits, np.float32), t_logits.numpy(), atol=1e-4, rtol=1e-4
    )
